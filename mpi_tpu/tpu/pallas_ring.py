"""Hand-rolled ring collectives as Pallas TPU kernels (RDMA over ICI).

SURVEY.md §7 Milestone 3 anticipated this: "possibly a Pallas DMA ring if
XLA's ppermute chaining leaves bandwidth on the table".  Exposed as
``allreduce(..., algorithm='pallas_ring')`` and
``reduce_scatter(..., algorithm='pallas_ring')``.

Design (v2 — pipelined; v1 serialized every step behind an RDMA wait and a
2-signal neighbor barrier, VERDICT round 1 "what's weak" #3):

* One unified ring of ``2(P-1)`` steps inside a single kernel: steps
  ``0..P-2`` are the reduce-scatter half (RDMA lands in a double-buffered
  comm buffer, gets accumulated into the working copy), steps
  ``P-1..2P-3`` are the allgather half (RDMA lands DIRECTLY in the
  symmetric slice of the neighbor's output — no staging, no extra copy).
* **Bidirectional counter-rotating rings** (v3): ICI links are
  full-duplex, so each chunk's tiles are split between a right-going
  ring and a left-going mirror ring running concurrently — each an
  independent pipelined flow with its own (parity, flow) semaphore
  column and disjoint tile range.  Twice the usable line-rate of a
  single ring; degrades to unidirectional at one tile per chunk.
* **Segment pipelining**: each chunk's per-direction tile range is
  split into ≤4 segments (flows) with per-(parity, flow) DMA semaphores.  A segment's step-``u+1`` RDMA
  starts the moment its step-``u`` accumulation stores — so while segment
  i+1 of step u is still landing/accumulating, segment i of step u+1 is
  already on the wire.  The RDMA ring streams behind the compute instead
  of strictly alternating with it.
* **Credit flow control** replaces the per-step neighbor barrier: after a
  device consumes landing slot (parity, seg) it signals one credit to its
  LEFT neighbor (the writer of that slot); a sender re-using the slot two
  steps later first waits for that credit.  Cost: one remote semaphore
  signal per consumed segment, off the critical path — versus v1's two
  signals + a blocking wait per step for every device in lockstep.
* Entry/exit neighbor barriers (one each) still bracket the kernel so an
  RDMA can never land on a chip whose kernel hasn't started / has exited.
* Accumulation stages HBM→VMEM in ``tile_rows``×128 tiles (VMEM is
  ~16 MB; chunks can be tens of MB for the 256 MB north-star buffer).

Under the Pallas **interpreter** (the CPU-mesh test path) remote
semaphore signalling is unavailable, so barriers/credits are skipped and
every RDMA is started+waited serially — same data path, no pipelining;
the overlap logic itself is exercised by the AOT compile checks in the
real-TPU test tier (tests/test_tpu_real.py).

**Protocol invariants** — verified by the discrete-event model in
``mpi_tpu/tpu/ring_model.py`` (exhaustive interleaving search for small
(P, K); adversarial schedules with payload tracking up to P=8, K=4 —
tests/test_pallas_protocol.py), since the pipelined path cannot execute
on fewer than two chips:

1. no deadlock under any event ordering respecting semaphore semantics
   (each semaphore has a single waiter, so the op graph is a
   conflict-free Petri net — verified, not assumed);
2. an RDMA never lands in a (parity, segment) slot whose previous
   payload is unconsumed (the credit handshake's guarantee);
3. no buffer region is written while an in-flight RDMA reads it, on
   either end;
4. every semaphore drains to zero by kernel exit (Mosaic's invariant);
5. payload correctness under every explored ordering (contribution-set
   semantics, both collectives).

Supported: float32 AND bfloat16; SUM, MAX, MIN; the full axis OR a split
communicator's groups (one independent ring per group, same kernel);
1-D AND multi-axis meshes (a ring over one axis of a 2-D+ training mesh
addresses its RDMA neighbors by mesh coordinate — ``_kernel``'s
``mesh_ids``; VERDICT r3 missing #2).  Diagnosed restrictions: other
dtypes/ops.  Interpreter fallbacks (vma typing / multi-axis mesh) warn
and count via the ``pallas_ring_fallbacks`` mpit pvar.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = {jnp.dtype(jnp.float32): 8, jnp.dtype(jnp.bfloat16): 16}
_MAX_SEGMENTS = 4

# a flow = one pipelined stream of RDMAs: (direction, first_tile, num_tiles);
# direction +1 sends right (classic ring), -1 sends left (counter-rotating)
Flow = Tuple[int, int, int]


def _segments(total_tiles: int) -> List[Tuple[int, int]]:
    """Split a chunk of ``total_tiles`` row-tiles into ≤_MAX_SEGMENTS
    contiguous (first_tile, num_tiles) pieces for the pipeline."""
    k = min(_MAX_SEGMENTS, total_tiles)
    base, extra = divmod(total_tiles, k)
    segs, t0 = [], 0
    for s in range(k):
        n = base + (1 if s < extra else 0)
        segs.append((t0, n))
        t0 += n
    return segs


def _flows(total_tiles: int, bidirectional: bool) -> List[Flow]:
    """Partition each chunk's row-tiles into counter-rotating flows.

    ICI links are full-duplex: a single right-going ring leaves every
    link's left direction idle.  Splitting each chunk between a
    right-going ring (first ~half of its tiles) and a left-going ring
    (the rest) runs both directions concurrently — the classic trick that
    doubles ring-allreduce bus bandwidth (VERDICT r2 next-step #3).  At
    one tile per chunk there is nothing to split and the kernel degrades
    to the unidirectional ring."""
    tB = total_tiles // 2 if bidirectional else 0
    tA = total_tiles - tB
    flows: List[Flow] = [(+1, t0, nt) for (t0, nt) in _segments(tA)]
    if tB:
        flows += [(-1, tA + t0, nt) for (t0, nt) in _segments(tB)]
    return flows


def _kernel(params_smem, x_hbm, out_hbm, comm_hbm, a_vmem, b_vmem,
            copy_sem_a, copy_sem_b, send_sem, recv_sem, credit_sem, *,
            axis_name: str, size: int, rows: int, tile_rows: int,
            flows: List[Flow], rot: int, allgather: bool,
            pipelined: bool, combine=None, rs: bool = True,
            mesh_ids: bool = False):
    """``rot`` shifts the chunk schedule: 0 → the ring ends with rank r
    owning chunk (r+1)%P (allreduce layout); -1 → rank r owns chunk r
    (reduce_scatter layout).  ``allgather=False`` stops after the
    reduce-scatter half.  ``flows`` carries the counter-rotating split:
    each flow is an independent pipelined stream over its own tile range
    and (parity, flow) semaphore column; direction -1 flows mirror the
    ring (send left, credit right, chunk schedule negated).

    ``params_smem`` = [group rank, left neighbor, right neighbor] (int32,
    SMEM), computed host-side.  For COMM_WORLD these are the classic ring
    formulas; for a split communicator they come from the group tables, so
    every group runs its own independent ring inside the one SPMD kernel
    — same instruction stream, per-device neighbors.

    ``mesh_ids`` selects the neighbor ADDRESSING mode (VERDICT r3
    missing #2 — multi-axis meshes):  False → the neighbor's axis index
    IS its logical device id (1-D mesh; the path validated on silicon).
    True → the neighbor is named by its coordinate along ``axis_name``
    via a dict-MESH device id ``{axis_name: idx}``; Mosaic fills the
    other mesh axes with this device's own coordinates and converts to
    a logical id through the mesh strides — the ring stays inside the
    (sub)ring of devices sharing this device's other-axis coordinates,
    which is exactly what a per-axis collective on a 2-D+ training mesh
    means.  Only the ADDRESS SPELLING changes: the protocol state
    machine (which semaphores are signalled/waited, in what order) is
    identical in both modes, so ring_model.py's verification carries
    over to the multi-axis case by pure relabeling of device ids."""
    my = params_smem[0]          # group-local rank (chunk schedule index)
    left = params_smem[1]        # axis index of the upstream +1 neighbor
    right = params_smem[2]       # axis index of the downstream +1 neighbor
    P = size

    def dev_kw(target):
        """device_id kwargs for an RDMA/signal aimed at axis index
        ``target`` (see ``mesh_ids`` above)."""
        if mesh_ids:
            return dict(device_id={axis_name: target},
                        device_id_type=pltpu.DeviceIdType.MESH)
        return dict(device_id=target,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
    # rs=False is the ALLGATHER-ONLY mode: zero reduce-scatter steps, P-1
    # land-direct steps — the same unified schedule starting at the AG half
    # (each rank's own chunk circulates; no accumulation, half the steps)
    n_rs = P - 1 if rs else 0          # reduce-scatter steps: u in [0, n_rs)
    n_steps = n_rs + (P - 1 if allgather else 0)

    def send_chunk(u, dirn):
        # chunk forwarded at step u (RS: the one accumulated at u-1;
        # AG: the one received at u-1).  The -1 direction is the mirror
        # image r ↦ -r of the ring: its schedule is the +1 formula negated.
        if dirn > 0:
            return lax.rem(my - u + rot + 2 * P, P)
        return lax.rem(my + u - rot + 2 * P, P)

    def accum_chunk(u, dirn):
        if dirn > 0:
            return lax.rem(my - u - 1 + rot + 2 * P, P)
        return lax.rem(my + u + 1 - rot + 2 * P, P)

    def rdma(u, fi):
        """The step-u RDMA for flow fi (symmetric SPMD descriptor: names
        my outgoing copy AND the incoming one via my recv_sem)."""
        dirn, t0, nt = flows[fi]
        r0, nr = t0 * tile_rows, nt * tile_rows
        slot = u % 2
        target = right if dirn > 0 else left
        c = send_chunk(u, dirn)
        if u < n_rs:  # reduce-scatter: land in the comm buffer
            src = out_hbm.at[pl.ds(c * rows + r0, nr)]
            dst = comm_hbm.at[slot, pl.ds(r0, nr)]
        else:         # allgather: land straight in the neighbor's output
            # AG step a sends chunk (my∓1±a) — the same unified
            # send_chunk(u) as the RS half, per direction
            src = out_hbm.at[pl.ds(c * rows + r0, nr)]
            dst = out_hbm.at[pl.ds(c * rows + r0, nr)]
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst,
            send_sem=send_sem.at[slot, fi], recv_sem=recv_sem.at[slot, fi],
            **dev_kw(target))

    def start_send(u, fi):
        if pipelined:
            if u >= 2:
                # send-sem hygiene: my step-(u-2) send on this (slot, flow)
                # must have fully left before the semaphore is re-armed
                rdma(u - 2, fi).wait_send()
                # flow control, BOTH halves: the receiver re-uses this
                # (parity, flow) recv semaphore from step u-2.  In the RS
                # half its landing slot is also recycled (buffer hazard);
                # in the AG half destinations are distinct but the
                # counting recv semaphore is not — if this RDMA completed
                # before the step-u-1 one, the receiver's wait_recv(u-1)
                # would unblock on OUR bytes and forward a chunk that
                # hasn't landed.  So never run more than 2 steps ahead of
                # the receiver's consumption.
                pltpu.semaphore_wait(credit_sem.at[u % 2, fi], 1)
            rdma(u, fi).start()
        else:
            rdma(u, fi).start()
            rdma(u, fi).wait()

    def neighbor_barrier():
        if not pipelined:
            return
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(left))
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(right))
        pltpu.semaphore_wait(bar, 2)

    # working copy: out <- x (HBM -> HBM local DMA).  In the ag-only mode
    # x is just MY block: it seeds chunk ``my`` and every other chunk is
    # fully overwritten by an incoming land-direct RDMA before any read
    # (send_chunk(u) = the chunk received at step u-1), so no size*block
    # zero grid is ever materialized or streamed (review round 3).
    if rs:
        init = pltpu.make_async_copy(x_hbm, out_hbm, copy_sem_a)
    else:
        init = pltpu.make_async_copy(
            x_hbm, out_hbm.at[pl.ds(my * rows, rows)], copy_sem_a)
    init.start()
    init.wait()

    # entry sync: the first RDMA must not land on a chip whose kernel
    # hasn't started (execution skew would let it write unowned scratch)
    neighbor_barrier()

    # warm-up: step-0 sends carry original data — no dependency
    for fi in range(len(flows)):
        start_send(0, fi)

    for u in range(n_steps):
        slot = u % 2
        for fi in range(len(flows)):
            dirn, t0, nt = flows[fi]
            if pipelined:
                rdma(u, fi).wait_recv()  # flow's segment landed
            if u < n_rs:
                # accumulate landing[slot, flow] into out[accum_chunk, flow]
                ci = accum_chunk(u, dirn)
                for t in range(t0, t0 + nt):
                    row0 = ci * rows + t * tile_rows
                    cp_a = pltpu.make_async_copy(
                        out_hbm.at[pl.ds(row0, tile_rows)], a_vmem,
                        copy_sem_a)
                    cp_b = pltpu.make_async_copy(
                        comm_hbm.at[slot, pl.ds(t * tile_rows, tile_rows)],
                        b_vmem, copy_sem_b)
                    cp_a.start()
                    cp_b.start()
                    cp_a.wait()
                    cp_b.wait()
                    a_vmem[:] = (a_vmem[:] + b_vmem[:] if combine is None
                                 else combine(a_vmem[:], b_vmem[:]))
                    cp_out = pltpu.make_async_copy(
                        a_vmem, out_hbm.at[pl.ds(row0, tile_rows)],
                        copy_sem_a)
                    cp_out.start()
                    cp_out.wait()
            if pipelined and u + 2 < n_steps:
                # step-u consumption done (RS: landing slot accumulated;
                # AG: chunk landed) → credit the writer (the flow's
                # upstream neighbor), which re-arms this (parity, flow) at
                # step u+2.  Guarded so every credit is consumed and the
                # semaphore drains to zero by kernel exit (Mosaic checks).
                writer = left if dirn > 0 else right
                pltpu.semaphore_signal(
                    credit_sem.at[slot, fi], inc=1, **dev_kw(writer))
            # this flow's segment is now ready for the next hop
            if u + 1 < n_steps:
                start_send(u + 1, fi)

    if pipelined:
        # drain: my two newest sends per flow are still only started
        for fi in range(len(flows)):
            if n_steps >= 2:
                rdma(n_steps - 2, fi).wait_send()
            rdma(n_steps - 1, fi).wait_send()
    # exit sync: don't let this chip's NEXT collective race a straggling
    # neighbor still reading its landing zone
    neighbor_barrier()


def _geometry(n: int, size: int, tile_rows: int) -> Tuple[int, int]:
    """rows per chunk (multiple of tile_rows) and padded element count."""
    per_chunk = -(-n // size)
    rows = -(-per_chunk // _LANES)
    rows = -(-rows // tile_rows) * tile_rows
    return rows, size * rows * _LANES


# Elementwise combiners: positions only ever combine with the SAME
# position of other ranks' chunks, so the zero padding of _geometry can
# never contaminate a real lane — any identity works for the pad.
_COMBINES = {
    "sum": None,  # None → the kernel's inlined add (the common path)
    "max": lambda a, b: jnp.maximum(a, b),
    "min": lambda a, b: jnp.minimum(a, b),
}


def _check_args(x: jnp.ndarray, axis_name: str, size: int, tile_rows: int,
                op: str) -> Tuple[bool, bool]:
    """Validate dtype/op/tiling; returns ``(vma_on, multi_axis)``:
    whether varying-axes (vma) typing is active on the enclosing
    shard_map, and whether the enclosing mesh has axes beyond
    ``axis_name`` (→ the kernel must address RDMA neighbors by mesh
    coordinate instead of logical id — ``_kernel``'s ``mesh_ids``)."""
    dtype = jnp.dtype(x.dtype)
    if dtype not in _SUBLANES:
        raise NotImplementedError(
            f"pallas_ring supports float32/bfloat16 for now, got {x.dtype}")
    if op not in _COMBINES:
        raise NotImplementedError(
            f"pallas_ring supports {sorted(_COMBINES)} for now, got {op!r}")
    sub = _SUBLANES[dtype]
    if tile_rows % sub or tile_rows < sub:
        raise ValueError(
            f"tile_rows must be a positive multiple of {sub} "
            f"({dtype} sublane tile), got {tile_rows}")
    try:
        from jax.sharding import get_abstract_mesh

        mesh_axes = get_abstract_mesh().axis_names
    except Exception:
        mesh_axes = (axis_name,)
    multi_axis = tuple(mesh_axes) not in ((), (axis_name,))
    # vma typing may be active even when the payload is replicated; probe
    # with axis_index, which is varying exactly when check_vma is on
    try:
        vma_on = bool(jax.typeof(lax.axis_index(axis_name)).vma)
    except (AttributeError, NameError):
        vma_on = False  # no vma typing / not under shard_map (yet)
    return vma_on, multi_axis


def _fallback(coll: str, axis_name: str, vma_on: bool,
              multi_axis: bool) -> None:
    """The interpreter cannot execute the kernel body under vma typing
    (hbm↔scratch mixes trip the checker) nor discharge remote DMAs on a
    multi-axis mesh (jax's dma_start discharge rule is 1-D-only) — those
    calls run the same ring schedule as vma-typed ppermute steps instead.
    Correctness-equivalent, but a sim benchmark of "pallas_ring" would
    silently measure the wrong implementation (VERDICT r3 weak #4), so
    every fallback take warns AND bumps the ``pallas_ring_fallbacks``
    mpit pvar.  This fires at TRACE time (once per compilation), which is
    exactly when the substitution is decided."""
    import warnings

    from .. import mpit

    why = " and ".join(
        w for w, on in (("vma typing is active", vma_on),
                        (f"the mesh has axes beyond {axis_name!r}",
                         multi_axis)) if on)
    warnings.warn(
        f"pallas_ring {coll}: executing the ppermute ring fallback on the "
        f"interpreter ({why}); timings will not reflect the RDMA kernel. "
        f"The compiled TPU path runs the kernel itself.",
        RuntimeWarning, stacklevel=3)
    mpit.count(pallas_fallbacks=1)


def _world_pairs_of(size: int, groups):
    """world_pairs callable expanding group-local (src, dst) pairs to
    world-level ppermute pairs (identity for the full axis), validated
    like TpuCommunicator's — used by the vma-typed interpreter fallback."""
    from ..checker import validate_perm

    axis_size = size if groups is None else sum(len(g) for g in groups)

    def world_pairs(pairs):
        if groups is None:
            pairs = list(pairs)
        else:
            pairs = [(g[s], g[d]) for g in groups for (s, d) in pairs]
        validate_perm(pairs, axis_size)
        return pairs

    return world_pairs


def _ring_params(axis_name: str, size: int, groups) -> jnp.ndarray:
    """Per-device [grank, left, right] int32 vector (traced, host tables).

    ``left``/``right`` are AXIS indices (what the RDMA device_id needs);
    ``grank`` is the group-local rank (what the chunk schedule needs).
    For groups=None they collapse to the classic (idx±1) mod P ring."""
    idx = lax.axis_index(axis_name)
    if groups is None:
        return jnp.stack([idx, lax.rem(idx - 1 + size, size),
                          lax.rem(idx + 1, size)]).astype(jnp.int32)
    axis_size = sum(len(g) for g in groups)
    grank_t = np.zeros(axis_size, np.int32)
    left_t = np.zeros(axis_size, np.int32)
    right_t = np.zeros(axis_size, np.int32)
    for g in groups:
        for pos, world in enumerate(g):
            grank_t[world] = pos
            left_t[world] = g[(pos - 1) % len(g)]
            right_t[world] = g[(pos + 1) % len(g)]
    return jnp.stack([jnp.asarray(grank_t)[idx], jnp.asarray(left_t)[idx],
                      jnp.asarray(right_t)[idx]]).astype(jnp.int32)


def _launch(x: jnp.ndarray, axis_name: str, size: int, tile_rows: int,
            interpret: bool, rot: int, allgather: bool,
            collective_id: int, bidirectional: bool = True,
            vma_on: bool = False, groups=None,
            op: str = "sum", rs: bool = True,
            mesh_ids: bool = False) -> jnp.ndarray:
    """Shared pallas_call setup for both ring collectives; returns the
    padded [size*rows, _LANES] result grid.

    ``vma_on``: varying-axes typing is active on the enclosing shard_map.
    The compiled kernel supports it directly — the out_shape declares the
    result varying over ``axis_name`` and Mosaic lowers the body outside
    vma land (verified by the real-TPU AOT tier).  Callers on the
    *interpreter* must not reach here with ``vma_on`` (the interpreter
    evaluates the body as jax ops, where hbm↔scratch mixes trip the vma
    checker) — they take the vma-typed ppermute fallback instead."""
    dtype = jnp.dtype(x.dtype)
    shape = x.shape
    n = int(np.prod(shape)) if shape else 1
    if rs:
        rows, padded = _geometry(n, size, tile_rows)
        flat = x.reshape(-1)
        if padded != n:
            flat = jnp.pad(flat, (0, padded - n))
        grid_in = flat.reshape(size * rows, _LANES)
    else:
        # ag-only: x is ONE pre-padded chunk ([rows, _LANES] worth); the
        # kernel seeds chunk ``my`` with it and the ring fills the rest
        rows = n // _LANES
        grid_in = x.reshape(rows, _LANES)
    flows = _flows(rows // tile_rows, bidirectional)

    kern = functools.partial(
        _kernel, axis_name=axis_name, size=size, rows=rows,
        tile_rows=tile_rows, flows=flows, rot=rot, allgather=allgather,
        pipelined=not interpret, combine=_COMBINES[op], rs=rs,
        mesh_ids=mesh_ids)
    compiler_params = None if interpret else pltpu.CompilerParams(
        collective_id=collective_id, has_side_effects=True)
    k = len(flows)
    if vma_on:
        # the result varies over the ring axis AND over any other mesh
        # axis the input already varies over (multi-axis meshes: a dp
        # ring's payload is usually mp-varying too)
        try:
            in_vma = frozenset(jax.typeof(grid_in).vma)
        except (AttributeError, NameError):
            in_vma = frozenset()
        out_shape = jax.ShapeDtypeStruct((size * rows, _LANES), dtype,
                                         vma=in_vma | {axis_name})
    else:
        out_shape = jax.ShapeDtypeStruct((size * rows, _LANES), dtype)
    params = _ring_params(axis_name, size, groups)
    return pl.pallas_call(
        kern,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            # RDMA landing zone — unused (1-row stub) in the ag-only mode,
            # where RDMAs land directly in the output
            pl.ANY((2, rows if rs else 1, _LANES), dtype),
            pltpu.VMEM((tile_rows, _LANES), dtype),
            pltpu.VMEM((tile_rows, _LANES), dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2, k)),             # send (parity, flow)
            pltpu.SemaphoreType.DMA((2, k)),             # recv (parity, flow)
            pltpu.SemaphoreType.REGULAR((2, k)),         # landing credits
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(params, grid_in)


def flow_summary(n_elements: int, size: int, tile_rows: int = 256,
                 dtype=jnp.float32, bidirectional: bool = True) -> dict:
    """Per-direction wire traffic of one ring step for an ``n_elements``
    payload — derived from the same geometry the kernel launches with, so
    benchmark diagnostics can't drift from what actually transfers."""
    itemsize = jnp.dtype(dtype).itemsize
    rows, _ = _geometry(n_elements, size, tile_rows)
    fl = _flows(rows // tile_rows, bidirectional)
    per_tile = tile_rows * _LANES * itemsize
    return {
        "right_bytes_per_chunk": sum(nt for d, _, nt in fl if d > 0) * per_tile,
        "left_bytes_per_chunk": sum(nt for d, _, nt in fl if d < 0) * per_tile,
        "n_flows": len(fl),
    }


def pallas_ring_allreduce(x: jnp.ndarray, axis_name: str, size: int,
                          tile_rows: int = 256,
                          interpret: bool = False,
                          bidirectional: bool = True,
                          groups=None, op: str = "sum") -> jnp.ndarray:
    """Allreduce ``x`` (f32/bf16; ``op`` in 'sum'/'max'/'min') over
    ``axis_name`` with the in-kernel pipelined RDMA ring — bidirectional
    (counter-rotating) by default.  Call inside shard_map over a mesh
    with that axis.

    Works under ``check_vma=True``: compiled, the kernel declares its
    result varying over the axis (brand it with ``comm.replicate`` if a
    replicated out_spec is needed); on the *interpreter* the same ring
    schedule executes as vma-typed ppermute steps instead (the kernel body
    cannot be interpreted under vma typing — kernel-body interpretation is
    covered by the check_vma=False tests, the pipelined protocol by
    mpi_tpu/tpu/ring_model.py, and the compiled path by the real-TPU AOT
    tier).

    ``groups``: optional equal-sized partition of the axis (a split
    communicator's axis_index_groups); each group runs its own
    independent ring — ``size`` is then the GROUP size.

    Multi-axis meshes (a 2-D+ training mesh, VERDICT r3 missing #2):
    compiled, the kernel addresses neighbors by their coordinate along
    ``axis_name`` (dict-MESH device ids — see ``_kernel``), so the ring
    runs per-(other-axes slice) exactly like any per-axis collective;
    the interpreter takes the ppermute fallback (jax's remote-DMA
    discharge rule is 1-D-only)."""
    vma_on, multi_axis = _check_args(x, axis_name, size, tile_rows, op)
    if size == 1:
        return x
    if (vma_on or multi_axis) and interpret:
        from ..ops import BY_NAME
        from . import collectives as algos

        _fallback("allreduce", axis_name, vma_on, multi_axis)
        grank = _ring_params(axis_name, size, groups)[0]
        return algos.ring_allreduce(x, axis_name, size, grank,
                                    _world_pairs_of(size, groups),
                                    op=BY_NAME[op])
    shape = x.shape
    n = int(np.prod(shape)) if shape else 1
    out = _launch(x, axis_name, size, tile_rows, interpret,
                  rot=0, allgather=True, collective_id=13,
                  bidirectional=bidirectional, vma_on=vma_on, groups=groups,
                  op=op, mesh_ids=multi_axis)
    return out.reshape(-1)[:n].reshape(shape)


def pallas_ring_allgather(x: jnp.ndarray, axis_name: str, size: int,
                          tile_rows: int = 256,
                          interpret: bool = False,
                          bidirectional: bool = True,
                          groups=None) -> jnp.ndarray:
    """Allgather: every rank contributes its block ``x``; returns the
    stacked [size, *x.shape] grid in rank order.  The ALLGATHER-ONLY mode
    of the unified ring kernel: P-1 pipelined land-direct RDMA steps (no
    accumulation — each rank's chunk circulates straight into every
    output), same credits/barriers/counter-rotating flows as the
    allreduce.  f32/bf16; check_vma / multi-axis-mesh handling as in
    :func:`pallas_ring_allreduce`."""
    vma_on, multi_axis = _check_args(x, axis_name, size, tile_rows, "sum")
    grank = _ring_params(axis_name, size, groups)[0]
    if size == 1:
        return x[None]
    if (vma_on or multi_axis) and interpret:
        from . import collectives as algos

        _fallback("allgather", axis_name, vma_on, multi_axis)
        return algos.ring_allgather(x, axis_name, size, grank,
                                    _world_pairs_of(size, groups))
    block_shape = x.shape
    block_n = int(np.prod(block_shape)) if block_shape else 1
    rows, _ = _geometry(block_n * size, size, tile_rows)
    per_chunk = rows * _LANES
    flat = x.reshape(-1)
    if per_chunk != block_n:
        flat = jnp.pad(flat, (0, per_chunk - block_n))
    # only MY padded block crosses into the kernel — it seeds chunk
    # ``grank`` in-kernel; every other chunk is written by the ring
    out = _launch(flat, axis_name, size, tile_rows, interpret,
                  rot=0, allgather=True, collective_id=15,
                  bidirectional=bidirectional, vma_on=vma_on, groups=groups,
                  rs=False, mesh_ids=multi_axis)
    out = out.reshape(size, per_chunk)[:, :block_n]
    return out.reshape((size,) + block_shape)


def pallas_ring_reduce_scatter(x: jnp.ndarray, axis_name: str, size: int,
                               tile_rows: int = 256,
                               interpret: bool = False,
                               bidirectional: bool = True,
                               groups=None, op: str = "sum") -> jnp.ndarray:
    """Reduce-scatter-block (the ZeRO primitive; ``op`` in
    'sum'/'max'/'min'): ``x`` is the full
    [P*block, ...] stack on every rank; rank r returns block r reduced
    over all ranks.  Runs ONLY the reduce-scatter half of the ring —
    half the wire traffic of the allreduce.

    ``x``'s leading dimension must equal ``size`` (the communicator's
    stacked-blocks convention, matching ``lax.psum_scatter`` tiled=False).

    check_vma / multi-axis-mesh handling is as in
    :func:`pallas_ring_allreduce`."""
    if x.ndim == 0 or x.shape[0] != size:
        raise ValueError(
            f"reduce_scatter needs leading dimension == ring size {size} "
            f"(one block per rank), got shape {x.shape}")
    vma_on, multi_axis = _check_args(x, axis_name, size, tile_rows, op)
    if size == 1:
        return x[0]
    if (vma_on or multi_axis) and interpret:
        from ..ops import BY_NAME
        from . import collectives as algos

        _fallback("reduce_scatter", axis_name, vma_on, multi_axis)
        grank = _ring_params(axis_name, size, groups)[0]
        return algos.ring_reduce_scatter(x, axis_name, size, grank,
                                         _world_pairs_of(size, groups),
                                         op=BY_NAME[op])
    block_shape = x.shape[1:]
    block_n = int(np.prod(block_shape))
    rows, _ = _geometry(block_n * size, size, tile_rows)
    # lay each BLOCK into its own chunk of the grid so chunk boundaries
    # align with block boundaries (per-block zero padding)
    per_chunk = rows * _LANES
    blocks = x.reshape(size, block_n)
    pad = per_chunk - block_n
    if pad:
        blocks = jnp.pad(blocks, ((0, 0), (0, pad)))
    grid = blocks.reshape(-1)
    out = _launch(grid, axis_name, size, tile_rows, interpret,
                  rot=-1, allgather=False, collective_id=14,
                  bidirectional=bidirectional, vma_on=vma_on, groups=groups,
                  op=op, mesh_ids=multi_axis)
    grank = _ring_params(axis_name, size, groups)[0]
    mine = lax.dynamic_slice(out.reshape(size, per_chunk), (grank, 0),
                             (1, per_chunk))
    return mine.reshape(-1)[:block_n].reshape(block_shape)
