"""Seeded bug: the revoke travels through an ALIAS — ``c2`` and
``comm`` are the same communicator, which only name-alias resolution
sees."""


def recover(comm, x):
    c2 = comm
    c2.revoke()
    comm.allreduce(x)
