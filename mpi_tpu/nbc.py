"""Engine-owned nonblocking collectives — schedule state machines, not
threads (ISSUE 12 tentpole; the MPICH/libNBC shape) — plus MPI-4
persistent collectives built on the same compiled-schedule object.

Why: every i-collective used to spawn one ``_ThreadRequest`` thread per
call (``communicator.py``): at production request rates thread spawn is
the latency floor, and 1000 concurrent iallreduces meant 1000 OS
threads.  With the async progress engine attached (``progress=thread``,
mpi_tpu/progress.py) the schedules already exist as pure data
(mpi_tpu/schedules.py), so a nonblocking collective compiles into a
per-rank *step plan* — ``[(sends, recvs), ...]`` span/value tables —
and runs as a state machine advanced by the engine's completion
callbacks:

* every internal receive of the plan is posted up front on an isolated
  per-call context (the ``_nbc_comm`` scheme), with ``_on_complete``
  kicking this machine;
* receive ACTIONS (fold via ``op.combine_into`` / copy / store) are
  applied strictly in plan order on a small bounded **fold pool**
  (cvar ``nbc_fold_workers``, default 2, one pool per world) so
  reductions never run on the engine thread;
* sends are credit-limited ``send_ahead`` steps past the last completed
  step — exactly the blocking algorithms' dependency/window structure
  (ring folds gate the next forward; pairwise alltoall keeps
  ``_SEG_WINDOW`` rounds in flight);
* ``wait()``/``test()`` stay caller-financed fallbacks (the engine
  merely makes them unnecessary), with the same FT detector /
  revocation / recv_timeout slicing as ``_progress_wait_request``.

Zero per-call thread creation is pvar-asserted: ``nbc_threads_spawned``
counts every ``_ThreadRequest`` spawn and stays 0 for the state-machine
path, while ``nbc_state_machines`` counts compiled-schedule requests.

Fallbacks (today's thread semantics, unchanged): ``progress=none``
worlds, the runtime verifier (per-call signature exchange is a blocking
ring — state machines skip it, so verified i-collectives keep the
thread), compressed/topk algorithms, payloads a span plan cannot fold
(object dtypes), and the ``nbc_mode=thread`` cvar kill switch.  Mixed
eligibility inside one group is safe by construction for the payload-
dependent cases (alltoall/reduce): the plan's wire traffic is the
blocking algorithm's frame sequence on the same per-call context.

MPI-4 persistent collectives (``allreduce_init`` / ``bcast_init`` /
``alltoall_init`` / ``reduce_scatter_init`` [S: MPI-4 ch.6.11]) hoist
everything a hot training loop pays per call — child-context creation,
tuned-table algorithm resolution, schedule compilation, working-buffer
allocation, and the verifier's collective signature (exchanged ONCE at
init; per-round checks are frozen) — into init; each ``start()`` only
refills the bound buffer, re-posts the plan's receives, and fires
(``persistent_starts`` pvar).  Without the engine, ``start()`` falls
back to one thread per round on the same hoisted context.
"""

from __future__ import annotations

import gc
import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import bufpool as _bufpool
from . import coll_sm as _coll_sm
from . import mpit as _mpit
from . import ops as _ops
from . import schedules
from . import telemetry as _telemetry
from . import tuning as _tuning
from .communicator import (P2PCommunicator, Request, _CompletedRequest,
                           _FT_POLL_S, _SEG_WINDOW, _TAG_COLL, _as_array,
                           _maybe_stack, _unpost, _unwrap,
                           seed_allreduce_algorithm)
from .errors import BufferPinnedError, ProcFailedError
from .transport.base import ANY_SOURCE, RecvTimeout, payload_nbytes

__all__ = ["try_state_machine", "persistent_init", "PersistentColl"]

# Dispatch mode: "auto" = state machines whenever the world runs the
# progress engine (the i-collective entry points consult this through
# communicator._nbc_sm); "thread" = always today's one-thread-per-call
# _ThreadRequest semantics (the honest pre/post bench toggle and the
# escape hatch).  mpit cvar ``nbc_mode``; MPI_TPU_NBC seeds the default.
MODES = ("auto", "thread")
_MODE = os.environ.get("MPI_TPU_NBC", "auto")

# Payload ceiling of the state-machine path (mpit cvar
# ``nbc_sm_max_bytes``, 0 = no cap): calls larger than this keep the
# threaded blocking algorithms — their SEGMENTED pipelines (sub-span
# frames + windowed credit, _seg_exchange) own the bandwidth regime,
# and a blocking caller's recv-wait drains its own ring INLINE
# (the user-waiter priority) where a state machine's waiter rides the
# engine thread's doorbell hops.  Two spellings:
#
# * span modes gate on the working buffer — reduction geometry is
#   congruent across ranks (the reduction contract), so the gate is
#   group-coherent by construction;
# * ialltoall gates on its largest BLOCK (the largest single frame a
#   value plan would ship whole).  This decision is rank-local and
#   deliberately so: both paths emit the identical pairwise
#   whole-frame sequence on the same per-call context, so a gated rank
#   interoperates frame-for-frame with an ungated peer.
#
# The remaining value plans (bcast/allgather/gather/scatter/barrier)
# are NOT size-gated: bcast receivers don't know the payload size
# before the frame lands, and allgather's thread fallback picks
# DOUBLING on pow2 groups (a different wire pattern than the ring
# plan) — a payload-conditioned gate there could split one group
# across incompatible algorithms.

_SM_MAX_BYTES = int(os.environ.get("MPI_TPU_NBC_SM_MAX_BYTES",
                                   str(1 << 20)))

# Fold-pool width per world (mpit cvar ``nbc_fold_workers``; read at the
# pool's first use).  2 keeps one worker free while another blocks in a
# ring-full forward; the pool is deliberately tiny — it exists so folds
# never run on the engine thread, not to parallelize numpy.
_FOLD_WORKERS = int(os.environ.get("MPI_TPU_NBC_FOLD_WORKERS", "2"))

# The initial send window is emitted inline on the issuing caller when
# it is at most this many bytes (latency path: skip one pool hop);
# larger first windows go to the fold pool so issue() never blocks the
# caller in a ring-full send of a bandwidth-size payload.
_INLINE_FIRE_MAX = 64 << 10

# Compiled plan memo: (kind, algorithm, p, rank, geometry) -> steps.
# Plans are pure data; 1000 concurrent same-shape iallreduces compile
# once.  Bounded FIFO — plans are cheap to rebuild.
_PLAN_MEMO: Dict[Tuple, Tuple] = {}
_PLAN_MEMO_MAX = 256
_PLAN_LOCK = threading.Lock()


def mode() -> str:
    return _MODE


def _plan(key: Tuple, build: Callable[[], Tuple]) -> Tuple:
    with _PLAN_LOCK:
        hit = _PLAN_MEMO.get(key)
    if hit is not None:
        return hit
    steps = build()
    with _PLAN_LOCK:
        if len(_PLAN_MEMO) >= _PLAN_MEMO_MAX:
            _PLAN_MEMO.pop(next(iter(_PLAN_MEMO)))
        _PLAN_MEMO[key] = steps
    return steps


# -- the bounded fold pool ----------------------------------------------------


class FoldPool:
    """A tiny per-world worker pool that advances state machines: recv
    completions enqueue the machine (deduplicated), a worker drains its
    ready actions and posts the sends they unlock.  Workers are created
    ONCE per world — the fixed-cost counterpart of the per-call threads
    this module removes (``nbc_threads_spawned`` stays 0)."""

    def __init__(self, nworkers: int) -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads = []
        for i in range(max(1, int(nworkers))):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"mpi-tpu-nbc-fold-{i}")
            t.start()
            self._threads.append(t)

    def submit(self, sm: "_SMColl") -> None:
        self._q.put(sm)

    def _run(self) -> None:
        while True:
            sm = self._q.get()
            if sm is None:
                return
            # _pump records its own errors on the machine; a raise here
            # would only kill the worker
            sm._pump()

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(None)


def pool_for(transport) -> FoldPool:
    pool = getattr(transport, "_nbc_fold_pool", None)
    if pool is None:
        with _PLAN_LOCK:  # two first-machines racing must share one pool
            pool = getattr(transport, "_nbc_fold_pool", None)
            if pool is None:
                pool = transport._nbc_fold_pool = FoldPool(_FOLD_WORKERS)
    return pool


# -- the state machine --------------------------------------------------------


class _SMColl(Request):
    """One nonblocking collective as a schedule state machine.

    State is guarded by ``self._lock``; advancement (``_pump``) is
    idempotent and may run on a fold-pool worker, the engine's
    completion callback path (via the pool), or the waiting caller —
    whoever gets there first.  Receive actions apply strictly in plan
    order (deterministic fold order, the blocking algorithms' exact
    sequence); sends are emitted in step order once their credit is
    due.  Errors (transport, FT, fold) are recorded and re-raised at
    wait()/test(), with the machine's remaining posted receives
    un-posted so no stale queue heads survive (the ``_unpost`` rule)."""

    __slots__ = ("kind", "_parent", "_comm", "_mode", "_steps",
                 "_send_ahead", "_work", "_svals", "_rvals", "_op",
                 "_finish", "_actions", "_srem", "_ai", "_rdt", "_nss",
                 "_done", "_error", "_result", "_lock", "_qlock",
                 "_queued", "_pool", "_t0")

    # every frame of a state machine travels on the internal collective
    # tag — what the engine's stalled-poll publication reports
    _tag = _TAG_COLL

    def __init__(self, parent: P2PCommunicator, child: P2PCommunicator,
                 kind: str, plan_mode: str, steps: Tuple,
                 send_ahead: int, work: Optional[np.ndarray],
                 svals: Optional[list], rvals: Optional[list],
                 op: Optional[_ops.ReduceOp],
                 finish: Callable[["_SMColl"], Any]) -> None:
        self.kind = kind
        self._parent = parent
        self._comm = child
        self._mode = plan_mode
        self._steps = steps
        self._send_ahead = max(1, send_ahead)
        self._work = work
        self._svals = svals
        self._rvals = rvals
        self._op = op
        self._finish = finish
        self._srem = [len(st[1]) for st in steps]
        # (req, step_i, spec, steer_dest) — steer_dest is the work-span
        # view registered with the recv registry (None when not a
        # steerable store span); _apply needs the SAME object for the
        # delivered-by-identity check
        self._actions: List[Tuple[Any, int, Tuple, Any]] = []
        self._ai = 0
        self._rdt = 0   # recv-done-through: first step with recvs pending
        self._nss = 0   # next step whose sends are not yet emitted
        self._done = False
        self._error: Optional[BaseException] = None
        self._result: Any = None
        self._lock = threading.Lock()
        self._qlock = threading.Lock()
        self._queued = False
        self._pool = pool_for(child._t)
        # flight-recorder span anchor (0 = tracing off at issue time)
        rec = _telemetry.REC
        self._t0 = time.perf_counter_ns() if rec is not None else 0
        child._coll_name = kind  # ProcFailedError diagnoses name the coll

    # -- issue-time arming -------------------------------------------------

    def _arm(self) -> "_SMColl":
        """Post every receive of the plan (in step order — per-source
        FIFO then matches the peer's step-ordered sends) with this
        machine's kick as the completion callback, atomically with the
        engine (the post-then-attach gap rule from _seg_exchange), then
        fire the initial send window."""
        eng = self._parent._progress
        child = self._comm
        reg = child._recv_reg
        with eng.cv:
            for step_i, (sends, recvs) in enumerate(self._steps):
                for spec in recvs:
                    req = child._irecv_internal(spec[0], _TAG_COLL)
                    # Rendezvous steering (mpi_tpu/recvpool.py): span
                    # STORES may land directly in the working buffer.
                    # Fold spans never register — an early arrival
                    # would clobber the accumulator (the _seg_exchange
                    # rule).  _apply recognises a steered segment by
                    # identity and skips the store + CoW touch.
                    dest = None
                    if (reg is not None and self._mode == "span"
                            and not spec[3]):
                        dest = self._work[spec[1]:spec[2]]
                        reg.attach(req._steer_token, dest)
                    req._on_complete = self._kick
                    self._actions.append((req, step_i, spec, dest))
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("sm", "arm",
                     attrs={"kind": self.kind, "steps": len(self._steps),
                            "recvs": len(self._actions)})
        if self._first_window_bytes() <= _INLINE_FIRE_MAX:
            self._pump()
        else:
            self._pool.submit(self)
        return self

    def _first_window_bytes(self) -> int:
        total = 0
        for st in self._steps[:self._send_ahead]:
            for spec in st[0]:
                if self._mode == "span":
                    total += (spec[2] - spec[1]) * self._work.itemsize
                else:
                    v = None if spec[1] < 0 else self._svals[spec[1]]
                    total += payload_nbytes(v) or 0
        return total

    # -- advancement -------------------------------------------------------

    def _kick(self) -> None:
        with self._qlock:
            if self._queued:
                return
            self._queued = True
        self._pool.submit(self)

    def _pump(self) -> None:
        with self._qlock:
            self._queued = False
        with self._lock:
            if self._done or self._error is not None:
                return
            try:
                self._advance_locked()
            except BaseException as e:  # noqa: BLE001 - surfaced at wait
                self._error = e
                _unpost([r for r, _, _, _ in self._actions[self._ai:]
                         if r is not None and not r._done])
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("sm", "fail",
                             attrs={"kind": self.kind,
                                    "error": type(e).__name__})
                self._notify()

    def _advance_locked(self) -> None:
        n = len(self._steps)
        rdt0, nss0 = self._rdt, self._nss
        progressed = True
        while progressed:
            progressed = False
            while self._ai < len(self._actions):
                req, step_i, spec, dest = self._actions[self._ai]
                if not req._done:
                    break
                self._apply(spec, req._value, dest)
                self._srem[step_i] -= 1
                self._ai += 1
                progressed = True
            while self._rdt < n and self._srem[self._rdt] == 0:
                self._rdt += 1
                progressed = True
            while self._nss < n and self._nss < self._rdt + self._send_ahead:
                for spec in self._steps[self._nss][0]:
                    self._emit(spec)
                self._nss += 1
                progressed = True
        rec = _telemetry.REC
        if rec is not None and (self._rdt, self._nss) != (rdt0, nss0):
            # one SM-step transition event per pump that moved the
            # machine (recv-done-through / next-send-step watermarks —
            # the libNBC progress picture, per call, per rank)
            rec.emit("sm", "step",
                     attrs={"kind": self.kind, "rdt": self._rdt,
                            "nss": self._nss, "of": n})
        if self._rdt == n and self._nss == n and not self._done:
            self._result = self._finish(self)
            self._done = True
            if rec is not None:
                rec.emit("sm", "done",
                         dur_ns=(time.perf_counter_ns() - self._t0
                                 if self._t0 else 0),
                         attrs={"kind": self.kind, "steps": n})
            self._notify()

    def _apply(self, spec: Tuple, got: Any, dest=None) -> None:
        if self._mode == "span":
            _, lo, hi, fold = spec
            view = self._work[lo:hi] if dest is None else dest
            if fold:
                self._op.combine_into(view, got)
            elif got is not view:
                # ownership CoW (bufpool.py): the span may have just been
                # SENT — retained frames must snapshot before overwrite
                _bufpool.touch(view)
                view[...] = got
                self._comm._count_recv_store(dest)
            # else: steered straight into the span by the transport
            # reader (which did the CoW touch) — nothing left to do
        else:
            _, slot = spec
            if slot >= 0:
                self._rvals[slot] = got

    def _emit(self, spec: Tuple) -> None:
        child = self._comm
        if self._mode == "span":
            dst, lo, hi = spec
            child._send_internal(child._coll_payload(self._work[lo:hi]),
                                 dst, _TAG_COLL)
        else:
            dst, slot = spec
            payload = None if slot < 0 else self._svals[slot]
            child._send_internal(payload, dst, _TAG_COLL)

    def _notify(self) -> None:
        eng = self._parent._progress
        with eng.cv:
            eng.cv.notify_all()

    def _fail(self, err: BaseException) -> None:
        """Record a CALLER-detected failure (FT verdict, recv timeout)
        on the machine, exactly like _pump records advancement errors:
        remaining posted receives are un-posted so no stale queue heads
        survive on a reused persistent child context, and later
        wait()/test() calls re-raise ``err`` instead of reporting the
        round still in flight."""
        with self._lock:
            if self._done or self._error is not None:
                return
            self._error = err
            _unpost([r for r, _, _, _ in self._actions[self._ai:]
                     if not r._done])
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("sm", "fail",
                     attrs={"kind": self.kind,
                            "error": type(err).__name__})
        self._notify()

    def _pending_world_srcs(self) -> Tuple[int, ...]:
        """World ranks whose frames this machine is still waiting on —
        the exact per-call OR-set (verifier residual (d))."""
        child = self._comm
        out = set()
        for req, _, _, _ in self._actions[self._ai:]:
            if not req._done:
                out.add(child._world(req._source))
        return tuple(sorted(out))

    # -- completion --------------------------------------------------------

    def _drive(self) -> None:
        """Caller-financed completion attempt: drain our posted queues
        through the engine's completion lock (never a blocking consume
        — the engine may already have matched a sibling), then advance
        inline.  Liveness never depends on the engine thread or the
        fold pool."""
        eng = self._parent._progress
        cbs: List = []
        with eng.cv:
            for req, _, _, _ in self._actions[self._ai:]:
                if not req._done:
                    cbs.extend(eng.try_complete(req))
        for cb in cbs:
            cb()
        self._pump()

    def wait(self) -> Any:
        eng = self._parent._progress
        child = self._comm
        ft = child._ft
        timeout = child.recv_timeout
        start = time.monotonic()
        deadline = None if timeout is None else start + timeout
        while True:
            if not self._done and self._error is None:
                self._drive()
            if self._error is not None:
                self._vnote(True)
                raise self._error
            if self._done:
                self._vnote(True)
                return self._result
            if ft is not None:
                ft.check(child)
                suspects = child._ft_suspects(ANY_SOURCE, _TAG_COLL)
                if suspects:
                    err: BaseException = ProcFailedError(
                        f"rank {child.rank}: peer death detected while "
                        f"waiting on nonblocking collective {self.kind!r}",
                        failed=suspects, collective=self.kind)
                    self._fail(err)
                    raise err
            if deadline is not None and time.monotonic() >= deadline:
                err = RecvTimeout(
                    f"{self.kind} state machine timed out after {timeout}s "
                    f"waiting on sources {self._pending_world_srcs()}; "
                    f"pending={child._t.mailbox.pending_summary()}")
                self._fail(err)
                raise err
            with eng.cv:
                if not self._done and self._error is None:
                    eng.cv.wait(_FT_POLL_S)

    def test(self) -> Tuple[bool, Any]:
        if not self._done and self._error is None:
            self._drive()
        if self._error is not None:
            self._vnote(True, blocking=False)
            raise self._error
        if self._done:
            self._vnote(True, blocking=False)
            return True, self._result
        # empty path: FT gate + per-call OR-set poll note (the engine
        # publishes exactly the sources THIS machine still waits on)
        self._comm._empty_poll_check(ANY_SOURCE, _TAG_COLL, req=self)
        return False, None


# -- plan construction --------------------------------------------------------


def _resolve_allreduce_algorithm(comm: P2PCommunicator, arr: np.ndarray,
                                 algorithm: str) -> Optional[str]:
    """The algorithm an nbc clone's blocking allreduce would execute
    (its arena always declines): tuned rows first, seed constants
    otherwise.  None = not a plan-able wire algorithm (compressed, or
    an unknown name — the thread path owns raising for those)."""
    if algorithm in ("auto", "sm", "fused"):
        pick = None
        if algorithm in ("auto", "fused") and comm.size > 1:
            pick = _tuning.pick(
                comm, "allreduce", arr.nbytes,
                ("ring", "rabenseifner", "reduce_bcast")
                + (("recursive_halving",)
                   if schedules.is_pow2(comm.size) else ())
                + _coll_sm.gate(comm))
        if pick is not None and pick != "sm":
            return pick
        return seed_allreduce_algorithm(arr.nbytes, comm.size)
    if algorithm in ("ring", "rabenseifner", "reduce_bcast"):
        return algorithm
    if algorithm == "recursive_halving" and schedules.is_pow2(comm.size):
        return algorithm
    return None


def _allreduce_steps(algorithm: str, p: int, r: int, n: int) -> Tuple:
    key = ("allreduce", algorithm, p, r, n)
    if algorithm == "reduce_bcast":
        return _plan(key, lambda: tuple(
            schedules.reduce_bcast_allreduce_steps(p, r, n)))
    offs = schedules.chunk_offsets(n, p)
    build = {"ring": schedules.ring_allreduce_steps,
             "recursive_halving": schedules.halving_allreduce_steps,
             "rabenseifner": schedules.rabenseifner_allreduce_steps}[algorithm]
    return _plan(key, lambda: tuple(build(p, r, offs)))


def _build(parent: P2PCommunicator, kind: str, args: tuple,
           kwargs: dict) -> Optional[dict]:
    """Phase 1 — pure: validate + resolve + compile.  Returns the build
    dict (plan + buffers + finisher) or None when this call cannot ride
    a state machine (the caller falls back to the thread path, which
    re-raises any user error at wait() exactly as before)."""
    p, r = parent.size, parent.rank
    if kind == "iallreduce":
        obj, = args
        op = kwargs.get("op", _ops.SUM)
        algorithm = kwargs.get("algorithm", "auto")
        if kwargs.get("compress_key") is not None:
            return None  # top-k residual state: the blocking path owns it
        arr, scalar = _as_array(obj)
        if arr.dtype.hasobject or arr.dtype.kind == "V":
            return None
        if _SM_MAX_BYTES and arr.nbytes > _SM_MAX_BYTES:
            return None  # bandwidth regime: segmented threaded path
        algorithm = _resolve_allreduce_algorithm(parent, arr, algorithm)
        if algorithm is None:
            return None
        if p == 1:
            return {"done": _unwrap(arr.copy(), scalar)}
        work = arr.flatten()
        shape = arr.shape
        return {
            "mode": "span", "send_ahead": 1, "op": op, "work": work,
            "steps": _allreduce_steps(algorithm, p, r, work.size),
            "finish": lambda sm: _unwrap(sm._work.reshape(shape), scalar),
        }

    if kind == "ireduce":
        obj, = args
        op = kwargs.get("op", _ops.SUM)
        root = int(kwargs.get("root", 0))
        if not (0 <= root < p):
            return None  # thread path raises the standard error at wait
        arr, scalar = _as_array(obj)
        if arr.dtype.hasobject or arr.dtype.kind == "V":
            return None
        if _SM_MAX_BYTES and arr.nbytes > _SM_MAX_BYTES:
            return None
        if p == 1:
            return {"done": _unwrap(arr.copy(), scalar)}
        work = arr.flatten()
        shape = arr.shape
        is_root = r == root
        return {
            "mode": "span", "send_ahead": 1, "op": op, "work": work,
            "steps": _plan(("reduce", p, r, root, work.size), lambda: tuple(
                schedules.reduce_tree_steps(p, r, root, work.size))),
            "finish": lambda sm: (_unwrap(sm._work.reshape(shape), scalar)
                                  if is_root else None),
        }

    if kind == "ibcast":
        obj, = args
        root = int(kwargs.get("root", 0))
        if not (0 <= root < p):
            return None
        if p == 1:
            return {"done": obj}
        vals = [obj if r == root else None]
        return {
            "mode": "value", "send_ahead": 1, "svals": vals, "rvals": vals,
            "steps": _plan(("bcast", p, r, root), lambda: tuple(
                schedules.bcast_value_steps(p, r, root))),
            "finish": lambda sm: sm._rvals[0],
        }

    if kind == "iallgather":
        obj, = args
        if p == 1:
            return {"done": [obj]}
        vals: List[Any] = [None] * p
        vals[r] = obj
        return {
            "mode": "value", "send_ahead": 1, "svals": vals, "rvals": vals,
            "steps": _plan(("allgather", p, r), lambda: tuple(
                schedules.allgather_ring_value_steps(p, r))),
            "finish": lambda sm: _maybe_stack(obj, list(sm._rvals)),
        }

    if kind == "ialltoall":
        orig, = args
        try:
            if len(orig) != p:
                return None  # thread path raises the standard error
        except TypeError:
            return None
        objs = list(orig)
        if p == 1:
            return {"done": _maybe_stack(orig, [objs[0]])}
        if _SM_MAX_BYTES and max(
                (payload_nbytes(o) or 0) for o in objs) > _SM_MAX_BYTES:
            # bandwidth regime: the caller-financed windowed blocking
            # exchange owns it (rank-local gate — see _SM_MAX_BYTES)
            return None
        rvals: List[Any] = [None] * p
        rvals[r] = objs[r]
        return {
            "mode": "value", "send_ahead": _SEG_WINDOW,
            "svals": objs, "rvals": rvals,
            "steps": _plan(("alltoall", p, r), lambda: tuple(
                schedules.alltoall_value_steps(p, r))),
            # stack against the ORIGINAL payload (an [P, ...] array
            # input stacks, a list never does — blocking parity)
            "finish": lambda sm, _orig=orig: _maybe_stack(
                _orig, list(sm._rvals)),
        }

    if kind == "ireduce_scatter":
        blocks, = args
        op = kwargs.get("op", _ops.SUM)
        algorithm = kwargs.get("algorithm", "auto")
        if algorithm not in ("auto", "ring", "fused", "sm"):
            return None  # compressed / unknown: the blocking path owns it
        try:
            if len(blocks) != p:
                return None
        except TypeError:
            return None
        arr = parent._blocks_as_array(blocks)
        if arr is None:
            return None  # heterogeneous/object blocks: generic path
        if _SM_MAX_BYTES and arr.nbytes > _SM_MAX_BYTES:
            return None
        was_scalar = arr.ndim == 1
        if p == 1:
            return {"done": _unwrap(np.asarray(blocks[0]).copy(),
                                    was_scalar)}
        shape = arr.shape[1:]
        work = (arr.reshape(-1).copy()
                if isinstance(blocks, np.ndarray) else arr.reshape(-1))
        bn = work.size // p
        return {
            "mode": "span", "send_ahead": 1, "op": op, "work": work,
            "steps": _plan(("reduce_scatter", p, r, work.size),
                           lambda: tuple(
                schedules.block_ring_reduce_scatter_steps(p, r, bn))),
            # one-shot: COPY the owned block out so the p-times-larger
            # work buffer isn't pinned by a small result
            "finish": lambda sm: _unwrap(
                sm._work[r * bn:(r + 1) * bn].reshape(shape).copy(),
                was_scalar),
            # persistent double-buffer re-fire (ISSUE 19 satellite): the
            # handle owns the preallocated work buffers, so a round's
            # result can stay a VIEW of one — _note_result pins it and
            # the BufferPinnedError fence covers the k+2 overwrite
            "span_view": lambda sm: _unwrap(
                sm._work[r * bn:(r + 1) * bn].reshape(shape), was_scalar),
        }

    if kind == "ibarrier":
        if p == 1:
            return {"done": None}
        return {
            "mode": "value", "send_ahead": 1, "svals": [], "rvals": [],
            "steps": _plan(("barrier", p, r), lambda: tuple(
                schedules.barrier_value_steps(p, r))),
            "finish": lambda sm: None,
        }

    if kind == "igather":
        obj, = args
        root = int(kwargs.get("root", 0))
        if not (0 <= root < p):
            return None
        if p == 1:
            return {"done": [obj]}
        if r == root:
            rvals: List[Any] = [None] * p
            rvals[r] = obj
            steps = ((tuple(), tuple((s, s) for s in range(p)
                                     if s != root)),)
            return {"mode": "value", "send_ahead": 1, "svals": rvals,
                    "rvals": rvals, "steps": steps,
                    "finish": lambda sm: list(sm._rvals)}
        vals = [obj]
        return {"mode": "value", "send_ahead": 1, "svals": vals,
                "rvals": vals, "steps": ((((root, 0),), tuple()),),
                "finish": lambda sm: None}

    if kind == "iscatter":
        objs, = args
        root = int(kwargs.get("root", 0))
        if not (0 <= root < p):
            return None
        if r == root:
            try:
                if objs is None or len(objs) != p:
                    return None  # thread path raises the standard error
            except TypeError:
                return None
            objs = list(objs)
            if p == 1:
                return {"done": objs[0]}
            steps = ((tuple((d, d) for d in range(p) if d != root),
                      tuple()),)
            return {"mode": "value", "send_ahead": 1, "svals": objs,
                    "rvals": objs, "steps": steps,
                    "finish": lambda sm, _root=root: sm._svals[_root]}
        vals = [None]
        return {"mode": "value", "send_ahead": 1, "svals": vals,
                "rvals": vals, "steps": ((tuple(), ((root, 0),)),),
                "finish": lambda sm: sm._rvals[0]}

    return None


def _launch(parent: P2PCommunicator, kind: str, build: dict,
            child: Optional[P2PCommunicator] = None) -> Request:
    _mpit.count(collectives=1)  # thread rounds count in the blocking call
    if "done" in build:
        return _CompletedRequest(build["done"])
    if child is None:
        child = parent._nbc_comm()
    _mpit.count(nbc_state_machines=1)
    sm = _SMColl(parent, child, kind, build["mode"], build["steps"],
                 build["send_ahead"], build.get("work"),
                 build.get("svals"), build.get("rvals"),
                 build.get("op"), build["finish"])
    return sm._arm()


def try_state_machine(parent: P2PCommunicator, kind: str, *args: Any,
                      **kwargs: Any) -> Optional[Request]:
    """The i-collective entry points' state-machine attempt: a Request
    when this call compiled onto the engine, None to take the thread
    path.  Caller already checked engine-on / verifier-off / mode."""
    build = _build(parent, kind, args, kwargs)
    if build is None:
        return None
    return _launch(parent, kind, build)


# -- MPI-4 persistent collectives --------------------------------------------


#: kinds persistent_init compiles (everything else stays on the generic
#: thread-backed mpi4.PersistentCollective)
PERSISTENT_KINDS = ("allreduce", "bcast", "alltoall", "reduce_scatter")


class PersistentColl(Request):
    """A planned collective handle (MPI_Allreduce_init & co.).

    Init hoists: one private child context for every round, tuned-table
    algorithm resolution, compiled schedule, working-buffer allocation,
    and — with the runtime verifier on — the collective-signature
    exchange (checked ONCE here; the per-round check is frozen on the
    child, per MPI-4: a persistent collective's arguments cannot change
    between starts).  ``start()`` re-reads the bound buffer (the MPI
    buffer-reuse idiom), re-posts the plan's receives on the same
    context, and fires; rounds on one context can never cross-match
    because start() requires the previous round complete and every rank
    starts its persistent collectives in the same order [S].

    Engine-compiled allreduce and reduce_scatter rounds re-fire on two
    PREALLOCATED working buffers alternated per start (no per-round
    allocation);
    round k's result is a view of one of them and stays valid until
    round k+2 starts — hold a result across two later starts and you
    must copy it (``np.array(r)``), the usual double-buffer contract.
    With the runtime verifier on the contract is FENCED (ISSUE 18
    satellite, the PR-12/17 residual): a ``start()`` that would
    overwrite a round result the caller still references raises the
    named :class:`~mpi_tpu.errors.BufferPinnedError` instead of
    silently invalidating it.
    """

    def __init__(self, parent: P2PCommunicator, kind: str, args: tuple,
                 kwargs: dict) -> None:
        self._parent = parent
        self._kind = kind
        self._args, self._kwargs = args, kwargs
        self._child = parent._nbc_comm()
        self._child._coll_name = kind
        self._req: Optional[Request] = None
        self._last: Any = None
        self._started = False
        # double-buffered re-fire (PR-12 residual (e)): two preallocated
        # working buffers alternated across starts — see _round_build
        self._dbl: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._round = 0
        # verify-mode fence state: weakref per working buffer to the
        # round result handed out from it (see _fence_check)
        self._pinned: List[Optional["weakref.ref"]] = [None, None]
        # resolve + compile once, from the bound buffer's geometry; a
        # None build means every round runs the blocking method on a
        # thread (same hoisted context)
        self._build0 = _build(parent, "i" + kind, args, kwargs)
        self._geometry = self._payload_geometry()
        if (kind == "allreduce" and self._build0 is not None
                and "done" not in self._build0):
            # hoist the tuned-table consult: the geometry is bound, so
            # the resolution is too — per-start rebuilds see the
            # explicit algorithm name and skip the table
            arr, _ = _as_array(args[0])
            resolved = _resolve_allreduce_algorithm(
                parent, arr, kwargs.get("algorithm", "auto"))
            if resolved is not None:
                self._kwargs = {**kwargs, "algorithm": resolved}
        if parent._verify is not None and parent.size > 1:
            op = kwargs.get("op")
            payload = None
            if kind in ("allreduce", "reduce_scatter"):
                # block 0 for reduce_scatter (the blocking path's exact
                # signature geometry — never a stacking asarray, which
                # RAISES on the ragged blocks the generic thread rounds
                # support)
                payload = np.asarray(args[0] if kind == "allreduce"
                                     else args[0][0])
            self._child._verify_coll(
                kind, root=kwargs.get("root"), op=op, payload=payload,
                algorithm=kwargs.get("algorithm", "auto"))
            # per MPI-4 the argument list is bound: freeze the per-round
            # signature exchange on the child — the hoist this handle
            # exists for
            self._child._verify_sig_frozen = True

    def _payload_geometry(self) -> Optional[Tuple]:
        if self._kind in ("allreduce", "reduce_scatter"):
            try:
                arr = np.asarray(self._args[0])
            except ValueError:
                return None  # ragged blocks: the generic rounds own them
            if arr.dtype.hasobject:
                return None  # object payloads have no bindable geometry
            return (arr.shape, arr.dtype)
        return None

    @property
    def active(self) -> bool:
        return self._req is not None

    def start(self) -> "PersistentColl":
        if self._req is not None and not self._req.test()[0]:
            raise RuntimeError(
                "start() while the previous round of this persistent "
                "collective is still in flight (wait() it first)")
        _mpit.count(persistent_starts=1)
        self._started = True
        if self._geometry is not None:
            arr = np.asarray(self._args[0])
            if (arr.shape, arr.dtype) != self._geometry:
                raise ValueError(
                    f"persistent {self._kind}: bound buffer geometry "
                    f"changed since init ({self._geometry} -> "
                    f"{(arr.shape, arr.dtype)}); MPI persistent "
                    f"collectives bind the argument list")
        build = self._round_build()
        if build is not None:
            self._req = _launch(self._parent, "i" + self._kind, build,
                                child=self._child)
        else:
            from .communicator import _ThreadRequest

            fn = getattr(self._child, self._kind)
            a, kw = self._args, self._kwargs
            self._req = _ThreadRequest(lambda: fn(*a, **kw))
        return self

    def _round_build(self) -> Optional[dict]:
        """Per-start plan refresh: reuse the compiled steps, re-read the
        bound buffer content (start-time snapshot [S]).  None = thread
        fallback (no engine, verifier per-round coverage wanted off the
        frozen path, or an uncompilable payload)."""
        if (self._build0 is None or self._parent._progress is None
                or _MODE != "auto"):
            return None
        if (self._kind in ("allreduce", "reduce_scatter")
                and "done" not in self._build0):
            # Fully preallocated re-fire (PR-12 residual (e); extended
            # to reduce_scatter by ISSUE 19): the compiled steps, op,
            # and finisher are round-invariant — only the working
            # buffer's CONTENT changes per start.  Instead of
            # re-running _build (a fresh flatten() alloc every round),
            # alternate two preallocated buffers: round k's result (a
            # view of buffer k % 2) stays valid until round k+2 starts,
            # the one-round grace double buffering exists to give.  The
            # CoW touch protects retained replay frames still
            # referencing the previous occupant (the sent spans of
            # round k-2) before the overwrite.
            if self._dbl is None:
                w = self._build0["work"]
                self._dbl = (np.empty_like(w), np.empty_like(w))
            i = self._round & 1
            if self._parent._verify is not None:
                self._fence_check(i)
            buf = self._dbl[i]
            self._round += 1
            _bufpool.touch(buf)
            np.copyto(buf, np.asarray(self._args[0]).reshape(-1))
            build = {**self._build0, "work": buf}
            view = self._build0.get("span_view")
            if view is not None:
                # reduce_scatter's one-shot finisher copies its block
                # out; on the double-buffered path the handle owns the
                # buffers, so hand out the view and let the fence guard
                # the overwrite instead
                build["finish"] = view
            return build
        # span work buffers are per-round flatten() copies and the
        # value finishers return fresh lists, so round results never
        # alias the bound buffer or a later round's state — safe to
        # hand out without a defensive copy.  Size-1 "done" builds must
        # also re-run: _build0's snapshot was taken at INIT, and start()
        # promises a start-time read of the bound buffer.
        return _build(self._parent, "i" + self._kind, self._args,
                      self._kwargs)

    def _note_result(self, value: Any) -> None:
        """Verify-mode bookkeeping: remember (weakly) which working
        buffer this round's result aliases, so _fence_check can tell
        whether the caller is still holding it when the buffer comes
        back around."""
        if (self._parent._verify is None or self._dbl is None
                or not isinstance(value, np.ndarray)):
            return
        try:
            for i in (0, 1):
                # a value that IS the buffer (not a view of it) can't be
                # distinguished from our own strong ref — skip it
                if value is not self._dbl[i] and np.shares_memory(
                        value, self._dbl[i]):
                    self._pinned[i] = weakref.ref(value)
                    return
        except TypeError:
            pass

    def _fence_check(self, i: int) -> None:
        """The double-buffer contract, fenced (PR-12/17 residual): a
        round result stays valid for exactly one further start().  If
        the caller still references the result that round i's buffer
        backs when start() wants to overwrite it, raise the named error
        instead of silently invalidating their array.  self._last is
        exempt: the handle's own reference is not a caller pin."""
        ref = self._pinned[i]
        if ref is None:
            return
        obj = ref()
        if obj is not None and obj is not self._last:
            # a dropped reference may merely await collection — give the
            # collector one shot before declaring a contract violation
            obj = None
            gc.collect()
            obj = ref()
        if obj is None or obj is self._last:
            self._pinned[i] = None
            return
        raise BufferPinnedError(
            f"persistent {self._kind}: start() would overwrite the "
            f"round-{self._round - 2 if self._round >= 2 else 0} result "
            f"the caller still references (double-buffer grace is one "
            f"round); copy it first (np.array(result))")

    def wait(self) -> Any:
        if self._req is None:
            if not self._started:
                raise RuntimeError(
                    "wait() before start() on a persistent collective")
            return self._last
        req = self._req
        value = req.wait()
        self._last, self._req = value, None
        self._drop_result_retention(req)
        self._note_result(value)
        return value

    def test(self) -> Tuple[bool, Any]:
        if self._req is None:
            return (True, self._last) if self._started else (False, None)
        req = self._req
        done, value = req.test()
        if done:
            self._last, self._req = value, None
            self._drop_result_retention(req)
            self._note_result(value)
        return done, value

    @staticmethod
    def _drop_result_retention(req: Request) -> None:
        """A finished _SMColl can outlive the round (a fold-pool
        worker's frame keeps the last item it processed alive until the
        next one arrives), and its _result slot would then count as a
        pin in _fence_check.  This handle is the request's only
        consumer, so forget the result once it's been handed over."""
        if isinstance(req, _SMColl):
            req._result = None


# positional-argument names of each persistent kind, mirroring the
# blocking methods' signatures — persistent_init normalizes positionals
# into kwargs so _build (i-collective shape) and the thread fallback
# (blocking method shape) read one canonical form
_PERSISTENT_SIG = {
    "allreduce": ("op", "algorithm", "compress_key"),
    "bcast": ("root", "algorithm"),
    "alltoall": ("algorithm",),
    "reduce_scatter": ("op", "algorithm"),
}


def persistent_init(comm: P2PCommunicator, kind: str, payload: Any,
                    *args: Any, **kwargs: Any) -> PersistentColl:
    if kind not in PERSISTENT_KINDS:
        raise ValueError(
            f"no engine-owned persistent plan for {kind!r}; have "
            f"{list(PERSISTENT_KINDS)}")
    names = _PERSISTENT_SIG[kind]
    if len(args) > len(names):
        raise TypeError(
            f"{kind}_init takes at most {1 + len(names)} positional "
            f"arguments ({('payload',) + names}), got {1 + len(args)}")
    for name, value in zip(names, args):
        if name in kwargs:
            raise TypeError(f"{kind}_init got {name!r} twice")
        kwargs[name] = value
    return PersistentColl(comm, kind, (payload,), kwargs)
