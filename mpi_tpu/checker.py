"""Static schedule checking — the framework's race-detector analogue.

SURVEY.md §5: the reference has no sanitizer; its race surface (tag matching
across threads) disappears under SPMD, where the remaining failure mode is a
malformed communication schedule.  This module validates schedules statically:
every ppermute permutation must be a *partial permutation* (no rank sends
twice, no rank receives twice in one round), and a whole schedule must deliver
every payload exactly once.  The TPU backend runs these checks at trace time
(they are pure-Python, zero cost on device); the CPU backends use them in
tests; `verify_matching` cross-checks per-rank send/recv logs the way a
message-race detector would (used with the recording communicator in
mpi_tpu/trace.py).
"""

from __future__ import annotations

from collections import Counter, deque
from typing import Dict, Iterable, List, Sequence, Tuple

Pair = Tuple[int, int]


class ScheduleError(ValueError):
    """A communication schedule is structurally invalid."""


def validate_perm(pairs: Iterable[Pair], size: int) -> None:
    """Check that ``pairs`` is a valid partial permutation over ``size`` ranks.

    Raises ScheduleError if any rank appears twice as source or twice as
    destination, or if any endpoint is out of range.  This is exactly the
    precondition of ``lax.ppermute`` — violating it silently misdelivers on
    some backends, which is the SPMD analogue of a data race.
    """
    pairs = list(pairs)
    srcs = Counter(s for s, _ in pairs)
    dsts = Counter(d for _, d in pairs)
    for s, d in pairs:
        if not (0 <= s < size and 0 <= d < size):
            raise ScheduleError(f"pair ({s}, {d}) out of range for size {size}")
    dup_s = [r for r, c in srcs.items() if c > 1]
    dup_d = [r for r, c in dsts.items() if c > 1]
    if dup_s or dup_d:
        raise ScheduleError(
            f"not a partial permutation: duplicate sources {dup_s}, "
            f"duplicate destinations {dup_d}"
        )


def validate_rounds(rounds: Sequence[Sequence[Pair]], size: int) -> None:
    for i, pairs in enumerate(rounds):
        try:
            validate_perm(pairs, size)
        except ScheduleError as e:
            raise ScheduleError(f"round {i}: {e}") from e


def find_deadlock(waits: Dict[int, Tuple[str, Sequence[int]]],
                  ranks: Iterable[int],
                  exited: Iterable[int] = ()) -> List[int]:
    """AND-OR wait-for-graph analysis: the pure core of the runtime
    deadlock detector (mpi_tpu/verify/deadlock.py) — same model as the
    MUST-class MPI verifiers.

    ``waits[r] = (mode, targets)`` describes a *blocked* rank: with
    ``mode='AND'`` (a specific-source recv, a waitall set) r needs EVERY
    target to progress; with ``mode='OR'`` (an ANY_SOURCE recv, a
    waitany set) ANY progressing target can release it.  ``ranks`` is
    the whole world; ``exited`` ranks have terminated and can never send
    again.  Returns the sorted list of ranks proven deadlocked: the
    greatest set of blocked ranks none of whose release conditions can
    be met by a rank outside it (a cycle for AND edges, a knot for OR
    sets).  Ranks neither blocked nor exited are assumed able to
    progress — the analysis never false-positives on a slow peer, only
    on a closed blocking picture."""
    ranks = set(ranks)
    exited = set(exited) & ranks
    progressing = ranks - set(waits) - exited
    changed = True
    while changed:
        changed = False
        for r, (mode, targets) in waits.items():
            if r in progressing:
                continue
            targets = [t for t in targets if t in ranks and t != r]
            if not targets:
                # nothing known about the wait: assume it can progress
                progressing.add(r)
                changed = True
                continue
            ok = (any(t in progressing for t in targets) if mode == "OR"
                  else all(t in progressing for t in targets))
            if ok:
                progressing.add(r)
                changed = True
    return sorted(r for r in waits if r not in progressing)


def verify_matching(logs: Sequence[Sequence[tuple]],
                    strict_fifo: bool = True) -> List[str]:
    """Cross-check per-rank communication logs for unmatched traffic.

    ``logs[r]`` is rank r's ordered op log; entries are tuples
    ``('send', dst, tag)`` or ``('recv', src, tag)`` (src/tag may be the
    wildcard -1).  Returns a list of human-readable problems (empty =
    clean): sends with no matching recv, recvs with no matching send.

    ``strict_fifo=True`` (default): a specific-tag recv must match the
    HEAD of its (src, dst) channel — a recv whose tag only matches a
    deeper send is flagged.  MPI's envelope semantics permit skipping
    differently-tagged sends, and this library's Mailbox implements that;
    but a program that *relies* on it deadlocks on any strict-FIFO
    channel transport and reorders silently elsewhere, which is exactly
    the class of bug a sanitizer exists to flag (VERDICT r1 weak #6 /
    r2 weak #5: head-only matching).  Pass ``strict_fifo=False`` to check
    against pure MPI envelope semantics instead (first send with the
    SAME tag on the channel — per-(src, tag) FIFO).
    """
    problems: List[str] = []
    size = len(logs)
    # channel (src, dst) -> deque of send tags, in order
    sends: dict = {}
    for r, log in enumerate(logs):
        for op in log:
            if op[0] == "send":
                _, dst, tag = op
                sends.setdefault((r, dst), deque()).append(tag)
    for r, log in enumerate(logs):
        for op in log:
            if op[0] != "recv":
                continue
            _, src, tag = op
            candidates = (
                [(s, r) for s in range(size)] if src == -1 else [(src, r)]
            )
            matched = False
            # pass 1: a channel whose HEAD matches — legal in both modes
            # (scan ALL candidates first so a wildcard recv is not blamed
            # for skipping a queue when another sender's head matches)
            for ch in candidates:
                q = sends.get(ch)
                if q and (tag == -1 or q[0] == tag):
                    q.popleft()
                    matched = True
                    break
            if not matched:
                # pass 2: deep same-tag match (MPI envelope semantics;
                # flagged in strict mode — relies on tag reordering)
                for ch in candidates:
                    q = sends.get(ch)
                    if q and tag in q:
                        if strict_fifo:
                            problems.append(
                                f"rank {r}: recv(src={src}, tag={tag}) "
                                f"matches send #{list(q).index(tag)} on "
                                f"channel {ch[0]}->{ch[1]} but the channel "
                                f"head has tag {q[0]} — out-of-FIFO match "
                                f"(deadlocks a strict-FIFO transport; "
                                f"reorder sends/recvs or verify with "
                                f"strict_fifo=False)")
                        q.remove(tag)
                        matched = True
                        break
            if not matched:
                problems.append(f"rank {r}: recv(src={src}, tag={tag}) has no matching send")
    for (s, d), q in sends.items():
        for tag in q:
            problems.append(f"rank {s}: send(dst={d}, tag={tag}) was never received")
    return problems
