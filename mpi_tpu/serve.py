"""Resident world server: MPI-as-a-service over a warm worker pool.

ROADMAP direction #1: the "millions of users" shape for an MPI library
is many SMALL worlds churned at a high rate, not one big job — and the
cold path (fork N interpreters, import numpy, bind ports, handshake
rings) costs ~seconds per world.  This module keeps all of that warm:

* ``WorldServer`` (the ``python -m mpi_tpu.launcher serve`` daemon)
  spawns ``pool_size`` **worker processes once**, each holding its live
  transport endpoints (socket connections / shm rings + pre-mapped
  arenas) and an enabled ULFM detector, then **leases** sub-worlds to
  clients: an acquire is one control round-trip that reserves idle
  slots — no fork, no handshake — and a job builds its communicator
  locally on every leased worker from ``(slots, job_id)`` (communicator
  construction is pure bookkeeping over the warm transport).
* ``mpi_tpu.connect(addr)`` is the client: ``acquire(nranks)`` →
  ``lease.run(fn, *args)`` → ``release()``.  ``fn`` is pickled by
  reference (workers import the same code), runs as ``fn(comm, *args)``
  on every leased worker, and rank 0's return value comes back.  Every
  lease either completes or raises a NAMED error — a worker death
  mid-collective surfaces to the client as ``ProcFailedError``
  (``MPI_ERR_PROC_FAILED``) within the detection bound, never a hang.
* **Self-healing** (the elastic-membership layer, mpi_tpu/membership):
  the server watches worker liveness (child exit + the PR-3 heartbeat
  files); a death bumps the pool's membership epoch, survivors are
  told to drop the corpse's endpoints (``survivor_transition``), and a
  replacement worker is spawned to ``rejoin`` the world under the new
  epoch through the claim/admit/ready protocol — so the pool keeps
  serving under continuous ``kill -9`` chaos (``bench.py --chaos
  --serve`` drives exactly that and asserts worlds/sec never reaches
  zero).

Wire protocol: length-prefixed pickle frames on a local TCP socket; the
server is the only party that ever coordinates membership, so workers
need no agreement rounds — their ULFM detectors only CONVERT blocked
waits into errors.
"""

from __future__ import annotations

import argparse
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import membership
from . import mpit as _mpit
from . import telemetry as _telemetry
from .errors import (DeadlockError, EpochSkewError, NoQuorumError,
                     ProcFailedError, RejoinRefusedError, RevokedError,
                     ServerBusyError, error_class)
from .transport.base import RecvTimeout, TransportError
from .transport.socket import _recv_exact

_FRAME = struct.Struct("!I")
_HOST = "127.0.0.1"

# serve defaults — the knobs the README documents; constructor / CLI
# arguments override per server.
_POOL_SIZE = 4
_WORLD_LEASE_TIMEOUT_S = 30.0   # acquire wait + default run bound
_REJOIN_TIMEOUT_S = 20.0        # one healing round's handshake bound
_DETECT_TIMEOUT_S = 2.0         # pool-internal ULFM detection bound
_HEARTBEAT_S = 0.25

# Worker pvars piggybacked on every job_done reply (ISSUE 13): the
# server keeps the latest snapshot per slot and stats()/the metrics
# endpoint aggregate them — the pool's data-plane story (healed links,
# arena hits, detected deaths) without a second control round-trip.
_WORKER_PVARS = ("msgs_sent", "collectives_started", "link_reconnects",
                 "link_faults_masked", "coll_sm_hits",
                 "proc_failures_detected", "epoch_skews_detected",
                 "trace_events")

# Sliding window of the worlds/s gauge (per-second completion buckets).
_RATE_WINDOW_S = 60.0

# ISSUE 15: idle-worker pvar piggyback cadence (PR-13 residual: the
# latest-per-slot snapshot rode job_done ONLY, so a worker that never
# completed a job reported nothing) and the orphaned-worker budget — a
# worker whose server died polls the federation namespace this long for
# the survivor that adopted its pool before giving up and exiting.
_PVAR_PUSH_S = 1.0
_ORPHAN_TIMEOUT_S = 60.0

# Slack added to a timeout-bearing request's client-side reply bound
# (ServerClient._request): covers server scheduling + reply transit on
# a loaded box.  Generous on purpose — the bound only exists to turn an
# infinite wedge (frozen server, connection ESTABLISHED but silent)
# into a finite ServerLostError.
_RPC_GRACE_S = 15.0

# Bounded admission queue (ISSUE 15): acquires past this many waiting
# requests are rejected IMMEDIATELY with ServerBusyError instead of
# converting overload into unbounded acquire latency.
_MAX_PENDING = 64

# Federation leader-lease bound (mpi_tpu/federation.py): authority
# self-expires at half this, takeover fires past it.
_FED_LEASE_TIMEOUT_S = 3.0


class ServerLostError(TransportError):
    """The control connection to the world server died mid-request —
    the server process was killed or went away.  Distinct from a
    server-SHIPPED TransportError (a worker-side failure relayed by a
    live server): only THIS class means "fail over"; a federated
    client retries acquire/stats on a survivor, while an in-flight
    ``lease.run`` surfaces it named (the lease died with its server —
    re-acquire and decide about re-running the job yourself)."""


# -- framing ------------------------------------------------------------------


def _send_msg(sock: socket.socket, lock: Optional[threading.Lock],
              msg: dict) -> None:
    blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _FRAME.pack(len(blob)) + blob
    if lock is None:
        sock.sendall(frame)
    else:
        with lock:
            sock.sendall(frame)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


# -- error shipping -----------------------------------------------------------

_ERROR_KINDS = {
    "ProcFailedError": ProcFailedError,
    "RevokedError": RevokedError,
    "DeadlockError": DeadlockError,
    "EpochSkewError": EpochSkewError,
    "RejoinRefusedError": RejoinRefusedError,
    "RecvTimeout": RecvTimeout,
    "TransportError": TransportError,
    "ServerBusyError": ServerBusyError,
    "ServerLostError": ServerLostError,
    "NoQuorumError": NoQuorumError,
}


def _admission_order(waiters: List[dict], grants: Dict[str, int]
                     ) -> List[dict]:
    """Scheduling order of the waiting acquires (ISSUE 15 lease
    scheduler): strict priority first, then FAIR SHARE — fewest leases
    already granted to the waiter's client identity — then FIFO.  Pure
    so the policy is unit-testable; the grant loop walks this order and
    admits the first waiter the idle capacity can satisfy (work-
    conserving: an unsatisfiable large request does not idle the pool,
    it keeps its place and the lease timeout bounds its wait)."""
    return sorted(waiters, key=lambda w: (-w["priority"],
                                          grants.get(w["client"], 0),
                                          w["seq"]))


def _pack_error(exc: BaseException) -> dict:
    return {"kind": type(exc).__name__, "code": error_class(exc),
            "msg": str(exc),
            "failed": list(getattr(exc, "failed", ()) or ()),
            "collective": getattr(exc, "collective", None)}


def _raise_error(err: dict) -> None:
    """Re-raise a shipped worker/server error client-side under its own
    name: the lease contract is 'completes or raises a NAMED FT error',
    and `except ProcFailedError` must work across the wire."""
    kind = err.get("kind", "RuntimeError")
    msg = err.get("msg", "remote failure")
    if kind == "LeaseTimeout":
        raise TimeoutError(msg)
    cls = _ERROR_KINDS.get(kind)
    if cls is ProcFailedError:
        raise ProcFailedError(msg, failed=err.get("failed", ()),
                              collective=err.get("collective"))
    if cls is not None:
        raise cls(msg)
    raise RuntimeError(f"{kind}: {msg}")


# -- built-in jobs (bench / chaos / quickstart) -------------------------------


def job_allreduce(comm, n: int = 1024) -> float:
    """The demo/bench lease payload: a correctness-checkable allreduce.
    Returns sum(1..P) so the client can assert the world really ran."""
    import numpy as np

    out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32))
    return float(out[0])


def job_kill_rank(comm, victim: int = 1, n: int = 4096) -> float:
    """Chaos payload: lease-rank ``victim`` dies WITHOUT cleanup inside
    the leased world (after the barrier, so every rank has entered the
    job) while the rest run a collective on it — the kill-mid-lease
    acceptance story.  Survivors surface ProcFailedError; the client
    sees MPI_ERR_PROC_FAILED."""
    import numpy as np

    comm.barrier()
    if comm.rank == victim:
        os._exit(137)
    out = comm.allreduce(np.ones(int(n), np.float32), algorithm="ring")
    return float(out[0])


def job_sleep(comm, seconds: float = 0.1) -> int:
    comm.barrier()
    time.sleep(float(seconds))
    return comm.rank


def job_allreduce_arena(comm, n: int = 1024) -> tuple:
    """Arena-observability lease payload (ISSUE 11): one auto-routed
    allreduce, returning ``(value, coll_sm_hits delta, live arena
    names)`` from lease-rank 0 so the client can assert the lease rode
    the warm POOLED arena tier (``coll_sm_hits > 0`` under a shm pool;
    on socket pools the delta is honestly 0 — there is no arena)."""
    import numpy as np

    from . import coll_sm as _coll_sm
    from . import mpit as _mpit

    before = _mpit.pvar_read("coll_sm_hits")
    out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32))
    hits = _mpit.pvar_read("coll_sm_hits") - before
    return (float(out[0]), int(hits), sorted(_coll_sm.live_arenas()))


def job_allreduce_link_chaos(comm, n: int = 1024, resets: int = 2) -> float:
    """Link-chaos lease payload (ISSUE 10): each leased rank hard-resets
    its cached connection to the next rank ``resets`` times while
    running allreduces — a lease must ride HEALED links (socket pool:
    the resilient layer reconnects + replays; no ProcFailedError, no
    wrong result).  Returns the last allreduce's checkable value.  On
    transports without connection links (shm pool) the injector is a
    no-op and the job degenerates to job_allreduce."""
    import numpy as np

    inject = getattr(comm._t, "_inject_link_reset", None)
    comm.barrier()
    out = None
    for i in range(int(resets) + 1):
        if inject is not None and i < int(resets) and comm.size > 1:
            inject((comm._group[(comm.rank + 1) % comm.size]))
        out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32),
                             algorithm="ring")
    return float(out[0])


# -- the worker process -------------------------------------------------------


def _worker_main() -> int:
    """Body of one pool worker (``python -m mpi_tpu.serve --worker``):
    bring up the world transport (fresh pool member via init(), or a
    replacement rejoining under MPI_TPU_SERVE_REJOIN=epoch:slot), then
    serve jobs from the control connection.  A control reader thread
    applies membership transitions IMMEDIATELY (even mid-job — dropping
    a corpse's endpoints must not wait for the current lease), while
    the main thread runs one job at a time.

    ISSUE 15: under a federation namespace (MPI_TPU_SERVE_FED) the
    worker SURVIVES its server — on control-channel EOF it polls the
    namespace for the survivor that adopted its pool and re-registers
    there, keeping its warm transport, arenas, and FT detector; without
    a namespace, server death still ends the worker (nothing to fail
    over to)."""
    import faulthandler
    import signal as _signal

    from . import ft as _ft
    from . import init as _init
    from . import mpit as _mpit
    from .communicator import P2PCommunicator

    # field diagnosability: the server SIGUSR2s a worker whose job
    # blew the lease timeout, so the worker's stacks land on its
    # inherited stderr — a wedged lease is diagnosable from the logs
    faulthandler.register(_signal.SIGUSR2, all_threads=True, chain=True)

    detect = os.environ.get("MPI_TPU_SERVE_DETECT_S")
    if detect:
        _mpit.cvar_write("fault_detect_timeout_s", float(detect))
    hb = os.environ.get("MPI_TPU_SERVE_HEARTBEAT_S")
    if hb:
        _mpit.cvar_write("fault_heartbeat_interval_s", float(hb))
    rdv = os.environ["MPI_TPU_RDV"]
    backend = os.environ.get("MPI_TPU_BACKEND", "socket")
    rejoin_spec = os.environ.get("MPI_TPU_SERVE_REJOIN")
    if rejoin_spec:
        epoch, slot = (int(x) for x in rejoin_spec.split(":"))
        rj_timeout = float(os.environ.get(
            "MPI_TPU_SERVE_REJOIN_TIMEOUT_S", 0) or 0) or None
        # the init() path enables tracing from the environment; the
        # rejoin path builds its transport directly, so mirror it here
        # — BEFORE the rejoin handshake, which is exactly the window
        # the rejoin-hello-race class of war story lives in
        _telemetry.enable_from_env(rank=slot)
        t, _ann = membership.rejoin_transport(
            rdv, slot=slot, epoch=epoch, backend=backend,
            timeout=rj_timeout)
        home = P2PCommunicator(t, range(t.world_size), ("epoch", epoch))
        home._mark_generation()
        _ft.enable(home, rdv_dir=rdv)
        # readiness AFTER ft.enable: the heartbeat file must be fresh
        # before survivors are told to re-admit this slot
        membership.publish_ready(rdv, epoch, t.world_rank)
        _mpit.count(rejoins=1)
    else:
        home = _init()  # MPI_TPU_FT=1 in the env: detector enabled
        t = home._t
    world_ft = t._ft_world
    slot = t.world_rank

    pool_id = (os.environ.get("MPI_TPU_SERVE_POOL")
               or os.path.basename(rdv.rstrip("/")))
    fed_ns = os.environ.get("MPI_TPU_SERVE_FED") or None
    orphan_timeout = float(os.environ.get(
        "MPI_TPU_SERVE_ORPHAN_TIMEOUT_S", str(_ORPHAN_TIMEOUT_S)))
    ctrl_addr = os.environ["MPI_TPU_SERVE_CTRL"]
    dead_addr: Optional[str] = None
    orphan_deadline: Optional[float] = None
    rc = 0
    while True:
        outcome = _worker_serve_one(ctrl_addr, t, world_ft, slot, pool_id)
        if outcome == "shutdown":
            break
        if fed_ns is None:
            # no federation: nothing to fail over to — exit LOUDLY
            # (a dial failure while the server lives would otherwise
            # crash-loop heal/respawn with zero diagnostic output)
            sys.stderr.write(
                f"mpi_tpu.serve: worker slot {slot} (pool {pool_id}) "
                f"control channel {outcome} (server {ctrl_addr}); no "
                f"federation namespace to fail over to — exiting\n")
            rc = 1
            break
        # the server died under us: the pool outlives its server
        # (ISSUE 15) — resolve the survivor that adopted this pool from
        # the federation namespace and RE-REGISTER there.  Everything
        # warm stays warm.  Only an ESTABLISHED registration dying
        # ("lost") excludes its address from the re-resolve and renews
        # the orphan budget; a failed DIAL ("unreachable") must not —
        # the current owner may be live-but-briefly-swamped, and
        # excluding it would strand this warm worker until the budget
        # ran out while the owner cold-healed the slot instead.
        from . import federation as _federation

        now = time.monotonic()
        if outcome == "lost":
            dead_addr = ctrl_addr
            orphan_deadline = now + orphan_timeout
        elif orphan_deadline is None:
            orphan_deadline = now + orphan_timeout
        remaining = orphan_deadline - now
        new_ctrl = _federation.wait_pool_owner(
            fed_ns, pool_id, not_ctrl=dead_addr,
            timeout=max(0.0, remaining)) if remaining > 0 else None
        if new_ctrl is None:
            sys.stderr.write(
                f"mpi_tpu.serve: worker slot {slot} (pool {pool_id}) "
                f"orphaned: no reachable pool owner within "
                f"{orphan_timeout}s — exiting\n")
            break
        ctrl_addr = new_ctrl
    # orderly pool shutdown: retire the pooled lease arenas (ISSUE 12
    # satellite, PR-11 residual (d)) — a worker set that never re-leased
    # after its last job has nobody else to unlink its /dev/shm segment
    from . import coll_sm as _coll_sm

    _coll_sm.retire_pooled(t)
    return rc


def _worker_serve_one(ctrl_addr: str, t, world_ft, slot: int,
                      pool_id: str) -> str:
    """One control-connection lifetime of a pool worker: dial, hello,
    serve jobs until an orderly ``shutdown`` op (→ "shutdown"), an
    ESTABLISHED registration dying (→ "lost": the server went away —
    exclude its address from the re-resolve), or a failed dial/hello
    (→ "unreachable": never registered — the target may be live but
    swamped, so the re-resolve may legitimately return it again)."""
    import queue

    from . import ft as _ft
    from .communicator import P2PCommunicator
    from .resilience import retry_connect

    host, port = ctrl_addr.rsplit(":", 1)
    try:
        ctrl = retry_connect(
            lambda: socket.create_connection((host, int(port)),
                                             timeout=10.0),
            timeout_s=10.0)
    except OSError:
        return "unreachable"
    ctrl.settimeout(None)
    ctrl.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    send_lock = threading.Lock()
    try:
        _send_msg(ctrl, send_lock, {
            "op": "hello", "slot": slot, "pool": pool_id,
            "pid": os.getpid(),
            "incarnation": membership.incarnation(), "epoch": t.epoch})
    except OSError:
        ctrl.close()
        return "unreachable"

    jobs: "queue.Queue[Optional[dict]]" = queue.Queue()
    shutdown = threading.Event()  # orderly stop vs connection death
    gone = threading.Event()      # this connection is finished

    def reader() -> None:
        while True:
            try:
                msg = _recv_msg(ctrl)
            except OSError:
                msg = None
            if msg is None or msg.get("op") == "shutdown":
                if msg is not None:
                    shutdown.set()
                gone.set()
                jobs.put(None)
                return
            op = msg.get("op")
            if op == "job":
                jobs.put(msg)
            elif op == "transition":
                # observe() FIRST: a job thread wedged in a ring-full
                # send to the corpse exits via the _peer_suspected
                # check, releasing the per-dest send lock that
                # survivor_transition's invalidate needs — the reverse
                # order deadlocks this reader against that sender for
                # a full local detection bound
                # self-filter as a second line of defense: observing
                # our own rank failed is never recoverable locally
                dead = [d for d in msg["dead"] if d != slot]
                for d in dead:
                    world_ft.observe(d, "server-declared dead "
                                        "(pool transition)")
                # even mid-job: the corpse's endpoints must go NOW, or
                # the current lease's sends keep streaming into them
                membership.survivor_transition(t, msg["epoch"], dead)
                try:
                    _send_msg(ctrl, send_lock,
                              {"op": "transition_ack", "slot": slot,
                               "epoch": msg["epoch"]})
                except OSError:
                    pass  # EOF path delivers the verdict next round
            elif op == "rejoined":
                world_ft.reset_rank(msg["slot"])
                t.min_peer_epoch[int(msg["slot"])] = int(msg["epoch"])

    threading.Thread(target=reader, daemon=True,
                     name=f"serve-ctrl-{slot}").start()

    fed_ns = os.environ.get("MPI_TPU_SERVE_FED") or None

    def pvar_push() -> None:
        # ISSUE 15 satellite (PR-13 metrics residual): the pvar
        # snapshot used to piggyback on job_done ONLY, so an idle or
        # wedged worker reported nothing — push it on the control
        # channel at a fixed cadence too, so stats() sees every worker
        while not gone.wait(_PVAR_PUSH_S):
            try:
                _send_msg(ctrl, send_lock, {
                    "op": "pvars", "slot": slot,
                    "pvars": {n: _mpit.pvar_read(n)
                              for n in _WORKER_PVARS}})
            except OSError:
                return
            if fed_ns is None:
                continue
            # the frozen-master escape (a SIGSTOP'd server keeps our
            # TCP connection ESTABLISHED forever — EOF alone can never
            # free us): if the namespace names a LIVE owner other than
            # the server we are serving, our master was deposed while
            # frozen — defect by closing the connection ourselves,
            # which drops us into the normal re-resolve path
            from . import federation as _federation

            rec = _federation.read_pool_owner(fed_ns, pool_id)
            if rec is not None and rec.get("ctrl") \
                    and rec["ctrl"] != ctrl_addr:
                srv = _federation.read_server_record(
                    fed_ns, str(rec.get("owner")))
                if srv is None or _federation.record_live(srv):
                    sys.stderr.write(
                        f"mpi_tpu.serve: worker slot {slot} (pool "
                        f"{pool_id}): ownership moved to "
                        f"{rec.get('owner')} while our master "
                        f"{ctrl_addr} held the connection — "
                        f"defecting\n")
                    gone.set()
                    try:
                        # shutdown BEFORE close: close() alone never
                        # wakes the reader thread blocked in recv()
                        ctrl.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        ctrl.close()
                    except OSError:
                        pass
                    return

    threading.Thread(target=pvar_push, daemon=True,
                     name=f"serve-pvars-{slot}").start()

    while True:
        msg = jobs.get()
        if msg is None:
            break
        job_id, slots = msg["job_id"], list(msg["slots"])
        rec = _telemetry.REC
        t_job = time.perf_counter_ns() if rec is not None else 0
        try:
            fn = pickle.loads(msg["fn"])
            args = pickle.loads(msg["args"])
            comm = P2PCommunicator(t, slots, ("lease", job_id))
            comm._ft = _ft.CommFT(world_ft, ("lease", job_id))
            # coll/sm arena via the POOLED path (ISSUE 11, closes the
            # PR-7 "leases skip the arena" residual): one epoch-stamped
            # arena per worker set, reused across leases — the epoch is
            # the SERVER's stamp shipped with the job, so every leased
            # worker keys the same segment even if a concurrent
            # transition broadcast races the dispatch
            comm._coll_sm_pool_ctx = ("lease-pool",
                                      int(msg.get("epoch", 0)))
            result = fn(comm, *args)
            reply = {"op": "job_done", "job_id": job_id, "slot": slot,
                     "ok": True}
            if comm.rank == 0:
                reply["result"] = pickle.dumps(
                    result, protocol=pickle.HIGHEST_PROTOCOL)
        except BaseException as e:  # noqa: BLE001 - shipped to the client
            reply = {"op": "job_done", "job_id": job_id, "slot": slot,
                     "ok": False, "error": _pack_error(e)}
        if rec is not None:
            rec.emit("lease", "job",
                     dur_ns=time.perf_counter_ns() - t_job,
                     attrs={"job_id": job_id, "slots": slots,
                            "ok": reply["ok"],
                            "error": (reply.get("error") or {}).get(
                                "kind")})
        # ISSUE 13: piggyback a pvar snapshot for the server's metrics
        # aggregation — latest-per-slot, summed by stats()
        reply["pvars"] = {n: _mpit.pvar_read(n) for n in _WORKER_PVARS}
        try:
            _send_msg(ctrl, send_lock, reply)
        except OSError:
            # server gone mid-reply: the lease died with it; drop the
            # reply and let the caller re-resolve the pool's owner
            gone.set()
            try:
                ctrl.close()
            except OSError:
                pass
            return "lost"
    try:
        ctrl.close()
    except OSError:
        pass
    return "shutdown" if shutdown.is_set() else "lost"


# -- the server ---------------------------------------------------------------


class _Worker:
    __slots__ = ("slot", "pool", "proc", "pid", "conn", "send_lock",
                 "state", "incarnation", "epoch", "lease_id",
                 "spawned_at")

    def __init__(self, slot: int, pool: str) -> None:
        self.slot = slot
        self.pool = pool
        self.proc: Optional[subprocess.Popen] = None
        # adopted workers (federation takeover) were never our children:
        # no Popen handle — the hello's pid + the heartbeat file carry
        # their liveness instead
        self.pid: Optional[int] = None
        self.conn: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.state = "starting"  # starting|idle|leased|dead
        self.incarnation: Optional[str] = None
        self.epoch = 0
        self.lease_id: Optional[int] = None
        self.spawned_at = time.monotonic()


class _Pool:
    """One warm worker pool: a transport world over one rendezvous dir.
    A server's HOME pool is forked by start(); ADOPTED pools (ISSUE 15
    federation takeover) arrive as metadata — their live orphaned
    workers re-register over the control channel, and worker-level
    healing runs the same announce/claim/admit protocol against the
    adopted rendezvous dir."""

    __slots__ = ("pool_id", "rdv", "backend", "size", "epoch", "home",
                 "adopted_at", "owned_since")

    def __init__(self, pool_id: str, rdv: str, backend: str, size: int,
                 home: bool, epoch: int = 0) -> None:
        self.pool_id = pool_id
        self.rdv = rdv
        self.backend = backend
        self.size = int(size)
        self.epoch = int(epoch)
        self.home = home
        self.adopted_at = None if home else time.monotonic()
        self.owned_since = time.time()


class WorldServer:
    """The resident daemon: a pool of warm workers, leased as worlds.

    Use as a context manager (tests / in-process benches) or through
    ``python -m mpi_tpu.launcher serve`` (deployment).  ``addr`` is the
    ``host:port`` clients pass to :func:`connect`."""

    def __init__(self, pool_size: int = _POOL_SIZE, backend: str = "socket",
                 host: str = _HOST, port: int = 0,
                 detect_timeout_s: float = _DETECT_TIMEOUT_S,
                 heartbeat_s: float = _HEARTBEAT_S,
                 world_lease_timeout_s: float = _WORLD_LEASE_TIMEOUT_S,
                 rejoin_timeout_s: float = _REJOIN_TIMEOUT_S,
                 env_extra: Optional[dict] = None,
                 metrics_port: Optional[int] = None,
                 federation: Optional[str] = None,
                 server_id: Optional[str] = None,
                 fed_lease_timeout_s: float = _FED_LEASE_TIMEOUT_S,
                 max_pending: int = _MAX_PENDING,
                 orphan_timeout_s: float = _ORPHAN_TIMEOUT_S) -> None:
        if pool_size < 1:
            raise ValueError("pool_size must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if backend == "shm":
            from .native import ensure_built

            ensure_built()  # compile once, not pool_size racing ranks
        self.pool_size = pool_size
        self.backend = backend
        self.detect_timeout_s = float(detect_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.world_lease_timeout_s = float(world_lease_timeout_s)
        self.rejoin_timeout_s = float(rejoin_timeout_s)
        self.max_pending = int(max_pending)
        self.orphan_timeout_s = float(orphan_timeout_s)
        self._env_extra = dict(env_extra or {})
        self.rdv = membership.new_rendezvous_dir(prefix="mpi_tpu_serve_")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(pool_size + 16)
        self.addr = "%s:%d" % self._listener.getsockname()[:2]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closing = False
        # pools (ISSUE 15): the home pool is this server's forked
        # worker world; adopted pools arrive via federation takeover.
        # Workers/healing/pvars are keyed (pool_id, slot) throughout.
        self._home = os.path.basename(self.rdv.rstrip("/"))
        self._pools: Dict[str, _Pool] = {
            self._home: _Pool(self._home, self.rdv, backend, pool_size,
                              home=True)}
        self._relinquished_home_epoch = 0
        self._workers: Dict[Tuple[str, int], _Worker] = {}
        self._leases: Dict[int, dict] = {}
        self._jobs: Dict[int, dict] = {}
        self._healing: Dict[Tuple[str, int], dict] = {}
        self._seq = 0
        # admission control (ISSUE 15): bounded waiter queue + the
        # fair-share grant ledger (leases granted per client identity)
        self._waiters: List[dict] = []
        self._client_grants: Dict[str, int] = {}
        self.stats_counters = {"leases_granted": 0, "leases_denied": 0,
                               "jobs_ok": 0, "jobs_failed": 0,
                               "heals_completed": 0, "workers_lost": 0,
                               "busy_rejected": 0,
                               "no_quorum_rejected": 0,
                               "orphans_reregistered": 0,
                               "pools_adopted": 0,
                               "pools_relinquished": 0}
        self._threads: List[threading.Thread] = []
        # federation membership (ISSUE 15): namespace dir + identity;
        # the member thread starts in start()
        self._fed_ns = federation
        self.server_id = server_id or ("srv-" + uuid.uuid4().hex[:8])
        self._fed_lease_timeout_s = float(fed_lease_timeout_s)
        self._fed = None
        # ISSUE 18: refuse NEW leases while the namespace store has no
        # quorum (minority side of a partition).  Default on; the
        # chaos "pre" leg turns it off to demonstrate the failure mode
        # it closes (a minority server serving on stale authority).
        self._store_fence = os.environ.get(
            "MPI_TPU_SERVE_STORE_FENCE", "1") != "0"
        # observability (ISSUE 13): uptime anchor for the worlds/s
        # gauge, per-second completed-job buckets (sliding window —
        # bounded at ~window-many keys regardless of rate, unlike a
        # timestamp deque whose maxlen would cap the measurable rate),
        # the latest per-slot worker pvar snapshot, and the optional
        # Prometheus endpoint (metrics_port; 0 = ephemeral, see
        # metrics_addr)
        self._t0 = time.monotonic()
        self._ok_buckets: Dict[int, int] = {}
        self._worker_pvars: Dict[Tuple[str, int], dict] = {}
        self._metrics_port = metrics_port
        self._metrics_httpd = None
        self.metrics_addr: Optional[str] = None
        self._host = host

    # -- lifecycle ---------------------------------------------------------

    @property
    def epoch(self) -> int:
        """The HOME pool's membership epoch (the single-pool stats
        contract every pre-federation caller relies on); per-pool
        epochs live in ``stats()["pools"]``."""
        pool = self._pools.get(self._home)
        return pool.epoch if pool is not None \
            else self._relinquished_home_epoch

    def start(self, wait_ready: bool = True,
              timeout: float = 120.0) -> "WorldServer":
        # the lease-acquire histogram is a process-global mpit pvar:
        # start this server's document clean so sequential in-process
        # servers (the test idiom) don't report a predecessor's tail
        # as their own p99.  (Two CONCURRENT servers in one process —
        # not a deployment shape — still share it.)
        _mpit.pvar_hist_reset("lease_acquire_s")
        for slot in range(self.pool_size):
            key = (self._home, slot)
            self._workers[key] = _Worker(slot, self._home)
            self._spawn_worker(key)
        for name, target in (("accept", self._accept_loop),
                             ("monitor", self._monitor_loop)):
            th = threading.Thread(target=target, daemon=True,
                                  name=f"serve-{name}")
            th.start()
            self._threads.append(th)
        if self._metrics_port is not None:
            self._start_metrics(self._metrics_port)
        if self._fed_ns is not None:
            # join the federation namespace: endpoint record, leader
            # lease, pool-ownership publication, takeover duties
            from . import federation as _federation

            self._fed = _federation.FederationMember(
                self, self._fed_ns, server_id=self.server_id,
                lease_timeout_s=self._fed_lease_timeout_s).start()
        if wait_ready:
            deadline = time.monotonic() + timeout
            with self._cond:
                while any(w.state == "starting"
                          for w in self._workers.values()):
                    if self._closing:
                        raise RuntimeError("server stopped during start")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"worker pool not ready within {timeout}s: "
                            + str({s: w.state for s, w
                                   in self._workers.items()}))
                    self._cond.wait(min(0.25, remaining))
        return self

    def __enter__(self) -> "WorldServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        with self._cond:
            if self._closing:
                return
            self._closing = True
            # every mutable read snapshotted HERE: the monitor thread
            # may be mid-heal, mutating conns and self._healing
            conns = [(w.conn, w.send_lock)
                     for w in self._workers.values()
                     if w.conn is not None]
            procs = [w.proc for w in self._workers.values()
                     if w.proc is not None]
            procs += [h["proc"] for h in self._healing.values()
                      if h.get("proc") is not None]
            # adopted workers are not our children: ask them to stop
            # via the shutdown op (sent below); their pids are the only
            # handle left for the last-resort sweep
            adopted_pids = [w.pid for w in self._workers.values()
                            if w.proc is None and w.pid]
            pools = list(self._pools.values())
            self._cond.notify_all()
        if self._fed is not None:
            # leave the namespace FIRST: records retract before the
            # pools die, so no leader assigns a takeover of a pool
            # whose workers are about to receive shutdown
            self._fed.stop()
        for conn, lk in conns:
            try:
                _send_msg(conn, lk, {"op": "shutdown"})
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        httpd = self._metrics_httpd
        if httpd is not None:
            self._metrics_httpd = None
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:  # pragma: no cover - teardown race
                pass
        deadline = time.monotonic() + 5.0
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(2.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        # adopted workers received the shutdown op above; sweep any
        # that did not exit (two masters of one pool must never coexist
        # with the rendezvous dirs about to vanish)
        deadline = time.monotonic() + 3.0
        for pid in adopted_pids:
            while membership._pid_alive(pid) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            if membership._pid_alive(pid):
                try:
                    os.kill(pid, 9)
                except OSError:
                    pass
        for pool in pools:
            membership.cleanup_rendezvous(pool.rdv)

    # -- metrics endpoint (ISSUE 13) ---------------------------------------

    def _start_metrics(self, port: int) -> None:
        """Serve ``GET /metrics`` (Prometheus text format, rendered by
        mpi_tpu/telemetry/metrics.py from the same ``stats()`` document
        ``client.stats()`` returns) on a side HTTP port.  Port 0 binds
        ephemeral — ``metrics_addr`` reports the outcome.  The handler
        only READS (stats() takes the server lock briefly); a scrape
        can never wedge the monitor/heal machinery."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from .telemetry import metrics as _metrics

        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = _metrics.prometheus_text(
                        server.stats()).encode()
                except Exception as e:  # noqa: BLE001 - shipped as 500
                    self.send_error(500, type(e).__name__)
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # noqa: D102
                pass  # scrapes are not server-log events

        httpd = ThreadingHTTPServer((self._host, int(port)), Handler)
        httpd.daemon_threads = True
        self._metrics_httpd = httpd
        self.metrics_addr = "%s:%d" % httpd.server_address[:2]
        th = threading.Thread(target=httpd.serve_forever,
                              daemon=True, name="serve-metrics")
        th.start()
        self._threads.append(th)

    # -- worker processes --------------------------------------------------

    def _worker_env(self, key: Tuple[str, int],
                    rejoin_epoch: Optional[int] = None) -> dict:
        from .launcher import cpu_pinned_env

        pool_id, slot = key
        pool = self._pools[pool_id]
        env = dict(os.environ)
        want = self._env_extra.get("MPI_TPU_RANK_JAX_PLATFORMS")
        cpu_pinned_env(env, want)
        env.update({
            "MPI_TPU_RANK": str(slot),
            "MPI_TPU_SIZE": str(pool.size),
            "MPI_TPU_RDV": pool.rdv,
            "MPI_TPU_BACKEND": pool.backend,
            "MPI_TPU_FT": "1",
            "MPI_TPU_SERVE_CTRL": self.addr,
            "MPI_TPU_SERVE_POOL": pool_id,
            "MPI_TPU_SERVE_DETECT_S": str(self.detect_timeout_s),
            "MPI_TPU_SERVE_HEARTBEAT_S": str(self.heartbeat_s),
            "MPI_TPU_SERVE_ORPHAN_TIMEOUT_S": str(self.orphan_timeout_s),
        })
        env.pop("MPI_TPU_SERVE_FED", None)
        if self._fed_ns is not None:
            # CLIENT spec: a raft:<idx>@... member spec must not leak
            # into workers — they resolve pool owners over the store's
            # RPC port, never by embedding a node
            from . import federation_store as _fstore

            env["MPI_TPU_SERVE_FED"] = _fstore.client_spec(self._fed_ns)
        env.pop("MPI_TPU_SERVE_REJOIN", None)
        if rejoin_epoch is not None:
            env["MPI_TPU_SERVE_REJOIN"] = f"{rejoin_epoch}:{slot}"
            env["MPI_TPU_SERVE_REJOIN_TIMEOUT_S"] = \
                str(self.rejoin_timeout_s)
        env.update(self._env_extra)
        return env

    def _spawn_worker(self, key: Tuple[str, int],
                      rejoin_epoch: Optional[int] = None
                      ) -> subprocess.Popen:
        proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_tpu.serve", "--worker"],
            env=self._worker_env(key, rejoin_epoch))
        if rejoin_epoch is None:
            self._workers[key].proc = proc
            self._workers[key].spawned_at = time.monotonic()
        return proc

    # -- accept / connection handling --------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True, name="serve-conn").start()

    def _conn_loop(self, conn: socket.socket) -> None:
        first = _recv_msg(conn)
        if first is None:
            conn.close()
            return
        if first.get("op") == "hello":
            self._worker_loop(conn, first)
        else:
            self._client_loop(conn, first)

    # -- worker side -------------------------------------------------------

    def _worker_loop(self, conn: socket.socket, hello: dict) -> None:
        slot = int(hello["slot"])
        pool_id = str(hello.get("pool") or self._home)
        key = (pool_id, slot)
        with self._cond:
            w = self._workers.get(key)
            pool = self._pools.get(pool_id)
            if w is None or pool is None:
                # unknown slot, or a pool relinquished/adopted-away in
                # the hello race: EOF sends the worker back to the
                # namespace, where the current owner's record is
                conn.close()
                return
            if w.conn is not None and w.state in ("idle", "leased") \
                    and hello.get("incarnation") != w.incarnation:
                # the slot is LIVE under a different incarnation (e.g.
                # a long-frozen ex-orphan thawing after its slot was
                # healed): refuse — two incarnations of one slot must
                # never coexist, and the EOF sends the zombie through
                # the re-resolve path to reap itself
                conn.close()
                return
            heal = self._healing.pop(key, None)
            if heal is not None:
                w.proc = heal["proc"]
                self.stats_counters["heals_completed"] += 1
            elif w.proc is None and not pool.home:
                # an orphan of the adopted pool re-registering (ISSUE
                # 15): everything about it is warm — it becomes
                # leasable the moment this hello lands
                self.stats_counters["orphans_reregistered"] += 1
            w.conn = conn
            w.pid = hello.get("pid")
            w.incarnation = hello.get("incarnation")
            w.epoch = int(hello.get("epoch", 0))
            w.lease_id = None
            # an adopted pool learns its epoch from its workers (the
            # dead server's transitions already reached them)
            pool.epoch = max(pool.epoch, w.epoch)
            # (conn, lock) pairs snapshotted under the lock — see
            # _begin_heal for the concurrent-death rationale
            peers = [(p.conn, p.send_lock)
                     for p in self._workers.values()
                     if p is not w and p.pool == pool_id
                     and p.conn is not None
                     and p.state not in ("dead",)]
            behind = w.epoch < pool.epoch
            catchup = {"op": "transition", "epoch": pool.epoch,
                       # never list the hello-ing worker's OWN slot
                       # (its state is still 'dead' right here): a
                       # worker observing itself failed would poison
                       # every FT decision of its future leases
                       "dead": [p.slot for p in self._workers.values()
                                if p is not w and p.pool == pool_id
                                and (p.state == "dead"
                                     or (pool_id, p.slot)
                                     in self._healing)]}
        if behind:
            # another death's transition was broadcast while this
            # worker was still rejoining (excluded as 'dead'): resync
            # it NOW or its first send to an up-epoch survivor raises
            # EpochSkewError forever while stats report a healthy pool
            try:
                _send_msg(conn, w.send_lock, catchup)
            except OSError:
                pass  # EOF path marks it dead next
        if heal is not None:
            # tell the survivors the slot is live again under its epoch
            # BEFORE the slot becomes leasable: a job dispatched to a
            # peer rides the same FIFO control connection as this
            # 'rejoined', so each peer clears its detector's failed
            # entry before it can possibly run a lease with the healed
            # slot — idle-first would let the first post-heal lease
            # raise a spurious ProcFailedError off the stale failed set
            for conn_p, lk_p in peers:
                try:
                    _send_msg(conn_p, lk_p,
                              {"op": "rejoined", "slot": slot,
                               "epoch": w.epoch})
                except OSError:
                    pass
        with self._cond:
            w.state = "idle"
            self._cond.notify_all()
        while True:
            msg = _recv_msg(conn)
            if msg is None:
                with self._cond:
                    if not self._closing \
                            and self._workers.get(key) is w \
                            and w.conn is conn and w.state != "dead":
                        self._mark_dead_locked(w, "control channel EOF")
                    self._cond.notify_all()
                return
            op = msg.get("op")
            if op == "job_done":
                self._job_done(key, msg)
            elif op == "pvars":
                # ISSUE 15 satellite: the idle/wedged-worker pvar push
                # — latest-per-slot, same aggregation as the job_done
                # piggyback, so stats() sees workers that never
                # completed a job.  Existence-guarded: an in-flight
                # push must not resurrect a key relinquish_pool just
                # popped (the usurper counts those slots now —
                # double-counting would falsify the roll-up for good)
                with self._cond:
                    if key in self._workers:
                        self._worker_pvars[key] = msg.get("pvars") or {}
            # transition_acks are informational: the monitor's spawn of
            # the replacement does not wait on them (a wedged worker
            # must not stall the pool's healing)

    def _job_done(self, key: Tuple[str, int], msg: dict) -> None:
        slot = key[1]
        with self._cond:
            pvars = msg.get("pvars")
            if pvars and key in self._workers:
                self._worker_pvars[key] = pvars
            job = self._jobs.get(msg["job_id"])
            if job is None:
                return
            job["pending"].discard(slot)
            if msg.get("ok"):
                if "result" in msg:
                    job["result"] = msg["result"]
            else:
                job["errors"].append(msg.get("error", {}))
            if not job["pending"]:
                job["event"].set()
            self._cond.notify_all()

    def _mark_dead_locked(self, w: _Worker, why: str) -> None:
        """State transition for a lost worker (caller holds the lock):
        pool-epoch bump + fail its in-flight job; the monitor loop
        picks the slot up for healing on its next tick."""
        if w.state == "dead":
            return
        pool = self._pools.get(w.pool)
        w.state = "dead"
        w.conn = None
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("lease", "worker_dead",
                     attrs={"slot": w.slot, "pool": w.pool, "why": why,
                            "epoch": (pool.epoch + 1 if pool is not None
                                      else -1)})
        if w.proc is not None and w.proc.poll() is None:
            # declared dead but the process lives (heartbeat-stale
            # wedge): kill it — two live incarnations of one slot must
            # never coexist, and the replacement hello overwrites
            # w.proc, dropping stop()'s only handle on this one
            try:
                w.proc.kill()
            except OSError:
                pass
        elif w.proc is None and w.pid:
            # adopted worker (no Popen handle): same rule, by pid
            try:
                os.kill(w.pid, 9)
            except OSError:
                pass
        self.stats_counters["workers_lost"] += 1
        if pool is not None:
            pool.epoch += 1
        for job in self._jobs.values():
            if job.get("pool") == w.pool and w.slot in job["pending"]:
                job["pending"].discard(w.slot)
                job["errors"].append({
                    "kind": "ProcFailedError",
                    "code": error_class(ProcFailedError("")),
                    "msg": f"leased worker slot {w.slot} died ({why})",
                    "failed": [w.slot], "collective": None})
                if not job["pending"]:
                    job["event"].set()

    # -- monitoring / healing ----------------------------------------------

    def _hb_stale(self, pool: _Pool, slot: int, now: float) -> bool:
        age = membership.heartbeat_age(pool.rdv, slot, now)
        if age is None:
            return False  # not yet published: proc liveness covers it
        return age > 3.0 * self.detect_timeout_s

    def _adopt_grace_s(self) -> float:
        """How long an adopted pool's slot may stay 'starting' (its
        orphan resolving the takeover from the namespace) before its
        heartbeat decides whether it is a corpse to heal."""
        return max(5.0, 3.0 * self.detect_timeout_s)

    def _monitor_loop(self) -> None:
        while not self._closing:
            time.sleep(self.heartbeat_s)
            if self._closing:
                return
            try:
                self._monitor_tick()
            except Exception as e:  # noqa: BLE001 - the pool's lifeline
                if self._closing:
                    return  # shutdown raced a heal (rdv dir removed)
                # a monitor crash must never silently end healing: a
                # STRUCTURED line (what failed, pool state) + telemetry
                # event instead of ISSUE 7's bare print_exc, then keep
                # ticking (ISSUE 13 satellite)
                import traceback

                with self._lock:
                    epoch, healing = self.epoch, sorted(self._healing)
                sys.stderr.write(
                    f"mpi_tpu.serve: monitor tick failed "
                    f"({type(e).__name__}: {str(e)[:200]}; epoch "
                    f"{epoch}, healing slots {healing}) — healing "
                    f"continues:\n{traceback.format_exc()}")
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("serve", "monitor_error",
                             attrs={"error": type(e).__name__,
                                    "epoch": epoch,
                                    "healing": healing})

    def _monitor_tick(self) -> None:
        now_wall = time.time()
        with self._cond:
            for key, w in self._workers.items():
                if w.state == "dead" or key in self._healing:
                    continue
                pool = self._pools.get(w.pool)
                if pool is None:
                    continue  # relinquish race: workers go next tick
                lost = (w.proc is not None
                        and w.proc.poll() is not None)
                if not lost and w.proc is None and w.pid \
                        and w.state != "starting":
                    # adopted worker: no Popen handle — pid liveness
                    lost = not membership._pid_alive(w.pid)
                if not lost and w.state != "starting":
                    lost = self._hb_stale(pool, w.slot, now_wall)
                if not lost and w.state == "starting" \
                        and pool.adopted_at is not None:
                    # an adopted slot whose orphan never re-registered:
                    # past the adoption grace, the heartbeat file (the
                    # one liveness signal that survives a change of
                    # ownership) decides corpse-or-slow
                    if time.monotonic() - pool.adopted_at \
                            > self._adopt_grace_s():
                        age = membership.heartbeat_age(pool.rdv, w.slot,
                                                       now_wall)
                        lost = (age is None
                                or age > 3.0 * self.detect_timeout_s)
                if lost:
                    self._mark_dead_locked(
                        w, "process exited"
                        if w.proc is not None
                        and w.proc.poll() is not None
                        else "heartbeat stale")
            # heal EVERY dead slot without a healing round in
            # flight — deaths are marked both here and by the
            # worker-connection EOF path, and both must converge on
            # a replacement
            dead_now = [w for key, w in self._workers.items()
                        if w.state == "dead" and key not in self._healing]
            if dead_now:
                self._cond.notify_all()
        if dead_now:
            self._begin_heal(dead_now)
        self._drive_healing()

    def _begin_heal(self, dead: List[_Worker]) -> None:
        """One healing round per affected pool: tell that pool's
        survivors, announce the vacancies on ITS rendezvous dir, spawn
        replacements that rejoin under the pool's bumped epoch —
        identical for the home pool and an adopted one (the membership
        protocol is all files under the pool's own rdv)."""
        by_pool: Dict[str, List[_Worker]] = {}
        for w in dead:
            by_pool.setdefault(w.pool, []).append(w)
        for pool_id, ws in by_pool.items():
            dead_slots = [w.slot for w in ws]
            with self._lock:
                pool = self._pools.get(pool_id)
                if pool is None:
                    continue  # relinquished mid-round: new owner heals
                epoch = pool.epoch
                # snapshot (conn, lock) PAIRS under the lock: a
                # concurrent death nulls worker.conn, and re-reading it
                # outside the lock would hand None to sendall
                # (AttributeError kills the monitor thread — the pool
                # would stop healing entirely)
                live = [(p.conn, p.send_lock)
                        for p in self._workers.values()
                        if p.pool == pool_id
                        and p.state not in ("dead", "starting")
                        and p.conn is not None]
            for conn, lk in live:
                try:
                    _send_msg(conn, lk,
                              {"op": "transition", "epoch": epoch,
                               "dead": dead_slots})
                except OSError:
                    pass  # its own death will be noticed next tick
            slots_meta = {
                s: {"ousted": membership.read_incarnation(pool.rdv, s),
                    # the server IS the membership authority: it
                    # observed the death and decided to replace, which
                    # is the ack — the refusal gate still protects
                    # against an UNINVITED ousted incarnation claiming
                    # before the server's replacement (it presents the
                    # ousted id; the spawned replacement presents a
                    # fresh one)
                    "acked": False}
                for s in dead_slots}
            membership.announce_rejoin(pool.rdv, epoch, slots_meta,
                                       pool.size, pool.backend)
            with self._lock:
                if self._closing:
                    return  # a stop() racing this heal owns every process
                if pool_id not in self._pools:
                    continue  # relinquished while announcing
                for w in ws:
                    key = (pool_id, w.slot)
                    proc = self._spawn_worker(key, rejoin_epoch=epoch)
                    self._healing[key] = {
                        "epoch": epoch, "proc": proc,
                        "since": time.monotonic(), "meta": slots_meta}

    def _drive_healing(self) -> None:
        """Per-tick healing duties: validate claims/admit replacements
        (the announcer role of the membership protocol), and respawn a
        replacement that died during its own rejoin handshake — the
        pool recovers, no epoch fork (the announce stays valid)."""
        with self._lock:
            healing = dict(self._healing)
        for key, h in healing.items():
            pool_id, slot = key
            pool = self._pools.get(pool_id)
            if pool is None:
                # the pool was relinquished mid-heal: the usurper owns
                # its healing now — reap our half-spawned replacement
                with self._lock:
                    self._healing.pop(key, None)
                try:
                    h["proc"].kill()
                except OSError:
                    pass
                continue
            membership.process_claims(pool.rdv, h["epoch"],
                                      {slot: h["meta"][slot]})
            proc = h["proc"]
            if proc.poll() is not None:
                with self._lock:
                    if self._closing or key not in self._healing:
                        continue
                    h["proc"] = self._spawn_worker(
                        key, rejoin_epoch=h["epoch"])
                    h["since"] = time.monotonic()
                    self._healing[key] = h
            elif time.monotonic() - h["since"] > self.rejoin_timeout_s:
                # the replacement is ALIVE but wedged in its handshake
                # past the rejoin bound: kill it — next tick's poll()
                # branch respawns, and process_claims sweeps its
                # leftover claim (dead pid).  Re-check under the lock
                # that this round is STILL healing (mirroring the
                # respawn branch): the worker may have completed its
                # hello since the snapshot, and killing a just-healed,
                # possibly-leased worker would livelock healing
                with self._lock:
                    still = (not self._closing
                             and self._healing.get(key) is h)
                if still:
                    try:
                        proc.kill()
                    except OSError:
                        pass

    # -- federation hooks (ISSUE 15; called by FederationMember) -----------

    def owned_pool_records(self) -> Dict[str, dict]:
        """Metadata of every pool this server currently serves — what
        the federation member publishes as ownership records."""
        with self._lock:
            return {pid: {"rdv": p.rdv, "backend": p.backend,
                          "size": p.size, "epoch": p.epoch,
                          "since": p.owned_since}
                    for pid, p in self._pools.items()}

    def fed_summary(self) -> dict:
        """The light per-server summary embedded in the endpoint
        record (federation_stats sums these across the namespace)."""
        now = time.monotonic()
        with self._lock:
            states = [w.state for w in self._workers.values()]
            return {"pools": len(self._pools),
                    "workers": len(states),
                    "idle": states.count("idle"),
                    "leases_active": len(self._leases),
                    "waiting": len(self._waiters),
                    "worlds_per_s": self._worlds_per_s_locked(now),
                    "backend": self.backend}

    def adopt_pool(self, pool_id: str, rec: dict, term: int = 0) -> bool:
        """Take over a dead server's pool (leader-assigned takeover):
        register its metadata and one 'starting' worker entry per slot
        — the live orphans re-register via their control-channel
        re-resolve, and a slot whose orphan never shows is healed
        through the normal announce/claim/admit path against the
        adopted rendezvous dir after the adoption grace."""
        with self._cond:
            if self._closing or pool_id in self._pools:
                return False
            pool = _Pool(pool_id, rec["rdv"],
                         rec.get("backend", "socket"), int(rec["size"]),
                         home=False, epoch=int(rec.get("epoch", 0)))
            self._pools[pool_id] = pool
            for s in range(pool.size):
                self._workers[(pool_id, s)] = _Worker(s, pool_id)
            self.stats_counters["pools_adopted"] += 1
            self._cond.notify_all()
        rec_t = _telemetry.REC
        if rec_t is not None:
            rec_t.emit("serve", "pool_adopted",
                       attrs={"pool": pool_id, "size": pool.size,
                              "epoch": pool.epoch, "term": term})
        sys.stderr.write(
            f"mpi_tpu.serve: server {self.server_id} adopted pool "
            f"{pool_id} ({pool.size} slots, epoch {pool.epoch}, "
            f"term {term})\n")
        return True

    def relinquish_pool(self, pool_id: str,
                        new_owner: Optional[str] = None) -> None:
        """The thawed-usurped path: the namespace says another server
        now owns this pool — stop serving it IMMEDIATELY.  Closing the
        worker control connections is the handover itself (a worker
        serves exactly one master at a time; EOF sends it to the
        namespace, where the usurper's record is), and every in-flight
        lease on the pool fails with a NAMED error, never a hang."""
        with self._cond:
            pool = self._pools.pop(pool_id, None)
            if pool is None:
                return
            if pool.home:
                self._relinquished_home_epoch = pool.epoch
            conns = []
            for key in [k for k in self._workers if k[0] == pool_id]:
                w = self._workers.pop(key)
                if w.conn is not None:
                    conns.append(w.conn)
                self._worker_pvars.pop(key, None)
            heal_procs = [self._healing.pop(k)["proc"]
                          for k in list(self._healing)
                          if k[0] == pool_id]
            for job in self._jobs.values():
                if job.get("pool") == pool_id and job["pending"]:
                    job["pending"].clear()
                    job["errors"].append({
                        "kind": "TransportError",
                        "msg": f"pool {pool_id} taken over by server "
                               f"{new_owner} (ownership moved "
                               f"mid-lease; re-acquire)",
                        "failed": [], "collective": None})
                    job["event"].set()
            for lease_id in [lid for lid, lease in self._leases.items()
                             if lease.get("pool") == pool_id]:
                self._leases.pop(lease_id)
            # queued acquires that can NEVER be satisfied by the
            # remaining pools must fail over NOW with the named
            # signal, not stall to a LeaseTimeout the federated
            # client treats as a live-server verdict
            cap = max((p.size for p in self._pools.values()),
                      default=0)
            for waiter in self._waiters:
                if waiter["nranks"] > cap:
                    waiter["lost"] = True
            self.stats_counters["pools_relinquished"] += 1
            self._cond.notify_all()
        for c in conns:
            try:
                # shutdown first: the worker side's reader thread is
                # blocked in recv(), which a bare close() never wakes
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for p in heal_procs:
            try:
                p.kill()  # the usurper heals its own pool
            except OSError:
                pass
        rec_t = _telemetry.REC
        if rec_t is not None:
            rec_t.emit("serve", "pool_relinquished",
                       attrs={"pool": pool_id, "to": new_owner})
        sys.stderr.write(
            f"mpi_tpu.serve: server {self.server_id} relinquished pool "
            f"{pool_id} to {new_owner} (taken over while this server "
            f"was unresponsive)\n")

    def is_leader(self) -> bool:
        return self._fed is not None and self._fed.is_leader()

    # -- client side -------------------------------------------------------

    def _client_loop(self, conn: socket.socket, first: dict) -> None:
        lock = threading.Lock()
        owned: List[int] = []  # lease ids owned by this connection
        msg: Optional[dict] = first
        try:
            while msg is not None:
                try:
                    reply = self._client_op(msg, owned)
                except Exception as e:  # noqa: BLE001 - shipped back
                    reply = {"error": _pack_error(e)}
                try:
                    _send_msg(conn, lock, reply)
                except OSError:
                    break
                if msg.get("op") == "shutdown":
                    threading.Thread(target=self.stop,
                                     daemon=True).start()
                    break
                msg = _recv_msg(conn)
        finally:
            for lease_id in list(owned):
                self._release(lease_id)
            conn.close()

    def _client_op(self, msg: dict, owned: List[int]) -> dict:
        op = msg.get("op")
        if op == "acquire":
            return self._acquire(msg, owned)
        if op == "run":
            return self._run_job(msg)
        if op == "release":
            self._release(int(msg["lease_id"]))
            if int(msg["lease_id"]) in owned:
                owned.remove(int(msg["lease_id"]))
            return {"ok": True}
        if op == "stats":
            return {"ok": True, "stats": self.stats()}
        if op == "shutdown":
            return {"ok": True}
        return {"error": {"kind": "ValueError",
                          "msg": f"unknown op {op!r}"}}

    def _pick_idle_locked(self, nranks: int
                          ) -> Optional[Tuple[str, List[int]]]:
        """A pool with ``nranks`` idle slots (a lease never spans
        pools — they are different transport worlds).  BEST-FIT: the
        pool with the FEWEST idle slots that still satisfies (home as
        the tiebreak), so small leases are packed into small remnants
        and a large later request keeps an unfragmented pool to land
        on — most-idle-first would carve up exactly the pool a
        full-size lease needs."""
        best = None
        for pool_id, pool in self._pools.items():
            idle = sorted(s for (pid, s), w in self._workers.items()
                          if pid == pool_id and w.state == "idle")
            if len(idle) >= nranks:
                score = (len(idle), 0 if pool.home else 1, pool_id)
                if best is None or score < best[0]:
                    best = (score, pool_id, idle[:nranks])
        return None if best is None else (best[1], best[2])

    def _try_grant_locked(self, waiter: dict
                          ) -> Optional[Tuple[str, List[int]]]:
        """Grant ``waiter`` iff it is the first waiter in admission
        order (priority → fair share → FIFO) that the current idle
        capacity can satisfy."""
        for w in _admission_order(self._waiters, self._client_grants):
            pick = self._pick_idle_locked(w["nranks"])
            if pick is None:
                continue
            return pick if w is waiter else None
        return None

    def _acquire(self, msg: dict, owned: List[int]) -> dict:
        nranks = int(msg["nranks"])
        timeout = float(msg.get("timeout") or self.world_lease_timeout_s)
        client_id = str(msg.get("client") or "anon")
        priority = int(msg.get("priority") or 0)
        t_req = time.monotonic()
        deadline = t_req + timeout
        with self._cond:
            if self._fed is not None and self._store_fence \
                    and not self._fed.healthy():
                # ISSUE 18 admission fence: this server sits on the
                # MINORITY side of a namespace-store partition (or the
                # store group has no leader).  Granting a lease here
                # could double-serve a pool the majority is about to
                # reassign — refuse with the NAMED verdict instead;
                # FederatedClient treats it as a failover signal and
                # lands on a majority-side server.  In-flight leases
                # run to completion (reads and running jobs are not
                # gated); only NEW authority is refused.
                self.stats_counters["leases_denied"] += 1
                self.stats_counters["no_quorum_rejected"] += 1
                raise NoQuorumError(
                    f"server {self.server_id} has no namespace-store "
                    f"quorum (minority side of a partition): refusing "
                    f"new leases — fail over to a majority-side "
                    f"server")
            # under the lock: the federation thread mutates _pools
            # (adopt/relinquish) — iterating it bare would crash with
            # dict-changed-size exactly during a takeover, when failed-
            # over acquires flood the survivor
            cap = max((p.size for p in self._pools.values()), default=0)
            if cap == 0:
                # every pool relinquished (thawed fully-usurped
                # server): this endpoint cannot serve ANY lease — ship
                # the failover signal, not an argument error, so a
                # federated client moves to a survivor
                raise ServerLostError(
                    "server owns no pools (relinquished after a "
                    "takeover): fail over to a live owner")
            if nranks < 1 or nranks > cap:
                raise ValueError(
                    f"nranks must be in [1, {cap}] for this pool")
            was_full = len(self._waiters) >= self.max_pending
            self._seq += 1
            waiter = {"client": client_id, "priority": priority,
                      "nranks": nranks, "seq": self._seq}
            self._waiters.append(waiter)
            # work-conserving door: an arrival the CURRENT idle
            # capacity can satisfy (net of better-ranked waiters) is
            # granted immediately and never occupies a queue slot —
            # a full queue of unsatisfiable large requests must not
            # bounce small ones that idle workers could serve now
            grant = self._try_grant_locked(waiter)
            if grant is None and was_full:
                # bounded admission queue with a PRIORITY-AWARE door
                # (ISSUE 15): overload becomes an immediate named
                # rejection, not unbounded latency — but an arrival
                # that outranks the WORST waiter (priority, then fair
                # share, then FIFO: the same admission order) bumps it
                # instead, so a flood of low-priority acquires can
                # never lock a prioritized client out of a full queue.
                # Either way depth stays <= max_pending and every
                # rejection is a named ServerBusyError.
                self._waiters.remove(waiter)
                order = _admission_order(self._waiters,
                                         self._client_grants)
                worst = order[-1] if order else None
                cand_key = (-priority,
                            self._client_grants.get(client_id, 0),
                            waiter["seq"])
                worst_key = None if worst is None else (
                    -worst["priority"],
                    self._client_grants.get(worst["client"], 0),
                    worst["seq"])
                self.stats_counters["leases_denied"] += 1
                self.stats_counters["busy_rejected"] += 1
                if worst is None or worst_key <= cand_key:
                    raise ServerBusyError(
                        f"admission queue full ({self.max_pending} "
                        f"waiting acquires, capacity "
                        f"{sum(p.size for p in self._pools.values())} "
                        f"workers): back off or fail over")
                worst["bumped"] = True
                self._waiters.remove(worst)
                self._waiters.append(waiter)
                self._cond.notify_all()
            try:
                while grant is None:
                    if self._closing:
                        raise RuntimeError("server shutting down")
                    if waiter.get("lost"):
                        raise ServerLostError(
                            "the pool(s) that could have served this "
                            "acquire were relinquished to another "
                            "server: fail over to the new owner")
                    if waiter.get("bumped"):
                        raise ServerBusyError(
                            f"bumped from the full admission queue by "
                            f"a higher-ranked acquire "
                            f"({self.max_pending} waiting): back off "
                            f"or fail over")
                    grant = self._try_grant_locked(waiter)
                    if grant is not None:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        idle = sum(1 for w in self._workers.values()
                                   if w.state == "idle")
                        self.stats_counters["leases_denied"] += 1
                        return {"error": {
                            "kind": "LeaseTimeout",
                            "msg": f"no {nranks} idle workers within "
                                   f"{timeout}s (pool {self.pool_size}, "
                                   f"idle {idle}, waiting "
                                   f"{len(self._waiters)})"}}
                    self._cond.wait(min(0.25, remaining))
            finally:
                if waiter in self._waiters:
                    self._waiters.remove(waiter)
            pool_id, slots = grant
            self._seq += 1
            lease_id = self._seq
            for s in slots:
                self._workers[(pool_id, s)].state = "leased"
                self._workers[(pool_id, s)].lease_id = lease_id
            epoch = self._pools[pool_id].epoch
            self._leases[lease_id] = {"slots": slots, "epoch": epoch,
                                      "pool": pool_id}
            self.stats_counters["leases_granted"] += 1
            # the fair-share ledger: whoever got this grant moves back
            # in the order among equals.  Bounded: an unbounded client-
            # uuid dict is a slow leak under connect()-churn, so reset
            # the baseline rather than grow without limit.
            self._client_grants[client_id] = \
                self._client_grants.get(client_id, 0) + 1
            if len(self._client_grants) > 4096:
                self._client_grants.clear()
        # lease-acquire latency distribution (ISSUE 13): always on —
        # the grant is a control round-trip, one histogram add is noise
        # (this is what the metrics endpoint's p50/p99 summarize)
        _mpit.hist_record("lease_acquire_s", time.monotonic() - t_req)
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("lease", "grant",
                     attrs={"lease_id": lease_id, "slots": slots,
                            "pool": pool_id, "epoch": epoch})
        owned.append(lease_id)
        return {"ok": True, "lease_id": lease_id, "slots": slots,
                "epoch": epoch, "pool": pool_id}

    def _run_job(self, msg: dict) -> dict:
        lease_id = int(msg["lease_id"])
        timeout = float(msg.get("timeout") or self.world_lease_timeout_s)
        with self._cond:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"unknown lease {lease_id}")
            pool_id = lease.get("pool", self._home)
            slots = list(lease["slots"])
            dead = [s for s in slots
                    if self._workers[(pool_id, s)].state != "leased"
                    or self._workers[(pool_id, s)].lease_id != lease_id]
            self._seq += 1
            job_id = self._seq
            job = {"pending": set(slots) - set(dead), "errors": [],
                   "result": None, "event": threading.Event(),
                   "pool": pool_id}
            if dead:
                job["errors"].append({
                    "kind": "ProcFailedError",
                    "code": error_class(ProcFailedError("")),
                    "msg": f"leased worker slot(s) {dead} died before "
                           f"the job started",
                    "failed": dead, "collective": None})
            self._jobs[job_id] = job
            targets = [(self._workers[(pool_id, s)].conn,
                        self._workers[(pool_id, s)].send_lock)
                       for s in job["pending"]]
        if not job["pending"]:
            job["event"].set()
        for conn, lk in targets:
            try:
                _send_msg(conn, lk, {
                    "op": "job", "job_id": job_id, "slots": slots,
                    # the lease's epoch stamp: keys the pooled coll/sm
                    # arena identically on every leased worker
                    "epoch": lease.get("epoch", 0),
                    "fn": msg["fn"], "args": msg["args"]})
            except OSError:
                pass  # its death is noticed by the monitor and synthesized
        ok = job["event"].wait(timeout)
        with self._cond:
            self._jobs.pop(job_id, None)
            stuck = sorted(job["pending"])
            # pin the exact PROC OBJECTS (and, for adopted workers that
            # were never our children, the hello pid) while holding the
            # lock: a concurrent heal could install a healthy
            # replacement under the same slot, and signalling by slot
            # would dump/kill it
            stuck_procs = [(s, self._workers[(pool_id, s)].proc,
                            self._workers[(pool_id, s)].pid)
                           for s in stuck
                           if (pool_id, s) in self._workers]
        if not ok:
            # dump the unresponsive workers' stacks to their stderr
            # (faulthandler SIGUSR2 handler) for the diagnosis, then
            # QUARANTINE them by killing: a worker that blew the lease
            # timeout is still wedged in the old job (its job loop is
            # serial), and returning it to the idle pool on release
            # would poison every subsequent lease it joins — killed, it
            # takes the already-tested healing path and comes back as a
            # fresh replacement under the next epoch
            import signal as _signal

            for s, proc, pid in stuck_procs:
                target = None
                if proc is not None and proc.poll() is None:
                    target = proc.pid
                elif proc is None and pid \
                        and membership._pid_alive(pid):
                    target = pid
                if target is not None:
                    try:
                        os.kill(target, _signal.SIGUSR2)
                        time.sleep(0.1)  # let the dump reach stderr
                        os.kill(target, _signal.SIGKILL)
                    except OSError:
                        pass
            sys.stderr.write(
                f"mpi_tpu.serve: job {job_id} on lease {lease_id} "
                f"blew the {timeout}s lease timeout; quarantined "
                f"worker slots {stuck}\n")
            return {"error": {
                "kind": "LeaseTimeout",
                "msg": f"job on lease {lease_id} did not complete "
                       f"within {timeout}s (unresponsive worker "
                       f"slots {stuck}: stacks dumped to the server "
                       f"log, workers killed for pool healing)"}}
        if job["errors"]:
            self.stats_counters["jobs_failed"] += 1
            # the most diagnosable error wins: a named FT error over a
            # generic one
            errs = sorted(
                job["errors"],
                key=lambda e: 0 if e.get("kind") in _ERROR_KINDS else 1)
            # ISSUE 13 satellite: a lease failure is attributable in
            # the server log — job/lease id, error class, failed slots
            sys.stderr.write(
                f"mpi_tpu.serve: job {job_id} on lease {lease_id} "
                f"failed: {errs[0].get('kind')}: "
                f"{str(errs[0].get('msg', ''))[:200]} "
                f"(failed slots {errs[0].get('failed')})\n")
            return {"error": errs[0]}
        with self._cond:
            self.stats_counters["jobs_ok"] += 1
            sec = int(time.monotonic())
            self._ok_buckets[sec] = self._ok_buckets.get(sec, 0) + 1
            for k in [k for k in self._ok_buckets
                      if sec - k > _RATE_WINDOW_S]:
                del self._ok_buckets[k]
        return {"ok": True, "result": job["result"]}

    def _release(self, lease_id: int) -> None:
        with self._cond:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            pool_id = lease.get("pool", self._home)
            for s in lease["slots"]:
                w = self._workers.get((pool_id, s))
                if w is not None and w.state == "leased" \
                        and w.lease_id == lease_id:
                    w.state = "idle"
                    w.lease_id = None
            self._cond.notify_all()

    def _worlds_per_s_locked(self, now: float) -> float:
        # worlds/s over the sliding window (completed jobs), the
        # gauge ROADMAP direction 1 asks for; uptime-bounded so a
        # young server reads its true rate, not a diluted one
        window = min(_RATE_WINDOW_S, max(1e-9, now - self._t0))
        recent = sum(c for sec, c in self._ok_buckets.items()
                     if now - sec <= _RATE_WINDOW_S)
        return round(recent / window, 3)

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            # single-pool back-compat: "workers"/"epoch" describe the
            # HOME pool; "idle" counts every pool (a lease can land on
            # any); "pools" carries the per-pool detail
            states = {s: w.state for (pid, s), w in self._workers.items()
                      if pid == self._home}
            pools = {
                pid: {"home": p.home, "epoch": p.epoch, "size": p.size,
                      "workers": {s: w.state
                                  for (wp, s), w
                                  in self._workers.items()
                                  if wp == pid}}
                for pid, p in self._pools.items()}
            agg: Dict[str, int] = {}
            for snap in self._worker_pvars.values():
                for k, v in snap.items():
                    agg[k] = agg.get(k, 0) + int(v)
            out = {
                "addr": self.addr, "backend": self.backend,
                "pool_size": self.pool_size, "epoch": self.epoch,
                "workers": states,
                "idle": sum(1 for w in self._workers.values()
                            if w.state == "idle"),
                "healing": [f"{pid}:{s}"
                            for pid, s in sorted(self._healing)],
                "leases_active": len(self._leases),
                "uptime_s": round(now - self._t0, 3),
                "worlds_per_s": self._worlds_per_s_locked(now),
                "worker_pvars": agg,
                "metrics_addr": self.metrics_addr,
                "pools": pools,
                "waiting": len(self._waiters),
                "max_pending": self.max_pending,
                "server_id": self.server_id,
                **self.stats_counters,
            }
        # None (not False) outside a federation: a standalone server
        # must not scrape as a non-leader federation member
        out["is_leader"] = (self.is_leader() if self._fed is not None
                            else None)
        if self._fed_ns is not None:
            # namespace roll-up (store reads; deliberately OUTSIDE the
            # server lock): keeps the Prometheus endpoint truthful
            # when pools move between servers.  Through the MEMBER's
            # own store handle (not the spec) — a raft member serves
            # its local applied state instead of dialing itself
            from . import federation as _federation

            out["federation"] = _federation.federation_stats(
                self._fed.store if self._fed is not None
                else self._fed_ns)
            if self._fed is not None:
                out["store_healthy"] = self._fed.healthy()
        # lease-acquire quantiles from the histogram pvar (log-bucket
        # estimates — mpit.hist_quantile documents the error bound)
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            est = _mpit.hist_quantile("lease_acquire_s", q)
            out[f"lease_acquire_{label}_ms"] = (
                None if est is None else round(est * 1e3, 3))
        return out


# -- the client ---------------------------------------------------------------


class WorldLease:
    """A leased world: run jobs on it, release it when done."""

    def __init__(self, client: "ServerClient", lease_id: int,
                 slots: List[int], epoch: int,
                 pool: Optional[str] = None) -> None:
        self._client = client
        self.lease_id = lease_id
        self.slots = list(slots)
        self.epoch = int(epoch)
        self.pool = pool  # which pool served it (federation takeovers)
        self._released = False

    @property
    def size(self) -> int:
        return len(self.slots)

    def run(self, fn, *args: Any, timeout: Optional[float] = None) -> Any:
        """Execute ``fn(comm, *args)`` on every leased worker (``fn``
        pickled by reference — workers must be able to import it);
        returns lease-rank 0's return value.  Raises the worker-side
        error BY NAME (ProcFailedError & co.) on any failure."""
        reply = self._client._request({
            "op": "run", "lease_id": self.lease_id,
            "fn": pickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL),
            "args": pickle.dumps(args, protocol=pickle.HIGHEST_PROTOCOL),
            "timeout": timeout})
        blob = reply.get("result")
        return pickle.loads(blob) if blob is not None else None

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._client._request({"op": "release",
                                   "lease_id": self.lease_id})

    def __enter__(self) -> "WorldLease":
        return self

    def __exit__(self, *exc) -> None:
        try:
            self.release()
        except (TransportError, OSError):
            pass  # server gone: the lease died with it (and a release
            # failure must never mask the body's real exception)


class ServerClient:
    """Client handle to a resident world server (see :func:`connect`).

    The initial connect retries the TRANSIENT dial failures
    (ConnectionRefusedError AND a connect timeout — ISSUE 15 satellite;
    mpi_tpu/resilience.py TRANSIENT_DIAL_ERRORS) with exponential
    backoff + jitter for up to the ``connect_retry_timeout_s`` mpit
    cvar: a freshly-spawned server (``launcher serve --addr-file``
    races its own bind) and a just-elected federation survivor look
    exactly like a refused/absorbed connection.  Any other failure — or
    one that outlives the budget — raises as before.

    A connection that dies MID-REQUEST raises :class:`ServerLostError`
    (a named TransportError subclass): the server process itself is
    gone, which is what a federated client fails over on."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 priority: int = 0, client_id: Optional[str] = None,
                 dial_retry_s: Optional[float] = None) -> None:
        from .resilience import retry_connect

        self._sock = retry_connect(
            lambda: socket.create_connection((host, port),
                                             timeout=timeout),
            timeout_s=dial_retry_s)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()  # one request/response in flight
        # fair-share identity + default priority (ISSUE 15): the server
        # schedules waiting acquires by (priority, grants-per-client,
        # FIFO) — one uuid per client handle is the ledger key
        self._id = client_id or uuid.uuid4().hex
        self.priority = int(priority)

    def _request(self, msg: dict) -> dict:
        # Bound the reply wait when the caller bounded the op: the
        # server enforces msg["timeout"] itself (acquire/run clamp to
        # world_lease_timeout_s), so a live server's reply — grant,
        # TimeoutError verdict, or any named error — must land within
        # it plus slack.  Without this a SIGSTOP-frozen server (socket
        # ESTABLISHED in the kernel, no reply, no EOF — the PR-15
        # frozen-master class) wedges the client in recv forever; with
        # it the stall surfaces as ServerLostError, which is exactly
        # what a federated client fails over on.  timeout-less ops
        # (stats, release) keep the blocking-read semantics.
        t = msg.get("timeout")
        with self._lock:
            try:
                self._sock.settimeout(float(t) + _RPC_GRACE_S
                                      if t else None)
                _send_msg(self._sock, None, msg)
                reply = _recv_msg(self._sock)
            except OSError as e:
                raise ServerLostError(
                    f"world server connection lost mid-request: "
                    f"{type(e).__name__}: {e}") from e
            finally:
                try:
                    self._sock.settimeout(None)
                except OSError:
                    pass  # socket already dead: the raise above rules
        if reply is None:
            raise ServerLostError("world server closed the connection")
        if "error" in reply:
            _raise_error(reply["error"])
        return reply

    def acquire(self, nranks: int, timeout: Optional[float] = None,
                priority: Optional[int] = None) -> WorldLease:
        """Lease ``nranks`` warm workers as a world: ONE round-trip (the
        server reserves idle slots; no fork, no handshake).  Raises
        TimeoutError when the pool cannot supply them in time, and
        ServerBusyError when the admission queue is at its bound."""
        reply = self._request({
            "op": "acquire", "nranks": int(nranks), "timeout": timeout,
            "client": self._id,
            "priority": self.priority if priority is None
            else int(priority)})
        return WorldLease(self, reply["lease_id"], reply["slots"],
                          reply["epoch"], pool=reply.get("pool"))

    def run(self, fn, *args: Any, nranks: int = 2,
            timeout: Optional[float] = None) -> Any:
        """acquire + run + release in one call (the simple path)."""
        lease = self.acquire(nranks, timeout=timeout)
        try:
            return lease.run(fn, *args, timeout=timeout)
        finally:
            try:
                lease.release()
            except (TransportError, OSError):
                pass  # server gone: must not mask run()'s real error

    def stats(self) -> dict:
        return self._request({"op": "stats"})["stats"]

    def shutdown(self) -> None:
        """Ask the server process to stop (admin surface)."""
        try:
            self._request({"op": "shutdown"})
        except (TransportError, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _parse_hostport(text: str) -> Optional[Tuple[str, int]]:
    host, _, port = text.rpartition(":")
    if not host:
        return None
    try:
        return host, int(port)
    except ValueError:
        return None


def _resolve_addr_file(path: str) -> Tuple[str, int]:
    """Resolve a ``serve --addr-file`` path to (host, port), retrying a
    MISSING or PARTIALLY-WRITTEN file with backoff for up to the
    ``connect_retry_timeout_s`` budget (ISSUE 15 satellite): a
    just-started — or just-elected — server publishing its record
    loses the race against an eager client routinely, and that is the
    same transient the refused-dial retry already heals.  A file that
    never appears (or never parses) within the budget raises a named
    TransportError; budget 0 keeps first-failure raise."""
    from .resilience import backoff_delays

    budget = float(_mpit.cvar_read("connect_retry_timeout_s"))
    deadline = time.monotonic() + budget
    delays = backoff_delays()
    while True:
        content = ""
        try:
            with open(path) as f:
                content = f.read().strip()
        except OSError:
            pass
        got = _parse_hostport(content) if content else None
        if got is not None:
            return got
        if time.monotonic() > deadline:
            raise TransportError(
                f"server address file {path!r} was not published as a "
                f"parseable host:port within {budget}s "
                f"(content {content[:40]!r})")
        time.sleep(min(next(delays), 0.25))


def connect(addr: Any, timeout: float = 30.0, priority: int = 0):
    """Connect to a resident world server — or a FEDERATION of them.

    ``addr`` is one of:

    * ``"host:port"``, a ``(host, port)`` tuple, or an in-process
      :class:`WorldServer` → a plain :class:`ServerClient`;
    * a path to a file containing ``host:port`` (the launcher's
      ``serve --addr-file``) → a :class:`ServerClient`; a missing or
      partially-written file is retried within the
      ``connect_retry_timeout_s`` budget;
    * a path to a DIRECTORY (a ``serve --federation`` namespace) or a
      list of ``"host:port"`` strings → a
      :class:`~mpi_tpu.federation.FederatedClient` that resolves live
      servers and fails acquire/stats over on server death."""
    if isinstance(addr, WorldServer):
        addr = addr.addr
    if isinstance(addr, (tuple, list)):
        # a server LIST only when every element is a "host:port"
        # string; anything else — including the legacy (host, port)
        # tuple whose port arrived as a string ("8080" has no colon) —
        # keeps the single-server meaning
        if addr and all(isinstance(a, str) and ":" in a for a in addr):
            from . import federation as _federation

            return _federation.FederatedClient(
                addrs=list(addr), timeout=timeout, priority=priority)
        host, port = addr[0], int(addr[1])
        return ServerClient(host, port, timeout=timeout,
                            priority=priority)
    text = str(addr)
    if os.path.isdir(text) or text.startswith("raft:"):
        from . import federation as _federation

        return _federation.FederatedClient(
            namespace=text, timeout=timeout, priority=priority)
    direct = None if os.path.exists(text) else _parse_hostport(text)
    if direct is not None:
        host, port = direct
    elif os.path.exists(text) or os.sep in text:
        # an existing file, or a PATH-shaped string that must be a
        # yet-to-be-published addr file: poll it within the budget
        host, port = _resolve_addr_file(text)
    else:
        # neither host:port nor path-shaped: a typo deserves an
        # immediate diagnostic, not a silent poll of the full budget
        raise ValueError(
            f"connect: {text!r} is neither a host:port address nor a "
            f"path to an addr file / federation namespace")
    return ServerClient(host, port, timeout=timeout, priority=priority)


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--worker":
        return _worker_main()
    ap = argparse.ArgumentParser(
        prog="mpi_tpu.launcher serve",
        description="resident world server: pool warm workers, lease "
                    "worlds to clients, self-heal under kill injection")
    ap.add_argument("--pool-size", type=int, default=_POOL_SIZE)
    ap.add_argument("--backend", choices=("socket", "shm"),
                    default="socket")
    ap.add_argument("--host", default=_HOST)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--addr-file", default=None,
                    help="write host:port here once listening "
                         "(clients: mpi_tpu.connect(path))")
    ap.add_argument("--detect-timeout", type=float,
                    default=_DETECT_TIMEOUT_S,
                    help="pool-internal ULFM detection bound (s)")
    ap.add_argument("--heartbeat", type=float, default=_HEARTBEAT_S)
    ap.add_argument("--lease-timeout", type=float,
                    default=_WORLD_LEASE_TIMEOUT_S,
                    help="world_lease_timeout_s: max wait for idle "
                         "workers / default job bound")
    ap.add_argument("--rejoin-timeout", type=float,
                    default=_REJOIN_TIMEOUT_S,
                    help="rejoin_timeout_s of one healing handshake")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve GET /metrics (Prometheus text format: "
                         "worlds/s, lease p50/p99, pool epoch, per-"
                         "worker health, aggregated worker pvars) on "
                         "this HTTP port; 0 binds an ephemeral port "
                         "(printed at startup)")
    ap.add_argument("--federation", default=None, metavar="SPEC",
                    help="join a federation namespace "
                         "(mpi_tpu/federation.py): a shared DIR "
                         "(FileStore — single host/NFS), or "
                         "raft:<idx>@h0:p0,h1:p1,... to embed store "
                         "node <idx> of a replicated quorum group "
                         "(mpi_tpu/federation_store.py — N hosts, no "
                         "shared FS; a partitioned minority refuses "
                         "leases with NoQuorumError).  N servers share "
                         "endpoint records + a CAS leader lease; a "
                         "dead server's pool is adopted by a survivor "
                         "and its workers re-register there; clients "
                         "connect(DIR | raft:h0:p0,...) and fail over")
    ap.add_argument("--server-id", default=None,
                    help="federation identity (default: random "
                         "srv-<hex8>)")
    ap.add_argument("--fed-lease-timeout", type=float,
                    default=_FED_LEASE_TIMEOUT_S, metavar="S",
                    help="leader-lease takeover bound; authority "
                         "self-expires at half this (the split-brain "
                         "safety margin)")
    ap.add_argument("--max-pending", type=int, default=_MAX_PENDING,
                    help="bounded admission queue depth: acquires "
                         "beyond this many waiters are rejected with "
                         "ServerBusyError instead of queueing "
                         "unboundedly")
    ap.add_argument("--orphan-timeout", type=float,
                    default=_ORPHAN_TIMEOUT_S, metavar="S",
                    help="how long an orphaned worker polls the "
                         "federation namespace for its pool's new "
                         "owner before exiting")
    args = ap.parse_args(argv)
    server = WorldServer(
        pool_size=args.pool_size, backend=args.backend, host=args.host,
        port=args.port, detect_timeout_s=args.detect_timeout,
        heartbeat_s=args.heartbeat,
        world_lease_timeout_s=args.lease_timeout,
        rejoin_timeout_s=args.rejoin_timeout,
        metrics_port=args.metrics_port,
        federation=args.federation, server_id=args.server_id,
        fed_lease_timeout_s=args.fed_lease_timeout,
        max_pending=args.max_pending,
        orphan_timeout_s=args.orphan_timeout)
    server.start()
    print(f"mpi_tpu serve: listening on {server.addr} "
          f"(pool {args.pool_size} x {args.backend})", flush=True)
    if args.federation:
        print(f"mpi_tpu serve: federation member {server.server_id} "
              f"in {args.federation}", flush=True)
    if server.metrics_addr:
        print(f"mpi_tpu serve: metrics on "
              f"http://{server.metrics_addr}/metrics", flush=True)
    if args.addr_file:
        tmp = args.addr_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(server.addr)
        os.replace(tmp, args.addr_file)
    try:
        while not server._closing:
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
