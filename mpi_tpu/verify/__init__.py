"""MUST-style runtime correctness verifier + static MPI linter.

The correctness-tooling layer of SURVEY.md §5: the framework's failure
modes are hangs (mismatched blocking cycles), silently divergent
collective schedules, and leaked/raced nonblocking requests — exactly
the bug classes MUST-class MPI verifiers and message-race detectors
catch.  This package grows the repo's seed (mpi_tpu/checker.py schedule
validation + mpi_tpu/trace.py matching verification, both re-exported
here) into a real subsystem:

* **Deadlock detection** (:mod:`.deadlock`): every verified blocking
  wait runs in slices (the FT slice-poll plumbing); past
  ``verify_stall_timeout_s`` the rank publishes its pending op
  out-of-band and the AND-OR wait-for analysis
  (:func:`mpi_tpu.checker.find_deadlock`) turns a closed blocking
  picture into :class:`~mpi_tpu.errors.DeadlockError` naming every
  rank, its pending op, and its call site — instead of a hang.
* **Collective matching** (:mod:`.collcheck`): per-entry signatures
  (sequence, name, root, reduce op, geometry class, algorithm, vector
  counts) cross-checked in-band on the reserved TAG_VERIFY ring before
  any data moves; divergence raises
  :class:`~mpi_tpu.errors.CollectiveMismatchError` on every rank.
* **Request/resource lints** (:mod:`.state`): leaked requests
  (GC'd/finalized unwaited), double-wait, overlapping live buffers
  across pending nonblocking ops (the message-race case), and unfreed
  communicators — reported through ``verify_*`` pvars and the
  finalize-time report (:func:`take_report` / :func:`finalize_report`).
* **Static lint v2** (:mod:`.lint` on the :mod:`.dataflow` +
  :mod:`.commgraph` engine, CLI ``tools/mpilint.py``): rules
  MPL001–MPL009 — collective schedule divergence, send-send cycles,
  count truncation, revoked-comm use, unwaited nonblocking requests,
  buffer reuse under a live request, unmatchable tag pairs,
  rank-dependent collective loops, and racy ``ANY_SOURCE`` receives —
  now firing on SYMBOLIC ranks (``r = c.rank``, ``(c.rank + 1) %
  c.size``, rank-guarded helpers) via guard-chain + constant/rank
  propagation, not just literals.
* **Wildcard-race detection** (:mod:`.vclock`): verify mode piggybacks
  a per-rank vector clock on every frame; an ``ANY_SOURCE`` receive
  that consumes a message CONCURRENT with another eligible pending
  sender (no happens-before edge between the sends) is reported as a
  named nondeterminism race — the ``verify_wildcard_races`` pvar, a
  finalize report line naming both candidate senders, and a trace
  event.  MPL009's static "maybe", observed at runtime.

Enable with ``MPI_TPU_VERIFY=1`` under the launcher (or
``python -m mpi_tpu.launcher --verify``), ``run_local(...,
verify=True)``, or :func:`enable` on any P2P communicator.  Off (the
default) the entire subsystem is a single ``is None`` attribute test
per operation — the zero-copy hot path's pvar contracts and bench p50s
are untouched (``bench.py --verify-overhead`` proves it).
"""

from __future__ import annotations

import os
from typing import Optional

from ..checker import ScheduleError, find_deadlock, validate_perm, \
    validate_rounds, verify_matching
from ..errors import CollectiveMismatchError, DeadlockError
from ..trace import TracingTransport, verify_run
from . import state as _state
from .collcheck import TAG_VERIFY
from .lint import Finding, lint_file, lint_paths, lint_source
from .state import (CommVerify, FileBoard, MemoryBoard, WorldVerify,
                    finalize_report, peek_report, take_report, user_site)
from .vclock import VClock

__all__ = [
    "enable", "is_enabled", "take_report", "peek_report", "finalize_report",
    "user_site",
    "MemoryBoard", "FileBoard", "WorldVerify", "CommVerify", "VClock",
    "DeadlockError", "CollectiveMismatchError", "TAG_VERIFY",
    "Finding", "lint_source", "lint_file", "lint_paths",
    # the folded-in seed: schedule checking + trace-based matching
    "ScheduleError", "validate_perm", "validate_rounds", "verify_matching",
    "find_deadlock", "verify_run", "TracingTransport",
]


def is_enabled(comm) -> bool:
    return getattr(comm, "_verify", None) is not None


def enable(comm, board=None, rdv_dir: Optional[str] = None,
           stall_timeout_s: Optional[float] = None):
    """Enable the runtime verifier on a P2P communicator (idempotent per
    transport; split/dup children inherit).  Process worlds default to
    ``pending.<rank>`` files under the rendezvous dir (``rdv_dir`` or
    the launcher's MPI_TPU_RDV); in-process worlds pass the shared
    :class:`MemoryBoard` (``run_local(..., verify=True)`` does this for
    you)."""
    if getattr(comm, "_verify", None) is not None:
        return comm
    world = getattr(comm._t, "_verify_world", None)
    if world is None:
        if board is None:
            rdv = rdv_dir or os.environ.get("MPI_TPU_RDV")
            if rdv is None:
                raise ValueError(
                    "the verifier needs an out-of-band board: pass board= "
                    "(in-process worlds) or rdv_dir= / set MPI_TPU_RDV "
                    "(process worlds)")
            board = FileBoard(rdv, comm._t.world_rank, comm._t.world_size)
        world = WorldVerify(
            comm._t, board,
            _state._STALL_TIMEOUT_S if stall_timeout_s is None
            else stall_timeout_s)
        comm._t._verify_world = world
    _attach_clock(comm._t)
    comm._verify = CommVerify(world)
    return comm


def _attach_clock(transport) -> None:
    """Attach one per-rank :class:`VClock` to the transport stack (the
    wildcard-race detector's send stamp + consume merge).  Wrapper
    transports (FaultyTransport, TracingTransport) delegate ``send`` to
    their inner transport, so the clock must sit on EVERY layer down the
    ``inner`` chain — they all share one mailbox, which gets the same
    clock as its consume-side merge point.  Idempotent."""
    t = transport
    if getattr(t, "verify_clock", None) is not None:
        return
    vc = VClock(t.world_rank, t.world_size)
    seen = set()
    while t is not None and id(t) not in seen:
        seen.add(id(t))
        t.verify_clock = vc
        mb = getattr(t, "mailbox", None)
        if mb is not None:
            mb.clock = vc
        t = getattr(t, "inner", None) or getattr(t, "_inner", None)
