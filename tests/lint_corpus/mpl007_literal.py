"""Seeded bug: the matched pair's tags can never meet (5 vs 6)."""


def main(comm):
    if comm.rank == 0:
        comm.send(b"m", 1, tag=5)
    elif comm.rank == 1:
        return comm.recv(0, tag=6)
    return None
