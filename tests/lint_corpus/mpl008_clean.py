"""Near-miss twin: same loop shape, rank-independent trip count."""


def main(comm):
    n = 4
    for _ in range(n):
        comm.barrier()
