"""Near-miss twin: every rank reaches the collective, through the same
symbolic guard shape as the buggy variant."""


def main(comm, data):
    r = comm.rank
    if r == 0:
        out = comm.bcast(data, root=0)
    else:
        out = comm.bcast(None, root=0)
    return out
