"""Near-miss twin: both paths complete the request."""


def main(comm, flag):
    req = comm.irecv(0, tag=1)
    if flag:
        return req.wait()
    done, value = req.test()
    if not done:
        value = req.wait()
    return value
