"""Runtime wildcard-race detection via piggybacked vector clocks.

The static rule MPL009 can only say "maybe": an ``ANY_SOURCE`` receive
with two eligible senders *might* match either.  Verify mode closes the
loop dynamically.  Every rank carries a vector clock; under verify each
send ticks the sender's component and ships the stamp with the message
(wrapped around the wire ctx by the transports, or passed straight to
the mailbox on same-process paths).  When a receive consumes a message
the receiver merges the stamp (componentwise max, then ticks itself),
so the clocks encode the happens-before order of the run.

The race check rides the one place that can see every alternative: the
mailbox consume scan.  When a *wildcard* receive (user tag) consumes a
message, any other pending message from a different sender that the same
receive could have matched is compared against the winner — if the two
send stamps are **concurrent** (neither ≤ the other componentwise, i.e.
no chain of messages ordered one send before the other), the match order
was decided by arrival timing alone and is reported as a named
nondeterminism race: the ``verify_wildcard_races`` pvar, a finalize
report line naming both candidate senders, and a trace event.

Off verify mode nothing here runs: transports hold ``verify_clock is
None`` and the mailbox holds ``clock is None`` — one ``is None`` test
per operation, and both pvars stay exactly 0.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from .. import mpit as _mpit
from .. import telemetry as _telemetry
from .state import report_add, user_site

# Wire marker for a stamped ctx: ("__mpi_tpu_vclock__", stamp, real_ctx).
# Only remote paths wrap (the stamp must survive pickling/framing);
# same-process deliveries hand the stamp to the mailbox directly.
_VC_MARK = "__mpi_tpu_vclock__"


def _concurrent(a: Sequence[int], b: Sequence[int]) -> bool:
    """Neither stamp happens-before the other."""
    a_le_b = all(x <= y for x, y in zip(a, b))
    b_le_a = all(y <= x for x, y in zip(a, b))
    return not a_le_b and not b_le_a


class VClock:
    """One rank's vector clock plus the race bookkeeping.

    Attached by :func:`mpi_tpu.verify.enable` as ``transport.verify_clock``
    (send-side stamping) and ``mailbox.clock`` (consume-side merge +
    race check).  All methods are self-contained so the transports need
    no verify imports — they only ever test ``verify_clock is None``.
    """

    def __init__(self, rank: int, size: int) -> None:
        self.rank = int(rank)
        self.size = int(size)
        self._vec: List[int] = [0] * self.size
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._seen = set()  # (lo_src, hi_src, tag) already reported
        self.races = 0      # this world's count (pvar aggregates globally)

    # -- send side ---------------------------------------------------------

    def tick_send(self) -> Tuple[int, ...]:
        """Advance our component and return the stamp to ship (8 bytes
        per component, priced by the verify_clock_bytes pvar)."""
        with self._lock:
            self._vec[self.rank] += 1
            stamp = tuple(self._vec)
        _mpit.count(verify_clock_bytes=8 * self.size)
        return stamp

    def wrap(self, ctx):
        """Stamp a wire-bound ctx (socket/shm framing paths)."""
        return (_VC_MARK, self.tick_send(), ctx)

    @staticmethod
    def unwrap(ctx):
        """(real_ctx, stamp-or-None) — reader side, right after parse,
        BEFORE steering consults keyed on the real ctx."""
        if isinstance(ctx, tuple) and len(ctx) == 3 and ctx[0] == _VC_MARK:
            return ctx[2], ctx[1]
        return ctx, None

    # -- receive-site attribution ------------------------------------------

    def set_site(self, site: Optional[str]) -> None:
        """Record the user call site of the wildcard receive the current
        thread is about to consume for (race message attribution)."""
        self._tls.site = site

    # -- consume side ------------------------------------------------------

    def note_consume(self, src: int, tag: int, stamp,
                     alternates: Sequence[Tuple[int, object]],
                     wildcard: bool) -> None:
        """Merge a consumed message's stamp into this rank's clock; when
        the consume was a wildcard match, compare the winner against
        every other pending eligible sender and report concurrent pairs.

        Called under the mailbox lock (the only place that can see the
        full alternate set atomically with the match decision); only
        leaf locks (mpit, report, trace ring) are taken below it.
        """
        if not isinstance(stamp, tuple) or len(stamp) != self.size:
            return  # stamp from a different world geometry: advisory only
        races = []
        if wildcard:
            for alt_src, alt_stamp in alternates:
                if alt_src == src:
                    continue
                if not isinstance(alt_stamp, tuple) \
                        or len(alt_stamp) != self.size:
                    continue
                if _concurrent(stamp, alt_stamp):
                    races.append(alt_src)
        with self._lock:
            for i, v in enumerate(stamp):
                if v > self._vec[i]:
                    self._vec[i] = v
            self._vec[self.rank] += 1
            fresh = []
            for alt_src in races:
                key = (min(src, alt_src), max(src, alt_src), tag)
                if key not in self._seen:
                    self._seen.add(key)
                    fresh.append(alt_src)
            self.races += len(fresh)
        for alt_src in fresh:
            self._report(src, alt_src, tag)

    def _report(self, src: int, alt_src: int, tag: int) -> None:
        site = getattr(self._tls, "site", None) or user_site()
        tag_s = "ANY_TAG" if tag == -1 else str(tag)
        report_add(
            f"wildcard race: recv(ANY_SOURCE, tag={tag_s}) at rank "
            f"{self.rank} matched the message from rank {src} while a "
            f"CONCURRENT message from rank {alt_src} was also eligible "
            f"(no happens-before edge between the two sends) — the match "
            f"order is nondeterministic; order the senders or receive by "
            f"explicit source [{site}]")
        _mpit.count(verify_wildcard_races=1)
        rec = _telemetry.recorder()
        if rec is not None:
            rec.emit("verify", "wildcard_race", attrs={
                "rank": self.rank, "matched_src": src,
                "concurrent_src": alt_src, "tag": tag, "site": site})
