"""Receive-side zero-copy (ISSUE 17): size-classed recv-pool +
posted-irecv registry for rendezvous steering.

PR 11 closed the send half of the socket hot path (refcounted
``BufRef`` retention, one vectored ``sendmsg`` per frame); this module
is the receive twin, in the UCX registration-cache / NCCL
receive-pool shape:

* :class:`RecvPool` — recycles large receive buffers between messages
  in POWER-OF-TWO SIZE CLASSES (floor ``min_bytes``), so a 3.5MB
  segment and a 4MB segment share the same already-faulted 4MB
  backing buffer instead of keying exact byte counts.  At bandwidth
  sizes the receiver's dominant cost on this class of box is not the
  copy but the PAGE FAULTS of touching a freshly-mmapped destination
  (measured on the 16MB stream: 48.8k minor faults, 84ms system time
  of a 120ms wall — glibc munmaps large frees, so every message pays
  one fault per 4KB page).  A buffer is recycled only when proven
  unreachable: a ``weakref.finalize`` on the handed-out view fires
  after collection and re-checks the backing buffer's refcount, so a
  still-alive user alias (numpy collapses ``.base`` chains onto the
  backing buffer) vetoes the recycle.  Priced by the
  ``recv_pool_hits`` / ``recv_pool_misses`` pvars.

* :class:`PostedRecvRegistry` — the rendezvous half.  Every INTERNAL
  receive (negative tag, specific source) is counted on its
  ``(source, context, tag)`` channel in program order: posted irecvs
  via :meth:`note_post` (which returns a token the collective can
  :meth:`attach` a destination view to), blocking recvs via
  :meth:`note_consume`.  The socket reader counts fresh data frames on
  the same channel — and because the resilient link delivers frames in
  sequence order and collectives consume a channel in program order,
  the Nth fresh frame on a channel belongs to the Nth counted
  consumer.  When that consumer is a posted irecv with an attached
  destination of matching geometry, :meth:`note_frame` returns the
  destination and the reader ``recv_into``s the body DIRECTLY into the
  posted buffer (``recv_bytes_steered`` / ``recv_pool_rendezvous``) —
  zero intermediate copy, and mailbox delivery of the very view object
  the fold site owns turns the final store into pointer-passing.
  Everything else (no posted buffer yet, geometry mismatch, compressed
  or multi-segment or pickled payloads, steering disabled) takes the
  pool-fallback path.

Correctness invariants (the reasons this is safe under replay/chaos):

* Counting is gated on ``LinkState.rx_fresh`` — a frame is counted
  only when it is the next in-sequence frame of the CURRENT stream
  generation, i.e. exactly the frames ``rx_gate`` will deliver, in
  delivery order.  Duplicates, stale generations, and out-of-order
  gap frames are never counted.
* A per-channel ``(generation, seq)`` watermark dedups the race where
  an old connection's drain and a new connection's replay present the
  same frame concurrently, and the case where a frame was counted but
  its connection died mid-body — the replay re-presentation is NOT
  recounted and takes the pool path, while the fold-site store
  overwrites any partial bytes the torn steer left behind (replay is
  bit-exact by the CoW retention contract, so even a completed-then-
  dropped duplicate steer writes the same bytes the consumer reads).
* ``purge_src`` (membership removal) clears a source's channels and
  resyncs arrivals to posts: the purged stream's in-flight frames
  died with it, and the watermark is fenced to the bumped generation
  so stragglers from the old incarnation can never count.
* A posted irecv that is cancelled (``_unpost``) removes its entry;
  an entry whose frame passed while it had no destination is dropped
  lazily.  A missed pairing therefore only ever costs steering (pool
  fallback), never correctness.

``recv_steering`` (cvar / MPI_TPU_RECV_STEERING) disables CLAIMING
only: channel accounting stays on so toggling mid-run cannot desync
the pairing, and the pre/post benches keep identical frame paths.

ISSUE 19 extends the registry from "socket, internal tags only" to the
whole receive plane:

* both byte-stream transports consult it — the shm ring drain steers
  an in-order frame straight from the ring into the posted view
  (transport/shm.py synthesizes the per-src (gen, seq) the ring frames
  don't carry);
* USER channels (tag >= 0) activate on the first ``irecv(buf=...)`` /
  started ``recv_init`` handle (:meth:`note_post_user`) — and because
  user matching admits wildcards, matched probes, and undisciplined
  blocking receives, every claimed user view carries an ALIASING GUARD
  (:class:`_LiveSteer`): the owner's pop is identity (zero-copy), any
  other consumer's pop is a private copy, and an owner that completes
  without its view rescues the steered bytes first.  Mispairing is
  therefore a performance event (``recv_user_fallbacks``), never a
  correctness event;
* multi-segment destinations (:meth:`attach` with a view list) match
  ``"segs"`` plans per segment, so the socket reader lands a
  multi-segment frame with one vectored ``recvmsg_into`` across the
  posted views (scatter-gather receive, the mirror of the PR 11
  single-``sendmsg`` send).
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from collections import deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import mpit as _mpit
from . import telemetry as _telemetry


def _env_flag(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return 1 if int(v) else 0
    except ValueError:
        return default


# Rendezvous claiming on/off (the ``recv_steering`` cvar seeds/reads
# this).  Accounting is NOT gated on it — see module docstring.
_STEERING = _env_flag("MPI_TPU_RECV_STEERING", 1)


def _copy_steered(obj):
    """Private snapshot of a steered user destination (single view or
    the multi-segment view list)."""
    if isinstance(obj, list):
        return [a.copy() for a in obj]
    return obj.copy()


class RecvPool:
    """Size-classed recycling pool for receive buffers (see module
    docstring).  API-compatible with the exact-size pool it replaces
    (``transport.codec._BufferPool``): ``empty(shape, dtype)`` returns
    a writable array the caller owns indefinitely."""

    def __init__(self, min_bytes: int = 1 << 20,
                 max_total: int = 256 << 20, max_per_size: int = 3):
        self._min, self._max_total = min_bytes, max_total
        self._max_per_size = max_per_size
        self._free: dict = {}      # class nbytes (pow2) -> [uint8 arrays]
        self._total = 0
        # RLock: _maybe_recycle runs inside weakref.finalize callbacks; a
        # cyclic-GC collection triggered while the lock is held can run
        # ANOTHER pooled array's finalizer on the same thread — a plain
        # Lock would self-deadlock there
        self._lock = threading.RLock()
        # Self-calibrate the no-alias refcount through the EXACT
        # production path (a hand-derived constant broke the alias veto:
        # the finalize registry's ref structure is an implementation
        # detail).  CPython fires the finalize synchronously when the
        # probe's refcount hits zero, so _maybe_recycle records the
        # baseline inline.  The probe is not priced in the pool pvars.
        self._baseline: Optional[int] = None
        self._counting = False
        probe = self.empty((self._min,), np.dtype(np.uint8))
        del probe
        if self._baseline is None:  # pragma: no cover - non-refcount VM
            self._baseline = -1     # disables recycling (pool = plain empty)
        self._counting = True

    @staticmethod
    def class_bytes(nbytes: int) -> int:
        """The pow2 size class a request of ``nbytes`` draws from."""
        return 1 << max(0, (int(nbytes) - 1).bit_length())

    def empty(self, shape, dtype: np.dtype) -> np.ndarray:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dtype.itemsize
        if nbytes < self._min:
            return np.empty(shape, dtype)
        cls = self.class_bytes(nbytes)
        with self._lock:
            stack = self._free.get(cls)
            buf = stack.pop() if stack else None
            if buf is not None:
                self._total -= cls
        hit = buf is not None
        if buf is None:
            buf = np.empty(cls, np.uint8)
        sub = buf if nbytes == cls else buf[:nbytes]
        arr = sub.view(dtype).reshape(shape)
        weakref.finalize(arr, self._maybe_recycle, buf)
        if self._counting:
            if hit:
                _mpit.count(recv_pool_hits=1)
            else:
                _mpit.count(recv_pool_misses=1)
        return arr

    def _maybe_recycle(self, buf: np.ndarray) -> None:
        refs = sys.getrefcount(buf)
        if self._baseline is None:
            self._baseline = refs  # calibration probe, not recycled
            return
        # anything beyond the calibrated no-alias baseline is a live user
        # alias (numpy collapses subview .base chains onto the backing
        # buffer): drop the buffer instead of recycling aliased memory
        if self._baseline < 0 or refs > self._baseline:
            return
        nbytes = buf.nbytes  # class size: pooled bufs are allocated per class
        with self._lock:
            stack = self._free.setdefault(nbytes, [])
            if (len(stack) < self._max_per_size
                    and self._total + nbytes <= self._max_total):
                stack.append(buf)
                self._total += nbytes


class _Entry:
    __slots__ = ("idx", "dest", "ds", "shape", "segs", "user", "declined")

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.dest = None                    # ndarray, or list of ndarrays
        self.ds: Optional[str] = None
        self.shape: Tuple[int, ...] = ()
        # multi-segment destination (list attach): per-segment
        # (dtype_str, shape) descriptors in fill order — matched against
        # a "segs" plan's descs for scatter-gather steering (ISSUE 19)
        self.segs: Optional[Tuple] = None
        # a USER-buffer entry (irecv(buf=)/recv_init): its claimed views
        # enter the _live aliasing-guard set (see PostedRecvRegistry)
        self.user = False
        # the poster looked at its destination and it was NOT steering
        # eligible (non-contiguous / read-only): a later dest-less
        # match is a decision, not a lost race — don't count it
        self.declined = False


class _Channel:
    __slots__ = ("posted", "arrived", "wm", "entries", "lag", "user")

    def __init__(self) -> None:
        self.posted = 0    # consumers counted (posted irecvs + blocking recvs)
        self.arrived = 0   # fresh data frames counted (+ self-send deliveries)
        self.wm: Tuple[int, int] = (0, 0)   # (gen, seq) counting watermark
        self.entries: deque = deque()       # outstanding posted-irecv entries
        # USER channels only (tag >= 0, activated by the first
        # irecv(buf=)): frames that were already DELIVERED before
        # activation were never counted, so the Nth counted arrival is
        # really the (N + lag)th thing the mailbox hands out — pairing
        # indexes consumers at arrived + lag.  A matched-probe steal
        # (mprobe removes a message from matching) shifts it back down.
        # Internal channels keep lag == 0 and behave exactly as before.
        self.lag = 0
        self.user = False


class _LiveSteer:
    """Aliasing guard for ONE claimed user destination: tracks the view
    (or list) from reader claim to consumer pop, so a mispaired pop —
    wildcard receive, matched probe, an out-of-order blocking recv, a
    heal that re-routed the frame — costs a COPY, never correctness
    (see PostedRecvRegistry.sanitize / pre_overwrite)."""

    __slots__ = ("obj", "writing", "sanitized", "owner_done", "rescue")

    def __init__(self, obj) -> None:
        self.obj = obj
        self.writing = True      # reader body-read in progress
        self.sanitized = False   # a foreign consumer already took a copy
        self.owner_done = False  # owner completed WITHOUT the view
        self.rescue = None       # owner-made snapshot for a later popper


class PostedRecvRegistry:
    """Pairs fresh inbound frames with posted internal irecvs by
    per-channel arrival/post order (see module docstring).  One per
    steering transport; all methods are thread-safe and cheap (one
    small critical section)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ch: Dict[Tuple[Any, Any, int], _Channel] = {}
        # user-buffer rendezvous (ISSUE 19): activated user channels and
        # the live claimed-view guard set.  The two bare ints are GIL-
        # safe fast-path gates — readers and completion sites skip the
        # lock entirely while the feature is unused.
        self._user_keys: set = set()
        self.user_count = 0
        self._live: Dict[int, _LiveSteer] = {}
        self.live_count = 0

    def _chan(self, src, ctx, tag) -> _Channel:
        key = (src, ctx, tag)
        ch = self._ch.get(key)
        if ch is None:
            ch = self._ch[key] = _Channel()
        return ch

    # -- consumer side (communicator / nbc) ---------------------------------

    def note_post(self, src, ctx, tag):
        """Count a posted internal irecv on its channel; returns a token
        for :meth:`attach` / :meth:`cancel`."""
        with self._lock:
            ch = self._chan(src, ctx, tag)
            ch.posted += 1
            e = _Entry(ch.posted)
            ch.entries.append(e)
            return ((src, ctx, tag), e)

    def note_consume(self, src, ctx, tag) -> None:
        """Count a BLOCKING internal recv (a consumer with nothing to
        steer into — keeps the channel indices aligned)."""
        with self._lock:
            self._chan(src, ctx, tag).posted += 1

    def note_post_user(self, src, ctx, tag, backlog: int = 0,
                       claimable: bool = True):
        """Count a posted USER irecv (``irecv(buf=...)`` / a started
        ``recv_init`` handle) on its channel, ACTIVATING the channel on
        first use: from here on the reader counts this channel's fresh
        frames exactly like an internal channel's.  ``backlog`` is the
        number of already-delivered (never counted) messages queued for
        this envelope at activation time — it seeds the pairing lag so
        the first counted frame pairs with the right consumer even when
        the sender raced ahead of the first posted buffer.
        ``claimable=False`` counts a BUFFERLESS user irecv posted on an
        already-active channel (alignment only — its pool fold is a
        decision, not a lost race, so it never ticks the fallback
        pvar); a later :meth:`attach` re-arms it."""
        with self._lock:
            ch = self._chan(src, ctx, tag)
            if not ch.user:
                ch.user = True
                ch.lag = backlog
                self._user_keys.add((src, ctx, tag))
                self.user_count = len(self._user_keys)
            ch.posted += 1
            e = _Entry(ch.posted)
            e.user = True
            e.declined = not claimable
            ch.entries.append(e)
            return ((src, ctx, tag), e)

    def user_active(self, src, ctx, tag) -> bool:
        """Whether a user channel was activated (reader counting gate +
        the blocking-recv note_consume gate).  Callers pre-gate on the
        bare ``user_count`` int so the common no-user-steering run never
        pays a lock here."""
        if not self.user_count:
            return False
        return (src, ctx, tag) in self._user_keys

    def note_steal(self, src, ctx, tag) -> None:
        """A matched probe (mprobe/improbe) REMOVED a message from this
        envelope's matching queue: later consumers each shift one
        message earlier, so the pairing lag drops by one.  Best-effort —
        any residual mispairing is caught by the sanitize/rescue guard,
        costing a copy, never correctness."""
        if not self.user_count:
            return
        with self._lock:
            ch = self._ch.get((src, ctx, tag))
            if ch is not None and ch.user:
                ch.lag -= 1

    def attach(self, token, dest) -> None:
        """Give a posted irecv's entry a destination the reader may
        steer into: a single view (matched against single-array frames)
        or a LIST of views (matched per-segment against multi-segment
        frames — the scatter-gather receive, ISSUE 19).  Only
        store-destination views qualify (contiguous, writable, filled
        by a plain assignment at the fold site)."""
        _key, e = token
        if isinstance(dest, list):
            if not all(isinstance(a, np.ndarray) and a.flags.writeable
                       and a.flags.c_contiguous for a in dest):
                with self._lock:
                    e.declined = True
                return
            with self._lock:
                e.dest = dest
                e.segs = tuple((a.dtype.str, tuple(a.shape)) for a in dest)
                e.declined = False
            return
        if not (dest.flags.writeable and dest.flags.c_contiguous):
            with self._lock:
                e.declined = True
            return
        with self._lock:
            e.dest = dest
            e.ds = dest.dtype.str
            e.shape = tuple(dest.shape)
            e.declined = False

    def cancel(self, token) -> None:
        """Remove a posted irecv's entry (``_unpost`` / failure paths),
        so a frame that never came cannot leave a stale claimable entry."""
        if token is None:
            return
        key, e = token
        with self._lock:
            ch = self._ch.get(key)
            if ch is not None:
                try:
                    ch.entries.remove(e)
                except ValueError:
                    pass

    # -- producer side (socket reader / self-send) --------------------------

    def note_frame(self, src, ctx, tag, seq: int, gen: int,
                   plan=None) -> Optional[np.ndarray]:
        """Count one FRESH data frame (caller must have checked
        ``LinkState.rx_fresh``); returns the posted destination to steer
        into when the paired consumer has one of matching geometry,
        else None (pool path).  ``plan`` is the codec's parsed meta
        (``("arr", dtype_str, shape)`` for the steerable single-array
        frames, anything else for the rest).

        A steerable frame that found NO destination because it lost
        the reader-vs-poster race (the frame outran the post, or the
        post outran its ``attach``) folds through the pool and is
        counted in the ``recv_pool_fold_fallbacks`` pvar (+ a trace
        instant) — ISSUE 18 satellite, the ISSUE 17 residual (c).
        Visibility only: nothing about the fold path itself changes,
        and the deterministic ``payload_copies`` accounting is
        untouched."""
        fold_race = False
        try:
            with self._lock:
                ch = self._chan(src, ctx, tag)
                if (gen, seq) <= ch.wm:
                    return None   # replay re-presentation: already counted
                ch.wm = (gen, seq)
                ch.arrived += 1
                # user channels: the Nth counted arrival is consumer
                # N + lag (pre-activation backlog / probe steals)
                j = ch.arrived + ch.lag
                q = ch.entries
                while q and q[0].idx < j:
                    q.popleft()   # stale: their frames already passed
                steerable = (_STEERING and plan is not None
                             and plan[0] in ("arr", "segs"))
                if not q or q[0].idx != j:
                    # no entry for this arrival: a genuine lost race
                    # only when NO consumer was counted yet (posted <
                    # j — the reader beat the poster); an entry-less
                    # match with posted >= j is a blocking recv, which
                    # never steers by design
                    fold_race = steerable and ch.posted < j
                    return None
                e = q.popleft()
                if e.dest is None or not steerable \
                        or not self._plan_fits(e, plan):
                    # dest-less entry: the irecv was POSTED but its
                    # attach() hadn't landed when the frame arrived —
                    # the other flavor of the same race (unless the
                    # poster explicitly declined an ineligible dest,
                    # which is a decision, not a race)
                    fold_race = (steerable and e.dest is None
                                 and not e.declined)
                    return None
                if e.user:
                    # aliasing guard: the claimed USER view is tracked
                    # from here until its consumer pops it.  A prior
                    # lifecycle of the same buffer still open (a broken
                    # round awaiting its foreign popper) declines the
                    # claim rather than corrupt the guard.
                    if id(e.dest) in self._live:
                        return None
                    self._live[id(e.dest)] = _LiveSteer(e.dest)
                    self.live_count = len(self._live)
                return e.dest
        finally:
            if fold_race:
                # outside the lock: pvar + trace instant
                _mpit.count(recv_pool_fold_fallbacks=1)
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("recvpool", "fold_fallback",
                             attrs={"src": src, "tag": tag})

    @staticmethod
    def _plan_fits(e: _Entry, plan) -> bool:
        """Geometry-exact match of a steerable plan against an entry's
        attached destination (single view vs "arr", view list vs
        "segs" — per segment)."""
        if plan[0] == "arr":
            return (e.segs is None and e.ds == plan[1]
                    and e.shape == tuple(plan[2]))
        if e.segs is None or len(e.segs) != len(plan[1]):
            return False
        return all(ds == eds and tuple(shape) == eshape
                   for (ds, shape), (eds, eshape) in zip(plan[1], e.segs))

    def note_local(self, src, ctx, tag) -> None:
        """Count a self-send delivery (value-copy path, never steered) so
        loopback traffic on a registered channel keeps indices aligned."""
        with self._lock:
            ch = self._chan(src, ctx, tag)
            ch.arrived += 1
            j = ch.arrived + ch.lag
            q = ch.entries
            while q and q[0].idx <= j:
                q.popleft()

    # -- user-buffer aliasing guard (ISSUE 19) ------------------------------
    #
    # A USER claim writes frame bytes into a buffer the application
    # owns, and the mailbox is a scan-queue: a wildcard receive, a
    # matched probe, or an out-of-order blocking recv can legally pop
    # the steered view instead of the buffer's own request.  The guard
    # turns every such mispairing into a copy: the reader brackets the
    # body read with steer_done/steer_abort, every user-facing
    # completion runs its payload through sanitize (identity for the
    # owner, a private copy for anyone else), and an armed owner that
    # completes WITHOUT its view first rescues the steered bytes
    # (pre_overwrite) so a later popper still reads the right data.
    # All transitions serialize on the registry condition variable;
    # whoever arrives second sees the first's state.

    def steer_done(self, obj) -> None:
        """Reader: the claimed user destination's body read finished —
        the view is about to be delivered."""
        with self._cv:
            ls = self._live.get(id(obj))
            if ls is not None and ls.obj is obj:
                ls.writing = False
                self._cv.notify_all()

    def steer_abort(self, obj) -> None:
        """Reader: the body read DIED mid-steer (torn frame / dead
        peer).  The view never reaches the mailbox; drop its guard so
        the (partially scribbled) buffer can be re-armed — the owner's
        completion overwrites the partial bytes on the fallback path."""
        with self._cv:
            ls = self._live.get(id(obj))
            if ls is not None and ls.obj is obj:
                del self._live[id(obj)]
                self.live_count = len(self._live)
            self._cv.notify_all()

    def sanitize(self, value, own=None):
        """Run a popped user-facing payload through the guard: the
        owning request (``own is value``) takes its view and closes the
        lifecycle; any OTHER consumer of a live steered view gets a
        private copy (or the owner's rescue snapshot), because the
        owner will overwrite that memory.  Payloads outside the guard
        pass through untouched — callers pre-gate on ``live_count``."""
        with self._cv:
            ls = self._live.get(id(value))
            if ls is None or ls.obj is not value:
                return value
            while ls.writing:
                self._cv.wait()
            if own is value:
                del self._live[id(value)]
                self.live_count = len(self._live)
                return value
            out = ls.rescue if ls.rescue is not None \
                else _copy_steered(value)
            ls.sanitized = True
            if ls.owner_done:
                del self._live[id(value)]
                self.live_count = len(self._live)
            return out

    def pre_overwrite(self, buf) -> None:
        """An ARMED owner is about to overwrite its registered buffer on
        the fallback path (its completion payload was not the view).
        If a claim landed bytes there that some other consumer has yet
        to pop, snapshot them first (the rescue) — and wait out a
        reader mid-steer so the snapshot is whole."""
        if not self.live_count:
            return
        with self._cv:
            ls = self._live.get(id(buf))
            if ls is None or ls.obj is not buf:
                return
            while ls.writing:
                self._cv.wait()
            if ls.sanitized:
                # the foreign popper already took its copy
                del self._live[id(buf)]
            else:
                ls.rescue = _copy_steered(buf)
                ls.owner_done = True   # entry waits for its popper
            self.live_count = len(self._live)

    def purge_src(self, src, gen: int) -> None:
        """Membership removal of ``src``: its in-flight frames died with
        the purged stream, so resync arrivals to posts, drop entries,
        and fence the watermark to the bumped generation."""
        with self._lock:
            for key, ch in self._ch.items():
                if key[0] == src:
                    ch.entries.clear()
                    ch.arrived = ch.posted
                    ch.wm = (gen, 0)

    # -- introspection (tests / diagnostics) --------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "channels": len(self._ch),
                "entries": sum(len(c.entries) for c in self._ch.values()),
                "posted": sum(c.posted for c in self._ch.values()),
                "arrived": sum(c.arrived for c in self._ch.values()),
                "user_channels": len(self._user_keys),
                "live_steers": len(self._live),
            }
