"""Seeded bug: the live-buffer write hides inside a loop's augmented
assignment — same race, one hop of dataflow away."""


def main(comm, buf):
    req = comm.isend(buf, 1, tag=2)
    for i in range(4):
        buf[i] += 1.0
    req.wait()
