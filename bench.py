#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line.

Measures the reference's config #1 (BASELINE.json:7: MPI_Allreduce(SUM) on
1K float32, 2 ranks) on BOTH transports on this host, same algorithm
(recursive halving), and reports the transport-swap speedup — the quantity
the north-star is about (socket/pickle path vs XLA-collective path):

* socket backend: 2 real rank processes over loopback TCP (the reference's
  architecture), p50 of 200 allreduce calls;
* SPMD backend: the same allreduce as one jitted shard_map program over 2
  devices, p50 of 200 dispatches.

On a host with >= 2 real TPU chips the SPMD leg runs over ICI and a second
north-star measurement (256 MB ring-allreduce bus-bandwidth, BASELINE.json:5)
is attempted; with one chip the SPMD leg uses 2 virtual CPU devices — an
apples-to-apples same-host comparison.  Details land in BENCH_DETAILS.json.
"""

import json
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.abspath(__file__))

SOCKET_PROG = """
import os, sys, time, statistics
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu

# pin ranks to distinct cores when the box has them (VERDICT r4 next #7:
# the socket leg's cross-run spread is scheduler contention); on a
# 1-core host this is a no-op and the min-of-N samples carry the story
ncpu = os.cpu_count() or 1
if ncpu >= 2 and hasattr(os, "sched_setaffinity"):  # Linux only
    try:
        os.sched_setaffinity(
            0, {{int(os.environ.get("MPI_TPU_RANK", 0)) % ncpu}})
    except OSError:
        pass

comm = mpi_tpu.init()
x = np.ones(1024, np.float32)
for _ in range(20):
    comm.allreduce(x, algorithm="recursive_halving")
ts = []
for _ in range(200):
    t0 = time.perf_counter()
    comm.allreduce(x, algorithm="recursive_halving")
    ts.append(time.perf_counter() - t0)
if comm.rank == 0:
    with open(os.environ["BENCH_OUT"], "w") as f:
        f.write(str(statistics.median(ts) * 1e6))
mpi_tpu.finalize()
"""

SPMD_PROG = """
import os, sys, time, statistics
sys.path.insert(0, {repo!r})
import jax
if {force_cpu!r} == "yes":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from mpi_tpu.tpu import TpuCommunicator, default_mesh

mesh = default_mesh(2)
comm = TpuCommunicator("world", mesh)
f = jax.jit(jax.shard_map(
    lambda x: comm.allreduce(x, algorithm="recursive_halving"),
    mesh=mesh, in_specs=P(), out_specs=P("world")))
# operand committed to its sharding up front, like any steady-state SPMD
# program's data — an uncommitted array pays per-call placement logic
# (~80us/call of pure dispatch overhead on this host, measured r3)
x = jax.device_put(jnp.ones(1024, jnp.float32), NamedSharding(mesh, P()))
f(x).block_until_ready()
ts = []
for _ in range(200):
    t0 = time.perf_counter()
    f(x).block_until_ready()
    ts.append(time.perf_counter() - t0)
with open(os.environ["BENCH_OUT"], "w") as fh:
    fh.write(str(statistics.median(ts) * 1e6))
"""

NORTHSTAR_PROG = """
import os, sys, time, statistics
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, json
from jax.sharding import NamedSharding, PartitionSpec as P
from mpi_tpu.tpu import TpuCommunicator, default_mesh

mesh = default_mesh()
P_ = len(jax.devices())
comm = TpuCommunicator("world", mesh)
# per-rank buffer size: 256MB on hardware, reduced in CPU-sim rehearsal
nbytes = int(os.environ.get("NS_BYTES", 256 * 1024 * 1024))
iters = int(os.environ.get("NS_ITERS", 10))
n = nbytes // 4
result = {{"nranks": P_, "nbytes": nbytes,
           "platform": jax.devices()[0].platform}}

# ICI line-rate probe: a saturating pure-ppermute ring of the same
# per-device payload — the denominator of the >=80%-of-line-rate
# north-star (BASELINE.json:5; SURVEY.md section 6)
try:
    ring_pairs = [(i, (i + 1) % P_) for i in range(P_)]
    probe = jax.jit(jax.shard_map(
        lambda x: jax.lax.ppermute(x, "world", ring_pairs),
        mesh=mesh, in_specs=P("world"), out_specs=P("world")),
        donate_argnums=0)
    xp = jnp.ones(n * P_, jnp.float32)  # nbytes per device
    xp = probe(xp)
    xp.block_until_ready()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        xp = probe(xp)
        xp.block_until_ready()
        ts.append(time.perf_counter() - t0)
    t = statistics.median(ts)
    result["ici_linerate_gbps_per_link"] = nbytes / t / 1e9
except Exception as e:
    result["linerate_error"] = str(e)[:300]

# The allreduce legs: every rank holds its OWN nbytes buffer.  The global
# [P, n] array is created ALREADY sharded one block per device (out-
# shardings on the init jit) — never replicated and never materialized on
# a single device first, the round-1 HBM-inflation trap.  Steady-state
# HBM per device: one input shard + one (replicated) result.
sharded = NamedSharding(mesh, P("world"))
make_sharded = jax.jit(lambda: jnp.ones((P_, n), jnp.float32),
                       out_shardings=sharded)
from mpi_tpu.tpu import pallas_ring as _pr

def _algo_fn(a):
    if a == "pallas_ring_unidir":
        return lambda x: _pr.pallas_ring_allreduce(
            x.reshape(-1), "world", P_, bidirectional=False,
            interpret=jax.devices()[0].platform == "cpu")
    return lambda x: comm.allreduce(x.reshape(-1), algorithm=a)

# per-direction traffic of the bidirectional kernel (counter-rotating
# rings split each chunk's tiles between the two ICI link directions)
result["pallas_ring_flows"] = _pr.flow_summary(n, P_)

for algo in ("ring", "fused", "pallas_ring", "pallas_ring_unidir"):
    try:
        # every algorithm runs under the default check_vma=True, EXCEPT
        # the pallas legs on the CPU sim: under interpret+vma the kernel
        # takes the vma-typed ppermute fallback, which would silently
        # measure the same code as the 'ring' leg — check_vma=False there
        # keeps the INTERPRETED KERNEL (the data path being rehearsed) in
        # the measurement.  On real chips (interpret=False) the compiled
        # kernel runs under check_vma=True like everything else.
        cv = (not algo.startswith("pallas_ring")
              or jax.devices()[0].platform != "cpu")
        f = jax.jit(jax.shard_map(
            lambda x, a=algo: _algo_fn(a)(x)[None],
            mesh=mesh, in_specs=P("world"), out_specs=P("world"),
            check_vma=cv))
        xg = make_sharded()
        f(xg).block_until_ready()
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            f(xg).block_until_ready()
            ts.append(time.perf_counter() - t0)
        t = statistics.median(ts)
        result[algo] = {{"busbw_gbps": nbytes * 2 * (P_ - 1) / P_ / t / 1e9,
                         "t_s": t}}
    except Exception as e:
        result[algo + "_error"] = str(e)[:300]
if ("ici_linerate_gbps_per_link" in result
        and isinstance(result.get("pallas_ring"), dict)):
    result["pallas_ring"]["pct_of_linerate"] = round(
        100 * result["pallas_ring"]["busbw_gbps"]
        / result["ici_linerate_gbps_per_link"], 1)
with open(os.environ["BENCH_OUT"], "w") as fh:
    json.dump(result, fh)
"""


ATTENTION_PROG = """
import os, sys, time, statistics, json
sys.path.insert(0, {repo!r})
import numpy as np
import jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from mpi_tpu.tpu import default_mesh
from mpi_tpu.tpu.pallas_attention import (pallas_ring_attention,
                                          _fallback_attention)

# Attention FLOPs accounting (VERDICT r4 next #4): exact ring attention
# over the global sequence S = P*Sb does 2*S*S*d MACs for QK^T plus
# 2*S*S*d for PV -> 4*S^2*d FLOPs total (the online-softmax exp/max
# bookkeeping is O(S^2) and excluded, as in flash-attention papers).
mesh = default_mesh()
P_ = len(jax.devices())
Sb = int(os.environ.get("ATT_SB", 512))
d = int(os.environ.get("ATT_D", 128))
iters = int(os.environ.get("ATT_ITERS", 5))
S = P_ * Sb
platform = jax.devices()[0].platform
interp = platform == "cpu"
flops = 4.0 * S * S * d
result = {{"nranks": P_, "sb": Sb, "d": d, "seq": S, "platform": platform,
           "flops_per_call": flops}}

rng = np.random.RandomState(0)
sharded = NamedSharding(mesh, P("world"))
q = jax.device_put(jnp.asarray(rng.randn(S, d), jnp.float32), sharded)

def bench(f, x):
    f(x).block_until_ready()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)

# pallas legs run check_vma=False on the CPU sim so the INTERPRETED
# KERNEL (serial data path) is measured, not the ppermute fallback —
# same reasoning as the northstar pallas legs; compiled kernel (vma
# typing ON) on chips.  The train leg is value_and_grad through the
# fused forward AND the fused ring backward (resident/tiled per the
# VMEM plan); its FLOPs factor: forward 2 matmuls (4*S^2*d) + backward
# 5 matmuls (s recompute, dP, dS*K, dS^T*Q, P^T*dO = 10*S^2*d) -> 3.5x.
def train(qb):
    def loss(qq, kk, vv):
        out = pallas_ring_attention(qq, kk, vv, "world", P_,
                                    interpret=interp)
        return jax.lax.psum(jnp.sum(out ** 2), "world")
    _, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(qb, qb, qb)
    return grads[0] + grads[1] + grads[2]

legs = {{
    "pallas_kernel": (
        lambda qb: pallas_ring_attention(qb, qb, qb, "world", P_,
                                         interpret=interp),
        not interp, 1.0),
    "pallas_kernel_train": (train, not interp, 3.5),
    "ppermute_ring": (
        lambda qb: _fallback_attention(qb, qb, qb, "world", P_,
                                       1.0 / d ** 0.5), True, 1.0),
}}
for name, (fn, cv, ff) in legs.items():
    try:
        f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("world"),
                                  out_specs=P("world"), check_vma=cv))
        t = bench(f, q)
        result[name] = {{"t_s": t, "gflops_per_s": ff * flops / t / 1e9,
                         "flops_per_call": ff * flops}}
    except Exception as e:
        result[name + "_error"] = str(e)[:300]

# plain dense attention on ONE device over the same global sequence —
# the no-parallelism baseline the ring is beating.  The dense [S, S]
# score matrix is the whole point of the comparison, so cap it at a
# size one device can hold instead of OOMing on large slices.
if 2 * S * S * 4 > 4 * 1024 ** 3:
    result["local_dense_1dev_skipped"] = (
        f"dense scores would need {{2 * S * S * 4 / 1e9:.1f}} GB")
else:
    try:
        def local(qf):
            s = (qf @ qf.T) / d ** 0.5
            return jax.nn.softmax(s, axis=-1) @ qf
        ql = jax.device_put(jnp.asarray(rng.randn(S, d), jnp.float32),
                            jax.devices()[0])
        t = bench(jax.jit(local), ql)
        result["local_dense_1dev"] = {{"t_s": t,
                                       "gflops_per_s": flops / t / 1e9}}
    except Exception as e:
        result["local_dense_1dev_error"] = str(e)[:300]

# MFU vs the chip's nominal f32 MXU peak (documented bf16 peak / 2 —
# the convention the module uses consistently so cross-round numbers
# compare; only computed when the device kind is recognized)
PEAKS_F32_TFLOPS = {{"TPU v4": 137.5, "TPU v5 lite": 98.5,
                     "TPU v5e": 98.5, "TPU v5p": 229.5, "TPU v6e": 459.0}}
kind = jax.devices()[0].device_kind
if platform == "tpu":
    for k, peak_tf in PEAKS_F32_TFLOPS.items():
        if kind.lower().startswith(k.lower()):
            result["mxu_peak_f32_tflops_per_chip"] = peak_tf
            for leg in ("pallas_kernel", "pallas_kernel_train",
                        "ppermute_ring", "local_dense_1dev"):
                if isinstance(result.get(leg), dict):
                    chips = 1 if leg == "local_dense_1dev" else P_
                    result[leg]["mfu_pct_f32"] = round(
                        100 * result[leg]["gflops_per_s"]
                        / (peak_tf * 1e3 * chips), 2)
            break
with open(os.environ["BENCH_OUT"], "w") as fh:
    json.dump(result, fh)
"""


def _cpu_env(ndev: int = 2) -> dict:
    """Child env that deterministically yields an ``ndev``-device CPU jax.

    On TPU-tunnel hosts a sitecustomize hook force-registers the TPU
    platform whenever its pool env vars are present; racing it with
    config updates after import is flaky.  Scrubbing the trigger vars
    makes the hook a no-op, so the child is a plain CPU jax process.
    """
    import re

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={ndev}").strip()
    return env


def _run_sub(code: str, env_extra: dict, timeout: float = 600.0,
             env_base: dict | None = None) -> str:
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "out.txt")
        env = dict(os.environ) if env_base is None else dict(env_base)
        env["BENCH_OUT"] = out
        env.update(env_extra)
        script = os.path.join(td, "prog.py")
        with open(script, "w") as f:
            f.write(code)
        subprocess.run([sys.executable, script], env=env, check=True,
                       timeout=timeout, cwd=REPO)
        with open(out) as f:
            return f.read()


def measure_process_p50(backend: str) -> float:
    """p50 of the 2-rank 1K-f32 allreduce over real rank processes on the
    given transport ('socket' = the reference architecture, 'shm' = the
    native data plane)."""
    sys.path.insert(0, REPO)
    from mpi_tpu.launcher import launch

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "out.txt")
        script = os.path.join(td, "prog.py")
        with open(script, "w") as f:
            f.write(SOCKET_PROG.format(repo=REPO))
        rc = launch(2, [script], env_extra={"BENCH_OUT": out}, timeout=300.0,
                    backend=backend)
        if rc != 0:
            raise RuntimeError(f"{backend} bench failed with exit code {rc}")
        with open(out) as f:
            return float(f.read())


def _shm_small_msg_diagnosis() -> dict:
    """Ground the shm-vs-socket small-message story with evidence
    (VERDICT r5 weak #1 / next-round #7: the r5 artifact showed
    shm-p50 >= socket-p50 at 1KB with no diagnosis attached).

    Runs the 1KB ping-pong on socket and on shm under three spin
    settings of the futex ring's receive path (MPI_TPU_SHM_SPIN_US:
    default, 0, 300).  The mechanism the legs separate: a blocked shm
    receiver pays a futex sleep + wakeup — two scheduler trips per
    message — unless it spins long enough for the sender to produce the
    frame, WHICH REQUIRES A SPARE CORE.  On a 1-core box the spin can
    never be satisfied (the sender only runs once the receiver yields),
    so every message eats the wakeup latency and shm's p50 can land
    above loopback TCP's, whose kernel wakeup overlaps its own syscall
    work — that is the r5 inversion.  With >=2 cores the long-spin leg
    removes the wakeup and shm beats socket by several x; the verdict
    field states which regime THIS run measured."""
    from benchmarks import host_sweep

    legs = {leg["leg"]: leg.get("p50_us")
            for leg in host_sweep.latency_diagnosis_legs()}
    diag = {"cpus": os.cpu_count(), "p50_us_by_leg": legs}
    sock, dflt, spin = (legs.get("socket"), legs.get("shm_default"),
                        legs.get("shm_spin_300us"))
    if None in (sock, dflt, spin):
        diag["verdict"] = "diagnosis leg failed; see p50_us_by_leg errors"
    elif dflt >= sock:
        diag["verdict"] = (
            f"inversion reproduced (shm {dflt:.0f}us >= socket "
            f"{sock:.0f}us): futex wakeup cost, not the transport — "
            f"spin=300us leg measures {spin:.0f}us, "
            f"{'removing' if spin < sock else 'NOT removing'} it on "
            f"{os.cpu_count()} core(s)")
    else:
        diag["verdict"] = (
            f"no inversion on this box ({os.cpu_count()} cores: the "
            f"receiver's spin can be satisfied while the sender runs): "
            f"shm {dflt:.0f}us < socket {sock:.0f}us, long-spin floor "
            f"{spin:.0f}us — the r5 inversion was the 1-core scheduler "
            f"(futex wakeup on every message), not the shm data plane")
    return diag


def _probe_devices() -> list:
    """Ask a SUBPROCESS (with a hard timeout) what jax.devices() says.

    On a tunneled single-chip host a wedged device pool makes the very
    first jax.devices() call block forever; probing in-process would
    hang the whole benchmark.  A failed/hung probe falls back to the
    CPU platform for this process — the headline metric's SPMD leg is
    cpu-sim on 1-chip boxes anyway, so the number stays meaningful."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('\\n'.join(str(d) for d in jax.devices()))"],
            capture_output=True, text=True, timeout=180.0)
        if out.returncode == 0:
            devs = [l for l in out.stdout.splitlines() if l.strip()]
            if devs:
                return devs, False
    except subprocess.TimeoutExpired:
        pass
    # wedged or absent accelerator: pin THIS process to CPU before jax
    # ever imports, so the benchmark completes regardless
    sys.path.insert(0, REPO)
    from mpi_tpu.launcher import cpu_pinned_env

    cpu_pinned_env(os.environ, "cpu")
    return ["cpu (device probe timed out/failed: wedged-tunnel fallback)"], True


def main() -> None:
    # n_real comes from the PROBE, never from an in-process jax.devices():
    # the parent must not hold (or hang on) the tunneled chip — the legs
    # that need devices run in subprocesses
    devices, wedged = _probe_devices()
    n_real = 0 if wedged else len(devices)
    details = {"devices": devices}

    # best-of-7 per leg (VERDICT r4 next #7 raised 3→7): each sample is
    # already a p50 of 200 calls, but on this 1-core box cross-RUN
    # scheduler contention dominates the variance (observed r3/r4: the
    # ratio swung 1.4x-3.8x between rounds); the min is the
    # least-contended sample of each transport and stays the headline
    # for cross-round continuity, with the median + spread reported
    # alongside so a moved headline can be told apart from a lucky
    # draw.  ALL samples are persisted (VERDICT r3 next #6).
    n_samples = int(os.environ.get("BENCH_SAMPLES", 7))
    details["wedged_tunnel_fallback"] = wedged
    details["cpu_pinning"] = (
        "per-rank sched_setaffinity" if (os.cpu_count() or 1) >= 2
        else f"unavailable ({os.cpu_count()} core)")
    socket_samples = [measure_process_p50("socket")
                      for _ in range(n_samples)]
    socket_us = min(socket_samples)
    details["socket_2rank_1kf32_p50_us"] = socket_us
    details["socket_samples_us"] = socket_samples
    try:
        # full n_samples like every other leg (VERDICT r5 weak #1: the shm
        # leg was the one still at 3 samples, and its p50 was undiagnosed)
        shm_samples = [measure_process_p50("shm") for _ in range(n_samples)]
        details["shm_2rank_1kf32_p50_us"] = min(shm_samples)
        details["shm_samples_us"] = shm_samples
        details["shm_1kb_diagnosis"] = _shm_small_msg_diagnosis()
    except Exception as e:  # native toolchain may be absent
        details["shm_error"] = str(e)[:200]

    force_cpu = "yes" if n_real < 2 else "no"
    spmd_samples = [float(_run_sub(
        SPMD_PROG.format(repo=REPO, force_cpu=force_cpu), {},
        env_base=_cpu_env() if force_cpu == "yes" else None))
        for _ in range(n_samples)]
    spmd_us = min(spmd_samples)
    details["spmd_2rank_1kf32_p50_us"] = spmd_us
    details["spmd_samples_us"] = spmd_samples
    details["spmd_leg_platform"] = "cpu-sim" if force_cpu == "yes" else "tpu-ici"

    # North-star leg (BASELINE.json:5): the REAL measurement needs >=2
    # chips; the rehearsal leg runs the IDENTICAL program on an 8-device
    # CPU mesh at reduced size on every invocation, so the measurement
    # code is proven before hardware day (VERDICT round 1 next-step #1).
    if n_real >= 2:
        try:
            details["northstar_256mb_ring"] = json.loads(
                _run_sub(NORTHSTAR_PROG.format(repo=REPO), {})
            )
        except Exception as e:  # pragma: no cover - multichip only
            details["northstar_error"] = str(e)
    try:
        details["northstar_sim_8dev"] = json.loads(_run_sub(
            NORTHSTAR_PROG.format(repo=REPO),
            {"NS_BYTES": str(8 * 1024 * 1024), "NS_ITERS": "5"},
            env_base=_cpu_env(8)))
    except Exception as e:
        details["northstar_sim_error"] = str(e)[:500]

    # Attention leg (VERDICT r4 next #4): FLOPs-based accounting for
    # the fused ring-attention kernel vs the ppermute ring vs plain
    # single-device dense attention.  On >=2 chips the compiled kernel
    # runs over ICI with an MFU-style % of the MXU peak; on one chip
    # the local-dense MFU still measures; the CPU-sim rehearsal runs
    # the IDENTICAL program every invocation so the measurement path
    # is proven before hardware day (same discipline as the northstar).
    # "chip" means an actual accelerator in the probe — a CPU-only
    # host's single TFRT_CPU device must not masquerade as one (the
    # CPU-sim rehearsal below covers that case)
    has_chip = not wedged and any("cpu" not in s.lower() for s in devices)
    if has_chip and n_real >= 2:
        try:
            details["attention_tpu"] = json.loads(_run_sub(
                ATTENTION_PROG.format(repo=REPO),
                {"ATT_SB": "2048", "ATT_ITERS": "10"}))
        except Exception as e:  # pragma: no cover - multichip only
            details["attention_tpu_error"] = str(e)[:500]
    elif has_chip:
        try:  # single chip: the local-dense MFU branch is still real
            details["attention_1chip"] = json.loads(_run_sub(
                ATTENTION_PROG.format(repo=REPO),
                {"ATT_SB": "2048", "ATT_ITERS": "10"}))
        except Exception as e:
            details["attention_1chip_error"] = str(e)[:500]
    try:
        details["attention_sim_8dev"] = json.loads(_run_sub(
            ATTENTION_PROG.format(repo=REPO),
            {"ATT_SB": "128", "ATT_ITERS": "3"}, env_base=_cpu_env(8)))
    except Exception as e:
        details["attention_sim_error"] = str(e)[:500]

    speedup = socket_us / spmd_us
    med_speedup = (statistics.median(socket_samples)
                   / statistics.median(spmd_samples))
    # ISSUE 4 satellite: every bench result JSON is oversubscription-
    # stamped (2 rank procs + the driver on this box's cores) so the
    # known ±2-3x noise cells are machine-identifiable
    details["oversubscribed"] = 3 > (os.cpu_count() or 1)
    with open(os.path.join(REPO, "BENCH_DETAILS.json"), "w") as f:
        json.dump(details, f, indent=2)

    print(json.dumps({
        "metric": "allreduce_1kf32_2rank_p50_speedup_spmd_over_socket",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "median_speedup": round(med_speedup, 3),
        "oversubscribed": 3 > (os.cpu_count() or 1),
        "socket_us_min_med_max": [round(min(socket_samples), 1),
                                  round(statistics.median(socket_samples),
                                        1),
                                  round(max(socket_samples), 1)],
        "spmd_us_min_med_max": [round(min(spmd_samples), 1),
                                round(statistics.median(spmd_samples), 1),
                                round(max(spmd_samples), 1)],
    }))


if __name__ == "__main__":
    if "--chaos" in sys.argv[1:]:
        # FaultyTransport drop/delay/duplicate sweep over the collective
        # family asserting diagnose-don't-hang (ISSUE 3 satellite);
        # --quick is the tier-1 smoke spelling, mirroring --sweep's.
        # --serve (ISSUE 7) swaps in the resident-pool leg: continuous
        # SIGKILL against a live world server, asserting worlds/sec
        # never reaches zero and every lease completes or raises a
        # named FT error.  --links (ISSUE 10) swaps in the link-fault
        # leg: connection resets against a 3-rank socket world under a
        # mixed-collective stream, asserting bit-parity with an
        # uninjected run, zero ProcFailedError, link_reconnects >=
        # resets, and that a genuine SIGKILL is still diagnosed within
        # the detection bound; --no-healing is the honest "pre" run
        # (link_retry_timeout_s=0, the same resets terminal).
        # --federation (ISSUE 15) swaps in the federated-serve leg:
        # SIGKILL servers of an N-server federation under an open-loop
        # client fleet — worlds/s never zero, every failure named,
        # orphans adopted, no leader-authority overlap, plus the
        # beyond-capacity admission-control leg; --pre is the honest
        # single-server baseline dying to zero (the committed
        # federation_{pre,post}.json artifacts).
        from benchmarks import chaos

        args = ["--quick"] if "--quick" in sys.argv[1:] else []
        if "--serve" in sys.argv[1:]:
            args.append("--serve")
        if "--links" in sys.argv[1:]:
            args.append("--links")
        if "--federation" in sys.argv[1:]:
            args.append("--federation")
        if "--partition" in sys.argv[1:]:
            # ISSUE 18: the replicated-store partition leg — a raft
            # fabric namespace (no shared dir), a store-level partition
            # isolating the raft leader, named NoQuorumError refusal on
            # the minority, stale-intent truncation on heal (the
            # committed federation_partition_{pre,post}.json artifacts)
            args.append("--partition")
        if "--pre" in sys.argv[1:]:
            args.append("--pre")
        if "--no-healing" in sys.argv[1:]:
            args.append("--no-healing")
        if "--trace-dir" in sys.argv[1:]:
            # ISSUE 13 satellite: run the links leg under the flight
            # recorder and merge the per-rank Chrome traces
            idx = sys.argv.index("--trace-dir")
            if idx + 1 >= len(sys.argv):
                sys.exit("bench.py: --trace-dir needs a directory")
            args += ["--trace-dir", sys.argv[idx + 1]]
        sys.exit(chaos.main(args))
    if "--hotpath" in sys.argv[1:]:
        # zero-copy hot-path leg (ISSUE 11): 16MB socket allreduce
        # under healing-off / eager-retain / zero-copy retention modes
        # (pvar-proven retention-without-copy + one sendmsg per frame)
        # plus the lease-rides-the-pooled-arena check; the full run
        # writes the committed hotpath_{pre,post}.json artifacts,
        # --quick is the tier-1 smoke spelling.
        from benchmarks import hotpath

        if "--quick" in sys.argv[1:]:
            sys.exit(hotpath.main(["--quick"]))
        sys.exit(hotpath.main(
            ["--out-pre", os.path.join(REPO, "benchmarks", "results",
                                       "hotpath_pre.json"),
             "--out-post", os.path.join(REPO, "benchmarks", "results",
                                        "hotpath_post.json")]))
    if "--serve-bench" in sys.argv[1:]:
        # world-churn leg (ISSUE 7): resident world server vs cold
        # launch() — worlds/sec + p99 world-acquire latency; the full
        # run writes the committed serve_{pre,post}.json artifacts.
        from benchmarks import serve_bench

        if "--quick" in sys.argv[1:]:
            sys.exit(serve_bench.main(["--quick"]))
        sys.exit(serve_bench.main(
            ["--out-pre", os.path.join(REPO, "benchmarks", "results",
                                       "serve_pre.json"),
             "--out-post", os.path.join(REPO, "benchmarks", "results",
                                        "serve_post.json")]))
    if "--compress" in sys.argv[1:]:
        # compressed-collectives leg (ISSUE 8): 64MB allreduce/
        # reduce_scatter under ring vs bf16/int8/top-k wire formats on
        # both host transports, byte-plane pvars recorded per call; the
        # full run writes the committed compress_{pre,post}.json
        # artifacts, --quick is the tier-1 smoke spelling.
        from benchmarks import compress_bench

        if "--quick" in sys.argv[1:]:
            sys.exit(compress_bench.main(["--quick"]))
        sys.exit(compress_bench.main(
            ["--out-pre", os.path.join(REPO, "benchmarks", "results",
                                       "compress_pre.json"),
             "--out-post", os.path.join(REPO, "benchmarks", "results",
                                        "compress_post.json")]))
    if "--verify-overhead" in sys.argv[1:]:
        # verifier cost leg (ISSUE 5): asserts the off-mode zero-cost
        # contract (pvar-identical hot path) and prices the on-mode.
        # --progress (ISSUE 6) adds the async-progress-engine leg:
        # same pvar contracts with the engine's thread running.
        # --trace (ISSUE 13) adds the flight-recorder leg: trace-off
        # asserts 0 trace events + unchanged wire accounting, trace-on
        # prices the ring buffer.
        from benchmarks import verify_overhead

        args = ["--quick"] if "--quick" in sys.argv[1:] else []
        if "--progress" in sys.argv[1:]:
            args.append("--progress")
        if "--trace" in sys.argv[1:]:
            args.append("--trace")
        sys.exit(verify_overhead.main(args))
    if "--tune" in sys.argv[1:]:
        # tuned-dispatch table generator (ISSUE 9): sweeps (transport x
        # P x payload x algorithm — including the arena as a measured
        # algorithm) and writes the per-machine tuning table under
        # benchmarks/results/tuning/ that algorithm='auto' consults
        # (mpi_tpu/tuning).  --quick is the tier-1 smoke spelling
        # (1KB, P=2, 1 sample, stdout only — no artifact written).
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import tune

        if "--quick" in sys.argv[1:]:
            sys.exit(tune.main(["--quick"]))
        sys.exit(tune.main([]))
    if "--recvpool" in sys.argv[1:] and "--shm" in sys.argv[1:]:
        # zero-copy-everywhere leg (ISSUE 19): the pvar-asserted steer
        # bench (shm ring steering + user irecv(buf=) rendezvous +
        # scatter-gather receives) on both host transports; the full
        # run writes the committed recvpool_shm_{pre,post}.json pair
        # ('pre' pins MPI_TPU_RECV_STEERING=0).  --quick is the tier-1
        # smoke spelling (64KB, 1 sample, stdout only).
        from benchmarks import host_sweep

        if "--quick" in sys.argv[1:]:
            sys.exit(host_sweep.main(["--recvpool", "--shm",
                                      "--label", "post", "--quick"]))
        rc = host_sweep.main(
            ["--recvpool", "--shm", "--label", "pre",
             "--out", os.path.join(REPO, "benchmarks", "results",
                                   "recvpool_shm_pre.json")])
        sys.exit(rc or host_sweep.main(
            ["--recvpool", "--shm", "--label", "post",
             "--out", os.path.join(REPO, "benchmarks", "results",
                                   "recvpool_shm_post.json")]))
    if "--persist" in sys.argv[1:]:
        # persistent-collective leg (ISSUE 12): osu_allreduce_persistent-
        # shaped fresh-call vs start() re-fire p50s at small payloads on
        # both host transports; writes BOTH committed artifacts —
        # persist_pre.json pins MPI_TPU_NBC=thread (the seed's per-call-
        # thread semantics) and persist_post.json nbc=auto (engine
        # schedule state machines).  --quick is the tier-1 smoke
        # spelling (stdout only).
        from benchmarks import host_sweep

        if "--quick" in sys.argv[1:]:
            sys.exit(host_sweep.main(["--persist", "--label", "post",
                                      "--quick"]))
        rc = host_sweep.main(
            ["--persist", "--label", "pre",
             "--out", os.path.join(REPO, "benchmarks", "results",
                                   "persist_pre.json")])
        sys.exit(rc or host_sweep.main(
            ["--persist", "--label", "post",
             "--out", os.path.join(REPO, "benchmarks", "results",
                                   "persist_post.json")]))
    if "--sweep" in sys.argv[1:]:
        # the OSU-style host data-plane size sweep (ISSUE 1 tentpole #4,
        # extended to alltoall/reduce_scatter/rabenseifner in ISSUE 2);
        # writes the post-change artifact next to the committed pre run.
        # --quick is the tier-1 smoke spelling (tiny sizes, 1 sample) that
        # keeps the sweep harness from bit-rotting between perf PRs.
        from benchmarks import host_sweep

        if "--quick" in sys.argv[1:]:
            # smoke run: stdout only, no artifact to leak or overwrite
            sys.exit(host_sweep.main(["--label", "post", "--quick"]))
        sys.exit(host_sweep.main(
            ["--label", "post",
             "--out", os.path.join(REPO, "benchmarks", "results",
                                   "host_sweep2_post.json")]))
    main()
