"""Seeded bug: the wildcard hides behind a variable and the senders
behind an else-branch over symbolic ranks."""


def main(comm):
    if comm.rank == 0:
        src = ANY_SOURCE
        return comm.recv(src, tag=2)
    comm.send(comm.rank, 0, tag=2)
    return None
