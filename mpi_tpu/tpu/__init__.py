"""backend=tpu — the headline SPMD backend (SURVEY.md §7 Milestones 1-2).

Under construction this round: run_spmd / TpuCommunicator land with
Milestone 1.  This stub exists so ``mpi_tpu.run(fn, backend='tpu')`` fails
with a clear message rather than an ImportError until then.
"""

from __future__ import annotations


def run_spmd(*args, **kwargs):  # pragma: no cover - placeholder
    raise NotImplementedError(
        "the TPU backend is still being built this round; use backend='local' "
        "or backend='socket' meanwhile"
    )
