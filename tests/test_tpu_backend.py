"""TPU backend semantic tests on the 8-device virtual CPU mesh
(SURVEY.md §4 items 2-3: all semantics validated against numpy oracles
multi-device without a TPU slice)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_tpu import ops
from mpi_tpu.tpu import SpmdSemanticsError, TpuCommunicator, default_mesh, run_spmd

P = 8


def data(n=P, shape=(5,), seed=0, dtype=np.float32):
    return np.asarray(np.random.RandomState(seed).randn(n, *shape), dtype)


# -- allreduce -------------------------------------------------------------


@pytest.mark.parametrize("algo", ["fused", "ring", "recursive_halving", "reduce_bcast"])
def test_allreduce_sum(algo):
    d = data(shape=(13,))  # 13 not divisible by 8: exercises padding

    def prog(comm, x):
        mine = x[comm.rank]
        return comm.allreduce(mine, op=ops.SUM, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    for r in range(P):
        np.testing.assert_allclose(out[r], d.sum(0), rtol=1e-5)


@pytest.mark.parametrize("algo", ["fused", "ring", "recursive_halving"])
@pytest.mark.parametrize(
    "op,oracle",
    [
        (ops.MAX, lambda d: d.max(0)),
        (ops.MIN, lambda d: d.min(0)),
        (ops.PROD, lambda d: d.prod(0)),
    ],
)
def test_allreduce_ops(algo, op, oracle):
    d = data(shape=(6,), seed=3)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], op=op, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    for r in range(P):
        np.testing.assert_allclose(out[r], oracle(d), rtol=1e-4)


def test_allreduce_int_dtype():
    d = np.arange(P * 4, dtype=np.int32).reshape(P, 4)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="ring")

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_array_equal(out[0], d.sum(0))


# -- bcast / reduce --------------------------------------------------------


@pytest.mark.parametrize("algo", ["fused", "tree"])
@pytest.mark.parametrize("root", [0, 3, 7])
def test_bcast(algo, root):
    d = data(shape=(4,), seed=5)

    def prog(comm, x):
        mine = x[comm.rank]
        return comm.bcast(mine, root=root, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    for r in range(P):
        np.testing.assert_allclose(out[r], d[root], rtol=1e-6)


@pytest.mark.parametrize("algo", ["fused", "tree"])
@pytest.mark.parametrize("root", [0, 5])
def test_reduce_sum_at_root(algo, root):
    d = data(shape=(4,), seed=6)

    def prog(comm, x):
        return comm.reduce(x[comm.rank], op=ops.SUM, root=root, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_allclose(out[root], d.sum(0), rtol=1e-5)
    for r in range(P):
        if r != root:
            np.testing.assert_allclose(out[r], np.zeros(4), atol=1e-6)


@pytest.mark.parametrize("algo", ["fused", "tree"])
def test_reduce_max_identity_on_non_roots(algo):
    d = -np.abs(data(shape=(3,), seed=7))  # all negative: exposes zero-fill bugs

    def prog(comm, x):
        return comm.reduce(x[comm.rank], op=ops.MAX, root=2, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_allclose(out[2], d.max(0), rtol=1e-5)
    assert np.all(out[[r for r in range(P) if r != 2]] == np.float32(-np.inf))


# -- allgather / alltoall --------------------------------------------------


@pytest.mark.parametrize("algo", ["fused", "ring", "doubling"])
def test_allgather(algo):
    d = data(shape=(3,), seed=8)

    def prog(comm, x):
        return comm.allgather(x[comm.rank], algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    for r in range(P):
        np.testing.assert_allclose(out[r], d, rtol=1e-6)


def test_gather_sharded_zero_comm(monkeypatch):
    """gather(sharded=True) (VERDICT r3 missing #3): each device returns
    only its [1, ...] slice; the out_spec assembles the global stack, so
    per-device HBM is O(payload) and the compiled program contains NO
    gather collective at all."""
    from jax.sharding import Mesh, PartitionSpec as P_

    mesh = default_mesh(P)
    comm = TpuCommunicator("world", mesh)
    d = data(shape=(6,), seed=31)

    def f(x):
        return comm.gather(x.reshape(6), sharded=True)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P_("world"),
                               out_specs=P_("world")))
    out = jf(jnp.asarray(d.reshape(-1)))
    np.testing.assert_allclose(np.asarray(out), d, rtol=1e-6)
    # each device holds exactly its own [1, 6] shard of the stack
    assert sorted(s.data.shape for s in out.addressable_shards) == \
        [(1, 6)] * P
    # zero communication: no collective op of any kind in the program
    hlo = jf.lower(jnp.asarray(d.reshape(-1))).as_text()
    for coll in ("all-gather", "all_gather", "all-reduce", "all_reduce",
                 "collective-permute", "all-to-all"):
        assert coll not in hlo, coll


def test_gather_sharded_misuse_fails_loudly():
    """VERDICT r4 weak #5: forgetting ``out_specs=P(axis)`` on a
    sharded-output gather must be a TYPED error, not a silently wrong
    [1, ...] slice — the slice is branded vma-varying over the axis
    even when the gathered VALUE is replicated (the contract is 'my
    slice of the stack', which is positional).  gatherv mirrors."""
    from jax.sharding import PartitionSpec as P_

    mesh = default_mesh(P)
    comm = TpuCommunicator("world", mesh)

    def f(x):
        return comm.gather(x * 0 + 1.0, sharded=True)  # replicated value

    with pytest.raises(Exception, match="(?i)vma|var[iy]|replicat|spec"):
        jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P_(),
                              out_specs=P_()))(jnp.ones(3))

    def g(x):
        return comm.gatherv(x * 0 + 1.0, [2] * P, sharded=True)

    with pytest.raises(Exception, match="(?i)vma|var[iy]|replicat|spec"):
        jax.jit(jax.shard_map(g, mesh=mesh, in_specs=P_(),
                              out_specs=P_()))(jnp.ones((2, 3)))


def test_gather_replicated_warns_above_cvar_threshold():
    """The replicated default warns (trace time) once size*payload
    exceeds the writable gather_replicated_warn_bytes cvar, naming the
    sharded spelling; igather inherits through gather."""
    from mpi_tpu import mpit

    d = data(shape=(64,), seed=32)
    old = mpit.cvar_read("gather_replicated_warn_bytes")
    mpit.cvar_write("gather_replicated_warn_bytes", 128)
    try:
        def prog(comm, x):
            return comm.gather(x[comm.rank])

        with pytest.warns(RuntimeWarning, match="sharded=True"):
            out = np.asarray(run_spmd(prog, d))
        for r in range(P):
            np.testing.assert_allclose(out[r], d, rtol=1e-6)
    finally:
        mpit.cvar_write("gather_replicated_warn_bytes", old)
    # silent below the threshold (restored default: 64 MiB)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error", RuntimeWarning)
        np.asarray(run_spmd(prog, d))


def test_gatherv_sharded_padded_blocks_and_ragged_concat():
    """gatherv(sharded=True): per-device zero-padded own block; the
    assembled padded stack + ragged_concat equals the replicated
    gatherv's exact ragged concatenation."""
    from jax.sharding import PartitionSpec as P_

    counts = [3, 1, 2, 4, 2, 3, 1, 2]
    maxc = max(counts)
    mesh = default_mesh(P)
    comm = TpuCommunicator("world", mesh)
    rng = np.random.RandomState(33)
    # per-rank padded payloads [P, maxc, 2]
    d = np.asarray(rng.randn(P, maxc, 2), np.float32)

    def f(x):
        return comm.gatherv(x.reshape(maxc, 2), counts, sharded=True)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P_("world"),
                               out_specs=P_("world")))
    stack = np.asarray(jf(jnp.asarray(d.reshape(P * maxc, 2))))
    got = TpuCommunicator.ragged_concat(stack, counts)
    want = np.concatenate([d[r, : counts[r]] for r in range(P)], axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # padding rows of each per-device block came back zeroed
    blocks = stack.reshape(P, maxc, 2)
    for r in range(P):
        np.testing.assert_array_equal(blocks[r, counts[r]:], 0.0)
    # replicated spelling agrees
    def prog(comm_, x):
        return comm_.gatherv(x[comm_.rank], counts)

    rep = np.asarray(run_spmd(prog, d))[0]
    np.testing.assert_allclose(rep, want, rtol=1e-6)


@pytest.mark.parametrize("algo", ["fused", "pairwise"])
def test_alltoall(algo):
    # block (src, dst) encoded as value src*100 + dst
    d = np.asarray(
        [[src * 100 + dst for dst in range(P)] for src in range(P)], np.float32
    )[..., None]

    def prog(comm, x):
        blocks = x[comm.rank]  # [P, 1] block dst for every dst
        return comm.alltoall(blocks, algorithm=algo)[:, 0]

    out = np.asarray(run_spmd(prog, d))
    for dst in range(P):
        np.testing.assert_array_equal(out[dst], [src * 100 + dst for src in range(P)])


# -- p2p -------------------------------------------------------------------


def test_shift_wrap():
    def prog(comm):
        return comm.shift(comm.rank.astype(jnp.float32), offset=1, wrap=True)

    out = np.asarray(run_spmd(prog)).ravel()
    np.testing.assert_array_equal(out, [(r - 1) % P for r in range(P)])


def test_shift_no_wrap_fill():
    def prog(comm):
        return comm.shift(comm.rank.astype(jnp.float32), offset=1, wrap=False, fill=-99.0)

    out = np.asarray(run_spmd(prog)).ravel()
    np.testing.assert_array_equal(out, [-99.0] + [float(r) for r in range(P - 1)])


def test_shift_negative_offset():
    def prog(comm):
        return comm.shift(comm.rank.astype(jnp.float32), offset=-1, wrap=True)

    out = np.asarray(run_spmd(prog)).ravel()
    np.testing.assert_array_equal(out, [(r + 1) % P for r in range(P)])


def test_exchange_static_pattern():
    def prog(comm):
        # 0→7 and 3→4, everyone else receives zeros
        return comm.exchange(comm.rank.astype(jnp.float32) + 1, [(0, 7), (3, 4)])

    out = np.asarray(run_spmd(prog)).ravel()
    expect = np.zeros(P)
    expect[7], expect[4] = 1.0, 4.0
    np.testing.assert_array_equal(out, expect)


def test_shift_no_wrap_requires_fill():
    comm = TpuCommunicator("world", default_mesh())
    with pytest.raises(SpmdSemanticsError, match="fill"):
        comm.shift(jnp.zeros(3), offset=1, wrap=False)  # fill=None: CPU gives None


def test_bcast_reduce_algorithm_portable():
    """algorithm= must be accepted with the same names on every backend."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        a = comm.bcast(np.arange(3.0) if comm.rank == 0 else None, root=0,
                       algorithm="fused")
        b = comm.reduce(np.float32(comm.rank), root=0, algorithm="fused")
        return a, b

    res = run_local(prog, 4)
    np.testing.assert_array_equal(res[1][0], np.arange(3.0))
    assert float(res[0][1]) == 6.0


def test_send_raises_spmd_diagnostic():
    comm = TpuCommunicator("world", default_mesh())
    with pytest.raises(SpmdSemanticsError, match="shift"):
        comm.send(1, dest=0)
    with pytest.raises(SpmdSemanticsError):
        comm.recv()
    with pytest.raises(SpmdSemanticsError):
        comm.sendrecv(1, dest=0)
    with pytest.raises(SpmdSemanticsError):
        comm.split(color=0)


# -- split -----------------------------------------------------------------


def test_split_parity_groups():
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    sub = world.split_by(lambda i: i % 2)
    assert sub.size == 4
    assert sub.axis_index_groups == [[0, 2, 4, 6], [1, 3, 5, 7]]

    def prog(comm):
        # comm is the world; use the pre-split sub inside the same trace
        return sub.allreduce(comm.rank.astype(jnp.float32), algorithm="ring")

    out = np.asarray(run_spmd(prog, mesh=mesh)).ravel()
    np.testing.assert_array_equal(out, [12.0, 16.0] * 4)


@pytest.mark.parametrize("algo", ["fused", "ring", "recursive_halving"])
def test_split_grouped_collectives(algo):
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    rows = world.split_by(lambda i: i // 4)  # [[0,1,2,3],[4,5,6,7]]
    d = data(shape=(9,), seed=11)

    def prog(comm, x):
        return rows.allreduce(x[comm.rank], op=ops.SUM, algorithm=algo)

    out = np.asarray(run_spmd(prog, d, mesh=mesh))
    for r in range(P):
        grp = range(0, 4) if r < 4 else range(4, 8)
        np.testing.assert_allclose(out[r], d[list(grp)].sum(0), rtol=1e-4, atol=1e-6)


def test_split_key_reorders():
    world = TpuCommunicator("world", default_mesh())
    sub = world.split_all([0] * P, keys=list(range(P - 1, -1, -1)))
    assert sub.axis_index_groups == [[7, 6, 5, 4, 3, 2, 1, 0]]


def test_nested_split():
    world = TpuCommunicator("world", default_mesh())
    rows = world.split_by(lambda i: i // 4)
    cols_of_rows = rows.split_by(lambda i: i % 2)
    assert cols_of_rows.axis_index_groups == [[0, 2], [1, 3], [4, 6], [5, 7]]

    def prog(comm):
        return cols_of_rows.allgather(comm.rank.astype(jnp.float32))

    out = np.asarray(run_spmd(prog))
    np.testing.assert_array_equal(out[0], [0.0, 2.0])
    np.testing.assert_array_equal(out[5], [5.0, 7.0])


def test_split_unequal_groups_rejected():
    world = TpuCommunicator("world", default_mesh())
    with pytest.raises(ValueError, match="equal-sized"):
        world.split_all([0, 0, 0, 1, 1, 1, 1, 1])


def test_split_none_color_rejected():
    world = TpuCommunicator("world", default_mesh())
    with pytest.raises(ValueError, match="color"):
        world.split_all([None, 0, 0, 0, 0, 0, 0, 1])


# -- misc ------------------------------------------------------------------


def test_barrier_traces():
    def prog(comm):
        comm.barrier()
        return comm.rank

    out = np.asarray(run_spmd(prog)).ravel()
    np.testing.assert_array_equal(out, np.arange(P))


def test_scatter():
    d = np.arange(P * P, dtype=np.float32).reshape(P, P)

    def prog(comm, x):
        blocks = jnp.where(comm.rank == 3, x, jnp.zeros_like(x))  # only root has data
        return comm.scatter(blocks, root=3)

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_array_equal(out.ravel(), d.ravel())


def test_run_spmd_requires_enough_devices():
    with pytest.raises(ValueError, match="devices"):
        default_mesh(100)


def test_grouped_shift_stays_in_group():
    world = TpuCommunicator("world", default_mesh())
    rows = world.split_by(lambda i: i // 4)

    def prog(comm):
        return rows.shift(comm.rank.astype(jnp.float32), offset=1, wrap=True)

    out = np.asarray(run_spmd(prog)).ravel()
    # within [0..3]: comes from (grank-1)%4 of same group; same for [4..7]
    np.testing.assert_array_equal(out, [3, 0, 1, 2, 7, 4, 5, 6])
