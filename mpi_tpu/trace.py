"""Communication tracing + matching verification (SURVEY.md §5: the
framework's race-detector / sanitizer analogue).

Two layers:

* :class:`TracingTransport` — wraps any Transport at the plugin boundary and
  records every send/recv with timestamps; works under ``run_local``'s
  ``transport_wrapper`` hook or around a SocketTransport.
* :func:`verify_run` — runs a portable MPI program on the thread backend
  with tracing on every rank, then cross-checks the per-rank logs with
  mpi_tpu.checker.verify_matching: unmatched sends (message leaks) and
  unmatched receives are reported exactly like a message-race detector
  would.  The TPU backend needs none of this at runtime — SPMD matching is
  static — but the same user program can be linted here before being run
  under shard_map.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import checker
from .transport.base import Transport


class TracingTransport(Transport):
    """Decorator transport: records (op, peer, ctx, tag, t) tuples."""

    def __init__(self, inner: Transport) -> None:
        self.inner = inner
        self.world_rank = inner.world_rank
        self.world_size = inner.world_size
        self.mailbox = inner.mailbox
        self.aliases_payloads = inner.aliases_payloads
        # decorate, don't re-tune: a traced run must execute the same
        # collective wire schedule as the wrapped data plane
        self.coll_segment_hint = inner.coll_segment_hint
        self.log: List[Tuple] = []
        self._lock = threading.Lock()

    def _record(self, entry: Tuple) -> None:
        with self._lock:
            self.log.append(entry)

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        self._record(("send", dest, ctx, tag, time.monotonic()))
        self.inner.send(dest, ctx, tag, payload)

    def recv(self, source: int, ctx, tag: int, timeout: Optional[float] = None):
        payload, src, t = self.inner.recv(source, ctx, tag, timeout)
        # record the *matched* source/tag (wildcards resolved), which is what
        # matching verification needs
        self._record(("recv", src, ctx, t, time.monotonic()))
        return payload, src, t

    def poll(self, source: int, ctx, tag: int):
        hit = self.inner.poll(source, ctx, tag)
        if hit is not None:
            _, src, t = hit
            self._record(("recv", src, ctx, t, time.monotonic()))
        return hit

    def close(self) -> None:
        self.inner.close()

    def as_match_log(self) -> List[Tuple[str, int, int]]:
        """Project to checker.verify_matching format: (op, peer, tag)."""
        return [(op, peer, tag) for (op, peer, ctx, tag, _) in self.log]


def verify_run(
    fn: Callable,
    nranks: int,
    args: Sequence = (),
    kwargs: Optional[Dict] = None,
    timeout: float = 120.0,
    strict_fifo: bool = True,
    runtime_verify: bool = False,
) -> Tuple[List[Any], List[str]]:
    """Run ``fn(comm, *args)`` on the thread backend with full comm tracing;
    return (per-rank results, problems).  ``problems`` is empty iff every
    send was received, every recv was satisfied by a real send, and (with
    ``strict_fifo``, the default) no recv matched a send behind the head
    of its channel — see checker.verify_matching.

    ``runtime_verify=True`` additionally runs the MUST-style runtime
    verifier (mpi_tpu/verify) during the traced run — deadlocks raise
    DeadlockError, divergent collectives CollectiveMismatchError — and
    appends its lint report (leaked requests, buffer overlaps, ...) to
    ``problems``: one call covering both the post-hoc matching check and
    the online checks."""
    from .transport.local import run_local

    traces: List[Optional[TracingTransport]] = [None] * nranks
    lock = threading.Lock()

    def wrapper(t: Transport) -> Transport:
        tt = TracingTransport(t)
        with lock:
            traces[t.world_rank] = tt
        return tt

    results = run_local(fn, nranks, args=args, kwargs=kwargs, timeout=timeout,
                        transport_wrapper=wrapper, verify=runtime_verify)
    logs = [t.as_match_log() if t else [] for t in traces]
    problems = checker.verify_matching(logs, strict_fifo=strict_fifo)
    if runtime_verify:
        from .verify import finalize_report

        problems += finalize_report()
    return results, problems
