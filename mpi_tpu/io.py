"""MPI-IO — parallel file I/O (MPI-2 ch.9 [S]).

The reference library (SURVEY.md §0: MPI-1-level, no I/O chapter in
evidence) owes none of this; it is a beyond-parity subsystem completing
the MPI-2 surface.  Scope and design:

* **Explicit offsets** (``read_at``/``write_at``) are independent
  ``os.pread``/``os.pwrite`` on a per-rank fd — offsets are in *etype*
  units within the current **file view**.
* **File views** (``set_view``) reuse mpi_tpu/datatypes.py: the filetype's
  committed index map IS the view — visible element ``i`` lands at file
  element ``indices[i % k] + (i // k) * extent`` (k = map size), and runs
  of consecutive file bytes are coalesced before hitting the OS, so a
  strided view costs one syscall per contiguous run, not per element.
* **Individual file pointers** (``seek``/``read``/``write``) are plain
  per-rank state.
* **Shared file pointers** (``read_shared``/``write_shared``) are a
  fetch-and-add on a passive-target RMA window hosted at rank 0
  (mpi_tpu/window.py lock/unlock gives the atomicity) — the MPI-IO
  shared pointer is exactly a distributed counter.
* **Collective I/O** (``write_at_all``/``read_at_all``) implements
  two-phase collective buffering for writes: when the epoch's total
  payload is small enough to ship, ranks send their (byte-run, data)
  lists to an aggregator that applies them as one sorted sweep — the
  ROMIO optimization that turns P interleaved strided writes into a
  sequential pass; large payloads fall back to independent writes
  inside the same barrier bracket.

Process backends only (the fd and the window server live on ranks); for
sharded device arrays use mpi_tpu.checkpoint (orbax) — that is the
TPU-native bulk-I/O path.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

import numpy as np

from .communicator import Communicator, P2PCommunicator
from .datatypes import Datatype

__all__ = [
    "File", "file_open", "file_delete", "register_datarep", "Datarep",
    "MODE_RDONLY", "MODE_WRONLY", "MODE_RDWR", "MODE_CREATE", "MODE_EXCL",
    "MODE_APPEND", "MODE_DELETE_ON_CLOSE",
    "SEEK_SET", "SEEK_CUR", "SEEK_END",
]

MODE_RDONLY = 1
MODE_WRONLY = 2
MODE_RDWR = 4
MODE_CREATE = 8
MODE_EXCL = 16
MODE_APPEND = 32
MODE_DELETE_ON_CLOSE = 64

SEEK_SET, SEEK_CUR, SEEK_END = 0, 1, 2

_TAG_TWOPHASE = -30  # internal tag (negative: invisible to user wildcards)

# write_at_all ships runs to the aggregator only below this total;
# above it, shipping costs more than it saves and ranks write directly.
_COLLECTIVE_BUFFER_LIMIT = 8 << 20


# -- data representations (MPI_Register_datarep, MPI-2 §9.5 [S]) ------------


def _wants_position(fn, base_params: int) -> str:
    """How a datarep callback takes the optional ``position`` argument:
    ``"pos"`` (a trailing positional parameter NAMED ``position``, or
    *args), ``"kw"`` (a keyword-only parameter named ``position`` —
    review round 5: the natural ``*, position=0`` spelling must not be
    silently treated as position-free), or ``""`` (position-free; also
    for C callables hiding their signature).

    The positional detection requires the name (ADVICE r5 #1): a
    callback with an unrelated defaulted trailing arg — e.g.
    ``read_fn(raw, et, n, extra, strict=True)`` — must keep that
    parameter's default, not silently receive the element position in
    it.  Such a signature gets a warning so the ambiguity is loud."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return ""
    params = list(sig.parameters.values())
    kinds = [p.kind for p in params]
    if any(p.kind == inspect.Parameter.KEYWORD_ONLY
           and p.name == "position" for p in params):
        return "kw"
    if inspect.Parameter.VAR_POSITIONAL in kinds:
        return "pos"
    positional = [p for p in params
                  if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                                inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    if len(positional) <= base_params:
        return ""
    extra = positional[base_params]
    if extra.name == "position":
        return "pos"
    import warnings

    if extra.default is inspect.Parameter.empty:
        # A REQUIRED extra has no default to preserve — not passing the
        # position would TypeError on every call, so it still receives
        # it (the pre-r5 behavior); the warning only flags the name.
        warnings.warn(
            f"datarep callback {getattr(fn, '__name__', fn)!r} takes the "
            f"element position in a parameter named {extra.name!r}; name "
            f"it 'position' to make the contract explicit",
            UserWarning, stacklevel=3)
        return "pos"
    warnings.warn(
        f"datarep callback {getattr(fn, '__name__', fn)!r} has a trailing "
        f"defaulted parameter {extra.name!r}; only a parameter named "
        f"'position' receives the element position — {extra.name!r} keeps "
        f"its default (rename it to 'position' to opt in)",
        UserWarning, stacklevel=3)
    return ""


class Datarep:
    """How etype elements are represented IN THE FILE.  The MPI callback
    triple, pythonically collapsed (the buffer plumbing of the C
    signatures is what numpy slicing already does):

    * ``read_fn(raw: bytes, etype: np.dtype, count: int, extra
      [, position]) -> np.ndarray`` — file → memory representation;
    * ``write_fn(arr: np.ndarray, etype: np.dtype, extra
      [, position]) -> bytes`` — memory → file representation;
    * ``extent_fn(etype: np.dtype, extra) -> int`` — bytes ONE element
      occupies in the file (MPI's dtype_file_extent_fn); defaults to
      ``etype.itemsize`` (size-preserving representations).

    Conversions are elementwise (element i of the memory array ↔ bytes
    [i*extent, (i+1)*extent) of the file stream), which is what lets
    file views, shared pointers, and collective buffering keep operating
    in etype units with only the byte math rescaled.

    **Positional representations** (ADVICE r4 #3): a callback declaring
    the optional trailing ``position`` parameter receives the
    VIEW-relative etype index of its first element (MPI's ``position``
    argument), so element-indexed schemes (e.g. per-element keystreams)
    convert correctly even when a filetype scatters the batch across
    non-contiguous file runs — the batch is always contiguous IN THE
    VIEW.  Representations keyed to absolute FILE byte offsets (e.g.
    record headers between runs) are NOT expressible — a filetype's
    runs are invisible to the callback by design; model those as part
    of the filetype instead."""

    def __init__(self, name: str, read_fn, write_fn, extent_fn=None,
                 extra_state=None):
        self.name = name
        self._read, self._write = read_fn, write_fn
        self._extent, self._extra = extent_fn, extra_state
        self._read_pos = _wants_position(read_fn, 4)
        self._write_pos = _wants_position(write_fn, 3)

    def file_extent(self, etype: np.dtype) -> int:
        e = (int(self._extent(etype, self._extra)) if self._extent
             else etype.itemsize)
        if e <= 0:
            raise ValueError(
                f"datarep {self.name!r}: file extent must be positive, "
                f"got {e} for etype {etype}")
        return e

    def read(self, raw: bytes, etype: np.dtype, count: int,
             position: int = 0) -> np.ndarray:
        if self._read_pos == "pos":
            out = self._read(raw, etype, count, self._extra,
                             int(position))
        elif self._read_pos == "kw":
            out = self._read(raw, etype, count, self._extra,
                             position=int(position))
        else:
            out = self._read(raw, etype, count, self._extra)
        out = np.asarray(out, dtype=etype)
        if out.size != count:
            raise ValueError(
                f"datarep {self.name!r} read conversion returned "
                f"{out.size} elements for {count} requested")
        return out

    def write(self, arr: np.ndarray, etype: np.dtype,
              position: int = 0):
        """→ the file-representation bytes (``bytes`` or a zero-copy
        ``memoryview`` for identity representations)."""
        if self._write_pos == "pos":
            raw = self._write(arr, etype, self._extra, int(position))
        elif self._write_pos == "kw":
            raw = self._write(arr, etype, self._extra,
                              position=int(position))
        else:
            raw = self._write(arr, etype, self._extra)
        want = arr.size * self.file_extent(etype)
        if len(raw) != want:
            raise ValueError(
                f"datarep {self.name!r} write conversion emitted "
                f"{len(raw)} bytes for {arr.size} elements "
                f"(extent says {want})")
        return raw


_DATAREPS = {
    # memory representation IS the file representation — the write side
    # hands back a zero-copy view of the array's own buffer (the default
    # path must not regress to a full-payload memcpy per write)
    "native": Datarep(
        "native",
        lambda raw, et, n, _: np.frombuffer(raw, dtype=et, count=n).copy(),
        lambda arr, et, _: memoryview(arr).cast("B")),
    # the portable big-endian interchange format (matches
    # datatypes.pack_external for simple etypes)
    "external32": Datarep(
        "external32",
        lambda raw, et, n, _: np.frombuffer(
            raw, dtype=et.newbyteorder(">"), count=n).astype(et),
        lambda arr, et, _: np.ascontiguousarray(arr).astype(
            arr.dtype.newbyteorder(">"), copy=False).tobytes()),
}


def register_datarep(name: str, read_fn, write_fn, extent_fn=None,
                     extra_state=None) -> None:
    """MPI_Register_datarep: make ``name`` usable as ``set_view``'s
    ``datarep`` argument process-wide.  Callback shapes are documented on
    :class:`Datarep`.  Redefining a predefined or already-registered
    representation is erroneous (MPI_ERR_DUP_DATAREP)."""
    if name in _DATAREPS:
        raise ValueError(f"datarep {name!r} already registered "
                         f"(MPI_ERR_DUP_DATAREP)")
    _DATAREPS[name] = Datarep(name, read_fn, write_fn, extent_fn,
                              extra_state)


def _pwrite_full(fd: int, view, offset: int) -> None:
    """pwrite the whole buffer (one syscall caps at ~2GiB on Linux; a
    short write here would silently truncate the transfer)."""
    pos = 0
    n = len(view)
    while pos < n:
        w = os.pwrite(fd, view[pos:], offset + pos)
        if w <= 0:
            raise OSError(f"pwrite returned {w} at offset {offset + pos}")
        pos += w


def _pread_full(fd: int, nbytes: int, offset: int) -> bytes:
    """pread until ``nbytes`` or true EOF (a capped syscall is not EOF)."""
    chunks = []
    got = 0
    while got < nbytes:
        b = os.pread(fd, nbytes - got, offset + got)
        if not b:
            break  # EOF
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


class File:
    """An open parallel file (MPI_File).  Construct via :func:`file_open`."""

    def __init__(self, comm: Communicator, path: str, amode: int):
        if not isinstance(comm, P2PCommunicator):
            raise NotImplementedError(
                "MPI-IO files live on process ranks (fds + window server); "
                "open with a process-backend comm (COMM_WORLD under the "
                "launcher, or COMM_SELF for private files).  For sharded "
                "device arrays use mpi_tpu.checkpoint (orbax).")
        if not (amode & (MODE_RDONLY | MODE_WRONLY | MODE_RDWR)):
            raise ValueError("amode needs one of MODE_RDONLY/WRONLY/RDWR")
        self._comm = comm
        self._path = path
        self._amode = amode
        # collective create/truncate decisions happen once, at rank 0;
        # the OUTCOME is broadcast so a failure raises on every rank
        # instead of deadlocking peers in the barrier
        err: Optional[str] = None
        if comm.rank == 0:
            try:
                if amode & MODE_CREATE:
                    flags = os.O_CREAT | (os.O_EXCL if amode & MODE_EXCL else 0)
                    fd = os.open(path, flags | os.O_RDWR, 0o644)
                    os.close(fd)
                elif not os.path.exists(path):
                    raise OSError(f"file {path!r} does not exist "
                                  "(open without MODE_CREATE)")
            except OSError as e:
                err = f"{type(e).__name__}: {e}"
        err = comm.bcast(err, 0)
        if err is not None:
            raise OSError(f"collective open failed at rank 0: {err}")
        oflag = (os.O_RDONLY if amode & MODE_RDONLY and
                 not (amode & (MODE_WRONLY | MODE_RDWR)) else os.O_RDWR)
        self._fd = os.open(path, oflag)
        # the view: displacement (bytes) + etype + optional filetype map
        # + data representation (how etype elements look in the file)
        self._disp = 0
        self._etype = np.dtype(np.uint8)
        self._filetype: Optional[Datatype] = None
        self._datarep = _DATAREPS["native"]
        self._file_es = 1  # bytes per etype element IN THE FILE
        self._pos = 0            # individual pointer, etype units in view
        self._shared_win = None  # lazy: passive-target counter at rank 0
        self._open = True
        if amode & MODE_APPEND:
            self._pos = self._visible_end()

    # -- views -------------------------------------------------------------

    def set_view(self, disp: int = 0, etype: Any = np.uint8,
                 filetype: Optional[Datatype] = None,
                 datarep: str = "native") -> None:
        """MPI_File_set_view: offsets become etype-relative, the filetype's
        index map selects which file elements this rank sees.  Collective
        (each rank passes its OWN view — that is the point: disjoint
        filetypes partition the file).

        ``datarep`` names the file data representation: "native",
        "external32", or any name registered via
        :func:`register_datarep` — every typed read/write through this
        view then runs the representation's conversion callbacks, with
        file offsets scaled by its per-element file extent."""
        et = np.dtype(etype)
        try:
            rep = _DATAREPS[datarep]
        except KeyError:
            raise ValueError(
                f"unknown datarep {datarep!r}; have {sorted(_DATAREPS)} "
                f"(register custom representations with "
                f"register_datarep)") from None
        if filetype is not None:
            if filetype.base_dtype != et and filetype.base_dtype != np.uint8:
                raise ValueError(
                    f"filetype base {filetype.base_dtype} != etype {et}")
            filetype.commit()  # no overlap within one instance
            if filetype.indices.size:
                if filetype.extent <= 0:
                    raise ValueError("filetype extent must be positive "
                                     "for a view (it is the tiling period)")
                # The view tiles the map indefinitely: element i of
                # instance 0 collides with element j of instance m iff
                # indices[i] == indices[j] + m*extent — i.e. iff two
                # indices are congruent mod extent.  Distinct residues ⇔
                # no overlap at ANY shift (not just adjacent instances).
                # MPI permits overlapping filetypes on READ-ONLY files
                # (overlap is erroneous only for writing), so gate on amode.
                if self._amode & (MODE_WRONLY | MODE_RDWR):
                    res = filetype.indices % filetype.extent
                    if np.unique(res).size != res.size:
                        raise ValueError(
                            "filetype instances overlap when tiled (two "
                            "element displacements are congruent modulo the "
                            f"extent {filetype.extent}) — writes through "
                            "this view would silently collide (legal on a "
                            "MODE_RDONLY file)")
        self._disp = int(disp)
        self._etype = et
        self._filetype = filetype
        self._datarep = rep
        self._file_es = rep.file_extent(et)
        self._pos = 0
        self._comm.barrier()

    def get_view(self):
        return (self._disp, self._etype, self._filetype,
                self._datarep.name)

    # -- offset translation ------------------------------------------------

    def _byte_runs(self, offset: int, nelems: int) -> List[Tuple[int, int]]:
        """Visible [offset, offset+nelems) etype elements → coalesced
        (file_byte_offset, nbytes) runs.  All byte math is in FILE-side
        element sizes (the datarep's extent; == etype.itemsize for
        size-preserving representations like native/external32)."""
        es = self._file_es
        if nelems <= 0:
            return []
        if self._filetype is None:
            return [(self._disp + offset * es, nelems * es)]
        ft = self._filetype
        k = ft.indices.size
        if k == 0:
            raise ValueError("filetype selects zero elements")
        i = np.arange(offset, offset + nelems, dtype=np.int64)
        file_elems = ft.indices[i % k] + (i // k) * ft.extent
        if ft.base_dtype == np.uint8 and self._etype.itemsize != 1:
            raise ValueError("byte-based filetype with non-byte etype is "
                             "ambiguous; build the filetype over the etype")
        starts = self._disp + file_elems * es
        # coalesce consecutive elements into runs (vectorized: a run break
        # is wherever the gap between neighbors is not exactly one element)
        breaks = np.flatnonzero(np.diff(starts) != es)
        run_starts = starts[np.concatenate(([0], breaks + 1))]
        counts = np.diff(np.concatenate(([0], breaks + 1, [starts.size])))
        return [(int(s), int(c) * es) for s, c in zip(run_starts, counts)]

    # -- explicit offsets (independent) ------------------------------------

    def _to_file_rep(self, data: Any,
                     position: int = 0) -> Tuple[np.ndarray, memoryview]:
        """Coerce to etype and run the view's datarep write conversion
        (``position`` = view-relative etype offset of element 0, for
        positional representations); returns (memory array,
        file-representation bytes)."""
        arr = np.ascontiguousarray(np.asarray(data, dtype=self._etype))
        return arr, memoryview(
            self._datarep.write(arr, self._etype, position))

    def _write_runs(self, offset: int, nelems: int, view) -> None:
        """pwrite already-converted file-representation bytes across the
        view's byte runs (shared by write_at and write_at_all's
        independent branch, which must not convert twice)."""
        pos = 0
        for start, nbytes in self._byte_runs(int(offset), nelems):
            _pwrite_full(self._fd, view[pos:pos + nbytes], start)
            pos += nbytes

    def write_at(self, offset: int, data: Any) -> int:
        """pwrite ``data`` (coerced to etype, converted to the view's
        datarep) at view-relative ``offset`` (etype units); returns
        elements written."""
        self._check_open()
        arr, view = self._to_file_rep(data, int(offset))
        self._write_runs(offset, arr.size, view)
        return arr.size

    def read_at(self, offset: int, count: int) -> np.ndarray:
        """pread ``count`` etype elements at view-relative ``offset``,
        converted from the view's datarep; short reads at EOF return a
        shorter array (MPI: count via Get_count)."""
        self._check_open()
        chunks = []
        for start, nbytes in self._byte_runs(int(offset), int(count)):
            b = _pread_full(self._fd, nbytes, start)
            chunks.append(b)
            if len(b) < nbytes:  # true EOF inside a run
                break
        raw = b"".join(chunks)
        nel = len(raw) // self._file_es
        return self._datarep.read(raw[: nel * self._file_es],
                                  self._etype, nel, int(offset))

    # -- individual file pointer -------------------------------------------

    def _visible_end(self) -> int:
        """Number of VISIBLE etype elements the file currently holds under
        this view (SEEK_END must count through the filetype, not raw
        bytes — other ranks' elements are not ours)."""
        es = self._file_es
        nbytes = self.get_size() - self._disp
        if nbytes <= 0:
            return 0
        if self._filetype is None:
            return nbytes // es
        ft = self._filetype
        inst_bytes = ft.extent * es
        full = nbytes // inst_bytes
        rem = nbytes % inst_bytes
        extra = int(np.sum((ft.indices + 1) * es <= rem))
        return int(full) * ft.indices.size + extra

    def seek(self, offset: int, whence: int = SEEK_SET) -> None:
        self._check_open()
        if whence == SEEK_SET:
            pos = int(offset)
        elif whence == SEEK_CUR:
            pos = self._pos + int(offset)
        elif whence == SEEK_END:
            pos = self._visible_end() + int(offset)
        else:
            raise ValueError(f"bad whence {whence}")
        if pos < 0:
            raise ValueError(f"negative file position {pos}")
        self._pos = pos  # assigned only after validation

    def get_position(self) -> int:
        return self._pos

    def write(self, data: Any) -> int:
        n = self.write_at(self._pos, data)
        self._pos += n
        return n

    def read(self, count: int) -> np.ndarray:
        out = self.read_at(self._pos, count)
        self._pos += out.size
        return out

    # -- shared file pointer -----------------------------------------------

    def _shared_fetch_add(self, n: int) -> int:
        """Atomic fetch-and-add on the rank-0-hosted shared pointer —
        ONE server round-trip (MPI-3 MPI_Fetch_and_op), down from the
        4-message lock/get/put/unlock sequence."""
        if self._shared_win is None:
            # collective lazy init would hang (only callers reach here);
            # create eagerly instead the first time ANY shared op is used
            raise RuntimeError(
                "shared file pointer not initialized — open the file with "
                "file_open(..., shared=True) (collective) to use "
                "read_shared/write_shared")
        old = self._shared_win.fetch_and_op(
            0, np.asarray([n], dtype=np.int64))
        return int(np.asarray(old).reshape(-1)[0])

    def init_shared(self) -> None:
        """Collective: create the shared-pointer window (done automatically
        by ``file_open(..., shared=True)``)."""
        if self._shared_win is None:
            self._shared_win = self._comm.win_create(
                np.zeros(1, dtype=np.int64))

    def seek_shared(self, offset: int) -> None:
        """Collective in MPI; here rank-atomic: set the shared pointer."""
        w = self._shared_win
        if w is None:
            raise RuntimeError("file not opened with shared=True")
        w.lock(0, exclusive=True)
        w.put_at(0, np.asarray([int(offset)], dtype=np.int64))
        w.unlock(0)

    def write_shared(self, data: Any) -> int:
        """MPI_File_write_shared: each call atomically claims the next
        region of the file — ranks' records never overlap, order is
        whatever the pointer race decides [S]."""
        arr = np.asarray(data, dtype=self._etype)
        at = self._shared_fetch_add(arr.size)
        return self.write_at(at, arr)

    def read_shared(self, count: int) -> np.ndarray:
        at = self._shared_fetch_add(int(count))
        return self.read_at(at, count)

    # -- ordered shared-pointer collectives --------------------------------

    def _ordered_base(self, nelems: int) -> int:
        """Collective: claim a contiguous region ordered BY RANK (the
        MPI_File_*_ordered contract [S]): an exscan of sizes gives each
        rank its offset; rank size-1 advances the shared pointer past the
        whole epoch."""
        if self._shared_win is None:
            raise RuntimeError("file not opened with shared=True")
        sizes = self._comm.allgather(int(nelems))
        if not isinstance(sizes, (list, tuple)):  # stacked array form
            sizes = [int(s) for s in np.asarray(sizes).reshape(-1)]
        prefix = sum(sizes[: self._comm.rank])
        total = sum(sizes)
        # one rank advances the pointer for the whole epoch, atomically
        if self._comm.rank == 0:
            base = self._shared_fetch_add(total)
        else:
            base = None
        base = self._comm.bcast(base, 0)
        self._comm.barrier()
        return int(base) + prefix

    def write_ordered(self, data: Any) -> int:
        """MPI_File_write_ordered: like write_shared but records land in
        RANK ORDER — collective."""
        arr = np.asarray(data, dtype=self._etype)
        at = self._ordered_base(arr.size)
        n = self.write_at(at, arr)
        self._comm.barrier()
        return n

    def read_ordered(self, count: int) -> np.ndarray:
        """MPI_File_read_ordered: collective rank-ordered read through the
        shared pointer."""
        at = self._ordered_base(int(count))
        out = self.read_at(at, count)
        self._comm.barrier()
        return out

    # -- collective I/O ----------------------------------------------------

    def write_at_all(self, offset: int, data: Any) -> int:
        """MPI_File_write_at_all with two-phase collective buffering:
        small strided epochs aggregate at rank 0 and hit the file as ONE
        offset-sorted sweep; large payloads write independently inside
        the same barrier bracket."""
        self._check_open()
        arr, view = self._to_file_rep(data, int(offset))
        total = self._comm.allreduce(len(view))
        # the aggregate-vs-independent branch must be COLLECTIVE: ranks
        # compare the (already-allreduced) total against RANK 0's limit,
        # so an MPI_T cvar_write on a subset of ranks cannot diverge the
        # control flow (ADVICE r3 #2 — divergence surfaced as rank 0
        # blocking in _recv_internal for payloads that never come)
        limit = self._comm.bcast(_COLLECTIVE_BUFFER_LIMIT, 0)
        if total > limit:
            # reuse the bytes already converted above — a second
            # write_at would run the datarep conversion (and hold a
            # second full copy) exactly on the large-payload branch
            self._write_runs(int(offset), arr.size, view)
            self._comm.barrier()
            return arr.size
        # phase 1: ship (run, bytes) lists to the aggregator
        runs = self._byte_runs(int(offset), arr.size)
        payload, pos = [], 0
        for start, nbytes in runs:
            payload.append((start, bytes(view[pos:pos + nbytes])))
            pos += nbytes
        if self._comm.rank == 0:
            everyone = [payload] + [
                self._comm._recv_internal(r, _TAG_TWOPHASE)
                for r in range(1, self._comm.size)]
            # phase 2: one sorted sequential sweep
            flat = sorted((s, b) for rankruns in everyone for s, b in rankruns)
            for start, blob in flat:
                _pwrite_full(self._fd, memoryview(blob), start)
        else:
            self._comm._send_internal(payload, 0, _TAG_TWOPHASE)
        self._comm.barrier()
        return arr.size

    def read_at_all(self, offset: int, count: int) -> np.ndarray:
        """Collective read: barrier-bracketed independent preads (reads
        need no write-ordering phase; the bracket gives the collective
        completion semantics)."""
        self._comm.barrier()
        out = self.read_at(offset, count)
        self._comm.barrier()
        return out

    # -- sizes / sync / lifecycle ------------------------------------------

    def get_size(self) -> int:
        self._check_open()
        return os.fstat(self._fd).st_size

    def set_size(self, size: int) -> None:
        """Collective truncate/extend."""
        self._check_open()
        if self._comm.rank == 0:
            os.ftruncate(self._fd, int(size))
        self._comm.barrier()

    def preallocate(self, size: int) -> None:
        self.set_size(max(self.get_size(), int(size)))

    def sync(self) -> None:
        self._check_open()
        os.fsync(self._fd)

    def close(self) -> None:
        """Collective close; honors MODE_DELETE_ON_CLOSE."""
        if not self._open:
            return
        os.fsync(self._fd)
        self._comm.barrier()
        os.close(self._fd)
        self._open = False
        if self._shared_win is not None:
            self._shared_win.free()
            self._shared_win = None
        if self._amode & MODE_DELETE_ON_CLOSE and self._comm.rank == 0:
            try:
                os.unlink(self._path)
            except OSError:
                pass
        self._comm.barrier()

    def _check_open(self) -> None:
        if not self._open:
            raise RuntimeError("file is closed")

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def file_open(comm: Communicator, path: str, amode: int = MODE_RDWR,
              shared: bool = False, info: Optional[dict] = None) -> File:
    """MPI_File_open (collective).  ``shared=True`` additionally creates
    the shared-file-pointer window (needed for read/write_shared).
    ``info``: MPI_Info hints — accepted and currently advisory no-ops
    (collective buffering is always on below _COLLECTIVE_BUFFER_LIMIT)."""
    f = File(comm, path, amode)
    if shared:
        f.init_shared()
    return f


def file_delete(path: str) -> None:
    """MPI_File_delete."""
    os.unlink(path)
