"""Seeded bug: a wildcard receive two different ranks race to match."""


def main(comm):
    if comm.rank == 0:
        return comm.recv(ANY_SOURCE, tag=7)
    if comm.rank == 1:
        comm.send(b"x", 0, tag=7)
    if comm.rank == 2:
        comm.send(b"y", 0, tag=7)
    return None
