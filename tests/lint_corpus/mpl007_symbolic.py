"""Seeded bug: the mismatch is ``t`` vs ``t + 1`` — only constant
propagation proves the pair unmatchable."""


def main(comm):
    t = 5
    if comm.rank == 0:
        comm.send(b"m", 1, tag=t)
    elif comm.rank == 1:
        return comm.recv(0, tag=t + 1)
    return None
