"""Derived datatypes (mpi_tpu/datatypes.py): index-map constructors vs
numpy slicing oracles, composition, pack/unpack round trips, the jit
path, and typed send/recv over the local backend."""

import numpy as np
import pytest
pytest.importorskip(
    "hypothesis",
    reason="property tests need hypothesis, absent from this environment")
from hypothesis import given, settings
from hypothesis import strategies as st

from mpi_tpu import datatypes as dt
from mpi_tpu.transport.local import run_local


# -- constructors vs slicing oracles ---------------------------------------


def test_contiguous():
    t = dt.type_contiguous(5, np.float64).commit()
    buf = np.arange(10.0)
    assert np.array_equal(t.pack(buf), buf[:5])
    assert t.size == 5 * 8 and t.extent == 5


def test_vector_matrix_column():
    a = np.arange(20.0).reshape(4, 5)
    col = dt.type_vector(4, 1, 5, np.float64).commit()
    assert np.array_equal(col.pack(a), a[:, 0])
    # column j: pack from the flattened buffer offset j — use indexed shift
    shifted = dt.Datatype(col.base_dtype, col.indices + 2, col.extent)
    assert np.array_equal(shifted.pack(a), a[:, 2])


def test_vector_blocks():
    t = dt.type_vector(3, 2, 4, np.int32).commit()
    buf = np.arange(12, dtype=np.int32)
    assert np.array_equal(t.pack(buf), [0, 1, 4, 5, 8, 9])
    assert t.extent == (3 - 1) * 4 + 2


def test_indexed():
    t = dt.type_indexed([2, 1, 3], [0, 4, 7], np.int64).commit()
    buf = np.arange(10)
    assert np.array_equal(t.pack(buf), [0, 1, 4, 7, 8, 9])


def test_subarray_2d():
    full = np.arange(30.0).reshape(5, 6)
    t = dt.type_create_subarray([5, 6], [2, 3], [1, 2], np.float64).commit()
    assert np.array_equal(t.pack(full).reshape(2, 3), full[1:3, 2:5])
    # extent spans the whole array: count=2 walks consecutive arrays
    two = np.stack([full, full * 10])
    packed = t.pack(two, count=2)
    assert np.array_equal(packed[6:].reshape(2, 3), full[1:3, 2:5] * 10)


def test_subarray_3d():
    full = np.arange(2 * 3 * 4).reshape(2, 3, 4)
    t = dt.type_create_subarray([2, 3, 4], [1, 2, 2], [1, 0, 1], np.int64).commit()
    assert np.array_equal(t.pack(full).reshape(1, 2, 2), full[1:2, 0:2, 1:3])


def test_composition_vector_of_contiguous():
    pair = dt.type_contiguous(2, np.float32)
    t = dt.type_vector(3, 1, 2, pair).commit()  # every other pair
    buf = np.arange(12, dtype=np.float32)
    assert np.array_equal(t.pack(buf), [0, 1, 4, 5, 8, 9])


def test_struct_and_structured_dtype():
    rec = np.dtype([("a", np.int32), ("b", np.float64), ("c", np.int8)])
    t = dt.from_structured(rec).commit()
    buf = np.zeros(3, dtype=rec)
    buf["a"] = [1, 2, 3]
    buf["b"] = [0.5, 1.5, 2.5]
    buf["c"] = [7, 8, 9]
    packed = t.pack(buf, count=3)
    out = np.zeros(3, dtype=rec)
    t.unpack(packed, out, count=3)
    assert np.array_equal(out["a"], buf["a"])
    assert np.array_equal(out["b"], buf["b"])
    assert np.array_equal(out["c"], buf["c"])
    # size counts field bytes only; extent includes padding holes
    assert t.size == 4 + 8 + 1
    assert t.extent == rec.itemsize


def test_struct_heterogeneous_manual():
    t = dt.type_create_struct([2, 1], [0, 8], [np.int32, np.float64]).commit()
    raw = bytearray(16)
    np.frombuffer(raw, np.int32, 2, 0)[:] = [11, 22]
    np.frombuffer(raw, np.float64, 1, 8)[:] = [3.25]
    packed = t.pack(np.frombuffer(bytes(raw), np.uint8))
    out = np.zeros(16, np.uint8)
    t.unpack(packed, out)
    assert np.array_equal(np.frombuffer(out, np.int32, 2, 0), [11, 22])
    assert np.frombuffer(out, np.float64, 1, 8)[0] == 3.25


def test_resized_extent():
    t = dt.type_create_resized(dt.type_contiguous(2, np.int32), 0, 4).commit()
    buf = np.arange(10, dtype=np.int32)
    assert np.array_equal(t.pack(buf, count=2), [0, 1, 4, 5])


# -- validation -------------------------------------------------------------


def test_commit_rejects_overlap():
    bad = dt.type_indexed([2, 2], [0, 1], np.int32)
    with pytest.raises(ValueError, match="twice"):
        bad.commit()


def test_pack_bounds_checked():
    t = dt.type_vector(4, 1, 5, np.float64)
    with pytest.raises(ValueError, match="buffer has"):
        t.pack(np.zeros(10))


def test_dtype_mismatch_rejected():
    t = dt.type_contiguous(2, np.float64)
    with pytest.raises(TypeError):
        t.pack(np.zeros(4, np.float32))


def test_unpack_rejects_noncontiguous_target():
    """A strided view as the unpack target would scatter into a silent
    copy — must be rejected, not quietly dropped."""
    t = dt.type_vector(4, 1, 5, np.float64).commit()
    grid = np.zeros((4, 5))
    payload = np.arange(4.0)
    with pytest.raises(TypeError, match="C-contiguous"):
        t.unpack(payload, grid.T)
    with pytest.raises(TypeError, match="ndarray"):
        t.unpack(payload, [0.0] * 20)


def test_negative_displacement_rejected_even_uncommitted():
    """Without the check, Python negative indexing would alias the buffer
    tail instead of erroring."""
    bad = dt.type_indexed([1], [-1], np.float64)  # commit() not called
    with pytest.raises(ValueError, match="negative"):
        bad.pack(np.arange(4.0))
    with pytest.raises(ValueError, match="negative"):
        bad.unpack(np.zeros(1), np.zeros(4))


def test_subarray_bounds_rejected():
    with pytest.raises(ValueError, match="out of bounds"):
        dt.type_create_subarray([4, 4], [2, 2], [3, 0], np.float32)


def test_jax_paths_bounds_checked():
    """jnp.take would silently clamp/fill OOB — the static check must fire
    at trace time like the numpy path does."""
    t = dt.type_vector(4, 1, 5, np.float64).commit()
    with pytest.raises(ValueError, match="buffer has"):
        t.pack_jax(np.arange(10.0))
    with pytest.raises(ValueError, match="buffer has"):
        t.unpack_jax(np.zeros(4), np.zeros(10))


def test_unpack_dtype_mismatch_rejected():
    t = dt.type_contiguous(3, np.int64).commit()
    with pytest.raises(TypeError, match="payload dtype"):
        t.unpack(np.array([1.9, 2.9, -3.9]), np.zeros(3, np.int64))


def test_recv_buf_without_datatype_rejected():
    from mpi_tpu import api

    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(3.0), dest=1)
            return None
        with pytest.raises(ValueError, match="BOTH"):
            api.MPI_Recv(source=0, comm=comm, buf=np.zeros(3))
        return comm.recv(source=0)  # drain the message

    run_local(prog, 2)


# -- pack/unpack round trip property ---------------------------------------


@settings(max_examples=60, deadline=None)
@given(st.integers(1, 5), st.integers(1, 4), st.integers(1, 8),
       st.integers(1, 3))
def test_vector_roundtrip_property(count, blocklen, stride, instances):
    stride = max(stride, blocklen)
    t = dt.type_vector(count, blocklen, stride, np.float64).commit()
    need = t.extent * instances
    buf = np.random.default_rng(0).normal(size=need)
    packed = t.pack(buf, instances)
    out = np.full(need, np.nan)
    t.unpack(packed, out, instances)
    idx = np.concatenate([t.indices + i * t.extent for i in range(instances)])
    assert np.array_equal(out[idx], buf[idx])
    mask = np.ones(need, bool)
    mask[idx] = False
    assert np.all(np.isnan(out[mask]))  # untouched holes stay untouched


# -- MPI_Pack / MPI_Unpack ---------------------------------------------------


def test_pack_unpack_position_cursor():
    t = dt.type_vector(2, 1, 3, np.int32).commit()
    buf = np.arange(6, dtype=np.int32)
    cursor = bytearray()
    dt.pack(buf, t, 1, cursor)
    dt.pack(buf * 10, t, 1, cursor)
    assert len(cursor) == 2 * dt.pack_size(1, t)
    out1 = np.zeros(6, np.int32)
    out2 = np.zeros(6, np.int32)
    off = dt.unpack(cursor, t, out1)
    dt.unpack(cursor, t, out2, offset=off)
    assert out1[0] == 0 and out1[3] == 3
    assert out2[0] == 0 and out2[3] == 30


# -- jit path ---------------------------------------------------------------


def test_pack_jax_matches_numpy():
    import jax

    a = np.arange(24.0, dtype=np.float32).reshape(4, 6)
    t = dt.type_create_subarray([4, 6], [2, 3], [1, 2], np.float32).commit()
    jpacked = jax.jit(t.pack_jax)(a)
    assert np.array_equal(np.asarray(jpacked), t.pack(a))
    out = jax.jit(t.unpack_jax)(jpacked, np.zeros_like(a))
    expect = np.zeros_like(a)
    t.unpack(t.pack(a), expect)
    assert np.array_equal(np.asarray(out), expect)


# -- typed send/recv over a real backend ------------------------------------


def test_typed_send_recv_column_local_backend():
    """Rank 0 sends column 2 of its matrix; rank 1 scatters it into
    column 0 of a zero matrix — the classic MPI_Type_vector demo."""
    from mpi_tpu import api

    a = np.arange(20.0).reshape(4, 5)

    def prog(comm):
        col = dt.type_vector(4, 1, 5, np.float64).commit()
        if comm.rank == 0:
            api.MPI_Send(a, dest=1, comm=comm,
                         datatype=dt.Datatype(col.base_dtype,
                                              col.indices + 2, col.extent))
            return None
        out = np.zeros((4, 5))
        api.MPI_Recv(source=0, comm=comm, datatype=col, buf=out)
        return out

    res = run_local(prog, 2)
    assert np.array_equal(res[1][:, 0], a[:, 2])
    assert np.all(res[1][:, 1:] == 0)


def test_typed_halo_exchange_subarray():
    """2-rank halo exchange of interior edge columns using subarray types —
    the Jacobi-face pattern the constructor exists for."""
    from mpi_tpu import api

    n = 6

    def prog(comm):
        grid = np.full((n, n), float(comm.rank + 1))
        send_col = 1 if comm.rank == 1 else n - 2
        recv_col = n - 1 if comm.rank == 0 else 0
        tsend = dt.type_create_subarray([n, n], [n, 1], [0, send_col],
                                        np.float64).commit()
        trecv = dt.type_create_subarray([n, n], [n, 1], [0, recv_col],
                                        np.float64).commit()
        other = 1 - comm.rank
        payload = comm.sendrecv(tsend.pack(grid), other, other)
        trecv.unpack(payload, grid)
        return grid

    res = run_local(prog, 2)
    assert np.all(res[0][:, n - 1] == 2.0)
    assert np.all(res[0][:, : n - 1] == 1.0)
    assert np.all(res[1][:, 0] == 1.0)


def test_resized_lb_is_marker_not_shift():
    """MPI_Type_create_resized leaves typemap displacements unchanged; lb
    only affects the reported extent bookkeeping [S]."""
    from mpi_tpu import api

    t = dt.type_create_resized(np.int32, 1, 3).commit()
    assert np.array_equal(t.pack(np.arange(4, dtype=np.int32)), [0])
    assert api.MPI_Type_get_extent(t) == (4, 12)


def test_tiled_overlap_rejected_on_unpack_only():
    """Instances replicated at an extent inside the map's span overlap:
    order-dependent UNPACK must be rejected, while the overlapping SEND
    typemap stays legal (MPI permits reading an element twice)."""
    t = dt.type_create_resized(dt.type_contiguous(2, np.int32), 0, 1).commit()
    with pytest.raises(ValueError, match="overlap"):
        t.unpack(np.arange(4, dtype=np.int32), np.zeros(3, np.int32), count=2)
    packed = t.pack(np.arange(8, dtype=np.int32), count=2)
    assert np.array_equal(packed, [0, 1, 1, 2])  # element 1 read twice: fine


def test_errhandler_covers_typed_paths():
    """Pack/unpack failures inside typed MPI_Send/MPI_Recv honor the
    communicator's error handler; a custom handler's fallback is returned
    as-is, never scattered into buf."""
    from mpi_tpu import api, errors

    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        t = dt.type_contiguous(2, np.float64).commit()
        if comm.rank == 0:
            comm.send(np.arange(3.0), dest=1)
            # pack error on send side returns a code too
            code = api.MPI_Send(np.zeros(1), dest=1, comm=comm, datatype=t)
            assert isinstance(code, errors.ErrorCode)
            comm.send(np.arange(2.0), dest=1)  # keep rank 1's drain happy
            return None
        buf = np.zeros(2)
        code = api.MPI_Recv(source=0, comm=comm, datatype=t, buf=buf)
        assert isinstance(code, errors.ErrorCode)
        assert code == errors.MPI_ERR_TRUNCATE
        comm.set_errhandler(lambda c, e: "fallback")
        assert api.MPI_Recv(source=77, comm=comm, datatype=t, buf=buf) \
            == "fallback"
        assert np.all(buf == 0)
        comm.set_errhandler(errors.ERRORS_ARE_FATAL)
        return comm.recv(source=0)

    run_local(prog, 2)


def test_typed_halo_exchange_on_spmd_backend():
    """Datatypes compose with the TPU backend: pack_jax gathers the halo
    face inside the jitted SPMD program, shift ships it as one ppermute,
    unpack_jax scatters it — the device-side spelling of the typed halo
    exchange (same index maps as the process backends)."""
    import mpi_tpu

    n = 6

    def prog(comm):
        import jax.numpy as jnp

        grid = jnp.full((n, n), comm.rank + 1.0)
        send_face = dt.type_create_subarray([n, n], [n, 1], [0, n - 2],
                                            np.float32).commit()
        recv_face = dt.type_create_subarray([n, n], [n, 1], [0, 0],
                                            np.float32).commit()
        payload = send_face.pack_jax(grid)          # gather, on device
        got = comm.shift(payload, offset=1)         # one lax.ppermute
        return recv_face.unpack_jax(got, grid)      # scatter, on device

    res = np.asarray(mpi_tpu.run(prog, backend="tpu", nranks=None))
    p = res.shape[0]
    for r in range(p):
        left = (r - 1) % p + 1
        assert np.all(res[r][:, 0] == left)          # halo from left neighbor
        assert np.all(res[r][:, 1:] == r + 1)        # interior untouched


def test_jax_paths_dtype_checked():
    t = dt.type_contiguous(2, np.int32).commit()
    with pytest.raises(TypeError, match="dtype"):
        t.pack_jax(np.zeros(4, np.float32))
    with pytest.raises(TypeError, match="dtype"):
        t.unpack_jax(np.zeros(2, np.int32), np.zeros(4, np.float32))
    # float64 maps are satisfied by jax's canonical float32 arrays
    f64 = dt.type_contiguous(2, np.float64).commit()
    assert f64.pack_jax(np.arange(4.0)).dtype in (np.float32, np.float64)


def test_struct_pack_jax_matches_host_bytes():
    """Byte-based maps bitcast the buffer to a uint8 stream on the jit
    path, so jit and host packs agree byte-for-byte (review round 3:
    byte offsets were applied as element offsets)."""
    import jax.numpy as jnp

    rec = np.dtype([("a", np.float32), ("b", np.int32)])
    t = dt.from_structured(rec).commit()
    buf = np.zeros(2, dtype=rec)
    buf["a"] = [1.5, -2.25]
    buf["b"] = [7, -9]
    host = t.pack(buf, count=2)
    dev = t.pack_jax(jnp.asarray(buf.view(np.float32)), count=2)
    assert np.array_equal(np.asarray(dev), host)
    # and the unpack round-trips through the bitcast path
    out = t.unpack_jax(dev, jnp.zeros(4, jnp.float32), count=2)
    assert np.array_equal(np.asarray(out).view(rec)["b"], buf["b"])


def test_unpack_jax_validates_payload():
    c = dt.type_contiguous(2, np.float32).commit()
    with pytest.raises(TypeError, match="payload dtype"):
        c.unpack_jax(np.array([7, 8], np.int32), np.zeros(4, np.float32))
    with pytest.raises(ValueError, match="payload has"):
        c.unpack_jax(np.float32(5.0), np.zeros(4, np.float32))


def test_pack_external_big_endian_roundtrip():
    """external32 wire bytes are big-endian regardless of host order."""
    t = dt.type_vector(2, 1, 2, np.int32).commit()
    buf = np.array([0x01020304, 0, 0x0A0B0C0D, 0], np.int32)
    wire = dt.pack_external(buf, t)
    assert wire == bytes([1, 2, 3, 4, 0x0A, 0x0B, 0x0C, 0x0D])
    out = np.zeros(4, np.int32)
    used = dt.unpack_external(wire, t, out)
    assert used == 8
    assert np.array_equal(out, [0x01020304, 0, 0x0A0B0C0D, 0])


def test_pack_external_struct_field_wise():
    """Struct (byte-based) maps byteswap FIELD-WISE — a whole-stream
    swap on uint8 is a no-op and would leak host endianness (review
    round 3)."""
    t = dt.type_create_struct([1, 1], [0, 4], [np.int32, np.int16]).commit()
    buf = np.zeros(8, np.uint8)
    np.frombuffer(buf, np.int32, 1, 0)[:] = [0x01020304]
    np.frombuffer(buf, np.int16, 1, 4)[:] = [0x0A0B]
    wire = dt.pack_external(buf, t)
    assert wire == bytes([1, 2, 3, 4, 0x0A, 0x0B])  # big-endian per field
    out = np.zeros(8, np.uint8)
    dt.unpack_external(wire, t, out)
    assert np.frombuffer(out, np.int32, 1, 0)[0] == 0x01020304
    assert np.frombuffer(out, np.int16, 1, 4)[0] == 0x0A0B


def test_pack_external_structured_dtype_and_count():
    rec = np.dtype([("a", np.int32), ("b", np.int16)])
    t = dt.from_structured(rec).commit()
    buf = np.zeros(2, rec)
    buf["a"] = [0x01020304, 0x11121314]
    buf["b"] = [0x0A0B, 0x1A1B]
    wire = dt.pack_external(buf, t, count=2)
    assert wire[:4] == bytes([1, 2, 3, 4]) and wire[4:6] == bytes([0x0A, 0x0B])
    assert wire[6:10] == bytes([0x11, 0x12, 0x13, 0x14])
    out = np.zeros(2, rec)
    dt.unpack_external(wire, t, out, count=2)
    assert np.array_equal(out["a"], buf["a"]) and np.array_equal(out["b"], buf["b"])


def test_pack_external_complex_component_wise():
    """complex members swap per 4/8-byte COMPONENT, not per element —
    whole-element reversal would swap real/imag on the wire."""
    t = dt.type_create_struct([1], [0], [np.complex64]).commit()
    buf = np.zeros(8, np.uint8)
    np.frombuffer(buf, np.complex64)[:] = [1 + 2j]
    wire = dt.pack_external(buf, t)
    assert wire == bytes.fromhex("3f80000040000000")  # real then imag, BE
    out = np.zeros(8, np.uint8)
    dt.unpack_external(wire, t, out)
    assert np.frombuffer(out, np.complex64)[0] == 1 + 2j


def test_pack_external_bytes_and_resized():
    """MPI_BYTE external32 is the identity; resized structs keep their
    swap metadata."""
    byte_t = dt.type_contiguous(4, np.uint8).commit()
    assert dt.pack_external(np.arange(4, dtype=np.uint8), byte_t) == bytes([0, 1, 2, 3])
    mixed = dt.type_create_struct([1, 2], [0, 4], [np.int32, np.uint8]).commit()
    buf = np.zeros(6, np.uint8)
    np.frombuffer(buf, np.int32, 1, 0)[:] = [0x01020304]
    buf[4:6] = [9, 8]
    assert dt.pack_external(buf, mixed) == bytes([1, 2, 3, 4, 9, 8])
    rs = dt.type_create_resized(mixed, 0, 8).commit()
    assert dt.pack_external(np.zeros(8, np.uint8), rs) is not None


def test_mrecv_honors_errhandler():
    from mpi_tpu import api, errors

    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        comm.send("x", dest=0, tag=1)
        msg = comm.mprobe(source=0, tag=1)
        assert api.MPI_Mrecv(msg) == "x"
        code = api.MPI_Mrecv(msg)  # second consume: ErrorCode, not raise
        assert isinstance(code, errors.ErrorCode)
        comm.set_errhandler(errors.ERRORS_ARE_FATAL)

    run_local(prog, 1)


def test_hvector_and_hindexed_byte_units():
    t = dt.type_create_hvector(3, 1, 8, np.int32).commit()  # stride 2 elems
    buf = np.arange(6, dtype=np.int32)
    assert np.array_equal(t.pack(buf), [0, 2, 4])
    hi = dt.type_create_hindexed([1, 2], [4, 12], np.int32).commit()
    assert np.array_equal(hi.pack(np.arange(5, dtype=np.int32)), [1, 3, 4])
    with pytest.raises(ValueError, match="multiple of"):
        dt.type_create_hvector(2, 1, 5, np.int32)
    with pytest.raises(ValueError, match="multiple of"):
        dt.type_create_hindexed([1], [2], np.float64)


def test_hvector_derived_base_uses_extent_units():
    """Byte strides convert via the base EXTENT (a derived base spans
    extent elements) — itemsize division landed wrong offsets (review
    round 3)."""
    pair = dt.type_contiguous(2, np.int32)  # extent 8 bytes
    t = dt.type_create_hvector(2, 1, 8, pair).commit()
    assert np.array_equal(t.pack(np.arange(8, dtype=np.int32)), [0, 1, 2, 3])
    hi = dt.type_create_hindexed([1], [8], pair).commit()
    assert np.array_equal(hi.pack(np.arange(6, dtype=np.int32)), [2, 3])
