"""Shared-memory windows — MPI_Win_allocate_shared [S: MPI-3 ch.11.2.3].

The one RMA window kind where LOAD/STORE replaces message passing: all
ranks of a shared-memory communicator (every process world this
library's launcher starts is single-host — exactly MPI's
COMM_TYPE_SHARED domain) map ONE /dev/shm segment, and each rank's
window region is directly addressable by every other rank as a numpy
view.  ``remote(rank)`` is MPI_Win_shared_query; plain array reads and
writes are the RMA.

Synchronization is the window-sync model [S]: mmap(MAP_SHARED) on one
host is cache-coherent, so ``sync()`` only needs a compiler/CPU
ordering point (a lock round-trip) — ordering between ranks is the
caller's job via ``comm.barrier()`` or p2p, as in MPI.  The thread
backend maps the same file per-thread, which degenerates to plain
shared memory (views differ, coherence is trivial).
"""

from __future__ import annotations

import mmap
import os
import tempfile
import threading
from typing import Any, Optional

import numpy as np

from .communicator import Communicator, P2PCommunicator

__all__ = ["SharedWindow", "win_allocate_shared"]


class SharedWindow:
    """One shared segment; rank r owns the region ``local``; any region
    is load/store-addressable via ``remote(r)``."""

    def __init__(self, comm: P2PCommunicator, nelems: int, dtype: Any):
        self._comm = comm
        self._dtype = np.dtype(dtype)
        sizes = comm.allgather(int(nelems))
        if not isinstance(sizes, list):
            sizes = [int(s) for s in np.asarray(sizes).reshape(-1)]
        self._sizes = sizes
        self._offsets = np.concatenate(([0], np.cumsum(sizes)))[:-1]
        total_bytes = int(sum(sizes)) * self._dtype.itemsize
        # rank 0 creates the segment; everyone maps the same file
        if comm.rank == 0:
            base = "/dev/shm" if os.path.isdir("/dev/shm") else None
            fd, path = tempfile.mkstemp(prefix="mpi_tpu_shmwin_", dir=base)
            os.ftruncate(fd, max(total_bytes, 1))
            os.close(fd)
        else:
            path = None
        self._path = comm.bcast(path, 0)
        self._fd = os.open(self._path, os.O_RDWR)
        self._map = mmap.mmap(self._fd, max(total_bytes, 1),
                              mmap.MAP_SHARED)
        self._buf = np.frombuffer(self._map, dtype=self._dtype,
                                  count=int(sum(sizes)))
        self._open = True
        self._sync_lock = threading.Lock()
        comm.barrier()  # all mapped before anyone stores

    # -- addressing (MPI_Win_shared_query) ---------------------------------

    def remote(self, rank: int) -> np.ndarray:
        """Rank ``rank``'s region as a live shared view (loads AND stores
        hit the shared segment directly)."""
        self._check_open()
        if not (0 <= rank < self._comm.size):
            raise ValueError(f"rank {rank} out of range "
                             f"(size {self._comm.size})")
        off = int(self._offsets[rank])
        return self._buf[off:off + self._sizes[rank]]

    @property
    def local(self) -> np.ndarray:
        return self.remote(self._comm.rank)

    @property
    def whole(self) -> np.ndarray:
        """The entire segment (all ranks' regions, in rank order)."""
        self._check_open()
        return self._buf

    # -- synchronization ---------------------------------------------------

    def sync(self) -> None:
        """MPI_Win_sync: an ordering point for this rank's loads/stores
        (mmap MAP_SHARED is coherent on one host; a lock round-trip is
        the required memory barrier).  Cross-rank ORDERING still needs
        comm.barrier()/p2p, per MPI."""
        self._check_open()
        with self._sync_lock:
            pass

    def fence(self) -> None:
        """Convenience: sync + barrier — the bulk-synchronous epoch."""
        self.sync()
        self._comm.barrier()

    # -- lifecycle ---------------------------------------------------------

    def free(self) -> None:
        """Collective: detach; rank 0 unlinks after everyone detached.
        If the caller still holds live views (remote()/local arrays),
        the mapping cannot close eagerly — it is left to the GC; the
        segment file is unlinked regardless (the mapping keeps working
        until the views die, the name is gone immediately)."""
        if not self._open:
            return
        self._open = False
        self._buf = None
        try:
            self._map.close()
        except BufferError:
            pass  # user-held views pin the mapping; GC reclaims it
        os.close(self._fd)
        self._comm.barrier()
        if self._comm.rank == 0:
            try:
                os.unlink(self._path)
            except OSError:
                pass
        self._comm.barrier()

    def _check_open(self) -> None:
        if not self._open:
            raise RuntimeError("shared window is freed")


def win_allocate_shared(comm: Optional[Communicator], nelems: int,
                        dtype: Any = np.float64) -> SharedWindow:
    """MPI_Win_allocate_shared: collectively allocate one host-shared
    segment; rank r contributes ``nelems`` elements (may differ per
    rank, 0 allowed)."""
    if comm is None:
        from . import init

        comm = init()
    if not isinstance(comm, P2PCommunicator):
        raise NotImplementedError(
            "shared-memory windows are load/store on host RAM — a "
            "process-backend feature (COMM_TYPE_SHARED domain); device "
            "arrays already share HBM addressing inside one SPMD program")
    return SharedWindow(comm, int(nelems), dtype)
