#!/usr/bin/env python
"""Chaos smoke: FaultyTransport drop/delay/duplicate sweep over the
collective family, asserting DIAGNOSE-DON'T-HANG.

The failure story's CI tripwire (ISSUE 3 satellite): every cell runs one
in-process local world through a fault-injecting transport and records
the outcome.  A cell may *succeed* (the fault was absorbed — e.g. a
delay, or a duplicate the matching engine never mismatched) or *fail
diagnosably* (RecvTimeout / ProcFailedError / TransportError naming the
stuck channel) — what it may never do is HANG: a run_local deadlock
timeout fails the sweep.  That is exactly the library's failure-semantics
contract (README "Failure semantics"), checked across every collective
algorithm gate rather than argued about.

Duplicate-injection cells additionally record result corruption
(``wrong_result``) honestly instead of asserting it away: a duplicated
internal frame can legally mis-fold a later collective on the same
channel — the sweep documents which schedules are sensitive, it does not
promise they aren't.

Usage::

    python benchmarks/chaos.py            # full sweep, JSON to stdout
    python benchmarks/chaos.py --quick    # tier-1 smoke (fewer cells)
    python bench.py --chaos [--quick]     # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_tpu import mpit  # noqa: E402
from mpi_tpu.errors import ProcFailedError, RevokedError  # noqa: E402
from mpi_tpu.transport.base import RecvTimeout, TransportError  # noqa: E402
from mpi_tpu.transport.faulty import FaultyTransport  # noqa: E402
from mpi_tpu.transport.local import run_local  # noqa: E402

NRANKS = 4  # pow2: exercises halving/doubling gates too
RECV_TIMEOUT_S = 2.0  # the diagnosis bound a dropped message hits
WORLD_TIMEOUT_S = 30.0  # run_local deadlock ceiling = the HANG verdict

# (name, per-rank collective call).  Payloads are small (latency-path
# schedules) — chaos probes control-flow robustness, not bandwidth.
COLLECTIVES = [
    ("bcast", lambda c: c.bcast(np.arange(8.0), root=0)),
    ("reduce", lambda c: c.reduce(np.ones(8), root=0)),
    ("allreduce-ring", lambda c: c.allreduce(np.ones(8), algorithm="ring")),
    ("allreduce-halving", lambda c: c.allreduce(
        np.ones(8), algorithm="recursive_halving")),
    ("allreduce-rabenseifner", lambda c: c.allreduce(
        np.ones(8), algorithm="rabenseifner")),
    ("allgather-ring", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="ring")),
    ("allgather-doubling", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="doubling")),
    ("alltoall", lambda c: c.alltoall([np.full(2, c.rank)] * c.size)),
    ("reduce_scatter", lambda c: c.reduce_scatter(np.ones((c.size, 4)))),
    ("scatter", lambda c: c.scatter(
        [np.full(2, d) for d in range(c.size)] if c.rank == 0 else None,
        root=0)),
    ("gather", lambda c: c.gather(np.full(2, c.rank), root=0)),
    ("scan", lambda c: c.scan(np.ones(4))),
    ("barrier", lambda c: c.barrier()),
]

FAULTS = [
    ("drop", dict(drop_every=5)),
    ("delay", dict(delay_s=0.01)),
    ("duplicate", dict(duplicate_every=5)),
]

QUICK_COLLECTIVES = ("allreduce-ring", "alltoall", "reduce_scatter",
                     "barrier")


def _oracle(name: str, comm_size: int):
    """Expected fault-free result per rank (None = don't check)."""
    if name.startswith("allreduce"):
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(8, float(comm_size)))
    if name == "scan":
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(4, float(r + 1)))
    return None


def run_cell(coll_name: str, call, fault_kw: Dict) -> Dict:
    wrapper = FaultyTransport.wrapper(**fault_kw)
    check = _oracle(coll_name, NRANKS)

    def fn(comm):
        got = call(comm)
        if check is not None and not check(comm.rank, got):
            return "wrong_result"
        return "ok"

    t0 = time.monotonic()
    try:
        res = run_local(fn, NRANKS, transport_wrapper=wrapper,
                        recv_timeout=RECV_TIMEOUT_S, timeout=WORLD_TIMEOUT_S)
        outcome = ("wrong_result" if "wrong_result" in res else "ok")
    except TimeoutError as e:
        outcome = f"HANG: {e}"  # the one unacceptable verdict
    except RuntimeError as e:
        # run_local wraps the first rank error; classify its cause
        cause = e.__cause__
        if isinstance(cause, (RecvTimeout, ProcFailedError, RevokedError,
                              TransportError)):
            outcome = f"diagnosed:{type(cause).__name__}"
        else:
            outcome = f"error:{type(cause).__name__}: {str(cause)[:120]}"
    return {"collective": coll_name, "fault": dict(fault_kw),
            "outcome": outcome,
            "wall_ms": round((time.monotonic() - t0) * 1e3, 1)}


def run_chaos(quick: bool = False) -> Dict:
    t0 = time.time()
    ses = mpit.session_create()
    ses.reset_all()
    colls = [(n, c) for n, c in COLLECTIVES
             if not quick or n in QUICK_COLLECTIVES]
    cells: List[Dict] = []
    for fault_name, fault_kw in FAULTS:
        for coll_name, call in colls:
            cell = run_cell(coll_name, call, fault_kw)
            cell["fault_name"] = fault_name
            cells.append(cell)
    hangs = [c for c in cells if c["outcome"].startswith("HANG")]
    return {
        "quick": quick,
        "nranks": NRANKS,
        "recv_timeout_s": RECV_TIMEOUT_S,
        "cells": cells,
        "hangs": hangs,
        "injected": {"dropped": ses.read("faulty_dropped"),
                     "duplicated": ses.read("faulty_duplicated")},
        "ok": not hangs,
        "wall_s": round(time.time() - t0, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: a subset of collectives per fault")
    args = ap.parse_args(argv)
    result = run_chaos(quick=args.quick)
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
