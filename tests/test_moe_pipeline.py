"""EP (mixture-of-experts via alltoall) and PP (GPipe microbatch streaming
via non-wrap shift) examples: oracle parity on the thread backend and the
SPMD backend (SURVEY.md §2 strategy table: EP/PP expressed through the
framework's primitives)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from examples.moe import moe_layer, moe_oracle
from examples.pipeline import pipeline_forward, pipeline_oracle
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

P = 4


def _moe_fixtures(T=12, D=6, F=10, C=5):
    root = jax.random.PRNGKey(3)
    x_all = jax.random.normal(jax.random.fold_in(root, 0), (P, T, D),
                              jnp.float32)
    w_router = jax.random.normal(jax.random.fold_in(root, 1), (D, P),
                                 jnp.float32)
    w_in = jax.random.normal(jax.random.fold_in(root, 2), (P, D, F),
                             jnp.float32) * 0.3
    w_out = jax.random.normal(jax.random.fold_in(root, 3), (P, F, D),
                              jnp.float32) * 0.3
    return x_all, w_router, w_in, w_out, C


def test_moe_parity_both_backends():
    x_all, w_router, w_in, w_out, C = _moe_fixtures()
    expect = moe_oracle(np.asarray(x_all), np.asarray(w_router),
                        np.asarray(w_in), np.asarray(w_out), C)

    def prog(comm):
        r = comm.rank
        return moe_layer(comm, jnp.asarray(x_all)[r], w_router,
                         jnp.asarray(w_in)[r], jnp.asarray(w_out)[r], C)

    got_local = np.stack([np.asarray(o) for o in run_local(prog, P)])
    np.testing.assert_allclose(got_local, expect, atol=1e-4)
    got_spmd = np.asarray(run_spmd(prog, nranks=P))
    np.testing.assert_allclose(got_spmd, expect, atol=1e-4)


def test_moe_capacity_drops_tokens():
    """With capacity 1, at most one token per (source, expert) survives."""
    x_all, w_router, w_in, w_out, _ = _moe_fixtures()
    expect = moe_oracle(np.asarray(x_all), np.asarray(w_router),
                        np.asarray(w_in), np.asarray(w_out), 1)

    def prog(comm):
        r = comm.rank
        return moe_layer(comm, jnp.asarray(x_all)[r], w_router,
                         jnp.asarray(w_in)[r], jnp.asarray(w_out)[r], 1)

    got = np.stack([np.asarray(o) for o in run_local(prog, P)])
    np.testing.assert_allclose(got, expect, atol=1e-4)
    # capacity 1 must actually drop something relative to capacity 5
    full = moe_oracle(np.asarray(x_all), np.asarray(w_router),
                      np.asarray(w_in), np.asarray(w_out), 5)
    assert (np.abs(expect) < 1e-9).sum() > (np.abs(full) < 1e-9).sum()


def _pipeline_fixtures(M=6, B=3, D=5):
    root = jax.random.PRNGKey(9)
    micro_x = jax.random.normal(jax.random.fold_in(root, 0), (M, B, D),
                                jnp.float32)
    ws = [np.asarray(jax.random.normal(jax.random.fold_in(root, r), (D, D),
                                       jnp.float32)) * 0.5 for r in range(P)]
    bs = [np.asarray(jax.random.normal(jax.random.fold_in(root, 100 + r),
                                       (D,), jnp.float32)) * 0.1
          for r in range(P)]
    return micro_x, ws, bs


def test_pipeline_parity_both_backends():
    micro_x, ws, bs = _pipeline_fixtures()
    expect = pipeline_oracle(np.asarray(micro_x), ws, bs)

    def prog(comm):
        r = comm.rank
        w = jnp.asarray(np.stack(ws))[r]
        b = jnp.asarray(np.stack(bs))[r]
        return pipeline_forward(comm, jnp.asarray(micro_x), w, b)

    got_local = run_local(prog, P)
    np.testing.assert_allclose(np.asarray(got_local[P - 1]), expect,
                               atol=1e-5)
    got_spmd = np.asarray(run_spmd(prog, nranks=P))
    np.testing.assert_allclose(got_spmd[P - 1], expect, atol=1e-5)
