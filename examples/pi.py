"""Distributed π Monte-Carlo (SURVEY.md §2 component #13; BASELINE.json:11).

Each rank samples independently and the hit counts are summed with
``allreduce`` — the canonical 'first MPI program'.  Written once against the
portable Communicator API, it runs unmodified on every backend (the
source-compatibility contract, BASELINE.json:5):

    python -m mpi_tpu.launcher -n 4 examples/pi.py          # socket ranks
    python examples/pi.py --backend local -n 4              # threads
    python examples/pi.py --backend tpu -n 8                # one SPMD program

The program body is jax-numpy end-to-end, so the same code traces under
shard_map (rank is a traced scalar there) and executes eagerly per-process
on the CPU backends (rank is an int there).
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np

from mpi_tpu import ops


def pi_program(comm, n_per_rank: int = 200_000):
    key = jax.random.fold_in(jax.random.PRNGKey(42), comm.rank)
    pts = jax.random.uniform(key, (n_per_rank, 2))
    hits = jnp.sum((pts * pts).sum(axis=1) <= 1.0, dtype=jnp.float32)
    total = comm.allreduce(hits, op=ops.SUM)
    return 4.0 * total / (n_per_rank * comm.size)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--samples", type=int, default=200_000)
    args = ap.parse_args()

    result = mpi_tpu.run(pi_program, backend=args.backend, nranks=args.nranks,
                         n_per_rank=args.samples)
    est = float(np.ravel(np.asarray(jax.device_get(result)))[0])
    print(f"pi ~= {est:.6f}  (error {abs(est - np.pi):.2e})")


if __name__ == "__main__":
    main()
