#!/usr/bin/env python
"""Windowed-bandwidth probe for the shm-vs-socket gap (VERDICT r2 #4).

Runs the osu_bw windowed benchmark at bandwidth-sized payloads over both
process transports, sweeping the shm ring capacity, and prints one JSON
line per config — the measurement harness behind the root-cause note in
transport/shm.py.  Usage::

    python benchmarks/shm_bw_probe.py [--sizes 4194304,16777216] [--iters 8]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys, json, time, statistics
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu

comm = mpi_tpu.init()
nbytes = int(os.environ["PROBE_BYTES"])
iters = int(os.environ["PROBE_ITERS"])
window = max(2, min(64, (32 << 20) // max(1, nbytes)))
payload = np.zeros(max(1, nbytes // 4), np.float32)
samples = []
for i in range(2 + iters):
    comm.barrier()
    t0 = time.perf_counter()
    if comm.rank == 0:
        for w in range(window):
            comm.send(payload, dest=1, tag=w)
        comm.recv(source=1, tag=10_000)
    else:
        for w in range(window):
            comm.recv(source=0, tag=w)
        comm.send(b"ack", dest=0, tag=10_000)
    if i >= 2:
        samples.append(time.perf_counter() - t0)
if comm.rank == 0:
    t = statistics.median(samples)
    with open(os.environ["PROBE_OUT"], "w") as f:
        json.dump({{"bytes": nbytes, "window": window,
                    "bw_gbps": window * nbytes / t / 1e9}}, f)
mpi_tpu.finalize()
"""


def run_one(backend: str, nbytes: int, iters: int, ring_bytes=None):
    sys.path.insert(0, REPO)
    from mpi_tpu.launcher import launch

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "out.json")
        script = os.path.join(td, "prog.py")
        with open(script, "w") as f:
            f.write(WORKER.format(repo=REPO))
        env = {"PROBE_OUT": out, "PROBE_BYTES": str(nbytes),
               "PROBE_ITERS": str(iters)}
        if ring_bytes is not None:
            env["MPI_TPU_SHM_RING_BYTES"] = str(ring_bytes)
        rc = launch(2, [script], env_extra=env, timeout=600.0,
                    backend=backend)
        if rc != 0:
            return {"error": f"exit {rc}"}
        with open(out) as f:
            return json.load(f)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="4194304,16777216")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--rings", default="4194304,33554432,67108864",
                    help="shm ring capacities to sweep")
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]
    rings = [int(r) for r in args.rings.split(",")]
    for nbytes in sizes:
        r = run_one("socket", nbytes, args.iters)
        print(json.dumps({"backend": "socket", **r}), flush=True)
        for ring in rings:
            r = run_one("shm", nbytes, args.iters, ring_bytes=ring)
            print(json.dumps({"backend": "shm", "ring": ring, **r}),
                  flush=True)


if __name__ == "__main__":
    main()
