"""Regenerate TPU_EVIDENCE.json — machine-checkable silicon evidence.

VERDICT r3 missing #1: every chip-side claim must live in a committed,
regenerable artifact, not commit-message prose.  One command:

    python tools/tpu_evidence.py            # writes TPU_EVIDENCE.json

What it records, in order of strength:

1. **tunnel**: whether ``jax.devices()`` on the accelerator platform
   completes within the timeout (probed in a SUBPROCESS so a wedged
   device pool can never hang this script), and the platform/device it
   found.
2. **real_tpu_tests**: if the tunnel is up, the full real-TPU tier
   (``MPI_TPU_TEST_TPU=1 pytest -m tpu``) — per-test IDs and outcomes
   parsed from pytest's summary.
3. **entry_on_chip**: if the tunnel is up, ``__graft_entry__.entry()``
   executed on the chip (platform recorded from the result's device).
4. **cross_platform_export**: ALWAYS — ``jax.export`` of (a) the 1-D
   pallas_ring kernel, (b) the ring-attention kernel in both resident
   and TILED fold modes (Sb=8192/device — a block no resident score
   matrix could hold), (c) value_and_grad of the attention kernel
   (BOTH ring kernels — the fused backward — in one lowered module,
   no ppermute recompute), and (d) the FULL 2-D-mesh multichip step
   with the dp ring on ``pallas_ring``, for the TPU target, from
   whatever host this runs on.  jax.export executes the entire TPU
   lowering pipeline (Mosaic included) with no chip attached — the
   strongest evidence a wedged tunnel allows, and it runs even when
   the chip is healthy so the artifact's shape is stable across
   states.

The artifact is honest about failure: a wedged tunnel yields
``tunnel.ok = false`` with the probe's timeout, and the chip-gated
sections record ``skipped: tunnel wedged`` instead of vanishing.
"""

from __future__ import annotations

import datetime
import json
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "TPU_EVIDENCE.json")
PROBE_TIMEOUT = float(os.environ.get("MPI_TPU_PROBE_TIMEOUT", "180"))
TEST_TIMEOUT = float(os.environ.get("MPI_TPU_EVIDENCE_TEST_TIMEOUT", "2400"))


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def probe_tunnel() -> dict:
    """jax.devices() in a subprocess with a hard timeout."""
    code = ("import jax, json; ds = jax.devices(); "
            "print(json.dumps({'platform': ds[0].platform, "
            "'n_devices': len(ds), 'kind': getattr(ds[0], 'device_kind', '?')}))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=PROBE_TIMEOUT, cwd=ROOT)
    except subprocess.TimeoutExpired:
        return {"ok": False, "reason": f"jax.devices() hung > {PROBE_TIMEOUT}s "
                                       f"(wedged tunnel)"}
    if r.returncode != 0:
        return {"ok": False, "reason": "jax.devices() failed",
                "stderr": r.stderr[-500:]}
    info = json.loads(r.stdout.strip().splitlines()[-1])
    info["ok"] = info["platform"] not in ("cpu",)
    if not info["ok"]:
        info["reason"] = "only a CPU backend is visible"
    return info


def run_real_tpu_tier() -> dict:
    """MPI_TPU_TEST_TPU=1 pytest -m tpu, per-test outcomes."""
    env = dict(os.environ, MPI_TPU_TEST_TPU="1")
    cmd = [sys.executable, "-m", "pytest", "-m", "tpu", "tests/",
           "-q", "--no-header", "-rA"]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=TEST_TIMEOUT, cwd=ROOT, env=env)
    except subprocess.TimeoutExpired:
        return {"ran": False, "reason": f"tier exceeded {TEST_TIMEOUT}s"}
    tests = {}
    summary = {}
    for line in r.stdout.splitlines():
        m = re.match(r"(PASSED|FAILED|ERROR|SKIPPED)\s+(tests/\S+)", line)
        if m:
            tests[m.group(2)] = m.group(1)
        # the tally comes ONLY from pytest's final "=== ... ===" summary
        # line — a bare findall over full stdout would also match test
        # output that happens to contain "N passed"
        if re.match(r"=+ .*(passed|failed|skipped|error).* =+$", line):
            # canonical singular keys: pytest pluralizes ("1 error" vs
            # "2 errors"), which would make the artifact's schema vary
            # run to run for downstream checkers (ADVICE r4 #4)
            summary = {
                {"errors": "error", "warnings": "warning"}.get(k, k):
                int(n) for n, k in re.findall(
                    r"(\d+) (passed|failed|skipped|errors?|warnings?)",
                    line)}
    return {"ran": True, "returncode": r.returncode,
            "summary": summary, "tests": tests,
            "tail": r.stdout.strip().splitlines()[-3:]}


def run_entry_on_chip() -> dict:
    code = (
        "import __graft_entry__ as ge, jax, numpy as np\n"
        "f, args = ge.entry()\n"
        "out = f(*args)\n"
        "arrs = [np.asarray(o) for o in out]\n"
        "dev = list(out[0].devices())[0]\n"
        "import json\n"
        "print(json.dumps({'platform': dev.platform,"
        " 'shapes': [list(a.shape) for a in arrs],"
        " 'finite': bool(all(np.all(np.isfinite(a)) for a in arrs))}))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=TEST_TIMEOUT, cwd=ROOT)
    except subprocess.TimeoutExpired:
        return {"ran": False, "reason": "entry() timed out"}
    if r.returncode != 0:
        return {"ran": False, "reason": "entry() failed",
                "stderr": r.stderr[-500:]}
    info = json.loads(r.stdout.strip().splitlines()[-1])
    info["ran"] = True
    return info


def run_cross_platform_export() -> dict:
    """jax.export for the TPU target on a CPU-pinned subprocess — works
    on any host; exercises Mosaic lowering of the pallas kernels."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import warnings, json\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import PartitionSpec as P\n"
        "import __graft_entry__ as ge\n"
        "from mpi_tpu.tpu import default_mesh\n"
        "from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce\n"
        "res = {}\n"
        "mesh = default_mesh(8)\n"
        "f = jax.jit(jax.shard_map(lambda x: pallas_ring_allreduce("
        "x, 'world', 8, tile_rows=8), mesh=mesh, in_specs=P('world'),"
        " out_specs=P('world'), check_vma=False))\n"
        "exp = jax.export.export(f, platforms=['tpu'])("
        "jax.ShapeDtypeStruct((1024,), jnp.float32))\n"
        "res['pallas_ring_1d'] = {'platforms': list(exp.platforms),"
        " 'mosaic_kernel': 'tpu_custom_call' in exp.mlir_module()}\n"
        "from mpi_tpu.tpu.pallas_attention import pallas_ring_attention\n"
        "fa = jax.jit(jax.shard_map(lambda q, k, v: pallas_ring_attention("
        "q, k, v, 'world', 8, interpret=False), mesh=mesh,"
        " in_specs=(P('world'),) * 3, out_specs=P('world'),"
        " check_vma=False))\n"
        "aa = jax.ShapeDtypeStruct((8 * 64, 128), jnp.float32)\n"
        "expa = jax.export.export(fa, platforms=['tpu'])(aa, aa, aa)\n"
        "res['pallas_ring_attention'] = {'platforms': list(expa.platforms),"
        " 'mosaic_kernel': 'tpu_custom_call' in expa.mlir_module()}\n"
        "at = jax.ShapeDtypeStruct((8 * 8192, 128), jnp.float32)\n"
        "expt = jax.export.export(fa, platforms=['tpu'])(at, at, at)\n"
        "from mpi_tpu.tpu.pallas_attention import attention_vmem_plan\n"
        "res['pallas_ring_attention_tiled'] = {\n"
        "    'platforms': list(expt.platforms),\n"
        "    'mosaic_kernel': 'tpu_custom_call' in expt.mlir_module(),\n"
        "    'plan': attention_vmem_plan(8192, 128, 1, 1, jnp.float32),\n"
        "    'note': 'Sb=8192/device: resident score would be 256MB; '\n"
        "            'the tiled fold (HBM state, fori tiles) lowers'}\n"
        "def loss(q, k, v):\n"
        "    out = pallas_ring_attention(q, k, v, 'world', 8, causal=True,"
        " interpret=False)\n"
        "    return jax.lax.psum(jnp.sum(out ** 2), 'world')\n"
        "fg = jax.jit(jax.shard_map(lambda q, k, v: jax.value_and_grad("
        "loss, argnums=(0, 1, 2))(q, k, v), mesh=mesh,"
        " in_specs=(P('world'),) * 3, out_specs=(P(), (P('world'),) * 3),"
        " check_vma=False))\n"
        "ab = jax.ShapeDtypeStruct((8 * 32, 128), jnp.float32)\n"
        "expb = jax.export.export(fg, platforms=['tpu'])(ab, ab, ab)\n"
        "res['pallas_attention_fused_backward'] = {\n"
        "    'platforms': list(expb.platforms),\n"
        "    'mosaic_kernels': expb.mlir_module().count('tpu_custom_call'),\n"
        "    'ppermute_recompute_absent':"
        " 'collective_permute' not in expb.mlir_module(),\n"
        "    'note': 'value_and_grad lowers BOTH ring kernels (fwd+bwd)'}\n"
        "abt = jax.ShapeDtypeStruct((8 * 2048, 128), jnp.float32)\n"
        "expbt = jax.export.export(fg, platforms=['tpu'])(abt, abt, abt)\n"
        "res['pallas_attention_fused_backward_tiled'] = {\n"
        "    'platforms': list(expbt.platforms),\n"
        "    'mosaic_kernels': expbt.mlir_module().count('tpu_custom_call'),\n"
        "    'ppermute_recompute_absent':"
        " 'collective_permute' not in expbt.mlir_module(),\n"
        "    'bwd_plan': attention_vmem_plan(2048, 128, 1, 1,"
        " jnp.float32, for_backward=True),\n"
        "    'note': 'Sb=2048/device: the TILED fused backward lowers "
        "(resident temporaries would be 64MB)'}\n"
        "with warnings.catch_warnings():\n"
        "    warnings.simplefilter('ignore')\n"
        "    exp2 = ge.export_multichip_tpu(8)\n"
        "res['multichip_2d_pallas_ring'] = {'platforms': list(exp2.platforms),"
        " 'mosaic_kernel': 'tpu_custom_call' in exp2.mlir_module(),"
        " 'mesh': '2x4 (dp,mp)', 'dp_algorithm': 'pallas_ring'}\n"
        "print(json.dumps(res))\n")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=TEST_TIMEOUT, cwd=ROOT, env=env)
    except subprocess.TimeoutExpired:
        return {"ran": False, "reason": "export timed out"}
    if r.returncode != 0:
        return {"ran": False, "reason": "export failed",
                "stderr": r.stderr[-800:]}
    info = json.loads(r.stdout.strip().splitlines()[-1])
    info["ran"] = True
    return info


def main() -> None:
    evidence = {
        "generated": _utcnow(),
        "command": "python tools/tpu_evidence.py",
        "tunnel": probe_tunnel(),
    }
    if evidence["tunnel"].get("ok"):
        evidence["real_tpu_tests"] = run_real_tpu_tier()
        evidence["entry_on_chip"] = run_entry_on_chip()
    else:
        skip = {"skipped": "tunnel wedged/absent — see tunnel.reason"}
        evidence["real_tpu_tests"] = skip
        evidence["entry_on_chip"] = skip
    evidence["cross_platform_export"] = run_cross_platform_export()
    with open(OUT, "w") as f:
        json.dump(evidence, f, indent=2)
        f.write("\n")
    print(f"wrote {OUT}")
    print(json.dumps({k: (v.get("ok", v.get("ran")))
                      for k, v in evidence.items()
                      if isinstance(v, dict)}, indent=2))


if __name__ == "__main__":
    main()
