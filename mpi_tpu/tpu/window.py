"""One-sided RMA on the SPMD/TPU backend: windows as functional state.

The window is a per-rank array living inside the traced SPMD program; RMA
calls queue static-pattern transfers, and ``fence()`` lowers the whole
epoch to a sequence of ``lax.ppermute`` steps (one ICI hop per call) plus
masked updates — the TPU-native reading of "remote memory access": the
remote write IS an ICI DMA scheduled by XLA.

Semantics match mpi_tpu/window.py exactly (issue order; writes before
gets; fence closes the epoch) so the parity tests can diff backends
bit-for-bit.  Rank-dynamic targets (int form) are diagnosed with
SpmdSemanticsError — every device executes one trace, so the pattern must
be static (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from .. import ops as _ops
from ..window import GetFuture, _normalize_pairs
from . import collectives as algos

Pair = Tuple[int, int]


def _static_pairs(pairs, size: int) -> List[Pair]:
    import numpy as np

    if isinstance(pairs, (int, np.integer)):
        from .communicator import _unsupported

        raise _unsupported(
            "rank-dynamic RMA (an int target rank)",
            "Pass the static pattern form pairs=[(src, dst), ...] — the same "
            "list on every rank, like Communicator.exchange.")
    return _normalize_pairs(pairs, 0, size, allow_int=False)


class TpuWindow:
    """RMA window over a :class:`TpuCommunicator` (functional).

    ``local`` tracks the current window value through fences; programs
    thread it out of the traced function like any other jax value.
    """

    @staticmethod
    def _no_passive(*_a, **_k):
        raise NotImplementedError(
            "passive-target RMA (Win_lock/unlock) has no SPMD spelling — "
            "one traced program cannot leave a device's window passively "
            "accessible mid-trace; use fence epochs (active target) on "
            "this backend, or the process backends for lock/unlock")

    def lock(self, rank: int, exclusive: bool = True):
        self._no_passive()

    def unlock(self, rank: int):
        self._no_passive()

    def put_at(self, rank: int, data=None, loc=None):
        self._no_passive()

    def get_at(self, rank: int, loc=None):
        self._no_passive()

    def accumulate_at(self, rank: int, data=None, op=None, loc=None):
        self._no_passive()

    def fetch_and_op(self, rank: int, data=None, op=None, loc=None):
        self._no_passive()

    def compare_and_swap(self, rank: int, compare=None, new=None, loc=None):
        self._no_passive()

    def flush(self, rank: int):
        self._no_passive()

    # PSCW is rank-asymmetric control flow — same no-SPMD-spelling
    # diagnosis as passive target (fence is the active-target mode here)
    post = start = complete = wait = test = _no_passive
    # MPI-3 epoch/atomic helpers: all passive-target shaped
    lock_all = unlock_all = flush_all = _no_passive
    flush_local = flush_local_all = _no_passive
    get_accumulate = rput = rget = raccumulate = _no_passive

    def sync(self) -> None:
        """MPI_Win_sync is valid on any window; in one traced SPMD
        program the trace order IS the memory order — a correct no-op."""


    def __init__(self, comm, init: Any):
        self._comm = comm
        self._arr = jnp.asarray(init)
        # queued ops, in issue order (pairs are group-local; they are
        # world-mapped at fence via comm._world_pairs):
        # ("put", data, pairs, loc, None) / ("acc", data, pairs, loc, op)
        # ("get", None, pairs, loc, (fill, future))
        self._queue: List[Tuple] = []
        self._epoch = 0
        self._freed = False

    @property
    def local(self):
        """Current local window value (a traced array)."""
        return self._arr

    # -- epoch ops ---------------------------------------------------------

    def put(self, data: Any, pairs, loc: Any = None) -> None:
        """Queue a pattern put: (src, dst) ships src's ``data`` into dst's
        window (at static index ``loc`` if given)."""
        self._check_open()
        norm = _static_pairs(pairs, self._comm.size)
        self._queue.append(("put", jnp.asarray(data), norm, loc, None))

    def accumulate(self, data: Any, pairs, op: _ops.ReduceOp = _ops.SUM,
                   loc: Any = None) -> None:
        """Queue a pattern accumulate: dst window[loc] = op(window[loc], data)."""
        self._check_open()
        norm = _static_pairs(pairs, self._comm.size)
        self._queue.append(("acc", jnp.asarray(data), norm, loc, op))

    def get(self, pairs, fill: Any = 0, loc: Any = None) -> GetFuture:
        """Queue a pattern get; the future resolves at ``fence()`` to src's
        window[loc] on each dst rank (``fill`` elsewhere — SPMD programs
        produce a value on every rank)."""
        self._check_open()
        norm = _static_pairs(pairs, self._comm.size)
        fut = GetFuture()
        self._queue.append(("get", None, norm, loc, (fill, fut)))
        return fut

    def fence(self) -> None:
        """Close the epoch: lower queued ops to ppermutes, in issue order;
        writes land before gets are serviced (module docstring of
        mpi_tpu/window.py — the cross-backend refinement)."""
        self._check_open()
        comm = self._comm
        arr = self._arr
        writes = [q for q in self._queue if q[0] != "get"]
        gets = [q for q in self._queue if q[0] == "get"]
        for kind, data, norm, loc, op in writes:
            world = comm._world_pairs(norm)
            incoming = lax.ppermute(data, comm.axis_name, world)
            is_dst = algos._mask_of([d for _, d in world],
                                    comm._axis_size, comm.axis_name)
            if kind == "put":
                updated = (incoming if loc is None
                           else arr.at[loc].set(incoming))
            else:
                cur = arr if loc is None else arr[loc]
                combined = op.combine(cur, incoming)
                updated = (combined if loc is None
                           else arr.at[loc].set(combined))
            updated = jnp.broadcast_to(updated, arr.shape).astype(arr.dtype)
            arr = jnp.where(is_dst, updated, arr)
        for _, _, norm, loc, (fill, fut) in gets:
            world = comm._world_pairs(norm)
            src_val = arr if loc is None else arr[loc]
            out = lax.ppermute(src_val, comm.axis_name, world)
            is_dst = algos._mask_of([d for _, d in world],
                                    comm._axis_size, comm.axis_name)
            out = jnp.where(is_dst, out, jnp.full_like(out, fill))
            fut._resolve(out)
        self._arr = arr
        self._queue.clear()
        self._epoch += 1

    def free(self) -> None:
        self._freed = True

    def _check_open(self) -> None:
        if self._freed:
            raise RuntimeError("operation on a freed Window")
