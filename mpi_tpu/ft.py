"""User-level fault tolerance (ULFM) — detection, revocation, recovery.

The MPI Forum's User-Level Failure Mitigation proposal [S] is the
standard shape for surviving rank death without tearing the world down:

* **Detection** — a liveness layer notices a dead peer within a bounded
  time (``fault_detect_timeout_s`` mpit cvar), *independent* of whether
  any survivor is blocked on that peer.  Every rank runs one detector
  thread that (a) publishes its own heartbeat and (b) watches every
  peer's; a peer whose heartbeat goes stale is marked failed.  Two
  liveness substrates behind one interface: heartbeat FILES under the
  rendezvous dir for process worlds (socket/shm — a dead process stops
  touching its file), and a shared in-memory beat table for the local
  thread world (where FaultyTransport's ``kill_after_n`` injection
  simulates death — see transport/faulty.py).
* **Conversion** — with fault tolerance enabled, every blocking wait in
  the communicator (p2p recv/probe AND the segmented collective
  engine's irecv drains) runs in short slices, re-checking the detector
  between slices; a detector hit (or transport send failure) surfaces
  as :class:`~mpi_tpu.errors.ProcFailedError` (``MPI_ERR_PROC_FAILED``)
  naming the suspected ranks, the collective, and the pipeline segment
  — instead of the shm transport's 120s stall constant or an unbounded
  socket hang.
* **Propagation** — ``comm.revoke()`` broadcasts a revocation on the
  reserved control tag; any rank entering or blocked inside an
  operation on a revoked communicator raises
  :class:`~mpi_tpu.errors.RevokedError` (``MPI_ERR_REVOKED``).  This is
  what unblocks survivors who were *not* talking to the corpse.
* **Recovery** — ``comm.shrink()`` (survivors agree on the failed set
  and build a dense sub-communicator) and ``comm.agree()``
  (fault-tolerant boolean agreement, the checkpoint-commit primitive —
  see mpi_tpu/checkpoint.py ``save(..., agree=True)``).

The agreement protocol (:func:`_agreement`) is a lockstep iterated
all-to-all exchange of monotone (failed-view, AND-value) pairs that
terminates after two consecutive *clean* rounds (view stable and every
received pair equal to the one sent).  Views and AND-values only grow /
only fall, so with crash-stop failures that are stable by the time the
protocol starts (the checkpoint/restart use case) all survivors
converge to identical results; a failure racing the protocol itself is
absorbed in extra rounds, and the one dishonest corner — a FALSE
suspicion (live peer stalled past the detection bound) — can split the
group, exactly the accuracy/completeness tradeoff every timeout-based
failure detector has.  Documented, not hidden.

Enable per world: ``mpi_tpu.ft.enable(comm)`` (process worlds pick the
liveness substrate from the transport), ``MPI_TPU_FT=1`` in the
launcher environment, or ``run_local(..., fault_tolerance=True)``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import mpit as _mpit
from . import telemetry as _telemetry
from .errors import ProcFailedError, RevokedError
from .transport.base import ANY_SOURCE, RecvTimeout, TransportError

# Reserved control tags (negative: user wildcards can never match them —
# transport/base.py Mailbox._matches; distinct from communicator.py's
# collective/barrier/shift tags).
TAG_REVOKE = -6
TAG_SHRINK = -7
TAG_AGREE = -8

# Detection bound: a peer whose heartbeat is stale this long is declared
# failed.  Deliberately far below transport/shm.py's 120s no-progress
# stall constant — the detector, not the data plane, is the failure
# authority.  mpit cvar: fault_detect_timeout_s.
_DETECT_TIMEOUT_S = 5.0
# How often each rank publishes its own heartbeat (and scans peers').
# mpit cvar: fault_heartbeat_interval_s.
_HEARTBEAT_S = 0.25
# Slice length of fault-tolerant (and runtime-verified — the verifier
# reuses this slice-poll plumbing, communicator._sliced_wait) blocking
# waits: the latency between a detector hit, an arriving revocation, or
# a publishable stall and the blocked wait noticing.
POLL_S = 0.05
_POLL_S = POLL_S  # historical name, kept for in-tree references


class MemoryLiveness:
    """Shared beat table for one in-process world (local thread ranks)."""

    def __init__(self, size: int) -> None:
        self._beats = [0] * size
        self._lock = threading.Lock()

    def beat(self, rank: int) -> None:
        with self._lock:
            self._beats[rank] += 1

    def stamp(self, rank: int) -> Optional[int]:
        with self._lock:
            return self._beats[rank]


class FileLiveness:
    """Heartbeat files ``hb.<rank>`` under the rendezvous dir: a rank
    touches its own file every interval; a dead process stops touching.
    The stamp is the file's mtime — no content parsing, no partial-read
    hazard."""

    def __init__(self, rdv_dir: str, rank: int) -> None:
        self._rdv = rdv_dir
        self._path = os.path.join(rdv_dir, f"hb.{rank}")
        with open(self._path, "w") as f:
            f.write("alive")

    def beat(self, rank: int) -> None:
        try:
            os.utime(self._path, None)
        except OSError:
            pass  # rendezvous dir tearing down — world is exiting

    def stamp(self, rank: int) -> Optional[int]:
        try:
            return os.stat(os.path.join(self._rdv, f"hb.{rank}")).st_mtime_ns
        except OSError:
            return None  # not yet published (or swept): treated as stale


class WorldFT:
    """Per-process failure-detection state: the detector thread, the
    failed set (WORLD ranks), and the liveness substrate.  Shared by
    every communicator derived from one transport."""

    def __init__(self, transport, liveness, detect_timeout_s: float,
                 heartbeat_s: float) -> None:
        self._t = transport
        self._liveness = liveness
        self.detect_timeout_s = float(detect_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.failed: set = set()  # world ranks; reads are snapshot-cheap
        # world ranks whose failure ANY communicator acknowledged via
        # failure_ack — the membership layer's re-admission gate: an
        # ousted-but-live incarnation may only rejoin once its failure
        # has been acknowledged (mpi_tpu/membership.py accept_rejoin)
        self.acked_world: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # peer -> (last stamp seen, local monotonic time it changed)
        now = time.monotonic()
        self._last: Dict[int, Tuple[Optional[int], float]] = {
            p: (None, now) for p in range(transport.world_size)
            if p != transport.world_rank
        }
        liveness.beat(transport.world_rank)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mpi-tpu-ft-detector-{transport.world_rank}")
        self._thread.start()

    # -- detection ---------------------------------------------------------

    def _loop(self) -> None:
        last_tick = time.monotonic()
        while not self._stop.is_set():
            # A kill-injected rank (FaultyTransport.killed) is dead to the
            # world: it stops heartbeating AND stops accusing others.
            if getattr(self._t, "killed", False):
                return
            self._liveness.beat(self._t.world_rank)
            now = time.monotonic()
            # Stall threshold: well past the nominal loop period (so a
            # tight detect_timeout <= 2*heartbeat cannot make EVERY
            # iteration look like a stall and silently suppress
            # detection forever) AND a real fraction of the bound.
            if now - last_tick > max(self.detect_timeout_s / 2,
                                     3.0 * self.heartbeat_s):
                # WE were descheduled (loaded box, GC pause): peer
                # staleness measured across our own stall is not
                # evidence — while stalled we also stopped beating, so
                # symmetric false accusations would split live worlds.
                # Restart every unchanged peer's window; a genuinely
                # dead peer is still caught one window later (bounded).
                self._last = {p: (s, now) for p, (s, _) in
                              self._last.items()}
            last_tick = now
            for peer, (stamp, changed) in list(self._last.items()):
                if peer in self.failed:
                    continue
                cur = self._liveness.stamp(peer)
                if cur is not None and cur != stamp:
                    self._last[peer] = (cur, now)
                elif now - changed > self.detect_timeout_s:
                    self.observe(peer, "heartbeat stale for "
                                       f"{now - changed:.1f}s")
            self._stop.wait(self.heartbeat_s)

    def observe(self, world_rank: int, why: str) -> None:
        """Mark a world rank failed (detector hit OR transport evidence,
        e.g. a failed send); counts the detection pvar exactly once."""
        with self._lock:
            if world_rank in self.failed:
                return
            self.failed.add(world_rank)
        _mpit.count(proc_failed=1)
        rec = _telemetry.REC
        if rec is not None:
            # the rejoin-hello-race / lease-stall class of war story is
            # exactly "WHEN did this rank first suspect whom, and why"
            rec.emit("ft", "suspect",
                     attrs={"rank": world_rank, "why": why[:120]})

    def link_suspect(self, peer: int) -> bool:
        """PEER-fault verdict for the socket link layer's fault
        classification (mpi_tpu/resilience.py): True when ``peer`` is
        already in the failed set OR its heartbeat is stale past the
        detection bound right now — the direct read covers the window
        where the detector thread has the evidence but has not ticked
        yet, so a reconnect loop never spends its budget courting a
        corpse.  Fresh heartbeat evidence (a stamp that moved since the
        detector last looked) is an immediate NOT-suspect verdict."""
        with self._lock:
            if peer in self.failed:
                return True
        last = self._last.get(peer)
        if last is None:
            return False  # self, or an unknown rank: no liveness claim
        stamp, changed = last
        try:
            cur = self._liveness.stamp(peer)
        except Exception:  # pragma: no cover - substrate tearing down
            return False
        if cur is not None and cur != stamp:
            return False
        return time.monotonic() - changed > self.detect_timeout_s

    def failed_snapshot(self) -> set:
        """Consistent copy of the failed set: callers iterate/intersect
        it, and an unlocked copy racing the detector's add() can raise
        'set changed size during iteration' — an undiagnostic crash in
        place of the ProcFailedError the caller is building."""
        with self._lock:
            return set(self.failed)

    def ack_world(self, world_ranks) -> None:
        """Record world ranks as failure-acknowledged (failure_ack)."""
        with self._lock:
            self.acked_world |= set(world_ranks)

    def reset_rank(self, world_rank: int) -> None:
        """Re-admit a replaced slot (mpi_tpu/membership.py epoch
        transition): clear the failed/acked state and restart the
        detection window so the rejoined incarnation gets a full
        ``detect_timeout_s`` before it can be suspected again.  Called
        AFTER the replacement published readiness (its heartbeat file
        is fresh by then), so the detector cannot instantly re-fail it
        off the corpse's stale mtime."""
        with self._lock:
            self.failed.discard(world_rank)
            self.acked_world.discard(world_rank)
        if world_rank != self._t.world_rank:
            self._last[world_rank] = (None, time.monotonic())

    def stop(self) -> None:
        self._stop.set()


class CommFT:
    """Per-communicator fault-tolerance state: revocation flag, the
    acknowledged-failure set (comm ranks), and agreement epochs.  nbc
    clones share their parent's instance (a revoke must unblock a
    nonblocking collective in flight); split/dup/shrink children get a
    fresh one (MPI: revocation does not propagate across communicator
    creation)."""

    def __init__(self, world: WorldFT, home_ctx) -> None:
        self.world = world
        self.home_ctx = home_ctx
        self.revoked = False
        self.acked: set = set()  # comm ranks acknowledged via failure_ack
        self._epochs: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._last_revoke_poll = 0.0

    def next_epoch(self, tag: int) -> int:
        with self._lock:
            self._epochs[tag] = self._epochs.get(tag, 0) + 1
            return self._epochs[tag]

    def current_epoch(self, tag: int) -> int:
        with self._lock:
            return self._epochs.get(tag, 0)

    def check(self, comm) -> None:
        """Entry/slice check of every fault-tolerant operation: raise if
        this communicator is revoked, applying any queued revocation
        first (the one delivery point of TAG_REVOKE — counts the
        ``revokes_delivered`` pvar).  The mailbox scan for a queued
        revocation is rate-limited to the _POLL_S cadence: it is an
        O(pending-messages) walk under the mailbox lock, and this check
        runs on EVERY FT-enabled send — unthrottled it would tax the
        zero-copy pipeline exactly where the segmented engine earns its
        keep (the sliced blocking waits already re-check every slice)."""
        if not self.revoked:
            now = time.monotonic()
            if now - self._last_revoke_poll >= _POLL_S:
                self._last_revoke_poll = now  # benign race: extra poll
                try:
                    hit = comm._t.poll(ANY_SOURCE, self.home_ctx,
                                       TAG_REVOKE)
                except TransportError:
                    hit = None  # closed mailbox: normal wait path reports
                if hit is not None:
                    self.revoked = True
                    _mpit.count(revokes=1)
        if self.revoked:
            raise RevokedError(
                f"communicator (ctx={comm._ctx}) has been revoked")


def enable(comm, liveness=None, rdv_dir: Optional[str] = None,
           detect_timeout_s: Optional[float] = None,
           heartbeat_s: Optional[float] = None):
    """Enable ULFM fault tolerance on a P2P communicator (idempotent per
    transport; the detector thread is shared).  Process worlds default
    to heartbeat files under the rendezvous dir (``rdv_dir``, or the
    launcher's MPI_TPU_RDV); the local thread world passes the shared
    :class:`MemoryLiveness` (run_local does this for you)."""
    if getattr(comm, "_ft", None) is not None:
        return comm
    world = getattr(comm._t, "_ft_world", None)
    if world is None:
        if liveness is None:
            rdv = rdv_dir or os.environ.get("MPI_TPU_RDV")
            if rdv is None:
                raise ValueError(
                    "fault tolerance needs a liveness substrate: pass "
                    "liveness= (in-process worlds) or rdv_dir= / set "
                    "MPI_TPU_RDV (process worlds)")
            liveness = FileLiveness(rdv, comm._t.world_rank)
        world = WorldFT(
            comm._t, liveness,
            _DETECT_TIMEOUT_S if detect_timeout_s is None
            else detect_timeout_s,
            _HEARTBEAT_S if heartbeat_s is None else heartbeat_s)
        comm._t._ft_world = world
    comm._ft = CommFT(world, comm._ctx)
    return comm


# -- fault-tolerant agreement (the shrink/agree engine) ----------------------


def _agreement(comm, tag: int, value: bool) -> Tuple[int, bool]:
    """Lockstep iterated exchange among the ranks of ``comm`` not yet
    believed dead: each round every participant sends its (view, value)
    to every other and collects one message from each, folding received
    views (bitwise OR over comm-rank bitmasks — the "all-reduce over
    liveness bitmaps") and values (AND).  A peer that times out past the
    detection bound, is detector-flagged, or fails a send joins the
    view.  Terminates after two consecutive clean rounds; returns
    (final view bitmask, AND of surviving contributions).

    Runs on the RAW transport (not the communicator's send/recv): shrink
    and agree must work on a *revoked* communicator [S: ULFM], so the
    revocation check is deliberately bypassed here."""
    ft = comm._ft
    p, r = comm.size, comm.rank
    epoch = ft.next_epoch(tag)
    view = 0
    for cr in comm.get_failed():
        view |= 1 << cr
    value = bool(value)
    clean = 0
    rnd = 0
    while clean < 2:
        rnd += 1
        sent_view, sent_value = view, value
        live = [q for q in range(p) if q != r and not (view >> q) & 1]
        for q in live:
            try:
                comm._t.send(comm._group[q], comm._ctx, tag,
                             (epoch, rnd, view, value))
            except (TransportError, ValueError) as e:
                view |= 1 << q
                ft.world.observe(comm._group[q],
                                 f"send failed during agreement: {e}")
        all_equal = True
        for q in live:
            got = _agreement_recv(comm, q, tag, epoch, rnd)
            if got is None:
                view |= 1 << q
                all_equal = False
                continue
            pview, pval = got
            view |= pview
            value = value and pval
            if pview != sent_view or pval != sent_value:
                all_equal = False
        clean = clean + 1 if (view == sent_view and all_equal) else 0
    return view, value


def _agreement_recv(comm, peer: int, tag: int, epoch: int,
                    rnd: int) -> Optional[Tuple[int, bool]]:
    """One agreement message from comm-rank ``peer``: sliced wait that
    gives up (returns None → peer joins the view) when the detector
    flags the peer or the bounded deadline passes.  Stale epochs (a dead
    rank's leftovers from an earlier agreement) are discarded; a FUTURE
    epoch would mean agreement calls were not issued in the same order
    on every rank — a programming error worth raising over."""
    ft = comm._ft
    peer_world = comm._group[peer]
    deadline = time.monotonic() + max(3.0 * ft.world.detect_timeout_s, 2.0)
    while True:
        # Message FIRST, suspicion second: a false suspicion (live peer
        # stalled past the detection bound on a loaded box) must never
        # discard an agreement message that has already arrived —
        # dropping a live participant here is the one way the protocol
        # can split the group.
        try:
            payload, _, _ = comm._t.recv(peer_world, comm._ctx, tag,
                                         timeout=_POLL_S)
        except RecvTimeout:
            if peer_world in ft.world.failed:
                return None
            if time.monotonic() > deadline:
                # overdue joins THIS agreement's view only — a protocol
                # timeout is weak evidence (the peer may just not have
                # entered the collective yet), so it must not poison
                # the world-level failed set the way detector/transport
                # evidence (WorldFT.observe) does
                return None
            continue
        except TransportError:
            return None  # transport torn down under us: peer unreachable
        got_epoch, got_rnd, pview, pval = payload
        if got_epoch < epoch:
            continue  # stale leftover: discard
        if got_epoch > epoch:
            raise RuntimeError(
                f"agreement epoch skew from rank {peer}: got {got_epoch}, "
                f"expected {epoch} (agreements must be issued in the same "
                f"order on every rank)")
        if got_rnd != rnd:
            continue  # defensive: lockstep + FIFO should prevent this
        return int(pview), bool(pval)


def failed_comm_ranks(comm) -> List[int]:
    """Comm ranks of ``comm`` currently believed dead (sorted)."""
    ft = getattr(comm, "_ft", None)
    if ft is None:
        return []
    failed_world = ft.world.failed_snapshot() & set(comm._group)
    return sorted(comm._group.index(w) for w in failed_world)
