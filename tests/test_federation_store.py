"""Replicated namespace store (ISSUE 18): the NamespaceStore
conformance suite, run against BOTH backends — the dir-backed
:class:`FileStore` (versioned-file link-CAS) and the raft-replicated
:class:`RaftStore` (an in-process 3-node fabric) — plus backend-
specific legs: the FileStore frozen-holder CAS regression (the PR-15
takeover window, now structurally closed), raft log-replay
idempotence (the applied-nonce table), and the named-NoQuorumError
minority verdict under an injected store partition.

The conformance half is the contract the federation tier programs
against: whatever passes here can carry leases, server records, and
authority logs without the caller knowing which backend it got."""

import os
import socket
import sys
import threading
import time
import uuid

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mpi_tpu import federation, federation_store as fstore  # noqa: E402
from mpi_tpu.errors import NoQuorumError  # noqa: E402

# propose RTTs are sub-ms in-process; elections dominate setup
ELECT_S = 0.3


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _mk_fabric(n=3, elect_s=ELECT_S):
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(n)]
    nodes = [fstore.RaftNode(i, addrs, elect_timeout_s=elect_s)
             for i in range(n)]
    deadline = time.monotonic() + 30.0
    while not any(nd.role == "leader" for nd in nodes):
        if time.monotonic() > deadline:
            for nd in nodes:
                nd.close()
            raise RuntimeError("raft fabric never elected a leader")
        time.sleep(0.05)
    return addrs, nodes


@pytest.fixture(scope="module")
def raft_fabric():
    addrs, nodes = _mk_fabric()
    yield addrs, nodes
    for nd in nodes:
        nd.close()


@pytest.fixture(params=["file", "raft"])
def store(request, tmp_path):
    """One conformance subject per backend.  The raft subject is a
    member-mode handle on node 0 of a shared module fabric (propose
    forwards to whoever leads); tests isolate by unique keys."""
    if request.param == "file":
        yield fstore.FileStore(str(tmp_path))
    else:
        _, nodes = request.getfixturevalue("raft_fabric")
        yield fstore.RaftStore(nodes[0], owns_node=False)


def _key():
    return f"t.{uuid.uuid4().hex[:12]}"


# -- conformance: the contract both backends honor ---------------------------


def test_cas_create_update_and_stale_rejection(store):
    k = _key()
    assert store.get(k) is None
    r1 = store.cas(k, None, {"n": 1})
    assert r1 is not None and r1.value == {"n": 1}
    # create-if-absent against an existing key loses
    assert store.cas(k, None, {"n": 99}) is None
    r2 = store.cas(k, r1.ver, {"n": 2})
    assert r2 is not None and r2.ver > r1.ver
    # a stale version token is rejected, not last-writer-wins
    assert store.cas(k, r1.ver, {"n": 3}) is None
    got = store.get(k)
    assert got.value == {"n": 2} and got.ver == r2.ver
    # stamps are wall-clock-ish and move forward: the staleness clock
    # LeaderLease reads
    assert abs(r2.stamp - time.time()) < 30.0
    assert r2.stamp >= r1.stamp


def test_cas_single_winner_under_contention(store):
    """The lease primitive: N threads racing read-modify-CAS on one
    counter — every successful cas is exactly one increment (atomic
    arbitration, no lost updates), regardless of backend."""
    k = _key()
    store.cas(k, None, {"n": 0})
    nthreads, wins = 6, [0] * 6
    deadline = time.monotonic() + 30.0

    def contender(i):
        while wins[i] < 5 and time.monotonic() < deadline:
            cur = store.get(k)
            if cur is None:
                continue
            rec = store.cas(k, cur.ver, {"n": cur.value["n"] + 1})
            if rec is not None:
                wins[i] += 1

    threads = [threading.Thread(target=contender, args=(i,))
               for i in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(w == 5 for w in wins), wins
    assert store.get(k).value["n"] == sum(wins)


def test_put_delete_and_scan(store):
    pre = f"s.{uuid.uuid4().hex[:8]}."
    ra = store.put(pre + "a", {"x": 1})
    store.put(pre + "b", {"x": 2})
    store.put("other." + pre, {"x": 3})  # outside the prefix
    got = store.scan(pre)
    assert set(got) == {pre + "a", pre + "b"}
    # upsert bumps the version
    ra2 = store.put(pre + "a", {"x": 10})
    assert ra2.ver > ra.ver
    assert store.scan(pre)[pre + "a"].value == {"x": 10}
    assert store.delete(pre + "b")
    assert store.get(pre + "b") is None
    assert set(store.scan(pre)) == {pre + "a"}
    # deletion is not a hole: the key is re-creatable
    assert store.cas(pre + "b", None, {"x": 4}) is not None


def test_watch_delivers_updates_and_deletes(store):
    pre = f"w.{uuid.uuid4().hex[:8]}."
    store.put(pre + "pre", {"x": 0})  # pre-existing: no event
    w = store.watch(pre)
    try:
        store.put(pre + "k", {"x": 1})
        ev = w.next(timeout=10.0)
        assert ev is not None and ev[0] == pre + "k"
        assert ev[1].value == {"x": 1}
        store.delete(pre + "k")
        ev = w.next(timeout=10.0)
        assert ev == (pre + "k", None)
    finally:
        w.close()


def test_append_log_order_and_reread_stability(store):
    """Authority-interval logs: append-only, in order, and re-reading
    never re-applies (the replay shape assert_no_leader_overlap's
    history audit depends on)."""
    lk = f"audit.log.conf-{uuid.uuid4().hex[:8]}"
    for i in range(5):
        store.append(lk, {"i": i})
    logs = store.log_scan("audit.log.conf-")
    assert [r["i"] for r in logs[lk]] == [0, 1, 2, 3, 4]
    assert store.log_scan("audit.log.conf-") == logs  # idempotent read
    store.append(lk, {"i": 5})
    assert [r["i"] for r in store.log_scan(
        "audit.log.conf-")[lk]] == [0, 1, 2, 3, 4, 5]


def test_leader_lease_expiry_and_takeover(store):
    """The federation LeaderLease running ON the conformance subject:
    B cannot take a live lease, CAN take a stale one (term bump), the
    deposed holder demotes, and the interval history stays
    overlap-free — identical semantics on both backends."""
    a = federation.LeaderLease(store, "A", lease_timeout_s=0.8)
    b = federation.LeaderLease(store, "B", lease_timeout_s=0.8)
    assert a.tick() and a.is_leader()
    assert not b.tick()  # live holder: refused
    time.sleep(0.9)      # past the lease bound: A never renewed
    assert not a.is_leader()  # bounded authority lapsed on its own
    assert b.tick() and b.is_leader()
    assert b.term > a.term
    assert b.takeovers == 1
    assert not a.tick()  # thawed holder discovers usurpation
    assert a.demotions == 1
    federation.assert_no_leader_overlap(store)
    b.release()


# -- FileStore: the frozen-holder CAS window (PR-15 regression) ---------------


def test_filestore_frozen_holder_mid_cas_loses(tmp_path):
    """The PR-15 accepted race, now structurally closed: a holder
    frozen (SIGSTOP-shaped: the _test_mid_cas seam blocks it) BETWEEN
    its current-version read and its publish thaws after a usurper's
    takeover committed — its publish must LOSE the version-slot
    arbitration, leaving exactly one winner."""
    frozen, release = threading.Event(), threading.Event()
    holder_store = fstore.FileStore(str(tmp_path))
    usurper_store = fstore.FileStore(str(tmp_path))
    seed = holder_store.cas("leader.lease", None, {"id": "H", "term": 1})
    assert seed is not None

    def seam(key):
        frozen.set()
        assert release.wait(10.0)

    holder_store._test_mid_cas = seam  # instance seam: holder only
    out = {}

    def holder_renew():
        out["holder"] = holder_store.cas(
            "leader.lease", seed.ver, {"id": "H", "term": 1, "r": 1})

    th = threading.Thread(target=holder_renew)
    th.start()
    assert frozen.wait(10.0)  # holder read ver, now frozen in the window
    won = usurper_store.cas("leader.lease", seed.ver,
                            {"id": "U", "term": 2})
    assert won is not None  # takeover committed while holder frozen
    release.set()
    th.join(10.0)
    assert out["holder"] is None  # thawed holder LOSES, no silent overwrite
    final = usurper_store.get("leader.lease")
    assert final.value["id"] == "U" and final.ver == won.ver


def test_filestore_version_gc_truncates_but_never_recycles(tmp_path):
    """The version-chain GC keeps the arbitration sound across many
    generations: 40 sequential CASes leave a readable current record,
    bounded CONTENT (older slots truncated to placeholders), and every
    slot NAME still present — a recycled name would hand a straggler
    frozen past GC a silent win, the lost-update variant of the PR-15
    window."""
    st = fstore.FileStore(str(tmp_path))
    rec = st.cas("k", None, {"n": 0})
    for i in range(1, 40):
        rec = st.cas("k", rec.ver, {"n": i})
        assert rec is not None
    assert st.get("k").value == {"n": 39}
    names = [n for n in os.listdir(str(tmp_path))
             if not n.startswith(".tmp.")]
    assert len(names) == 40  # every slot name survives (no recycling)
    nonempty = [n for n in names if os.path.getsize(
        os.path.join(str(tmp_path), n)) > 0]
    assert len(nonempty) <= 3  # content bounded: current + fallback
    # a straggler holding a long-stale version token cannot re-win a
    # truncated slot
    assert st.cas("k", 5, {"n": -1}) is None
    assert st.get("k").value == {"n": 39}


def test_filestore_tombstone_gc_interrupted_never_resurrects(
        tmp_path, monkeypatch):
    """The delete/recreate window (ISSUE 19 satellite): tombstone GC
    unlinks the chain ASCENDING, so a GC that dies mid-walk removes
    stale predecessors first and the tombstone LAST — an interrupted
    collection leaves the key visibly dead instead of resurrecting the
    pre-delete value, and the recreate wins a slot above every prior
    name."""
    import json

    st = fstore.FileStore(str(tmp_path))
    rec = st.cas("k", None, {"n": 0})                     # v1
    rec = st.cas("k", rec.ver, {"n": 1})                  # v2
    assert st.delete("k", rec.ver)                        # v3 tombstone
    # backdate the tombstone past the GC horizon
    p3 = os.path.join(str(tmp_path), "k.v3.json")
    with open(p3) as f:
        w = json.load(f)
    w["stamp"] = time.time() - 3600.0
    with open(p3, "w") as f:
        json.dump(w, f)
    # simulated mid-GC crash: exactly one unlink lands
    real_unlink, calls = os.unlink, []

    def partial_unlink(path):
        if not calls:
            calls.append(path)
            real_unlink(path)

    monkeypatch.setattr(os, "unlink", partial_unlink)
    st.scan("")                                           # triggers GC
    monkeypatch.undo()
    # ascending: the ONE unlink that landed was the oldest slot, never
    # the tombstone — the key is still dead, not resurrected to {"n":1}
    assert calls and calls[0].endswith("k.v1.json")
    assert st.get("k") is None
    # recreate inside the window: wins, above every prior slot
    rec = st.cas("k", None, {"n": 9})
    assert rec is not None and rec.ver == 4
    assert st.get("k").value == {"n": 9}


def test_filestore_recreate_in_gc_window_tops_every_stale_slot(tmp_path):
    """Post-partial-GC residue: only truncated placeholders remain
    (nothing parseable).  The recreate must neither EEXIST-fail against
    a leftover name nor recycle one — the epoch check starts the new
    chain ABOVE the highest stale slot number."""
    st = fstore.FileStore(str(tmp_path))
    rec = st.cas("k", None, {"n": 0})                     # v1
    rec = st.cas("k", rec.ver, {"n": 1})                  # v2
    rec = st.cas("k", rec.ver, {"n": 2})                  # v3; v1 truncated
    assert st.delete("k", rec.ver)                        # v4; v2 truncated
    # GC collected the tombstone and the fallback, then died: the
    # empty placeholders v1/v2 are still on disk
    for v in (4, 3):
        os.unlink(os.path.join(str(tmp_path), f"k.v{v}.json"))
    rec = st.cas("k", None, {"n": 9})
    assert rec is not None and rec.ver == 3               # tops slot 2
    assert st.get("k").value == {"n": 9}


# -- RaftStore: replication-specific legs -------------------------------------


def test_raft_log_replay_is_idempotent():
    """Exactly-once under retry: re-applying a command with an
    already-seen nonce (the retransmit/replay shape) returns the
    cached result and does NOT re-execute — an append is not
    duplicated, a cas does not double-fire."""
    addrs = [f"127.0.0.1:{_free_ports(1)[0]}"]
    node = fstore.RaftNode(0, addrs, elect_timeout_s=0.2)
    try:
        cmd = {"op": "append", "key": "leader.log.x",
               "rec": {"i": 0}, "nonce": "N1", "stamp": 1.0}
        with node._lock:
            assert node._apply_cmd(cmd, 1) == ("ok",)
            assert node._apply_cmd(cmd, 2) == ("ok",)  # replayed
            assert node.logs["leader.log.x"] == [{"i": 0}]  # applied ONCE
            c2 = {"op": "cas", "key": "k", "ev": None, "val": {"n": 1},
                  "nonce": "N2", "stamp": 1.0}
            r = node._apply_cmd(c2, 3)
            assert r[0] == "ok"
            assert node._apply_cmd(c2, 4) == r  # cached, not re-arbitrated
            assert node.kv["k"][0] == {"n": 1}
    finally:
        node.close()


def test_raft_minority_partition_named_refusal_and_heal():
    """The partition matrix on a private fabric: isolate the leader →
    its mutations raise the NAMED NoQuorumError (healthy() False), the
    majority re-elects and keeps committing; heal → the deposed
    leader's uncommitted entries are truncated away and every node
    converges on the majority's history."""
    addrs, nodes = _mk_fabric()
    stores = [fstore.RaftStore(nd, owns_node=False) for nd in nodes]
    try:
        lead = next(i for i, nd in enumerate(nodes)
                    if nd.role == "leader")
        stores[lead].put("seed", {"v": 0})
        pmap = {i: (1 if i == lead else 0) for i in range(3)}
        for nd in nodes:
            nd.install_partition(pmap)
        time.sleep(2.5 * ELECT_S)  # isolated leader's acks go stale
        assert not stores[lead].healthy()
        with pytest.raises(NoQuorumError):
            stores[lead].cas("minority", None, {"v": 1})
        # majority side: re-elects among itself and commits
        maj = (lead + 1) % 3
        deadline = time.monotonic() + 20.0
        committed = None
        while committed is None and time.monotonic() < deadline:
            try:
                committed = stores[maj].cas("majority", None, {"v": 2})
            except NoQuorumError:
                time.sleep(0.1)
        assert committed is not None
        assert stores[maj].healthy()
        for nd in nodes:
            nd.install_partition(None)  # heal
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            got = stores[lead].get("majority")
            if got is not None and got.value == {"v": 2} \
                    and stores[lead].get("minority") is None:
                break
            time.sleep(0.1)
        # the ex-leader converged on the MAJORITY history: its
        # uncommitted minority intent is gone, not replayed
        assert stores[lead].get("majority").value == {"v": 2}
        assert stores[lead].get("minority") is None
        assert sum(nd.truncated_entries for nd in nodes) >= 1
    finally:
        for nd in nodes:
            nd.close()


def test_raft_client_store_rpc_roundtrip(raft_fabric):
    """The worker/client path: a socket RaftClientStore against the
    fabric mirrors the member handle's view — same CAS arbitration,
    same scan, over the wire."""
    addrs, nodes = raft_fabric
    client = fstore.RaftClientStore(list(addrs))
    try:
        k = _key()
        r1 = client.cas(k, None, {"via": "rpc"})
        assert r1 is not None
        assert client.cas(k, None, {"via": "again"}) is None
        assert client.get(k).value == {"via": "rpc"}
        member = fstore.RaftStore(nodes[0], owns_node=False)
        deadline = time.monotonic() + 10.0
        while member.get(k) is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert member.get(k).value == {"via": "rpc"}
    finally:
        client.close()


def test_member_and_client_spec_resolution(tmp_path):
    """Spec grammar: dir → FileStore; raft:<idx>@addrs → member spec
    (parsed, not started here); raft:addrs → cached client store; and
    client_spec() strips the member index for worker hand-off."""
    st = fstore.resolve_store(str(tmp_path))
    assert isinstance(st, fstore.FileStore)
    assert fstore.resolve_store(str(tmp_path)) is st  # cached
    idx, addrs = fstore.parse_member_spec("raft:2@h1:1,h2:2,h3:3")
    assert idx == 2 and addrs == ["h1:1", "h2:2", "h3:3"]
    assert fstore.client_spec("raft:2@h1:1,h2:2") == "raft:h1:1,h2:2"
    assert fstore.client_spec(str(tmp_path)) == str(tmp_path)
    c1 = fstore.resolve_store("raft:h1:1,h2:2")
    c2 = fstore.resolve_store("raft:0@h1:1,h2:2")  # member → client
    assert c1 is c2  # same addr-set: one cached client handle
    with pytest.raises(ValueError):
        fstore.parse_member_spec("raft:h1:1,h2:2")  # no index: not a member
