"""End-to-end failure story (VERDICT round 1, next-step #6): MPI_Abort's
kill-all contract under the launcher, and a rank crash mid-collective
surfacing as a diagnosable error on the survivors — never a hang — on
BOTH process transports."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

ABORT_PROG = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import mpi_tpu
    from mpi_tpu import api

    comm = mpi_tpu.init()
    if comm.rank == 1:
        api.MPI_Abort(13)
    # every other rank would block forever; the launcher must kill them
    marker = os.environ["MARKER_DIR"] + f"/survived.{{comm.rank}}"
    try:
        comm.recv(source=1, tag=9)           # never sent
    finally:
        pass
    open(marker, "w").write("should not get here")
""")

CRASH_PROG = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import mpi_tpu
    from mpi_tpu.transport.base import RecvTimeout, TransportError

    comm = mpi_tpu.init()
    comm.recv_timeout = 10.0  # the failure-detector knob (SURVEY.md §5)
    if comm.rank == 1:
        os._exit(42)  # die mid-collective, no cleanup
    try:
        # ring allreduce needs rank 1's message: must DIAGNOSE, not hang
        comm.allreduce(np.ones(4, np.float32), algorithm="ring")
    except (RecvTimeout, TransportError) as e:
        print(f"rank {{comm.rank}} diagnosed: {{type(e).__name__}}", flush=True)
        sys.exit(0)
    sys.exit(5)  # collective impossibly succeeded
""")


def _launch(nranks, script_path, backend, env_extra=None, timeout=120.0):
    env = dict(os.environ)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-m", "mpi_tpu.launcher", "-n", str(nranks),
         "--backend", backend, str(script_path)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout)
    return proc


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_mpi_abort_kills_all_and_propagates(tmp_path, backend):
    """MPI_Abort(13) on rank 1: exit code 13 propagates; ranks 0/2 (blocked
    in a recv that can never complete) are killed — the run terminates well
    inside the timeout and no survivor marker is written."""
    script = tmp_path / "abort.py"
    script.write_text(ABORT_PROG.format(repo=REPO))
    t0 = time.monotonic()
    proc = _launch(3, script, backend,
                   env_extra={"MARKER_DIR": str(tmp_path)}, timeout=180.0)
    took = time.monotonic() - t0
    assert proc.returncode == 13, proc.stderr[-800:]
    assert "MPI_Abort(code=13)" in proc.stderr
    survivors = [f for f in os.listdir(tmp_path) if f.startswith("survived.")]
    assert survivors == [], survivors
    assert took < 120.0  # killed, not timed out


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_rank_crash_under_launcher_propagates_promptly(tmp_path, backend):
    """Rank 1 dies (os._exit 42, no close handshake) mid-collective under
    the launcher: code 42 propagates and the surviving rank is killed long
    before any timeout — the L0 kill-all contract."""
    script = tmp_path / "crash.py"
    script.write_text(CRASH_PROG.format(repo=REPO))
    t0 = time.monotonic()
    proc = _launch(2, script, backend, timeout=180.0)
    took = time.monotonic() - t0
    assert proc.returncode == 42, proc.stderr[-500:]
    assert took < 120.0  # killed, not hung to the harness timeout


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_rank_crash_without_launcher_diagnosed(tmp_path, backend):
    """WITHOUT the launcher's kill-all, the survivor's transport itself
    must surface the dead peer: the ring-allreduce recv raises
    RecvTimeout/TransportError (the SURVEY §5 failure-detection analogue)
    instead of hanging."""
    script = tmp_path / "crash.py"
    script.write_text(CRASH_PROG.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({"MPI_TPU_RANK": str(r), "MPI_TPU_SIZE": "2",
                    "MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": backend})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    out0, err0 = procs[0].communicate(timeout=150.0)
    procs[1].wait(timeout=30.0)
    assert procs[1].returncode == 42
    assert "rank 0 diagnosed:" in out0, (
        f"stdout={out0[-500:]!r} stderr={err0[-800:]!r}")
    assert procs[0].returncode == 0, err0[-500:]


RESTART_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import mpi_tpu
    from mpi_tpu import checkpoint

    comm = mpi_tpu.init()
    ckpt = os.path.join({ckpt!r}, "state")
    state = (checkpoint.load(ckpt, comm) if checkpoint.exists(ckpt)
             else {{"step": 0, "acc": np.zeros(4)}})
    start = state["step"]
    for step in range(start, 6):
        state = {{"step": step + 1, "acc": state["acc"] + comm.rank + step}}
        checkpoint.save(ckpt, state, comm)
        if step == 2 and os.environ["MPI_TPU_ATTEMPT"] == "0" \\
                and comm.rank == 1:
            os._exit(41)  # simulated mid-run crash on the first attempt
    total = comm.allreduce(float(state["acc"].sum()))
    if comm.rank == 0:
        with open(os.path.join({ckpt!r}, "result.txt"), "w") as f:
            f.write(f"{{state['step']}} {{total}}")
    mpi_tpu.finalize()
""")


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_restart_resumes_from_checkpoint(tmp_path, backend):
    """The complete failure story (SURVEY.md §5): a rank dies mid-run on
    attempt 0; the launcher kills the world, relaunches, and the program
    resumes from its last committed checkpoint — finishing with exactly
    the state a crash-free run produces."""
    from mpi_tpu.launcher import launch

    script = tmp_path / "worker.py"
    script.write_text(RESTART_WORKER.format(repo=REPO,
                                            ckpt=str(tmp_path)))
    rc = launch(2, [str(script)], timeout=120.0, backend=backend,
                restarts=2)
    assert rc == 0
    step, total = (tmp_path / "result.txt").read_text().split()
    assert step == "6"
    # oracle: acc accumulates (rank + step) 4-wide for steps 0..5
    expect = sum(4.0 * (r + s) for r in (0, 1) for s in range(6))
    assert float(total) == expect


def test_restarts_exhausted_propagates_failure(tmp_path):
    from mpi_tpu.launcher import launch

    script = tmp_path / "always_crash.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import mpi_tpu
        comm = mpi_tpu.init()
        os._exit(43)
    """))
    rc = launch(2, [str(script)], timeout=60.0, restarts=1)
    assert rc == 43
