"""Resident world server: MPI-as-a-service over a warm worker pool.

ROADMAP direction #1: the "millions of users" shape for an MPI library
is many SMALL worlds churned at a high rate, not one big job — and the
cold path (fork N interpreters, import numpy, bind ports, handshake
rings) costs ~seconds per world.  This module keeps all of that warm:

* ``WorldServer`` (the ``python -m mpi_tpu.launcher serve`` daemon)
  spawns ``pool_size`` **worker processes once**, each holding its live
  transport endpoints (socket connections / shm rings + pre-mapped
  arenas) and an enabled ULFM detector, then **leases** sub-worlds to
  clients: an acquire is one control round-trip that reserves idle
  slots — no fork, no handshake — and a job builds its communicator
  locally on every leased worker from ``(slots, job_id)`` (communicator
  construction is pure bookkeeping over the warm transport).
* ``mpi_tpu.connect(addr)`` is the client: ``acquire(nranks)`` →
  ``lease.run(fn, *args)`` → ``release()``.  ``fn`` is pickled by
  reference (workers import the same code), runs as ``fn(comm, *args)``
  on every leased worker, and rank 0's return value comes back.  Every
  lease either completes or raises a NAMED error — a worker death
  mid-collective surfaces to the client as ``ProcFailedError``
  (``MPI_ERR_PROC_FAILED``) within the detection bound, never a hang.
* **Self-healing** (the elastic-membership layer, mpi_tpu/membership):
  the server watches worker liveness (child exit + the PR-3 heartbeat
  files); a death bumps the pool's membership epoch, survivors are
  told to drop the corpse's endpoints (``survivor_transition``), and a
  replacement worker is spawned to ``rejoin`` the world under the new
  epoch through the claim/admit/ready protocol — so the pool keeps
  serving under continuous ``kill -9`` chaos (``bench.py --chaos
  --serve`` drives exactly that and asserts worlds/sec never reaches
  zero).

Wire protocol: length-prefixed pickle frames on a local TCP socket; the
server is the only party that ever coordinates membership, so workers
need no agreement rounds — their ULFM detectors only CONVERT blocked
waits into errors.
"""

from __future__ import annotations

import argparse
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from . import membership
from . import mpit as _mpit
from . import telemetry as _telemetry
from .errors import (DeadlockError, EpochSkewError, ProcFailedError,
                     RejoinRefusedError, RevokedError, error_class)
from .transport.base import RecvTimeout, TransportError
from .transport.socket import _recv_exact

_FRAME = struct.Struct("!I")
_HOST = "127.0.0.1"

# serve defaults — the knobs the README documents; constructor / CLI
# arguments override per server.
_POOL_SIZE = 4
_WORLD_LEASE_TIMEOUT_S = 30.0   # acquire wait + default run bound
_REJOIN_TIMEOUT_S = 20.0        # one healing round's handshake bound
_DETECT_TIMEOUT_S = 2.0         # pool-internal ULFM detection bound
_HEARTBEAT_S = 0.25

# Worker pvars piggybacked on every job_done reply (ISSUE 13): the
# server keeps the latest snapshot per slot and stats()/the metrics
# endpoint aggregate them — the pool's data-plane story (healed links,
# arena hits, detected deaths) without a second control round-trip.
_WORKER_PVARS = ("msgs_sent", "collectives_started", "link_reconnects",
                 "link_faults_masked", "coll_sm_hits",
                 "proc_failures_detected", "epoch_skews_detected",
                 "trace_events")

# Sliding window of the worlds/s gauge (per-second completion buckets).
_RATE_WINDOW_S = 60.0


# -- framing ------------------------------------------------------------------


def _send_msg(sock: socket.socket, lock: Optional[threading.Lock],
              msg: dict) -> None:
    blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _FRAME.pack(len(blob)) + blob
    if lock is None:
        sock.sendall(frame)
    else:
        with lock:
            sock.sendall(frame)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


# -- error shipping -----------------------------------------------------------

_ERROR_KINDS = {
    "ProcFailedError": ProcFailedError,
    "RevokedError": RevokedError,
    "DeadlockError": DeadlockError,
    "EpochSkewError": EpochSkewError,
    "RejoinRefusedError": RejoinRefusedError,
    "RecvTimeout": RecvTimeout,
    "TransportError": TransportError,
}


def _pack_error(exc: BaseException) -> dict:
    return {"kind": type(exc).__name__, "code": error_class(exc),
            "msg": str(exc),
            "failed": list(getattr(exc, "failed", ()) or ()),
            "collective": getattr(exc, "collective", None)}


def _raise_error(err: dict) -> None:
    """Re-raise a shipped worker/server error client-side under its own
    name: the lease contract is 'completes or raises a NAMED FT error',
    and `except ProcFailedError` must work across the wire."""
    kind = err.get("kind", "RuntimeError")
    msg = err.get("msg", "remote failure")
    if kind == "LeaseTimeout":
        raise TimeoutError(msg)
    cls = _ERROR_KINDS.get(kind)
    if cls is ProcFailedError:
        raise ProcFailedError(msg, failed=err.get("failed", ()),
                              collective=err.get("collective"))
    if cls is not None:
        raise cls(msg)
    raise RuntimeError(f"{kind}: {msg}")


# -- built-in jobs (bench / chaos / quickstart) -------------------------------


def job_allreduce(comm, n: int = 1024) -> float:
    """The demo/bench lease payload: a correctness-checkable allreduce.
    Returns sum(1..P) so the client can assert the world really ran."""
    import numpy as np

    out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32))
    return float(out[0])


def job_kill_rank(comm, victim: int = 1, n: int = 4096) -> float:
    """Chaos payload: lease-rank ``victim`` dies WITHOUT cleanup inside
    the leased world (after the barrier, so every rank has entered the
    job) while the rest run a collective on it — the kill-mid-lease
    acceptance story.  Survivors surface ProcFailedError; the client
    sees MPI_ERR_PROC_FAILED."""
    import numpy as np

    comm.barrier()
    if comm.rank == victim:
        os._exit(137)
    out = comm.allreduce(np.ones(int(n), np.float32), algorithm="ring")
    return float(out[0])


def job_sleep(comm, seconds: float = 0.1) -> int:
    comm.barrier()
    time.sleep(float(seconds))
    return comm.rank


def job_allreduce_arena(comm, n: int = 1024) -> tuple:
    """Arena-observability lease payload (ISSUE 11): one auto-routed
    allreduce, returning ``(value, coll_sm_hits delta, live arena
    names)`` from lease-rank 0 so the client can assert the lease rode
    the warm POOLED arena tier (``coll_sm_hits > 0`` under a shm pool;
    on socket pools the delta is honestly 0 — there is no arena)."""
    import numpy as np

    from . import coll_sm as _coll_sm
    from . import mpit as _mpit

    before = _mpit.pvar_read("coll_sm_hits")
    out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32))
    hits = _mpit.pvar_read("coll_sm_hits") - before
    return (float(out[0]), int(hits), sorted(_coll_sm.live_arenas()))


def job_allreduce_link_chaos(comm, n: int = 1024, resets: int = 2) -> float:
    """Link-chaos lease payload (ISSUE 10): each leased rank hard-resets
    its cached connection to the next rank ``resets`` times while
    running allreduces — a lease must ride HEALED links (socket pool:
    the resilient layer reconnects + replays; no ProcFailedError, no
    wrong result).  Returns the last allreduce's checkable value.  On
    transports without connection links (shm pool) the injector is a
    no-op and the job degenerates to job_allreduce."""
    import numpy as np

    inject = getattr(comm._t, "_inject_link_reset", None)
    comm.barrier()
    out = None
    for i in range(int(resets) + 1):
        if inject is not None and i < int(resets) and comm.size > 1:
            inject((comm._group[(comm.rank + 1) % comm.size]))
        out = comm.allreduce(np.full(int(n), comm.rank + 1.0, np.float32),
                             algorithm="ring")
    return float(out[0])


# -- the worker process -------------------------------------------------------


def _worker_main() -> int:
    """Body of one pool worker (``python -m mpi_tpu.serve --worker``):
    bring up the world transport (fresh pool member via init(), or a
    replacement rejoining under MPI_TPU_SERVE_REJOIN=epoch:slot), then
    serve jobs from the control connection.  A control reader thread
    applies membership transitions IMMEDIATELY (even mid-job — dropping
    a corpse's endpoints must not wait for the current lease), while
    the main thread runs one job at a time."""
    import faulthandler
    import queue
    import signal as _signal

    from . import ft as _ft
    from . import init as _init
    from . import mpit as _mpit
    from .communicator import P2PCommunicator

    # field diagnosability: the server SIGUSR2s a worker whose job
    # blew the lease timeout, so the worker's stacks land on its
    # inherited stderr — a wedged lease is diagnosable from the logs
    faulthandler.register(_signal.SIGUSR2, all_threads=True, chain=True)

    detect = os.environ.get("MPI_TPU_SERVE_DETECT_S")
    if detect:
        _mpit.cvar_write("fault_detect_timeout_s", float(detect))
    hb = os.environ.get("MPI_TPU_SERVE_HEARTBEAT_S")
    if hb:
        _mpit.cvar_write("fault_heartbeat_interval_s", float(hb))
    rdv = os.environ["MPI_TPU_RDV"]
    backend = os.environ.get("MPI_TPU_BACKEND", "socket")
    rejoin_spec = os.environ.get("MPI_TPU_SERVE_REJOIN")
    if rejoin_spec:
        epoch, slot = (int(x) for x in rejoin_spec.split(":"))
        rj_timeout = float(os.environ.get(
            "MPI_TPU_SERVE_REJOIN_TIMEOUT_S", 0) or 0) or None
        # the init() path enables tracing from the environment; the
        # rejoin path builds its transport directly, so mirror it here
        # — BEFORE the rejoin handshake, which is exactly the window
        # the rejoin-hello-race class of war story lives in
        _telemetry.enable_from_env(rank=slot)
        t, _ann = membership.rejoin_transport(
            rdv, slot=slot, epoch=epoch, backend=backend,
            timeout=rj_timeout)
        home = P2PCommunicator(t, range(t.world_size), ("epoch", epoch))
        home._mark_generation()
        _ft.enable(home, rdv_dir=rdv)
        # readiness AFTER ft.enable: the heartbeat file must be fresh
        # before survivors are told to re-admit this slot
        membership.publish_ready(rdv, epoch, t.world_rank)
        _mpit.count(rejoins=1)
    else:
        home = _init()  # MPI_TPU_FT=1 in the env: detector enabled
        t = home._t
    world_ft = t._ft_world
    slot = t.world_rank

    host, port = os.environ["MPI_TPU_SERVE_CTRL"].rsplit(":", 1)
    ctrl = socket.create_connection((host, int(port)), timeout=30.0)
    ctrl.settimeout(None)
    ctrl.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    send_lock = threading.Lock()
    _send_msg(ctrl, send_lock, {
        "op": "hello", "slot": slot, "pid": os.getpid(),
        "incarnation": membership.incarnation(), "epoch": t.epoch})

    jobs: "queue.Queue[Optional[dict]]" = queue.Queue()

    def reader() -> None:
        while True:
            msg = _recv_msg(ctrl)
            if msg is None or msg.get("op") == "shutdown":
                jobs.put(None)
                return
            op = msg.get("op")
            if op == "job":
                jobs.put(msg)
            elif op == "transition":
                # observe() FIRST: a job thread wedged in a ring-full
                # send to the corpse exits via the _peer_suspected
                # check, releasing the per-dest send lock that
                # survivor_transition's invalidate needs — the reverse
                # order deadlocks this reader against that sender for
                # a full local detection bound
                # self-filter as a second line of defense: observing
                # our own rank failed is never recoverable locally
                dead = [d for d in msg["dead"] if d != slot]
                for d in dead:
                    world_ft.observe(d, "server-declared dead "
                                        "(pool transition)")
                # even mid-job: the corpse's endpoints must go NOW, or
                # the current lease's sends keep streaming into them
                membership.survivor_transition(t, msg["epoch"], dead)
                _send_msg(ctrl, send_lock,
                          {"op": "transition_ack", "slot": slot,
                           "epoch": msg["epoch"]})
            elif op == "rejoined":
                world_ft.reset_rank(msg["slot"])
                t.min_peer_epoch[int(msg["slot"])] = int(msg["epoch"])

    threading.Thread(target=reader, daemon=True,
                     name=f"serve-ctrl-{slot}").start()

    while True:
        msg = jobs.get()
        if msg is None:
            break
        job_id, slots = msg["job_id"], list(msg["slots"])
        rec = _telemetry.REC
        t_job = time.perf_counter_ns() if rec is not None else 0
        try:
            fn = pickle.loads(msg["fn"])
            args = pickle.loads(msg["args"])
            comm = P2PCommunicator(t, slots, ("lease", job_id))
            comm._ft = _ft.CommFT(world_ft, ("lease", job_id))
            # coll/sm arena via the POOLED path (ISSUE 11, closes the
            # PR-7 "leases skip the arena" residual): one epoch-stamped
            # arena per worker set, reused across leases — the epoch is
            # the SERVER's stamp shipped with the job, so every leased
            # worker keys the same segment even if a concurrent
            # transition broadcast races the dispatch
            comm._coll_sm_pool_ctx = ("lease-pool",
                                      int(msg.get("epoch", 0)))
            result = fn(comm, *args)
            reply = {"op": "job_done", "job_id": job_id, "slot": slot,
                     "ok": True}
            if comm.rank == 0:
                reply["result"] = pickle.dumps(
                    result, protocol=pickle.HIGHEST_PROTOCOL)
        except BaseException as e:  # noqa: BLE001 - shipped to the client
            reply = {"op": "job_done", "job_id": job_id, "slot": slot,
                     "ok": False, "error": _pack_error(e)}
        if rec is not None:
            rec.emit("lease", "job",
                     dur_ns=time.perf_counter_ns() - t_job,
                     attrs={"job_id": job_id, "slots": slots,
                            "ok": reply["ok"],
                            "error": (reply.get("error") or {}).get(
                                "kind")})
        # ISSUE 13: piggyback a pvar snapshot for the server's metrics
        # aggregation — latest-per-slot, summed by stats()
        reply["pvars"] = {n: _mpit.pvar_read(n) for n in _WORKER_PVARS}
        try:
            _send_msg(ctrl, send_lock, reply)
        except OSError:
            return 1  # server gone: nothing left to serve
    # orderly pool shutdown: retire the pooled lease arenas (ISSUE 12
    # satellite, PR-11 residual (d)) — a worker set that never re-leased
    # after its last job has nobody else to unlink its /dev/shm segment
    from . import coll_sm as _coll_sm

    _coll_sm.retire_pooled(t)
    return 0


# -- the server ---------------------------------------------------------------


class _Worker:
    __slots__ = ("slot", "proc", "conn", "send_lock", "state",
                 "incarnation", "epoch", "lease_id", "spawned_at")

    def __init__(self, slot: int) -> None:
        self.slot = slot
        self.proc: Optional[subprocess.Popen] = None
        self.conn: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.state = "starting"  # starting|idle|leased|dead
        self.incarnation: Optional[str] = None
        self.epoch = 0
        self.lease_id: Optional[int] = None
        self.spawned_at = time.monotonic()


class WorldServer:
    """The resident daemon: a pool of warm workers, leased as worlds.

    Use as a context manager (tests / in-process benches) or through
    ``python -m mpi_tpu.launcher serve`` (deployment).  ``addr`` is the
    ``host:port`` clients pass to :func:`connect`."""

    def __init__(self, pool_size: int = _POOL_SIZE, backend: str = "socket",
                 host: str = _HOST, port: int = 0,
                 detect_timeout_s: float = _DETECT_TIMEOUT_S,
                 heartbeat_s: float = _HEARTBEAT_S,
                 world_lease_timeout_s: float = _WORLD_LEASE_TIMEOUT_S,
                 rejoin_timeout_s: float = _REJOIN_TIMEOUT_S,
                 env_extra: Optional[dict] = None,
                 metrics_port: Optional[int] = None) -> None:
        if pool_size < 1:
            raise ValueError("pool_size must be >= 1")
        if backend == "shm":
            from .native import ensure_built

            ensure_built()  # compile once, not pool_size racing ranks
        self.pool_size = pool_size
        self.backend = backend
        self.detect_timeout_s = float(detect_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.world_lease_timeout_s = float(world_lease_timeout_s)
        self.rejoin_timeout_s = float(rejoin_timeout_s)
        self._env_extra = dict(env_extra or {})
        self.rdv = membership.new_rendezvous_dir(prefix="mpi_tpu_serve_")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(pool_size + 16)
        self.addr = "%s:%d" % self._listener.getsockname()[:2]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closing = False
        self.epoch = 0
        self._workers: Dict[int, _Worker] = {}
        self._leases: Dict[int, dict] = {}
        self._jobs: Dict[int, dict] = {}
        self._healing: Dict[int, dict] = {}  # slot -> {epoch, proc, since}
        self._seq = 0
        self.stats_counters = {"leases_granted": 0, "leases_denied": 0,
                               "jobs_ok": 0, "jobs_failed": 0,
                               "heals_completed": 0, "workers_lost": 0}
        self._threads: List[threading.Thread] = []
        # observability (ISSUE 13): uptime anchor for the worlds/s
        # gauge, per-second completed-job buckets (sliding window —
        # bounded at ~window-many keys regardless of rate, unlike a
        # timestamp deque whose maxlen would cap the measurable rate),
        # the latest per-slot worker pvar snapshot, and the optional
        # Prometheus endpoint (metrics_port; 0 = ephemeral, see
        # metrics_addr)
        self._t0 = time.monotonic()
        self._ok_buckets: Dict[int, int] = {}
        self._worker_pvars: Dict[int, dict] = {}
        self._metrics_port = metrics_port
        self._metrics_httpd = None
        self.metrics_addr: Optional[str] = None
        self._host = host

    # -- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True,
              timeout: float = 120.0) -> "WorldServer":
        # the lease-acquire histogram is a process-global mpit pvar:
        # start this server's document clean so sequential in-process
        # servers (the test idiom) don't report a predecessor's tail
        # as their own p99.  (Two CONCURRENT servers in one process —
        # not a deployment shape — still share it.)
        _mpit.pvar_hist_reset("lease_acquire_s")
        for slot in range(self.pool_size):
            self._workers[slot] = _Worker(slot)
            self._spawn_worker(slot)
        for name, target in (("accept", self._accept_loop),
                             ("monitor", self._monitor_loop)):
            th = threading.Thread(target=target, daemon=True,
                                  name=f"serve-{name}")
            th.start()
            self._threads.append(th)
        if self._metrics_port is not None:
            self._start_metrics(self._metrics_port)
        if wait_ready:
            deadline = time.monotonic() + timeout
            with self._cond:
                while any(w.state == "starting"
                          for w in self._workers.values()):
                    if self._closing:
                        raise RuntimeError("server stopped during start")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"worker pool not ready within {timeout}s: "
                            + str({s: w.state for s, w
                                   in self._workers.items()}))
                    self._cond.wait(min(0.25, remaining))
        return self

    def __enter__(self) -> "WorldServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        with self._cond:
            if self._closing:
                return
            self._closing = True
            # every mutable read snapshotted HERE: the monitor thread
            # may be mid-heal, mutating conns and self._healing
            conns = [(w.conn, w.send_lock)
                     for w in self._workers.values()
                     if w.conn is not None]
            procs = [w.proc for w in self._workers.values()
                     if w.proc is not None]
            procs += [h["proc"] for h in self._healing.values()
                      if h.get("proc") is not None]
            self._cond.notify_all()
        for conn, lk in conns:
            try:
                _send_msg(conn, lk, {"op": "shutdown"})
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        httpd = self._metrics_httpd
        if httpd is not None:
            self._metrics_httpd = None
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:  # pragma: no cover - teardown race
                pass
        deadline = time.monotonic() + 5.0
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(2.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        membership.cleanup_rendezvous(self.rdv)

    # -- metrics endpoint (ISSUE 13) ---------------------------------------

    def _start_metrics(self, port: int) -> None:
        """Serve ``GET /metrics`` (Prometheus text format, rendered by
        mpi_tpu/telemetry/metrics.py from the same ``stats()`` document
        ``client.stats()`` returns) on a side HTTP port.  Port 0 binds
        ephemeral — ``metrics_addr`` reports the outcome.  The handler
        only READS (stats() takes the server lock briefly); a scrape
        can never wedge the monitor/heal machinery."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from .telemetry import metrics as _metrics

        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = _metrics.prometheus_text(
                        server.stats()).encode()
                except Exception as e:  # noqa: BLE001 - shipped as 500
                    self.send_error(500, type(e).__name__)
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # noqa: D102
                pass  # scrapes are not server-log events

        httpd = ThreadingHTTPServer((self._host, int(port)), Handler)
        httpd.daemon_threads = True
        self._metrics_httpd = httpd
        self.metrics_addr = "%s:%d" % httpd.server_address[:2]
        th = threading.Thread(target=httpd.serve_forever,
                              daemon=True, name="serve-metrics")
        th.start()
        self._threads.append(th)

    # -- worker processes --------------------------------------------------

    def _worker_env(self, slot: int,
                    rejoin_epoch: Optional[int] = None) -> dict:
        from .launcher import cpu_pinned_env

        env = dict(os.environ)
        want = self._env_extra.get("MPI_TPU_RANK_JAX_PLATFORMS")
        cpu_pinned_env(env, want)
        env.update({
            "MPI_TPU_RANK": str(slot),
            "MPI_TPU_SIZE": str(self.pool_size),
            "MPI_TPU_RDV": self.rdv,
            "MPI_TPU_BACKEND": self.backend,
            "MPI_TPU_FT": "1",
            "MPI_TPU_SERVE_CTRL": self.addr,
            "MPI_TPU_SERVE_DETECT_S": str(self.detect_timeout_s),
            "MPI_TPU_SERVE_HEARTBEAT_S": str(self.heartbeat_s),
        })
        env.pop("MPI_TPU_SERVE_REJOIN", None)
        if rejoin_epoch is not None:
            env["MPI_TPU_SERVE_REJOIN"] = f"{rejoin_epoch}:{slot}"
            env["MPI_TPU_SERVE_REJOIN_TIMEOUT_S"] = \
                str(self.rejoin_timeout_s)
        env.update(self._env_extra)
        return env

    def _spawn_worker(self, slot: int,
                      rejoin_epoch: Optional[int] = None
                      ) -> subprocess.Popen:
        proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_tpu.serve", "--worker"],
            env=self._worker_env(slot, rejoin_epoch))
        if rejoin_epoch is None:
            self._workers[slot].proc = proc
            self._workers[slot].spawned_at = time.monotonic()
        return proc

    # -- accept / connection handling --------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True, name="serve-conn").start()

    def _conn_loop(self, conn: socket.socket) -> None:
        first = _recv_msg(conn)
        if first is None:
            conn.close()
            return
        if first.get("op") == "hello":
            self._worker_loop(conn, first)
        else:
            self._client_loop(conn, first)

    # -- worker side -------------------------------------------------------

    def _worker_loop(self, conn: socket.socket, hello: dict) -> None:
        slot = int(hello["slot"])
        with self._cond:
            w = self._workers.get(slot)
            if w is None:
                conn.close()
                return
            heal = self._healing.pop(slot, None)
            if heal is not None:
                w.proc = heal["proc"]
                self.stats_counters["heals_completed"] += 1
            w.conn = conn
            w.incarnation = hello.get("incarnation")
            w.epoch = int(hello.get("epoch", 0))
            w.lease_id = None
            # (conn, lock) pairs snapshotted under the lock — see
            # _begin_heal for the concurrent-death rationale
            peers = [(p.conn, p.send_lock)
                     for p in self._workers.values()
                     if p is not w and p.conn is not None
                     and p.state not in ("dead",)]
            behind = w.epoch < self.epoch
            catchup = {"op": "transition", "epoch": self.epoch,
                       # never list the hello-ing worker's OWN slot
                       # (its state is still 'dead' right here): a
                       # worker observing itself failed would poison
                       # every FT decision of its future leases
                       "dead": [p.slot for p in self._workers.values()
                                if p is not w
                                and (p.state == "dead"
                                     or p.slot in self._healing)]}
        if behind:
            # another death's transition was broadcast while this
            # worker was still rejoining (excluded as 'dead'): resync
            # it NOW or its first send to an up-epoch survivor raises
            # EpochSkewError forever while stats report a healthy pool
            try:
                _send_msg(conn, w.send_lock, catchup)
            except OSError:
                pass  # EOF path marks it dead next
        if heal is not None:
            # tell the survivors the slot is live again under its epoch
            # BEFORE the slot becomes leasable: a job dispatched to a
            # peer rides the same FIFO control connection as this
            # 'rejoined', so each peer clears its detector's failed
            # entry before it can possibly run a lease with the healed
            # slot — idle-first would let the first post-heal lease
            # raise a spurious ProcFailedError off the stale failed set
            for conn_p, lk_p in peers:
                try:
                    _send_msg(conn_p, lk_p,
                              {"op": "rejoined", "slot": slot,
                               "epoch": w.epoch})
                except OSError:
                    pass
        with self._cond:
            w.state = "idle"
            self._cond.notify_all()
        while True:
            msg = _recv_msg(conn)
            if msg is None:
                with self._cond:
                    if not self._closing and self._workers[slot] is w \
                            and w.conn is conn and w.state != "dead":
                        self._mark_dead_locked(w, "control channel EOF")
                    self._cond.notify_all()
                return
            if msg.get("op") == "job_done":
                self._job_done(slot, msg)
            # transition_acks are informational: the monitor's spawn of
            # the replacement does not wait on them (a wedged worker
            # must not stall the pool's healing)

    def _job_done(self, slot: int, msg: dict) -> None:
        with self._cond:
            pvars = msg.get("pvars")
            if pvars:
                self._worker_pvars[slot] = pvars
            job = self._jobs.get(msg["job_id"])
            if job is None:
                return
            job["pending"].discard(slot)
            if msg.get("ok"):
                if "result" in msg:
                    job["result"] = msg["result"]
            else:
                job["errors"].append(msg.get("error", {}))
            if not job["pending"]:
                job["event"].set()
            self._cond.notify_all()

    def _mark_dead_locked(self, w: _Worker, why: str) -> None:
        """State transition for a lost worker (caller holds the lock):
        epoch bump + fail its in-flight job; the monitor loop picks the
        slot up for healing on its next tick."""
        if w.state == "dead":
            return
        w.state = "dead"
        w.conn = None
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("lease", "worker_dead",
                     attrs={"slot": w.slot, "why": why,
                            "epoch": self.epoch + 1})
        if w.proc is not None and w.proc.poll() is None:
            # declared dead but the process lives (heartbeat-stale
            # wedge): kill it — two live incarnations of one slot must
            # never coexist, and the replacement hello overwrites
            # w.proc, dropping stop()'s only handle on this one
            try:
                w.proc.kill()
            except OSError:
                pass
        self.stats_counters["workers_lost"] += 1
        self.epoch += 1
        for job in self._jobs.values():
            if w.slot in job["pending"]:
                job["pending"].discard(w.slot)
                job["errors"].append({
                    "kind": "ProcFailedError",
                    "code": error_class(ProcFailedError("")),
                    "msg": f"leased worker slot {w.slot} died ({why})",
                    "failed": [w.slot], "collective": None})
                if not job["pending"]:
                    job["event"].set()

    # -- monitoring / healing ----------------------------------------------

    def _hb_stale(self, slot: int, now: float) -> bool:
        try:
            st = os.stat(os.path.join(self.rdv, f"hb.{slot}"))
        except OSError:
            return False  # not yet published: proc liveness covers it
        return now - st.st_mtime > 3.0 * self.detect_timeout_s

    def _monitor_loop(self) -> None:
        while not self._closing:
            time.sleep(self.heartbeat_s)
            if self._closing:
                return
            try:
                self._monitor_tick()
            except Exception as e:  # noqa: BLE001 - the pool's lifeline
                if self._closing:
                    return  # shutdown raced a heal (rdv dir removed)
                # a monitor crash must never silently end healing: a
                # STRUCTURED line (what failed, pool state) + telemetry
                # event instead of ISSUE 7's bare print_exc, then keep
                # ticking (ISSUE 13 satellite)
                import traceback

                with self._lock:
                    epoch, healing = self.epoch, sorted(self._healing)
                sys.stderr.write(
                    f"mpi_tpu.serve: monitor tick failed "
                    f"({type(e).__name__}: {str(e)[:200]}; epoch "
                    f"{epoch}, healing slots {healing}) — healing "
                    f"continues:\n{traceback.format_exc()}")
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("serve", "monitor_error",
                             attrs={"error": type(e).__name__,
                                    "epoch": epoch,
                                    "healing": healing})

    def _monitor_tick(self) -> None:
        now_wall = time.time()
        with self._cond:
            for w in self._workers.values():
                if w.state == "dead" or w.slot in self._healing:
                    continue
                lost = (w.proc is not None
                        and w.proc.poll() is not None)
                if not lost and w.state != "starting":
                    lost = self._hb_stale(w.slot, now_wall)
                if lost:
                    self._mark_dead_locked(
                        w, "process exited"
                        if w.proc is not None
                        and w.proc.poll() is not None
                        else "heartbeat stale")
            # heal EVERY dead slot without a healing round in
            # flight — deaths are marked both here and by the
            # worker-connection EOF path, and both must converge on
            # a replacement
            dead_now = [w for w in self._workers.values()
                        if w.state == "dead"
                        and w.slot not in self._healing]
            epoch = self.epoch
            if dead_now:
                self._cond.notify_all()
        if dead_now:
            self._begin_heal(dead_now, epoch)
        self._drive_healing()

    def _begin_heal(self, dead: List[_Worker], epoch: int) -> None:
        """One healing round: tell survivors, announce the vacancies,
        spawn replacements that rejoin under the new epoch."""
        dead_slots = [w.slot for w in dead]
        with self._lock:
            # snapshot (conn, lock) PAIRS under the lock: a concurrent
            # death nulls worker.conn, and re-reading it outside the
            # lock would hand None to sendall (AttributeError kills the
            # monitor thread — the pool would stop healing entirely)
            live = [(p.conn, p.send_lock) for p in self._workers.values()
                    if p.state not in ("dead", "starting")
                    and p.conn is not None]
        for conn, lk in live:
            try:
                _send_msg(conn, lk, {"op": "transition", "epoch": epoch,
                                     "dead": dead_slots})
            except OSError:
                pass  # its own death will be noticed next tick
        slots_meta = {
            s: {"ousted": membership.read_incarnation(self.rdv, s),
                # the server IS the membership authority: it observed
                # the death and decided to replace, which is the ack —
                # the refusal gate still protects against an UNINVITED
                # ousted incarnation claiming before the server's
                # replacement (it presents the ousted id; the spawned
                # replacement presents a fresh one)
                "acked": False}
            for s in dead_slots}
        membership.announce_rejoin(self.rdv, epoch, slots_meta,
                                   self.pool_size, self.backend)
        with self._lock:
            if self._closing:
                return  # a stop() racing this heal owns every process
            for w in dead:
                proc = self._spawn_worker(w.slot, rejoin_epoch=epoch)
                self._healing[w.slot] = {
                    "epoch": epoch, "proc": proc,
                    "since": time.monotonic(), "meta": slots_meta}

    def _drive_healing(self) -> None:
        """Per-tick healing duties: validate claims/admit replacements
        (the announcer role of the membership protocol), and respawn a
        replacement that died during its own rejoin handshake — the
        pool recovers, no epoch fork (the announce stays valid)."""
        with self._lock:
            healing = dict(self._healing)
        for slot, h in healing.items():
            membership.process_claims(self.rdv, h["epoch"],
                                      {slot: h["meta"][slot]})
            proc = h["proc"]
            if proc.poll() is not None:
                with self._lock:
                    if self._closing or slot not in self._healing:
                        continue
                    h["proc"] = self._spawn_worker(
                        slot, rejoin_epoch=h["epoch"])
                    h["since"] = time.monotonic()
                    self._healing[slot] = h
            elif time.monotonic() - h["since"] > self.rejoin_timeout_s:
                # the replacement is ALIVE but wedged in its handshake
                # past the rejoin bound: kill it — next tick's poll()
                # branch respawns, and process_claims sweeps its
                # leftover claim (dead pid).  Re-check under the lock
                # that this round is STILL healing (mirroring the
                # respawn branch): the worker may have completed its
                # hello since the snapshot, and killing a just-healed,
                # possibly-leased worker would livelock healing
                with self._lock:
                    still = (not self._closing
                             and self._healing.get(slot) is h)
                if still:
                    try:
                        proc.kill()
                    except OSError:
                        pass

    # -- client side -------------------------------------------------------

    def _client_loop(self, conn: socket.socket, first: dict) -> None:
        lock = threading.Lock()
        owned: List[int] = []  # lease ids owned by this connection
        msg: Optional[dict] = first
        try:
            while msg is not None:
                try:
                    reply = self._client_op(msg, owned)
                except Exception as e:  # noqa: BLE001 - shipped back
                    reply = {"error": _pack_error(e)}
                try:
                    _send_msg(conn, lock, reply)
                except OSError:
                    break
                if msg.get("op") == "shutdown":
                    threading.Thread(target=self.stop,
                                     daemon=True).start()
                    break
                msg = _recv_msg(conn)
        finally:
            for lease_id in list(owned):
                self._release(lease_id)
            conn.close()

    def _client_op(self, msg: dict, owned: List[int]) -> dict:
        op = msg.get("op")
        if op == "acquire":
            return self._acquire(msg, owned)
        if op == "run":
            return self._run_job(msg)
        if op == "release":
            self._release(int(msg["lease_id"]))
            if int(msg["lease_id"]) in owned:
                owned.remove(int(msg["lease_id"]))
            return {"ok": True}
        if op == "stats":
            return {"ok": True, "stats": self.stats()}
        if op == "shutdown":
            return {"ok": True}
        return {"error": {"kind": "ValueError",
                          "msg": f"unknown op {op!r}"}}

    def _acquire(self, msg: dict, owned: List[int]) -> dict:
        nranks = int(msg["nranks"])
        if nranks < 1 or nranks > self.pool_size:
            raise ValueError(
                f"nranks must be in [1, {self.pool_size}] for this pool")
        timeout = float(msg.get("timeout") or self.world_lease_timeout_s)
        t_req = time.monotonic()
        deadline = t_req + timeout
        with self._cond:
            while True:
                if self._closing:
                    raise RuntimeError("server shutting down")
                idle = sorted(s for s, w in self._workers.items()
                              if w.state == "idle")
                if len(idle) >= nranks:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.stats_counters["leases_denied"] += 1
                    return {"error": {
                        "kind": "LeaseTimeout",
                        "msg": f"no {nranks} idle workers within "
                               f"{timeout}s (pool {self.pool_size}, "
                               f"idle {len(idle)})"}}
                self._cond.wait(min(0.25, remaining))
            slots = idle[:nranks]
            self._seq += 1
            lease_id = self._seq
            for s in slots:
                self._workers[s].state = "leased"
                self._workers[s].lease_id = lease_id
            epoch = self.epoch
            self._leases[lease_id] = {"slots": slots, "epoch": epoch}
            self.stats_counters["leases_granted"] += 1
        # lease-acquire latency distribution (ISSUE 13): always on —
        # the grant is a control round-trip, one histogram add is noise
        # (this is what the metrics endpoint's p50/p99 summarize)
        _mpit.hist_record("lease_acquire_s", time.monotonic() - t_req)
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("lease", "grant",
                     attrs={"lease_id": lease_id, "slots": slots,
                            "epoch": epoch})
        owned.append(lease_id)
        return {"ok": True, "lease_id": lease_id, "slots": slots,
                "epoch": epoch}

    def _run_job(self, msg: dict) -> dict:
        lease_id = int(msg["lease_id"])
        timeout = float(msg.get("timeout") or self.world_lease_timeout_s)
        with self._cond:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"unknown lease {lease_id}")
            slots = list(lease["slots"])
            dead = [s for s in slots
                    if self._workers[s].state != "leased"
                    or self._workers[s].lease_id != lease_id]
            self._seq += 1
            job_id = self._seq
            job = {"pending": set(slots) - set(dead), "errors": [],
                   "result": None, "event": threading.Event()}
            if dead:
                job["errors"].append({
                    "kind": "ProcFailedError",
                    "code": error_class(ProcFailedError("")),
                    "msg": f"leased worker slot(s) {dead} died before "
                           f"the job started",
                    "failed": dead, "collective": None})
            self._jobs[job_id] = job
            targets = [(self._workers[s].conn, self._workers[s].send_lock)
                       for s in job["pending"]]
        if not job["pending"]:
            job["event"].set()
        for conn, lk in targets:
            try:
                _send_msg(conn, lk, {
                    "op": "job", "job_id": job_id, "slots": slots,
                    # the lease's epoch stamp: keys the pooled coll/sm
                    # arena identically on every leased worker
                    "epoch": lease.get("epoch", 0),
                    "fn": msg["fn"], "args": msg["args"]})
            except OSError:
                pass  # its death is noticed by the monitor and synthesized
        ok = job["event"].wait(timeout)
        with self._cond:
            self._jobs.pop(job_id, None)
            stuck = sorted(job["pending"])
            # pin the exact PROC OBJECTS while holding the lock: a
            # concurrent heal could install a healthy replacement under
            # the same slot, and signalling by slot would dump/kill it
            stuck_procs = [(s, self._workers[s].proc) for s in stuck]
        if not ok:
            # dump the unresponsive workers' stacks to their stderr
            # (faulthandler SIGUSR2 handler) for the diagnosis, then
            # QUARANTINE them by killing: a worker that blew the lease
            # timeout is still wedged in the old job (its job loop is
            # serial), and returning it to the idle pool on release
            # would poison every subsequent lease it joins — killed, it
            # takes the already-tested healing path and comes back as a
            # fresh replacement under the next epoch
            import signal as _signal

            for s, proc in stuck_procs:
                if proc is not None and proc.poll() is None:
                    try:
                        os.kill(proc.pid, _signal.SIGUSR2)
                        time.sleep(0.1)  # let the dump reach stderr
                        proc.kill()
                    except OSError:
                        pass
            sys.stderr.write(
                f"mpi_tpu.serve: job {job_id} on lease {lease_id} "
                f"blew the {timeout}s lease timeout; quarantined "
                f"worker slots {stuck}\n")
            return {"error": {
                "kind": "LeaseTimeout",
                "msg": f"job on lease {lease_id} did not complete "
                       f"within {timeout}s (unresponsive worker "
                       f"slots {stuck}: stacks dumped to the server "
                       f"log, workers killed for pool healing)"}}
        if job["errors"]:
            self.stats_counters["jobs_failed"] += 1
            # the most diagnosable error wins: a named FT error over a
            # generic one
            errs = sorted(
                job["errors"],
                key=lambda e: 0 if e.get("kind") in _ERROR_KINDS else 1)
            # ISSUE 13 satellite: a lease failure is attributable in
            # the server log — job/lease id, error class, failed slots
            sys.stderr.write(
                f"mpi_tpu.serve: job {job_id} on lease {lease_id} "
                f"failed: {errs[0].get('kind')}: "
                f"{str(errs[0].get('msg', ''))[:200]} "
                f"(failed slots {errs[0].get('failed')})\n")
            return {"error": errs[0]}
        with self._cond:
            self.stats_counters["jobs_ok"] += 1
            sec = int(time.monotonic())
            self._ok_buckets[sec] = self._ok_buckets.get(sec, 0) + 1
            for k in [k for k in self._ok_buckets
                      if sec - k > _RATE_WINDOW_S]:
                del self._ok_buckets[k]
        return {"ok": True, "result": job["result"]}

    def _release(self, lease_id: int) -> None:
        with self._cond:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            for s in lease["slots"]:
                w = self._workers[s]
                if w.state == "leased" and w.lease_id == lease_id:
                    w.state = "idle"
                    w.lease_id = None
            self._cond.notify_all()

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            states = {s: w.state for s, w in self._workers.items()}
            # worlds/s over the sliding window (completed jobs), the
            # gauge ROADMAP direction 1 asks for; uptime-bounded so a
            # young server reads its true rate, not a diluted one
            window = min(_RATE_WINDOW_S, max(1e-9, now - self._t0))
            recent = sum(c for sec, c in self._ok_buckets.items()
                         if now - sec <= _RATE_WINDOW_S)
            agg: Dict[str, int] = {}
            for snap in self._worker_pvars.values():
                for k, v in snap.items():
                    agg[k] = agg.get(k, 0) + int(v)
            out = {
                "addr": self.addr, "backend": self.backend,
                "pool_size": self.pool_size, "epoch": self.epoch,
                "workers": states,
                "idle": sum(1 for v in states.values() if v == "idle"),
                "healing": sorted(self._healing),
                "leases_active": len(self._leases),
                "uptime_s": round(now - self._t0, 3),
                "worlds_per_s": round(recent / window, 3),
                "worker_pvars": agg,
                "metrics_addr": self.metrics_addr,
                **self.stats_counters,
            }
        # lease-acquire quantiles from the histogram pvar (log-bucket
        # estimates — mpit.hist_quantile documents the error bound)
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            est = _mpit.hist_quantile("lease_acquire_s", q)
            out[f"lease_acquire_{label}_ms"] = (
                None if est is None else round(est * 1e3, 3))
        return out


# -- the client ---------------------------------------------------------------


class WorldLease:
    """A leased world: run jobs on it, release it when done."""

    def __init__(self, client: "ServerClient", lease_id: int,
                 slots: List[int], epoch: int) -> None:
        self._client = client
        self.lease_id = lease_id
        self.slots = list(slots)
        self.epoch = int(epoch)
        self._released = False

    @property
    def size(self) -> int:
        return len(self.slots)

    def run(self, fn, *args: Any, timeout: Optional[float] = None) -> Any:
        """Execute ``fn(comm, *args)`` on every leased worker (``fn``
        pickled by reference — workers must be able to import it);
        returns lease-rank 0's return value.  Raises the worker-side
        error BY NAME (ProcFailedError & co.) on any failure."""
        reply = self._client._request({
            "op": "run", "lease_id": self.lease_id,
            "fn": pickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL),
            "args": pickle.dumps(args, protocol=pickle.HIGHEST_PROTOCOL),
            "timeout": timeout})
        blob = reply.get("result")
        return pickle.loads(blob) if blob is not None else None

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._client._request({"op": "release",
                                   "lease_id": self.lease_id})

    def __enter__(self) -> "WorldLease":
        return self

    def __exit__(self, *exc) -> None:
        try:
            self.release()
        except (TransportError, OSError):
            pass  # server gone: the lease died with it (and a release
            # failure must never mask the body's real exception)


class ServerClient:
    """Client handle to a resident world server (see :func:`connect`).

    The initial connect retries ``ConnectionRefusedError`` with
    exponential backoff + jitter for up to the ``connect_retry_timeout_s``
    mpit cvar (mpi_tpu/resilience.py): a freshly-spawned server
    (``launcher serve --addr-file`` races its own bind) looks exactly
    like a refused connection, and first-failure raise forced every
    caller to hand-roll the same sleep loop.  Any other failure — or a
    refusal that outlives the budget — raises as before."""

    def __init__(self, host: str, port: int, timeout: float = 30.0) -> None:
        from .resilience import retry_connect

        self._sock = retry_connect(
            lambda: socket.create_connection((host, port),
                                             timeout=timeout))
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()  # one request/response in flight

    def _request(self, msg: dict) -> dict:
        with self._lock:
            _send_msg(self._sock, None, msg)
            reply = _recv_msg(self._sock)
        if reply is None:
            raise TransportError("world server closed the connection")
        if "error" in reply:
            _raise_error(reply["error"])
        return reply

    def acquire(self, nranks: int,
                timeout: Optional[float] = None) -> WorldLease:
        """Lease ``nranks`` warm workers as a world: ONE round-trip (the
        server reserves idle slots; no fork, no handshake).  Raises
        TimeoutError when the pool cannot supply them in time."""
        reply = self._request({"op": "acquire", "nranks": int(nranks),
                               "timeout": timeout})
        return WorldLease(self, reply["lease_id"], reply["slots"],
                          reply["epoch"])

    def run(self, fn, *args: Any, nranks: int = 2,
            timeout: Optional[float] = None) -> Any:
        """acquire + run + release in one call (the simple path)."""
        lease = self.acquire(nranks, timeout=timeout)
        try:
            return lease.run(fn, *args, timeout=timeout)
        finally:
            try:
                lease.release()
            except (TransportError, OSError):
                pass  # server gone: must not mask run()'s real error

    def stats(self) -> dict:
        return self._request({"op": "stats"})["stats"]

    def shutdown(self) -> None:
        """Ask the server process to stop (admin surface)."""
        try:
            self._request({"op": "shutdown"})
        except (TransportError, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect(addr: Any, timeout: float = 30.0) -> ServerClient:
    """Connect to a resident world server.  ``addr`` is ``"host:port"``,
    a ``(host, port)`` tuple, a :class:`WorldServer` (in-process), or a
    path to a file containing ``host:port`` (the launcher's
    ``serve --addr-file``)."""
    if isinstance(addr, WorldServer):
        addr = addr.addr
    if isinstance(addr, (tuple, list)):
        host, port = addr[0], int(addr[1])
    else:
        text = str(addr)
        if os.path.exists(text):
            with open(text) as f:
                text = f.read().strip()
        host, port = text.rsplit(":", 1)
        port = int(port)
    return ServerClient(host, port, timeout=timeout)


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--worker":
        return _worker_main()
    ap = argparse.ArgumentParser(
        prog="mpi_tpu.launcher serve",
        description="resident world server: pool warm workers, lease "
                    "worlds to clients, self-heal under kill injection")
    ap.add_argument("--pool-size", type=int, default=_POOL_SIZE)
    ap.add_argument("--backend", choices=("socket", "shm"),
                    default="socket")
    ap.add_argument("--host", default=_HOST)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--addr-file", default=None,
                    help="write host:port here once listening "
                         "(clients: mpi_tpu.connect(path))")
    ap.add_argument("--detect-timeout", type=float,
                    default=_DETECT_TIMEOUT_S,
                    help="pool-internal ULFM detection bound (s)")
    ap.add_argument("--heartbeat", type=float, default=_HEARTBEAT_S)
    ap.add_argument("--lease-timeout", type=float,
                    default=_WORLD_LEASE_TIMEOUT_S,
                    help="world_lease_timeout_s: max wait for idle "
                         "workers / default job bound")
    ap.add_argument("--rejoin-timeout", type=float,
                    default=_REJOIN_TIMEOUT_S,
                    help="rejoin_timeout_s of one healing handshake")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve GET /metrics (Prometheus text format: "
                         "worlds/s, lease p50/p99, pool epoch, per-"
                         "worker health, aggregated worker pvars) on "
                         "this HTTP port; 0 binds an ephemeral port "
                         "(printed at startup)")
    args = ap.parse_args(argv)
    server = WorldServer(
        pool_size=args.pool_size, backend=args.backend, host=args.host,
        port=args.port, detect_timeout_s=args.detect_timeout,
        heartbeat_s=args.heartbeat,
        world_lease_timeout_s=args.lease_timeout,
        rejoin_timeout_s=args.rejoin_timeout,
        metrics_port=args.metrics_port)
    server.start()
    print(f"mpi_tpu serve: listening on {server.addr} "
          f"(pool {args.pool_size} x {args.backend})", flush=True)
    if server.metrics_addr:
        print(f"mpi_tpu serve: metrics on "
              f"http://{server.metrics_addr}/metrics", flush=True)
    if args.addr_file:
        tmp = args.addr_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(server.addr)
        os.replace(tmp, args.addr_file)
    try:
        while not server._closing:
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
