"""Async progress engine (ISSUE 6 tentpole — mpi_tpu/progress.py).

Four contracts:

* background completion — with ``progress=thread`` a posted ``irecv``
  completes (``req._done`` flips) with NO wait/test call from any
  caller thread, on the local AND shm transports; the collective
  family (including the segmented multi-exchange paths and the
  i-collectives) keeps exact parity and the zero-pickled-bytes wire
  contract;
* deadlock coverage — a pure-polling ``MPI_Waitany`` drain loop (the
  PR-5 verifier residual) is published on the rank's behalf by the
  engine and raises :class:`DeadlockError` from the polling path; the
  same program under ``progress=none`` documents the residual (bounded
  no-detection); a merely-SLOW peer never false-positives, polling or
  not;
* FT interplay — a rank killed mid-``ialltoall`` with the engine
  running surfaces ProcFailedError within the same derived detection
  bound as without it;
* the off-mode zero-cost contract — ``progress=none`` creates no
  engine (``comm._progress is None`` is the ONE hot-path attribute
  test) and every ``progress_*`` pvar stays exactly 0.
"""

import time

import numpy as np
import pytest

from mpi_tpu import mpit, ops, progress
from mpi_tpu.api import MPI_Waitany
from mpi_tpu.errors import DeadlockError, ProcFailedError
from mpi_tpu.transport.faulty import FaultyTransport
from mpi_tpu.transport.local import KILLED, run_local
from tests.test_shm_backend import run_shm_world

DETECT_S = 1.0


# -- background completion ---------------------------------------------------


def test_irecv_completes_in_background_local():
    """The headline semantic: req._done flips while the receiver only
    sleeps — completion is engine-owned, not caller-financed."""
    def prog(comm):
        if comm.rank == 0:
            time.sleep(0.1)
            comm.send(np.arange(8.0), 1, tag=5)
            return "sent"
        req = comm.irecv(0, 5)
        deadline = time.time() + 10
        while not req._done and time.time() < deadline:
            time.sleep(0.01)  # deliberately NO wait()/test()
        assert req._done, "engine did not complete the irecv in background"
        return req.wait()

    out = run_local(prog, 2, progress="thread")
    np.testing.assert_array_equal(out[1], np.arange(8.0))


def test_irecv_completes_in_background_shm():
    """Same semantic on the shm transport: the engine's doorbell-parked
    park hook drains the native rings while every other thread of the
    rank sleeps (no user waiter, no helper cadence dependence)."""
    def prog(comm):
        progress.enable(comm)
        comm.barrier()
        if comm.rank == 0:
            comm.send(np.arange(1 << 15, dtype=np.float64), 1, tag=9)
            comm.barrier(algorithm="dissemination")
            return "sent"
        req = comm.irecv(0, 9)
        deadline = time.time() + 10
        while not req._done and time.time() < deadline:
            time.sleep(0.01)
        assert req._done, "shm engine did not drain/complete in background"
        got = req.wait()
        comm.barrier(algorithm="dissemination")
        return float(np.asarray(got)[-1])

    out = run_shm_world(prog, 2)
    assert out[1] == float((1 << 15) - 1)


def test_park_releases_progress_lock_and_recv_completes_inline():
    """PR-6 residual (c) regression: ``ShmTransport.progress_park``
    must NOT hold the progress lock across its futex nap.  While a
    parker naps, the lock is observably FREE — so a blocking user recv
    that starts mid-park takes the inline drain path itself instead of
    waiting a thread hop behind the engine — and the recv completes
    far inside the 5s park slice."""
    import threading

    def prog(comm):
        t = comm._t
        if comm.rank == 0:
            comm.barrier(algorithm="dissemination")
            time.sleep(0.35)  # rank 1's parker is napping by now
            comm.send(np.arange(1024.0), 1, tag=7)
            comm.barrier(algorithm="dissemination")
            return None
        stop = threading.Event()

        def parker():  # stands in for the engine loop's park call
            while not stop.is_set():
                try:
                    t.progress_park(5.0)
                except Exception:  # noqa: BLE001 - teardown race
                    return

        th = threading.Thread(target=parker, daemon=True)
        comm.barrier(algorithm="dissemination")
        th.start()
        time.sleep(0.15)  # inside the nap, before rank 0's send
        lock_free = t._progress_lock.acquire(blocking=False)
        if lock_free:
            t._progress_lock.release()
        t0 = time.monotonic()
        got = comm.recv(0, tag=7)
        took = time.monotonic() - t0
        stop.set()
        # the closing barrier's arrival rings the doorbell, popping the
        # parker out of its nap to observe `stop`
        comm.barrier(algorithm="dissemination")
        th.join(7.0)
        assert not th.is_alive(), "parker never exited"
        assert lock_free, \
            "progress_park held the progress lock across its futex nap"
        assert took < 2.0, \
            f"recv waited {took:.2f}s against a parked engine"
        return float(np.asarray(got)[-1])

    out = run_shm_world(prog, 2)
    assert out[1] == 1023.0


def test_user_recv_latency_unchanged_while_engine_parked():
    """ISSUE 12 satellite — the stronger spelling of PR-6 residual (c):
    with a REAL engine attached and parked on the doorbell, a blocking
    user recv whose message arrives mid-park completes at inline-drain
    latency.  If the park ever re-held the progress lock across its
    nap, each recv would queue up to a full park slice (0.25s) behind
    the engine — the median below would jump past the bound."""
    def prog(comm):
        progress.enable(comm)
        comm.barrier(algorithm="dissemination")
        if comm.rank == 0:
            for i in range(8):
                time.sleep(0.05)  # peer is blocked in recv, engine parked
                comm.send(np.arange(256.0), 1, tag=20 + i)
            comm.barrier(algorithm="dissemination")
            return None
        lats = []
        for i in range(8):
            t0 = time.monotonic()
            comm.recv(0, tag=20 + i)
            lats.append(time.monotonic() - t0)
        comm.barrier(algorithm="dissemination")
        return sorted(lats)[len(lats) // 2]

    idle0 = mpit.pvar_read("progress_idle_parks")
    out = run_shm_world(prog, 2)
    assert mpit.pvar_read("progress_idle_parks") > idle0, \
        "engine never actually parked during the run"
    # send cadence is 50ms, so the inline-drain median sits just above
    # it; a lock-across-the-nap regression adds ~a 250ms park slice
    assert out[1] < 0.15, \
        f"median blocking-recv latency {out[1]:.3f}s against a parked engine"


def test_collective_parity_and_wire_contract_under_thread():
    """The whole family stays exact under the engine, and the ring
    allreduce's zero-pickled-bytes contract survives — engine
    completion consumes already-delivered payloads, adding no wire
    traffic and no copies."""
    base_pickled = mpit.pvar_read("bytes_pickled_sent")

    def prog(comm):
        x = np.full(1 << 14, comm.rank + 1.0, np.float32)
        r1 = comm.allreduce(x, algorithm="ring")
        r2 = comm.ialltoall(
            [np.full(8, comm.rank * 10 + d, np.float64)
             for d in range(comm.size)]).wait()
        r3 = comm.iallreduce(np.float64(comm.rank)).wait()
        comm.barrier()
        return float(r1[0]), np.asarray(r2)[:, 0].tolist(), float(r3)

    out = run_local(prog, 3, progress="thread")
    for r, (s, col, isum) in enumerate(out):
        assert s == 6.0
        assert col == [d * 10.0 + r for d in range(3)]
        assert isum == 3.0
    assert mpit.pvar_read("progress_wakeups") > 0
    assert mpit.pvar_read("bytes_pickled_sent") == base_pickled


def test_seg_window_advanced_by_engine():
    """Forced multi-segment exchanges under the engine: the credit
    window's tail sends are posted by completion callbacks
    (_SegSender.advance) — parity proves ordering held."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)
    try:
        def prog(comm):
            x = np.arange(2048, dtype=np.float64) + comm.rank
            r = comm.allreduce(x, algorithm="ring")
            h = comm.allreduce(x, algorithm="recursive_halving")
            return r, h

        out = run_local(prog, 2, progress="thread")
        want = np.arange(2048, dtype=np.float64) * 2 + 1
        for r, h in out:
            np.testing.assert_allclose(r, want)
            np.testing.assert_allclose(h, want)
    finally:
        mpit.cvar_write("collective_segment_bytes", old)


# -- deadlock coverage (the PR-5 pure-polling residual) ----------------------


@pytest.fixture
def _fast_stall():
    old = mpit.cvar_read("verify_stall_timeout_s")
    mpit.cvar_write("verify_stall_timeout_s", 1.0)
    yield
    mpit.cvar_write("verify_stall_timeout_s", old)


def _drain_loop(comm, give_up_s):
    """A pure-polling drain loop (the body MPI_Waitany spins on) over an
    irecv that can never complete (cross pattern, nobody sends) —
    bounded so the no-engine leg documents the residual instead of
    hanging the suite."""
    req = comm.irecv((comm.rank + 1) % comm.size, tag=3)
    deadline = time.time() + give_up_s
    try:
        while time.time() < deadline:
            done, _ = req.test()
            if done:
                return "completed"
            time.sleep(0.001)
        return "no-detection"
    except DeadlockError as e:
        assert len(e.ranks) == comm.size
        return "deadlocked"


def test_waitany_drain_loop_deadlock_detected(_fast_stall):
    """progress=thread: the engine publishes the OR-set on the polling
    rank's behalf, the wait-for analysis closes, and the actual
    ``MPI_Waitany`` call raises DeadlockError from its polling loop —
    the residual the ROADMAP carried since PR 5."""
    base = mpit.pvar_read("verify_deadlocks_detected")

    def prog(comm):
        req = comm.irecv((comm.rank + 1) % comm.size, tag=3)
        try:
            MPI_Waitany([req])  # blocks polling: nobody ever sends
            return "completed"
        except DeadlockError as e:
            assert len(e.ranks) == comm.size
            return "deadlocked"

    out = run_local(prog, 2, verify=True, progress="thread", timeout=60)
    assert out == ["deadlocked", "deadlocked"], out
    assert mpit.pvar_read("verify_deadlocks_detected") > base


def test_waitany_drain_loop_escapes_without_engine(_fast_stall):
    """progress=none: the same program polls forever undiagnosed — the
    documented limit of blocking-waits-only participation, and the
    contrast that proves the engine (not some other change) closed
    it."""
    out = run_local(_drain_loop, 2, args=(4.0,), verify=True,
                    progress="none", timeout=60)
    assert out == ["no-detection", "no-detection"], out


def test_slow_peer_never_false_positives(_fast_stall):
    """Polling against a peer that is merely SLOW (computing, will send)
    must complete cleanly: the analysis needs a closed picture, and the
    sender rank has no blocked/polling entry."""
    base = mpit.pvar_read("verify_deadlocks_detected")

    def prog(comm):
        if comm.rank == 0:
            time.sleep(3.0)  # well past the 1s stall bound
            comm.send(b"late", 1, tag=2)
            return "sent"
        req = comm.irecv(0, 2)
        while True:
            i, v = MPI_Waitany([req])
            if i is not None:
                return v

    out = run_local(prog, 2, verify=True, progress="thread", timeout=60)
    assert out == ["sent", b"late"]
    assert mpit.pvar_read("verify_deadlocks_detected") == base


def test_waitany_publishes_exact_request_set(_fast_stall):
    """A stalled ``MPI_Waitany`` publishes the OR-set of ITS OWN request
    list.  Rank 0 posts two tracked irecvs (from 1 and from 2) but
    drains only the first through Waitany — the published entry must
    name source 1 alone, never the {1, 2} union over every tracked
    request (which would accuse rank 2 of blocking a loop that is not
    waiting for it)."""
    def prog(comm):
        if comm.rank == 0:
            req_a = comm.irecv(1, tag=5)
            req_b = comm.irecv(2, tag=6)
            i, v = MPI_Waitany([req_a])
            assert (i, v) == (0, b"from-1")
            return req_b.wait()
        if comm.rank == 1:
            time.sleep(3.0)  # hold rank 0 in the drain past the stall
            comm.send(b"from-1", 0, tag=5)
            return "sent"
        # rank 2: watch the shared board for rank 0's drain-loop entry,
        # record the targets it names, then release the second irecv
        board = comm._t._verify_world.board
        seen = None
        deadline = time.time() + 2.5
        while time.time() < deadline:
            e = board.read_all().get(0)
            if e is not None and e.get("kind") == "waitany-poll":
                seen = list(e.get("targets", ()))
                break
            time.sleep(0.05)
        comm.send(b"from-2", 0, tag=6)
        return seen

    out = run_local(prog, 3, verify=True, progress="thread", timeout=60)
    assert out[0] == b"from-2"
    assert out[1] == "sent"
    assert out[2] == [1], out[2]


def test_posted_irecv_without_polling_never_published(_fast_stall):
    """A rank that posts an irecv and then just computes (no polls) is
    NOT a drain loop: the engine must not publish it, even while a peer
    blocks on this rank — compute-overlap programs stay clean."""
    base = mpit.pvar_read("verify_deadlocks_detected")

    def prog(comm):
        if comm.rank == 0:
            # posts an irecv it will only consume much later, computes
            req = comm.irecv(1, 7)
            time.sleep(3.0)
            comm.send(np.arange(4.0), 1, tag=8)
            return req.wait()
        got = comm.recv(0, 8)  # blocks well past the stall bound
        comm.send(np.arange(2.0), 0, 7)
        return got

    out = run_local(prog, 2, verify=True, progress="thread", timeout=60)
    np.testing.assert_array_equal(out[0], np.arange(2.0))
    np.testing.assert_array_equal(out[1], np.arange(4.0))
    assert mpit.pvar_read("verify_deadlocks_detected") == base


# -- FT interplay ------------------------------------------------------------


def test_ft_kill_mid_ialltoall_detection_bound_unchanged():
    """Rank 1 dies mid-exchange with the engine running; the survivor's
    ialltoall wait converts the detector hit into ProcFailedError
    within the same multiple of the bound the engine-less suite
    asserts."""
    old = {k: mpit.cvar_read(k) for k in ("fault_detect_timeout_s",
                                          "fault_heartbeat_interval_s")}
    mpit.cvar_write("fault_detect_timeout_s", DETECT_S)
    mpit.cvar_write("fault_heartbeat_interval_s", 0.05)
    try:
        def kill_rank1(inner):
            return (FaultyTransport(inner, kill_after_n=2)
                    if inner.world_rank == 1 else inner)

        def prog(comm):
            blocks = [np.ones(1 << 12) * d for d in range(comm.size)]
            if comm.rank == 1:
                comm.alltoall(blocks)  # dies on send 2
                return "unreachable"
            t0 = time.monotonic()
            with pytest.raises(ProcFailedError) as ei:
                comm.ialltoall(blocks).wait()
            assert time.monotonic() - t0 < 6 * DETECT_S
            assert 1 in ei.value.failed
            return "diagnosed"

        out = run_local(prog, 3, transport_wrapper=kill_rank1,
                        fault_tolerance=True, progress="thread", timeout=60)
        assert out[0] == out[2] == "diagnosed"
        assert out[1] is KILLED
    finally:
        for k, v in old.items():
            mpit.cvar_write(k, v)


# -- off-mode zero-cost contract ---------------------------------------------


def test_off_mode_zero_wakeups_and_single_attribute():
    """progress=none: no engine object anywhere (the hot paths' one
    attribute test reads None) and every progress pvar stays 0 across
    real traffic."""
    ses = mpit.session_create()
    ses.reset_all()

    def prog(comm):
        assert comm._progress is None
        assert getattr(comm._t, "_progress_engine", None) is None
        comm.allreduce(np.arange(64.0))
        r = comm.ialltoall([np.arange(4.0)] * comm.size).wait()
        comm.irecv(comm.rank, 1)  # posted, never matched: still no engine
        comm.barrier()
        return np.asarray(r).shape

    run_local(prog, 2, progress="none")
    for p in ("progress_wakeups", "progress_completions",
              "progress_idle_parks"):
        assert ses.read(p) == 0, p


def test_mode_resolution_and_cvar():
    """Explicit arg > MPI_TPU_PROGRESS env > ``progress`` cvar; bad
    values rejected everywhere."""
    import os

    assert progress.resolve_mode("thread") == "thread"
    assert progress.resolve_mode() == "none"
    old_env = os.environ.pop("MPI_TPU_PROGRESS", None)
    try:
        mpit.cvar_write("progress", "thread")
        assert progress.resolve_mode() == "thread"
        assert mpit.cvar_read("progress") == "thread"
        os.environ["MPI_TPU_PROGRESS"] = "none"
        assert progress.resolve_mode() == "none"
        assert progress.resolve_mode("thread") == "thread"
    finally:
        mpit.cvar_write("progress", "none")
        if old_env is None:
            os.environ.pop("MPI_TPU_PROGRESS", None)
        else:
            os.environ["MPI_TPU_PROGRESS"] = old_env
    with pytest.raises(ValueError):
        progress.resolve_mode("fibers")
    with pytest.raises(ValueError):
        mpit.cvar_write("progress", "fibers")


def test_waitany_drain_detects_exited_peer(_fast_stall):
    """The engine also converts the wait-on-exited case for pollers: a
    drain loop over a peer whose program RETURNED is diagnosed (the
    exited entry closes the picture)."""
    def prog(comm):
        if comm.rank == 0:
            return "gone"  # publishes 'exited' via run_local
        return _drain_loop(comm, 20.0)

    out = run_local(prog, 2, verify=True, progress="thread", timeout=60)
    assert out[0] == "gone"
    assert out[1] == "deadlocked"
