"""IOR-style MPI-IO micro-benchmark (beyond-parity: exercises
mpi_tpu/io.py the way the OSU suite exercises the message layer).

Per pattern and message size, every rank writes/reads ``--blocks`` blocks
and the aggregate file bandwidth is reported (bytes all ranks moved ÷
wall time, max over ranks — the IOR convention).  Patterns:

* ``segmented``  — rank r owns one contiguous segment of the file
  (``write_at`` at rank-offset; the large-file streaming case);
* ``strided``    — ranks interleave block-sized records through a vector
  filetype view (the collective-buffering stress case; uses
  ``write_at_all`` two-phase aggregation when the epoch is small);
* ``shared``     — every record goes through the shared file pointer
  (fetch-and-add contention case).

Usage::

    python -m benchmarks.io_bench --backend local -n 4 \
        --sizes 64KB:4MB:4 --patterns segmented,strided
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import List

import numpy as np

try:
    import mpi_tpu
except ModuleNotFoundError:  # fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

from benchmarks.osu import parse_sizes  # shared size grammar
from mpi_tpu import datatypes as dt
from mpi_tpu import io as mio

PATTERNS = ("segmented", "strided", "shared")


def _bench_pattern(comm, path: str, pattern: str, size: int,
                   blocks: int, iters: int) -> dict:
    """One (pattern, size) point: returns aggregate write+read GB/s."""
    n = size  # bytes per block, uint8 etype
    block = np.full(n, comm.rank % 251, np.uint8)
    out = {"pattern": pattern, "size": size, "blocks": blocks,
           "nranks": comm.size}

    def run_epoch(write: bool) -> float:
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR,
                          shared=(pattern == "shared"))
        if pattern == "strided":
            # rank r's records interleave via the view DISPLACEMENT —
            # the same vector filetype for everyone, shifted by disp
            ft = dt.type_vector(blocks, n, n * comm.size, np.uint8)
            f.set_view(disp=comm.rank * n, etype=np.uint8, filetype=ft)
        comm.barrier()
        t0 = time.perf_counter()
        for b in range(blocks):
            if pattern == "segmented":
                at = (comm.rank * blocks + b) * n
                got = f.write_at(at, block) if write else f.read_at(at, n)
            elif pattern == "strided":
                # the view linearizes my records: block b at offset b*n
                if write:
                    got = f.write_at_all(b * n, block)
                else:
                    got = f.read_at_all(b * n, n)
            else:  # shared
                got = f.write_shared(block) if write else f.read_shared(n)
            if not write:
                # content check (cheap: ends of the block).  My patterns
                # read my own records back; shared reads SOME rank's
                # block-aligned record — uniform either way.
                assert got.size == n and got[0] == got[-1],                     f"corrupt readback ({pattern}, block {b})"
                if pattern != "shared":
                    assert got[0] == comm.rank % 251,                         f"cross-rank clobber ({pattern}, block {b})"
        f.sync()
        comm.barrier()
        dt_s = time.perf_counter() - t0
        f.close()
        return dt_s

    total = comm.size * blocks * n
    w = min(run_epoch(True) for _ in range(iters))
    r = min(run_epoch(False) for _ in range(iters))
    out["write_gbps"] = total / w / 1e9
    out["read_gbps"] = total / r / 1e9
    return out


def worker(comm, args) -> List[dict]:
    import shutil

    rows = []
    base = comm.bcast(tempfile.mkdtemp(prefix="io_bench_")
                      if comm.rank == 0 else None, 0)
    try:
        for pattern in args.patterns:
            for size in args.sizes:
                path = os.path.join(base, f"io_{pattern}_{size}.bin")
                row = _bench_pattern(comm, path, pattern, size,
                                     args.blocks, args.iters)
                if comm.rank == 0:
                    print(json.dumps(row), flush=True)
                rows.append(row)
                comm.barrier()
    finally:
        comm.barrier()
        if comm.rank == 0:
            shutil.rmtree(base, ignore_errors=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchmarks.io_bench")
    ap.add_argument("--backend", default=None)
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--sizes", default="64KB:1MB:3")
    ap.add_argument("--blocks", type=int, default=8)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--patterns", default="segmented,strided,shared")
    args = ap.parse_args(argv)
    args.sizes = parse_sizes(args.sizes)
    args.patterns = [p.strip() for p in args.patterns.split(",") if p.strip()]
    for p in args.patterns:
        if p not in PATTERNS:
            ap.error(f"unknown pattern {p!r} (choose from {PATTERNS})")
    mpi_tpu.run(worker, args, backend=args.backend, nranks=args.nranks)
    return 0


if __name__ == "__main__":
    sys.exit(main())
