"""Discrete-event model of the pipelined Pallas ring protocol.

``pallas_ring._kernel``'s pipelined path (credits, per-(parity, segment)
DMA semaphores, entry/exit barriers) never executes anywhere reachable
without a multi-chip slice: the interpreter runs the serial fallback and
P=1 returns early on the single real chip (VERDICT r2 missing #1).  This
module is the execution evidence: a host-side semaphore-level simulation
of the kernel's exact op sequence, checked under adversarial event
orderings.

**Faithfulness.**  ``device_program`` emits, per device, the literal
sequence of semaphore/DMA operations of ``_kernel`` with
``pipelined=True`` (each op is annotated with the kernel construct it mirrors).  The kernel's pipelined control flow is branch-free —
every wait/signal/DMA is unconditional once (P, K, collective) are fixed —
so the program IS a static op list, and the model cannot diverge from the
kernel by taking a different branch.

**Grouped rings**: a split communicator runs one independent ring per
group with per-device (grank, left, right) SMEM params — a pure
relabeling of device ids.  Each group's protocol is therefore isomorphic
to a full ring of the group's size, so the (P, K) coverage below covers
grouped rings of the same geometry; groups share no semaphores, buffers,
or barrier signals (each device signals only its own ring's neighbors).

**Semaphore semantics** (Mosaic's): counting semaphores; ``signal`` may
target a remote device; ``wait(n)`` blocks until value ≥ n, then atomically
subtracts n.  A remote copy is split into two independently-scheduled
completions: *leave* (source buffer free → send_sem increments on the
sender) and *arrive* (bytes written at the destination → recv_sem
increments on the receiver), with leave ≤ arrive per copy and NO ordering
across copies — the adversary controls all interleaving.

**Invariants checked** (the kernel's correctness argument):

1. *No deadlock*: from every reachable state some event is enabled until
   all devices exit.  (The semaphore graph is single-waiter — each
   semaphore is waited on by exactly one device — so the system is a
   conflict-free Petri net and deadlock-freedom is schedule-independent;
   the exhaustive search below verifies this for small (P, K) rather than
   assuming it.)
2. *No landing-slot overwrite*: an RDMA never arrives into a comm-buffer
   (parity, segment) slot whose previous payload has not been accumulated
   — the credit protocol's whole job.
3. *No source mutation in flight*: no device writes a buffer region that
   is the source of one of its own started-but-not-left RDMAs, and no RDMA
   arrives into a region concurrently being read as an RDMA source.
4. *Semaphores drain to zero* at exit (Mosaic's own hardware invariant —
   leftover counts corrupt the next collective using the same ids).
5. *Data correctness* under every explored ordering: payloads are modeled
   as sets of (rank, chunk, segment) contributions; after the allreduce
   every device holds every contribution, after the reduce-scatter rank r
   holds all contributions to chunk r.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Op vocabulary (one-to-one with the kernel's pltpu calls)
# ---------------------------------------------------------------------------

# sem keys: ("send", slot, seg) / ("recv", slot, seg) / ("credit", slot, seg)
# / ("bar",) — all owned (waited on) by exactly one device.
SemKey = Tuple


@dataclass(frozen=True)
class Wait:
    sem: SemKey
    n: int


@dataclass(frozen=True)
class Signal:          # pltpu.semaphore_signal(dev=target)
    target: int        # absolute device id
    sem: SemKey
    inc: int = 1


@dataclass(frozen=True)
class DmaStart:        # make_async_remote_copy(...).start()
    u: int
    seg: int


@dataclass(frozen=True)
class Accum:           # the VMEM accumulate of landing slot (u%2, seg)
    u: int
    seg: int


class ProtocolViolation(AssertionError):
    pass


def _send_chunk(my: int, u: int, P: int, rot: int, dirn: int) -> int:
    # pallas_ring._kernel send_chunk; dirn=-1 is the mirror ring
    return (my - u + rot) % P if dirn > 0 else (my + u - rot) % P

def _accum_chunk(my: int, u: int, P: int, rot: int, dirn: int) -> int:
    # pallas_ring._kernel accum_chunk
    return (my - u - 1 + rot) % P if dirn > 0 else (my + u + 1 - rot) % P


def device_program(my: int, P: int, K: int, *, rot: int,
                   allgather: bool, rs: bool = True,
                   dirs: Optional[Tuple[int, ...]] = None) -> List[object]:
    """The pipelined ``_kernel`` body for device ``my`` as a static op list
    (the pipelined=True body of pallas_ring._kernel).

    ``dirs`` gives the direction of each flow (+1 right-going, -1
    left-going mirror ring); default: K unidirectional flows.  A flow's
    credit goes to its upstream writer — left for +1, right for -1."""
    left, right = (my - 1) % P, (my + 1) % P
    dirs = dirs or (1,) * K
    F = len(dirs)
    # rs=False models the kernel's ALLGATHER-ONLY mode (zero RS steps)
    n_rs = P - 1 if rs else 0
    n_steps = n_rs + (P - 1 if allgather else 0)
    ops: List[object] = []

    # entry neighbor_barrier()
    ops += [Signal(left, ("bar",)), Signal(right, ("bar",)),
            Wait(("bar",), 2)]
    # warm-up sends, u=0 (no dependency: step-0 payload is original data)
    for fi in range(F):
        ops.append(DmaStart(0, fi))
    for u in range(n_steps):
        slot = u % 2
        for fi in range(F):
            writer = left if dirs[fi] > 0 else right
            ops.append(Wait(("recv", slot, fi), 1))      # rdma(u).wait_recv()
            if u < n_rs:
                ops.append(Accum(u, fi))                 # VMEM accumulate
            if u + 2 < n_steps:                          # credit the writer
                ops.append(Signal(writer, ("credit", slot, fi)))
            if u + 1 < n_steps:                          # start_send(u + 1):
                if u + 1 >= 2:                           # wait_send + credit gate
                    ops.append(Wait(("send", (u + 1) % 2, fi), 1))
                    ops.append(Wait(("credit", (u + 1) % 2, fi), 1))
                ops.append(DmaStart(u + 1, fi))
    # drain: the two newest sends per flow are still in flight
    for fi in range(F):
        if n_steps >= 2:
            ops.append(Wait(("send", (n_steps - 2) % 2, fi), 1))
        ops.append(Wait(("send", (n_steps - 1) % 2, fi), 1))
    # exit neighbor_barrier()
    ops += [Signal(left, ("bar",)), Signal(right, ("bar",)),
            Wait(("bar",), 2)]
    return ops


# ---------------------------------------------------------------------------
# Simulator
# ---------------------------------------------------------------------------

Region = Tuple[int, int]  # (chunk, seg) of a device's out buffer


@dataclass
class Dma:
    src: int
    u: int
    seg: int
    phase: str                  # "started" -> "left" -> gone on arrive
    payload: FrozenSet
    src_region: Region
    dst: int
    # destination: RS -> comm slot (u%2, seg); AG -> out region
    dst_slot: Optional[Tuple[int, int]]
    dst_region: Optional[Region]

    def key(self):
        return (self.src, self.u, self.seg, self.phase)


class RingSim:
    """One simulation run of P devices under a pluggable event policy."""

    def __init__(self, P: int, K: int, *, rot: int, allgather: bool,
                 rs: bool = True,
                 track_data: bool = True,
                 program_override=None,
                 dirs: Optional[Tuple[int, ...]] = None):
        if P < 2:
            raise ValueError("ring needs P >= 2")
        self.P, self.K = P, K
        self.dirs = tuple(dirs) if dirs else (1,) * K
        F = len(self.dirs)
        self.rot, self.allgather, self.rs = rot, allgather, rs
        self.n_rs = P - 1 if rs else 0
        self.n_steps = self.n_rs + (P - 1 if allgather else 0)
        prog_fn = program_override or device_program
        kw = dict(rot=rot, allgather=allgather, dirs=self.dirs)
        import inspect

        sig = inspect.signature(prog_fn)
        if "rs" in sig.parameters or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values()):
            kw["rs"] = rs
        elif not rs:
            raise ValueError("program_override does not model rs=False")
        self.progs = [prog_fn(d, P, K, **kw) for d in range(P)]
        self.pc = [0] * P
        self.sems: List[Dict[SemKey, int]] = [dict() for _ in range(P)]
        self.dmas: List[Dma] = []
        self.track_data = track_data
        # out[d][(chunk, flow)] = set of contributions (rank, chunk, flow)
        # (flows own disjoint tile ranges, so a flow index IS a region)
        # allreduce/RS: every chunk holds the device's own contribution;
        # ag-only: only the device's OWN chunk starts populated
        self.out = [{(c, s): (frozenset([(d, c, s)])
                              if rs or c == d else frozenset())
                     for c in range(P) for s in range(F)}
                    for d in range(P)]
        # comm[d][(slot, flow)] = (state, payload); landing double buffer
        self.comm = [{(sl, s): ("empty", frozenset())
                      for sl in range(2) for s in range(F)}
                     for d in range(P)]
        self.trace: List[str] = []
        # -- link-occupancy tracking (VERDICT r3 missing #4) --------------
        # Physical link i joins devices i and i+1; a right-going RDMA from
        # src rides link src, a left-going one from src rides link src-1.
        # Counters sample occupancy once per executed event ("tick"):
        # how often each direction had an RDMA in flight, how often BOTH
        # did simultaneously (the full-duplex overlap the bidirectional
        # design claims), and the same per physical link.
        self.ticks = 0
        self.dir_busy_ticks = {+1: 0, -1: 0}
        self.both_dir_ticks = 0
        self.link_overlap_ticks = [0] * P

    # -- event enumeration --------------------------------------------------

    def device_enabled(self, d: int) -> bool:
        if self.pc[d] >= len(self.progs[d]):
            return False
        op = self.progs[d][self.pc[d]]
        if isinstance(op, Wait):
            return self.sems[d].get(op.sem, 0) >= op.n
        return True

    def enabled_events(self) -> List[Tuple]:
        ev: List[Tuple] = [("dev", d) for d in range(self.P)
                           if self.device_enabled(d)]
        for i, dma in enumerate(self.dmas):
            if dma.phase == "started":
                ev.append(("leave", i))
            elif dma.phase == "left":
                ev.append(("arrive", i))
        return ev

    # -- event execution ----------------------------------------------------

    def _mk_dma(self, d: int, u: int, fi: int) -> Dma:
        P, rot = self.P, self.rot
        dirn = self.dirs[fi]
        target = (d + 1) % P if dirn > 0 else (d - 1) % P
        c = _send_chunk(d, u, P, rot, dirn)
        payload = self.out[d][(c, fi)] if self.track_data else frozenset()
        if u < self.n_rs:
            return Dma(d, u, fi, "started", payload, (c, fi), target,
                       dst_slot=(u % 2, fi), dst_region=None)
        return Dma(d, u, fi, "started", payload, (c, fi), target,
                   dst_slot=None, dst_region=(c, fi))

    def step(self, event: Tuple) -> None:
        kind = event[0]
        if kind == "dev":
            d = event[1]
            op = self.progs[d][self.pc[d]]
            self.pc[d] += 1
            if isinstance(op, Wait):
                have = self.sems[d].get(op.sem, 0)
                if have < op.n:
                    raise ProtocolViolation(
                        f"dev{d} executed un-enabled wait {op}")
                self.sems[d][op.sem] = have - op.n
            elif isinstance(op, Signal):
                t = op.target
                self.sems[t][op.sem] = self.sems[t].get(op.sem, 0) + op.inc
            elif isinstance(op, DmaStart):
                self.dmas.append(self._mk_dma(d, op.u, op.seg))
            elif isinstance(op, Accum):
                self._accum(d, op.u, op.seg)
            self.trace.append(f"dev{d}:{op}")
        elif kind == "leave":
            dma = self.dmas[event[1]]
            if self.track_data and \
                    self.out[dma.src][dma.src_region] != dma.payload:
                raise ProtocolViolation(
                    f"source region {dma.src_region} of dev{dma.src} step "
                    f"{dma.u} mutated while the RDMA was reading it "
                    f"(invariant 3)")
            dma.phase = "left"
            sk = ("send", dma.u % 2, dma.seg)
            self.sems[dma.src][sk] = self.sems[dma.src].get(sk, 0) + 1
            self.trace.append(f"leave:{dma.src}->{dma.dst} u={dma.u} "
                              f"seg={dma.seg}")
        elif kind == "arrive":
            i = event[1]
            dma = self.dmas[i]
            dst = dma.dst
            if dma.dst_slot is not None:          # RS: comm landing zone
                state, _ = self.comm[dst][dma.dst_slot]
                if state == "full":
                    raise ProtocolViolation(
                        f"RDMA u={dma.u} seg={dma.seg} from dev{dma.src} "
                        f"overwrote unconsumed landing slot {dma.dst_slot} "
                        f"on dev{dst} (invariant 2: write-before-credit)")
                self.comm[dst][dma.dst_slot] = ("full", dma.payload)
            else:                                  # AG: straight into out
                for other in self.dmas:
                    if (other is not dma and other.phase == "started"
                            and other.src == dst
                            and other.src_region == dma.dst_region):
                        raise ProtocolViolation(
                            f"AG RDMA from dev{dma.src} landed in region "
                            f"{dma.dst_region} of dev{dst} while dev{dst} "
                            f"was sending from it (invariant 3)")
                if self.track_data:
                    self.out[dst][dma.dst_region] = dma.payload
            rk = ("recv", dma.u % 2, dma.seg)
            self.sems[dst][rk] = self.sems[dst].get(rk, 0) + 1
            del self.dmas[i]
            self.trace.append(f"arrive:{dma.src}->{dst} u={dma.u} "
                              f"seg={dma.seg}")
        self._record_occupancy()

    def _record_occupancy(self) -> None:
        """Sample per-direction / per-link wire occupancy after an event.
        An RDMA occupies its link from start until arrive (the model's
        conservative in-flight window)."""
        self.ticks += 1
        busy: Dict[int, set] = {+1: set(), -1: set()}
        for dma in self.dmas:
            dirn = self.dirs[dma.seg]
            link = dma.src if dirn > 0 else (dma.src - 1) % self.P
            busy[dirn].add(link)
        for dirn in (+1, -1):
            if busy[dirn]:
                self.dir_busy_ticks[dirn] += 1
        if busy[+1] and busy[-1]:
            self.both_dir_ticks += 1
        for link in busy[+1] & busy[-1]:
            self.link_overlap_ticks[link] += 1

    def occupancy_summary(self) -> Dict[str, object]:
        """Link-occupancy evidence for the bidirectional-overlap claim
        (pallas_ring.py header: 'twice the usable line-rate'):
        ``both_dir_ticks`` counts event-ticks during which right-going
        AND left-going RDMAs were simultaneously in flight, and
        ``links_with_duplex_overlap`` how many physical links carried
        both directions at once at some point."""
        return {
            "ticks": self.ticks,
            "right_busy_ticks": self.dir_busy_ticks[+1],
            "left_busy_ticks": self.dir_busy_ticks[-1],
            "both_dir_ticks": self.both_dir_ticks,
            "links_with_duplex_overlap": sum(
                1 for t in self.link_overlap_ticks if t > 0),
            "n_links": self.P,
        }

    def _accum(self, d: int, u: int, seg: int) -> None:
        slot = (u % 2, seg)
        state, payload = self.comm[d][slot]
        if state != "full":
            raise ProtocolViolation(
                f"dev{d} accumulated empty landing slot {slot} at step {u} "
                f"(wait_recv matched a different copy)")
        ci = _accum_chunk(d, u, self.P, self.rot, self.dirs[seg])
        region = (ci, seg)
        for dma in self.dmas:
            if (dma.phase == "started" and dma.src == d
                    and dma.src_region == region):
                raise ProtocolViolation(
                    f"dev{d} step {u} accumulated into region {region} "
                    f"still being read by its own in-flight RDMA "
                    f"u={dma.u} (invariant 3)")
            if (dma.dst == d and dma.dst_region == region):
                raise ProtocolViolation(
                    f"dev{d} step {u} accumulated into region {region} "
                    f"targeted by an inbound AG RDMA from dev{dma.src} "
                    f"(invariant 3)")
        if self.track_data:
            self.out[d][region] = self.out[d][region] | payload
        self.comm[d][slot] = ("empty", frozenset())

    # -- termination + final invariants -------------------------------------

    def done(self) -> bool:
        return (all(self.pc[d] >= len(self.progs[d]) for d in range(self.P))
                and not self.dmas)

    def check_final(self) -> None:
        for d in range(self.P):
            for k, v in self.sems[d].items():
                if v != 0:
                    raise ProtocolViolation(
                        f"semaphore {k} on dev{d} = {v} at exit "
                        f"(invariant 4: must drain to zero)")
        if not self.track_data:
            return
        P, F = self.P, len(self.dirs)
        if not self.rs:
            # ag-only: chunk c everywhere = device c's original block
            for d in range(P):
                for c in range(P):
                    for s in range(F):
                        got = self.out[d][(c, s)]
                        want = frozenset([(c, c, s)])
                        if got != want:
                            raise ProtocolViolation(
                                f"allgather data wrong on dev{d} chunk {c} "
                                f"seg {s}: {sorted(got)} != {sorted(want)} "
                                f"(invariant 5)")
            return
        if self.allgather:
            for d in range(P):
                for c in range(P):
                    for s in range(F):
                        got = self.out[d][(c, s)]
                        want = frozenset((r, c, s) for r in range(P))
                        if got != want:
                            raise ProtocolViolation(
                                f"allreduce data wrong on dev{d} chunk {c} "
                                f"seg {s}: {sorted(got)} != full reduction "
                                f"(invariant 5)")
        else:
            for d in range(P):
                c = d  # rot=-1: the last RS step accumulates chunk ``my``
                for s in range(F):
                    got = self.out[d][(c, s)]
                    want = frozenset((r, c, s) for r in range(P))
                    if got != want:
                        raise ProtocolViolation(
                            f"reduce_scatter data wrong on dev{d} chunk {c} "
                            f"seg {s}: {sorted(got)} (invariant 5)")

    # -- drivers ------------------------------------------------------------

    def run(self, policy: str = "random", seed: int = 0,
            max_events: int = 1_000_000) -> None:
        """Run to completion under a scheduling policy.

        * ``random`` — uniformly random enabled event (seeded).
        * ``eager_compute`` — device ops first; DMA phases only when no
          device can move (maximum latency adversary).
        * ``lazy_lifo`` — when forced to move a DMA, move the NEWEST one
          (out-of-order completion adversary).
        * ``dma_first`` — complete DMAs as soon as possible (zero-latency).
        """
        rng = random.Random(seed)
        for _ in range(max_events):
            if self.done():
                self.check_final()
                return
            ev = self.enabled_events()
            if not ev:
                blocked = {
                    d: self.progs[d][self.pc[d]]
                    for d in range(self.P) if self.pc[d] < len(self.progs[d])}
                raise ProtocolViolation(
                    f"DEADLOCK (invariant 1): blocked={blocked} "
                    f"in-flight={[(x.src, x.u, x.seg, x.phase) for x in self.dmas]}")
            if policy == "random":
                choice = rng.choice(ev)
            elif policy == "eager_compute":
                dev = [e for e in ev if e[0] == "dev"]
                choice = rng.choice(dev) if dev else rng.choice(ev)
            elif policy == "lazy_lifo":
                dev = [e for e in ev if e[0] == "dev"]
                if dev:
                    choice = rng.choice(dev)
                else:
                    choice = max(ev, key=lambda e: e[1])
            elif policy == "dma_first":
                dma = [e for e in ev if e[0] != "dev"]
                choice = dma[0] if dma else rng.choice(ev)
            else:
                raise ValueError(policy)
            self.step(choice)
        raise ProtocolViolation("event budget exhausted (livelock?)")

    # -- exhaustive state-space search (protocol state only) ---------------

    def _snapshot(self):
        sems = tuple(tuple(sorted((k, v) for k, v in s.items() if v))
                     for s in self.sems)
        dmas = tuple(sorted(d.key() for d in self.dmas))
        slots = tuple(tuple(sorted((k, st) for k, (st, _) in c.items()
                                   if st != "empty"))
                      for c in self.comm)
        return (tuple(self.pc), sems, dmas, slots)


# ---------------------------------------------------------------------------
# Ring-attention circulation protocol (pallas_attention._kernel)
# ---------------------------------------------------------------------------


def attention_program(my: int, P: int) -> List[object]:
    """The pipelined ``pallas_attention._kernel`` body for device ``my``
    as a static op list (same one-to-one construction discipline as
    ``device_program``).  Single flow; ``Accum(a, 0)`` models the
    VMEM-copy+online-softmax fold of arrival ``a`` (a=0 → the device's
    own block, no slot involved).  Send ``u`` targets slot (u+1)%2;
    sends 0/1 are credit-free (virgin slots); the credit for slot a%2
    is signalled only after wait_send(a) — the forward must have READ
    the slot out before the writer may land arrival a+2 in it."""
    left, right = (my - 1) % P, (my + 1) % P
    ops: List[object] = [Signal(left, ("bar",)), Signal(right, ("bar",)),
                         Wait(("bar",), 2)]
    ops.append(Accum(0, 0))                       # fold own block
    if P >= 2:
        ops.append(DmaStart(0, 0))                # circulate own block
        ops.append(Wait(("send", 1, 0), 1))       # sem hygiene for send 0
    for a in range(1, P):
        slot = a % 2
        ops.append(Wait(("recv", slot, 0), 1))    # arrival a landed
        if a <= P - 2:
            if a >= 2:                            # dst slot needs a credit
                ops.append(Wait(("credit", (a + 1) % 2, 0), 1))
            ops.append(DmaStart(a, 0))            # forward the block
        ops.append(Accum(a, 0))                   # fold it
        if a <= P - 2:
            ops.append(Wait(("send", (a + 1) % 2, 0), 1))  # forward left
        if a + 2 <= P - 1:                        # slot reused at a+2
            ops.append(Signal(left, ("credit", slot, 0)))
    ops += [Signal(left, ("bar",)), Signal(right, ("bar",)),
            Wait(("bar",), 2)]
    return ops


class AttentionSim(RingSim):
    """RingSim specialization for the K/V circulation protocol: payloads
    are block ids moving through the 2-slot landing buffer; ``out`` is
    reused as the per-device fold log (which blocks were folded, in what
    order).  Invariants: the shared 1-4 (no deadlock, no slot overwrite,
    no read-while-landing, sems drain) plus (5') every device folds
    every block EXACTLY once, in ring order my, my-1, ..., my-P+1.

    ``hq``/``hkv`` model the multi-head/GQA payload layout (VERDICT r4
    weak #3 — executed checks, not relabeling arguments): the payload
    carries one (plane, block) entry per K and V head-plane, and the
    fold validates that EVERY plane of exactly one block is present —
    a send that split or mixed head planes across RDMAs would be
    caught.  ``causal=True`` models the fold-skip: arrivals with
    kv_idx > my leave the fold log untouched (the protocol events are
    identical — the kernel's pl.when gates only the MXU body), and the
    final check expects exactly the non-future blocks."""

    def __init__(self, P: int, hq: int = 1, hkv: int = 1,
                 causal: bool = False):
        # reuse RingSim's machinery with a 1-flow ALLGATHER-ish config;
        # programs/payloads are overridden below
        super().__init__(P, 1, rot=0, allgather=True, rs=False,
                         track_data=True,
                         program_override=lambda d, p, k, **kw:
                         attention_program(d, p))
        # fold log replaces the out grid; comm keeps (state, payload)
        self.folded: List[List[int]] = [[] for _ in range(P)]
        # what each device's NEXT send actually carries is read from the
        # slot at DmaStart time (catching schedule bugs for real)
        self.own_block = list(range(P))
        self.hq, self.hkv, self.causal = hq, hkv, causal
        self.planes = tuple([("k", h) for h in range(hkv)]
                            + [("v", h) for h in range(hkv)])

    def _block_of(self, payload, d: int, where: str) -> int:
        """The single block id a complete payload carries — every K and
        V head-plane present, all naming the same block."""
        blocks = {b for (_, b) in payload}
        planes = {p for (p, _) in payload}
        if len(blocks) != 1 or planes != set(self.planes):
            raise ProtocolViolation(
                f"dev{d} {where}: payload {sorted(payload)} is not ONE "
                f"block with all {len(self.planes)} head planes")
        return next(iter(blocks))

    def _mk_dma(self, d: int, u: int, fi: int) -> Dma:
        P = self.P
        if u == 0:
            payload = frozenset((pl, d) for pl in self.planes)
        else:
            state, payload = self.comm[d][(u % 2, 0)]
            if state != "full":
                raise ProtocolViolation(
                    f"dev{d} forwarded from EMPTY slot {(u % 2, 0)} at "
                    f"send {u} (forward started before arrival consumed)")
        return Dma(d, u, fi, "started", payload, (u % 2, fi), (d + 1) % P,
                   dst_slot=((u + 1) % 2, fi), dst_region=None)

    def step(self, event: Tuple) -> None:
        kind = event[0]
        if kind == "dev":
            d = event[1]
            op = self.progs[d][self.pc[d]]
            if isinstance(op, Signal) and op.sem[0] == "credit":
                # crediting left = promising MY slot is reusable; free it
                # (its content was folded AND forwarded out — the program
                # places the signal after Accum and wait_send)
                self.comm[d][(op.sem[1], op.sem[2])] = ("empty", frozenset())
            super().step(event)
            return
        if kind == "leave":
            # a forward (u>0) reads a comm SLOT; verify it never changed
            # under the in-flight read (RingSim's leave checks the out
            # grid instead, which attention does not use)
            dma = self.dmas[event[1]]
            if dma.u > 0:
                state, cur = self.comm[dma.src][dma.src_region]
                if state != "full" or cur != dma.payload:
                    raise ProtocolViolation(
                        f"slot {dma.src_region} of dev{dma.src} changed "
                        f"while forward u={dma.u} was reading it "
                        f"(invariant 3)")
            dma.phase = "left"
            sk = ("send", (dma.u + 1) % 2, dma.seg)
            self.sems[dma.src][sk] = self.sems[dma.src].get(sk, 0) + 1
            self._record_occupancy()
            return
        # arrive: attention's recv semaphores are indexed by the LANDING
        # slot parity (u+1)%2, not RingSim's u%2 — handle fully here
        i = event[1]
        dma = self.dmas[i]
        dst = dma.dst
        state, _ = self.comm[dst][dma.dst_slot]
        if state == "full":
            raise ProtocolViolation(
                f"arrival u={dma.u} from dev{dma.src} overwrote unconsumed "
                f"slot {dma.dst_slot} on dev{dst} (invariant 2)")
        for other in self.dmas:
            if (other is not dma and other.phase == "started"
                    and other.src == dst and other.u > 0
                    and other.src_region == dma.dst_slot):
                raise ProtocolViolation(
                    f"arrival u={dma.u} landed in slot {dma.dst_slot} of "
                    f"dev{dst} while dev{dst}'s forward u={other.u} was "
                    f"reading it (invariant 3)")
        self.comm[dst][dma.dst_slot] = ("full", dma.payload)
        rk = ("recv", dma.dst_slot[0], dma.seg)
        self.sems[dst][rk] = self.sems[dst].get(rk, 0) + 1
        del self.dmas[i]
        self._record_occupancy()

    def _accum(self, d: int, u: int, seg: int) -> None:
        if u == 0:
            self.folded[d].append(d)              # own block, no slot
            return
        slot = (u % 2, seg)
        state, payload = self.comm[d][slot]
        if state != "full":
            raise ProtocolViolation(
                f"dev{d} folded EMPTY slot {slot} at arrival {u}")
        b = self._block_of(payload, d, f"arrival {u}")
        if not self.causal or b <= d:
            self.folded[d].append(b)  # causal skips future blocks' MXU
        # the slot stays FULL until the credit signal frees it (it is
        # still the forward's RDMA source); never-credited tail slots
        # simply stay full to exit — no invariant needs them empty

    def check_final(self) -> None:
        for d in range(self.P):
            for k, vv in self.sems[d].items():
                if vv != 0:
                    raise ProtocolViolation(
                        f"semaphore {k} on dev{d} = {vv} at exit "
                        f"(invariant 4)")
            want = [(d - a) % self.P for a in range(self.P)]
            if self.causal:
                want = [b for b in want if b <= d]
            if self.folded[d] != want:
                raise ProtocolViolation(
                    f"dev{d} folded {self.folded[d]}, want ring order "
                    f"{want} (invariant 5')")


def _explore(fresh, max_states: int) -> int:
    """Shared exhaustive DFS over every interleaving (protocol state, no
    payload tracking): every reachable state must have an enabled event
    unless the run is complete, and every terminal state must pass
    ``check_final``.  Returns the number of distinct states visited."""
    seen = set()
    root = fresh()
    stack = [[]]  # paths (event lists); replay is cheap at these sizes
    seen.add(root._snapshot())
    visited = 1
    while stack:
        path = stack.pop()
        sim = fresh()
        for e in path:
            sim.step(e)
        if sim.done():
            sim.check_final()
            continue
        ev = sim.enabled_events()
        if not ev:
            raise ProtocolViolation(
                f"DEADLOCK at depth {len(path)}: pc={sim.pc} "
                f"dmas={[(d.src, d.u, d.seg, d.phase) for d in sim.dmas]}")
        for e in ev:
            child = fresh()
            for pe in path:
                child.step(pe)
            child.step(e)
            snap = child._snapshot()
            if snap in seen:
                continue
            seen.add(snap)
            visited += 1
            if visited > max_states:
                raise ProtocolViolation("state space larger than budget")
            stack.append(path + [e])
    return visited


def explore_attention(P: int, max_states: int = 2_000_000,
                      hq: int = 1, hkv: int = 1,
                      causal: bool = False) -> int:
    """Exhaustive DFS over the attention circulation protocol (the
    ``explore_all`` twin for AttentionSim)."""
    return _explore(lambda: AttentionSim(P, hq, hkv, causal), max_states)


# ---------------------------------------------------------------------------
# Ring-attention BACKWARD circulation (pallas_attention._bwd_kernel)
# ---------------------------------------------------------------------------


def attention_bwd_program(my: int, P: int) -> List[object]:
    """The pipelined ``pallas_attention._bwd_kernel`` body for device
    ``my`` as a static op list.  [K, V, dK, dV] circulate for a FULL
    cycle: sends 0..P-1, arrivals 1..P; arrival P is the home arrival
    (my own block back, all ranks' dK/dV accumulated), consumed without
    forwarding.  Fold-BEFORE-forward: ``Accum(a)`` both consumes and
    MUTATES slot a%2 (adds this rank's dK/dV contribution), then
    ``DmaStart(a)`` forwards the mutated payload.  Ordering invariant
    (review round 5 — the first ordering deadlocked at P>=3): the
    retire of hop a-1 (wait_send) and its credit signal come BEFORE
    hop a's credit wait, so every signal precedes, in program order,
    the waits it transitively feeds around the ring."""
    left, right = (my - 1) % P, (my + 1) % P
    ops: List[object] = [Accum(0, 0)]             # fold own block +
    #                                               assemble [K,V,dK,dV]
    ops += [Signal(left, ("bar",)), Signal(right, ("bar",)),
            Wait(("bar",), 2)]
    if P >= 2:
        ops.append(DmaStart(0, 0))                # circulate own block
    for a in range(1, P + 1):
        slot = a % 2
        ops.append(Wait(("recv", slot, 0), 1))    # arrival a landed
        if a < P:
            ops.append(Accum(a, 0))               # fold + mutate slot
            # retire snd(a-1) (its send sem parity = ((a-1)+1)%2), then
            # credit its source slot — BEFORE this hop's credit wait
            ops.append(Wait(("send", slot, 0), 1))
            if 1 <= a - 1 <= P - 2:
                ops.append(Signal(left, ("credit", (a - 1) % 2, 0)))
            if a >= 2:
                ops.append(Wait(("credit", (a + 1) % 2, 0), 1))
            ops.append(DmaStart(a, 0))            # forward mutated block
        else:
            ops.append(Wait(("send", slot, 0), 1))  # retire snd(P-1)
            ops.append(Accum(a, 0))               # consume home arrival
    ops += [Signal(left, ("bar",)), Signal(right, ("bar",)),
            Wait(("bar",), 2)]
    return ops


class AttentionBwdSim(AttentionSim):
    """The backward circulation's model: payloads are
    {(plane, block)} ∪ {("g", rank)} — the [K,V,dK,dV] head planes plus
    the set of ranks whose dK/dV contribution has been folded in.
    Invariants: the shared 1-4, plus

    5b. fold-before-forward: a forwarded payload ALWAYS contains the
        forwarding rank's own contribution (checked at DmaStart);
    5c. every device folds every block once in ring order (causal:
        the non-future blocks), mutating the slot payload;
    5d. the home arrival returns this device's OWN block carrying the
        contribution of EVERY rank (causal: every rank >= the block
        id) — the accumulators really made the full cycle."""

    def __init__(self, P: int, hq: int = 1, hkv: int = 1,
                 causal: bool = False):
        RingSim.__init__(self, P, 1, rot=0, allgather=True, rs=False,
                         track_data=True,
                         program_override=lambda d, p, k, **kw:
                         attention_bwd_program(d, p))
        self.folded = [[] for _ in range(P)]
        self.own_block = list(range(P))
        self.hq, self.hkv, self.causal = hq, hkv, causal
        self.planes = tuple([(pl, h) for pl in ("k", "v", "dk", "dv")
                             for h in range(hkv)])
        self.home: List[Optional[FrozenSet]] = [None] * P

    @staticmethod
    def _split(payload):
        return ({e for e in payload if e[0] != "g"},
                {e for e in payload if e[0] == "g"})

    def _block_of(self, payload, d: int, where: str) -> int:
        kv, _ = self._split(payload)
        blocks = {b for (_, b) in kv}
        planes = {p for (p, _) in kv}
        if len(blocks) != 1 or planes != set(self.planes):
            raise ProtocolViolation(
                f"dev{d} {where}: payload {sorted(kv)} is not ONE block "
                f"with all {len(self.planes)} planes")
        return next(iter(blocks))

    def _mk_dma(self, d: int, u: int, fi: int) -> Dma:
        P = self.P
        if u == 0:
            payload = frozenset({(pl, d) for pl in self.planes}
                                | {("g", d)})
        else:
            state, payload = self.comm[d][(u % 2, 0)]
            if state != "full":
                raise ProtocolViolation(
                    f"dev{d} forwarded from EMPTY slot {(u % 2, 0)} at "
                    f"send {u} (forward started before arrival consumed)")
            b = self._block_of(payload, d, f"send {u}")
            _, grads = self._split(payload)
            if (not self.causal or b <= d) and ("g", d) not in grads:
                raise ProtocolViolation(
                    f"dev{d} send {u} forwarded block {b} WITHOUT its own "
                    f"dK/dV contribution (fold-before-forward, "
                    f"invariant 5b): grads={sorted(grads)}")
        return Dma(d, u, fi, "started", payload, (u % 2, fi), (d + 1) % P,
                   dst_slot=((u + 1) % 2, fi), dst_region=None)

    def _accum(self, d: int, u: int, seg: int) -> None:
        P = self.P
        if u == 0:
            self.folded[d].append(d)  # own block (payload built at send)
            return
        slot = (u % 2, seg)
        state, payload = self.comm[d][slot]
        if state != "full":
            raise ProtocolViolation(
                f"dev{d} folded EMPTY slot {slot} at arrival {u}")
        b = self._block_of(payload, d, f"arrival {u}")
        if u == P:
            # home arrival: my block, everyone's contribution aboard
            _, grads = self._split(payload)
            if b != d:
                raise ProtocolViolation(
                    f"dev{d} home arrival carries block {b}, want {d} "
                    f"(invariant 5d)")
            want = {("g", r) for r in range(P)
                    if not self.causal or d <= r}
            if grads != want:
                raise ProtocolViolation(
                    f"dev{d} home arrival grads {sorted(grads)}, want "
                    f"{sorted(want)} (invariant 5d)")
            self.home[d] = payload
            return
        if not self.causal or b <= d:
            self.folded[d].append(b)
            # the fold MUTATES the slot: my contribution rides along
            self.comm[d][slot] = ("full", payload | {("g", d)})

    def check_final(self) -> None:
        super().check_final()  # sems drain + fold-log ring order (5c)
        for d in range(self.P):
            if self.home[d] is None:
                raise ProtocolViolation(
                    f"dev{d} never consumed its home arrival "
                    f"(invariant 5d)")


def explore_attention_bwd(P: int, max_states: int = 2_000_000,
                          hq: int = 1, hkv: int = 1,
                          causal: bool = False) -> int:
    """Exhaustive DFS over the backward circulation protocol."""
    return _explore(lambda: AttentionBwdSim(P, hq, hkv, causal),
                    max_states)


def explore_all(P: int, K: int, *, rot: int, allgather: bool,
                rs: bool = True,
                dirs: Optional[Tuple[int, ...]] = None,
                max_states: int = 2_000_000) -> int:
    """Exhaustive DFS over the collective-ring protocol (see
    ``_explore`` for the search contract)."""
    return _explore(
        lambda: RingSim(P, K, rot=rot, allgather=allgather, rs=rs,
                        track_data=False, dirs=dirs), max_states)
