"""Resilient socket links (ISSUE 10): sequenced frames, cumulative
acks + bounded retained-window replay, reconnect with backoff, and the
fault classification that keeps a dead peer on the ProcFailedError path
while a torn connection heals transparently.

The in-process worlds here run the REAL socket stack (threads over
loopback TCP) so the full wire path — hello/resume handshake, header
seq/ack fields, replay, flusher — is exercised without subprocess cost;
the subprocess acceptance leg lives in benchmarks/chaos.py
(``bench.py --chaos --links``) and its quick smoke in
tests/test_benchmarks.py.
"""

import os
import socket as _socketlib
import tempfile
import threading
import time

import numpy as np
import pytest

from mpi_tpu import ft, mpit, ops, progress
from mpi_tpu.communicator import P2PCommunicator
from mpi_tpu.errors import EpochSkewError, ProcFailedError
from mpi_tpu.resilience import LinkState, backoff_delays, retry_connect
from mpi_tpu.transport.base import TransportError
from mpi_tpu.transport.faulty import FaultyTransport
from mpi_tpu.transport.socket import SocketTransport

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_socket_world(fn, nranks, timeout=60.0, ft_detect=None):
    """fn(comm) on nranks real socket transports in threads; optionally
    FT-enabled over a shared MemoryLiveness (no rendezvous heartbeat
    files needed in-process)."""
    rdv = tempfile.mkdtemp(prefix="mpi_tpu_res_rdv_")
    results = [None] * nranks
    errors = []
    transports = [None] * nranks
    liveness = ft.MemoryLiveness(nranks) if ft_detect else None

    def runner(r):
        try:
            t = SocketTransport(r, nranks, rdv)
            transports[r] = t
            comm = P2PCommunicator(t, range(nranks))
            if ft_detect:
                ft.enable(comm, liveness=liveness,
                          detect_timeout_s=ft_detect, heartbeat_s=0.1)
            results[r] = fn(comm)
        except BaseException as e:  # noqa: BLE001
            import traceback

            errors.append((r, e, traceback.format_exc()))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    alive = [i for i, t in enumerate(threads) if t.is_alive()]
    for t in transports:
        if t is not None:
            t.close()
    if errors:
        r, e, tb = errors[0]
        raise RuntimeError(f"rank {r} failed:\n{tb}") from e
    if alive:
        raise TimeoutError(f"socket ranks did not finish: {alive}")
    return results


# -- LinkState unit layer -----------------------------------------------------


def test_backoff_delays_jittered_and_capped():
    delays = backoff_delays(base=0.01, factor=2.0, cap=0.1)
    seen = [next(delays) for _ in range(12)]
    # full jitter: every delay within the (growing, capped) ceiling
    ceiling = 0.01
    for d in seen:
        assert 0.0 <= d <= ceiling + 1e-9
        ceiling = min(0.1, ceiling * 2.0)
    assert max(seen) <= 0.1 + 1e-9


def test_linkstate_seq_ack_prune():
    ls = LinkState(2)
    for i in range(5):
        assert ls.tx_retain(1, 7, b"x" * 10) == i + 1
    assert ls.retained_bytes(1) == 50
    ls.tx_ack(1, 3)
    assert ls.retained_bytes(1) == 20
    ls.tx_ack(1, 2)  # stale (replayed header): monotone no-op
    assert ls.retained_bytes(1) == 20
    ls.tx_ack(1, 5)
    assert ls.retained_bytes(1) == 0


def test_linkstate_rx_dedup_and_gap():
    ls = LinkState(2)
    got = []
    assert ls.rx_gate(0, 1, lambda: got.append(1))
    assert ls.rx_gate(0, 2, lambda: got.append(2))
    # replay duplicates (reconnect raced a delivered frame): dropped
    assert not ls.rx_gate(0, 1, lambda: got.append("dup"))
    assert not ls.rx_gate(0, 2, lambda: got.append("dup"))
    assert got == [1, 2]
    assert ls.delivered(0) == 2
    # a GAP is a protocol violation, never silent reordering
    with pytest.raises(TransportError, match="sequence gap"):
        ls.rx_gate(0, 4, lambda: got.append("gap"))


def test_linkstate_resume_prunes_and_replays_tail():
    ls = LinkState(2)
    for i in range(4):
        ls.tx_retain(1, 7, bytes([i]) * 4)
    # peer reports it delivered up to 2: 1-2 pruned, 3-4 replayed
    pending = ls.resume(1, 2)
    assert [seq for seq, _, _ in pending] == [3, 4]
    assert ls.retained_bytes(1) == 8


def test_linkstate_generation_fences_stale_readers():
    """Review fix (PR 10): a reader thread still draining a REPLACED
    slot's old connection presents a stale stream generation — its
    piggybacked acks and frames must no-op, not poison the
    replacement's fresh streams.  (Without the fence, one stale ack of
    57 makes every real ack 1, 2, ... read as stale: the retained
    window toward the healthy rejoiner never prunes and wait_window
    declares it link-dead.)"""
    ls = LinkState(2)
    old_gen = ls.peer_gen(1)
    ls.tx_retain(1, 7, b"aaaa")
    ls.purge_peer(1)
    ls.tx_ack(1, 57, old_gen)          # stale reader's ack: dropped
    assert ls.tx_retain(1, 7, b"bb") == 1   # fresh stream from seq 1
    ls.tx_ack(1, 1, ls.peer_gen(1))    # the REAL ack must still prune
    assert ls.retained_bytes(1) == 0
    assert not ls.rx_gate(1, 57, lambda: None, old_gen)  # dropped, no gap
    assert ls.delivered(1) == 0
    assert ls.rx_gate(1, 1, lambda: None, ls.peer_gen(1))


def test_linkstate_purge_peer_clears_both_streams():
    ls = LinkState(3)
    ls.tx_retain(1, 7, b"abc")
    ls.rx_gate(1, 1, lambda: None)
    ls.purge_peer(1)
    assert ls.retained_bytes(1) == 0
    assert ls.delivered(1) == 0  # replacement's stream starts at 1
    assert ls.tx_retain(1, 7, b"d") == 1  # ... and so does ours


# -- live-world healing -------------------------------------------------------


def test_reset_between_frames_heals_with_parity():
    ses = mpit.session_create()
    ses.reset_all()

    def prog(comm):
        for i in range(12):
            if comm.rank == 0 and i in (3, 7):
                comm._t._inject_link_reset(1)
            out = comm.allreduce(np.full(512, float(comm.rank + i)),
                                 algorithm="ring")
            assert float(out[0]) == sum(r + i for r in range(comm.size))
        comm.barrier()

    run_socket_world(prog, 3)
    assert ses.read("link_reconnects") >= 2


def test_midframe_reset_replays_large_payload_bit_exact():
    ses = mpit.session_create()
    ses.reset_all()
    big = np.arange(1 << 20, dtype=np.float64)  # 8MB: multiple segments

    def prog(comm):
        inj = FaultyTransport(comm._t, link_reset_midframe_every=3)
        if comm.rank == 0:
            comm.send(big * 1.0, dest=1, tag=7)
            comm.send(big * 2.0, dest=1, tag=8)
        elif comm.rank == 1:
            a = comm.recv(source=0, tag=7)
            b = comm.recv(source=0, tag=8)
            assert np.array_equal(a, big)
            assert np.array_equal(b, big * 2.0)
        comm.barrier()
        return inj.link_midframe_resets

    res = run_socket_world(prog, 2)
    assert res[0] >= 1  # the sender actually tore a frame mid-body
    assert ses.read("link_faults_masked") >= 1
    assert ses.read("link_frames_replayed") >= 1


def test_hook_reset_storm_under_mixed_collectives():
    ses = mpit.session_create()
    ses.reset_all()

    def prog(comm):
        inj = FaultyTransport(comm._t, link_reset_every=11,
                              link_reset_midframe_every=17)
        for i in range(15):
            out = comm.allreduce(np.full(256, float(comm.rank + i)),
                                 algorithm="ring")
            assert float(out[0]) == sum(r + i for r in range(comm.size))
            got = comm.alltoall([np.full(4, float(comm.rank * 10 + d))
                                 for d in range(comm.size)])
            for s in range(comm.size):
                assert float(got[s][0]) == s * 10 + comm.rank
        comm.barrier()
        return inj.link_resets + inj.link_midframe_resets

    res = run_socket_world(prog, 3, timeout=120)
    total = sum(res)
    assert total >= 6, res
    # every sender-side reset of an established conn is healed by a
    # counted reconnect — the chaos acceptance inequality
    assert ses.read("link_reconnects") >= total
    assert ses.read("link_faults_masked") >= total


def test_reset_storm_bit_parity_with_steered_receives():
    """ISSUE 17 regression of the links-chaos acceptance: rendezvous
    steering stays BIT-exact while a reset storm tears connections —
    torn mid-steer frames are replayed onto the pool path, the fenced
    watermark keeps replays uncounted, and every recycled receive
    buffer delivers the same bytes the copy path would have.  Payloads
    are pool-class sized (>= 1MB) so both the steered and pool-staged
    receive paths run under the churn."""
    ses = mpit.session_create()
    ses.reset_all()
    n = 1 << 17  # 1MB doubles: above the recv-pool floor

    def prog(comm):
        inj = FaultyTransport(comm._t, link_reset_every=7,
                              link_reset_midframe_every=11)
        for i in range(10):
            x = np.full(n, float(comm.rank + i))
            out = comm.allreduce(x, algorithm="ring")
            want = float(sum(r + i for r in range(comm.size)))
            # bit parity, not allclose: integer-valued sums are exact
            assert np.array_equal(out, np.full(n, want)), i
        comm.barrier()
        return inj.link_resets + inj.link_midframe_resets

    res = run_socket_world(prog, 2, timeout=120)
    assert sum(res) >= 2, res
    assert ses.read("link_faults_masked") >= 2
    # the storm ran THROUGH the steering path, not around it
    assert ses.read("recv_bytes_steered") > 0


def test_accept_side_drop_retried_by_connector():
    def prog(comm):
        if comm.rank == 1:
            # the acceptor drops the next TWO incoming connections
            # after reading the hello (no ack): the connector's
            # bounded retry must win without user-visible noise
            FaultyTransport(comm._t, link_accept_drop=2)
        comm.barrier()
        if comm.rank == 0:
            comm.send(np.arange(100.0), dest=1, tag=3)
        elif comm.rank == 1:
            got = comm.recv(source=0, tag=3)
            assert np.array_equal(got, np.arange(100.0))
        comm.barrier()

    run_socket_world(prog, 2)


def test_link_stall_is_not_a_fault():
    ses = mpit.session_create()
    ses.reset_all()

    def prog(comm):
        inj = FaultyTransport(comm._t, link_stall_every=5,
                              link_stall_s=0.2)
        for i in range(8):
            out = comm.allreduce(np.full(64, 1.0), algorithm="ring")
            assert float(out[0]) == comm.size
        comm.barrier()
        return inj.link_stalls

    res = run_socket_world(prog, 2, timeout=60)
    assert sum(res) >= 1
    # a slow link heals nothing because nothing broke
    assert ses.read("link_reconnects") == 0
    assert ses.read("link_faults_masked") == 0


def test_window_bound_one_way_flood_acked_by_flusher():
    old = mpit.cvar_read("link_window_bytes")
    mpit.cvar_write("link_window_bytes", 64 << 10)
    try:
        def prog(comm):
            payload = np.ones(4096, np.float64)  # 32KB frames
            if comm.rank == 0:
                for i in range(40):  # 1.25MB >> 64KB window
                    comm.send(payload * i, dest=1, tag=4)
                # the window drains only through the peer's acks (all
                # traffic is one-way: the flusher is load-bearing)
                deadline = time.monotonic() + 10.0
                while (comm._t._link.retained_bytes(1) > 0
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert comm._t._link.retained_bytes(1) == 0
            elif comm.rank == 1:
                for i in range(40):
                    got = comm.recv(source=0, tag=4)
                    assert float(got[0]) == float(i)
            comm.barrier()

        run_socket_world(prog, 2, timeout=60)
    finally:
        mpit.cvar_write("link_window_bytes", old)


def test_dead_peer_resolves_to_proc_failed_not_masked():
    """The classification guard: a peer that is genuinely GONE (its
    transport closed, its heartbeat stopped) must surface
    ProcFailedError within the FT bound — the healing loop's suspect
    re-check aborts the retry, never a masked hang."""
    barrier = threading.Barrier(2)
    dead_evt = threading.Event()

    def prog(comm):
        if comm.rank == 1:
            barrier.wait()
            comm._t._ft_world.stop()   # heartbeat stops...
            comm._t.close()            # ... and the endpoints die
            dead_evt.set()
            time.sleep(4.0)            # stay resident; never answer
            return "corpse"
        barrier.wait()
        dead_evt.wait()
        t0 = time.monotonic()
        with pytest.raises(ProcFailedError):
            for i in range(200):
                comm.send(np.ones(2048), dest=1, tag=9)
                time.sleep(0.01)
        took = time.monotonic() - t0
        assert took < 8.0, f"diagnosis took {took:.1f}s"
        return "diagnosed"

    res = run_socket_world(prog, 2, timeout=60, ft_detect=1.0)
    assert res[0] == "diagnosed"


def test_engine_owned_recv_survives_reconnect():
    """progress=thread: a posted irecv completed by the ENGINE must be
    oblivious to a link teardown between post and send — delivery goes
    through the same sequenced reader/mailbox path."""

    def prog(comm):
        progress.enable(comm)
        try:
            if comm.rank == 1:
                req = comm.irecv(source=0, tag=6)
                comm.send(np.zeros(1), dest=0, tag=1)  # "posted" signal
                got = req.wait()
                assert np.array_equal(got, np.arange(64.0))
            else:
                comm.recv(source=1, tag=1)
                comm._t._inject_link_reset(1)  # tear the link first
                comm.send(np.arange(64.0), dest=1, tag=6)
            comm.barrier()
        finally:
            eng = getattr(comm._t, "_progress_engine", None)
            if eng is not None:
                eng.stop()

    run_socket_world(prog, 2)


def test_healing_disabled_streams_unretained_and_faults_terminal():
    """Review fix (PR 10): link_retry_timeout_s = 0 restores the
    pre-resilience contract end to end — frames stream directly
    (nothing snapshotted or retained, zero link_bytes_retained, no
    window stall verdicts), and a mid-frame fault is TERMINAL."""
    ses = mpit.session_create()
    ses.reset_all()
    old = mpit.cvar_read("link_retry_timeout_s")
    mpit.cvar_write("link_retry_timeout_s", 0)
    try:
        def prog(comm):
            if comm.rank == 0:
                for i in range(5):
                    comm.send(np.arange(4096.0) * i, dest=1, tag=3)
                assert comm._t._link.retained_bytes(1) == 0
            else:
                for i in range(5):
                    got = comm.recv(source=0, tag=3)
                    assert np.array_equal(got, np.arange(4096.0) * i)
            comm.barrier()

        run_socket_world(prog, 2)
        assert ses.read("link_bytes_retained") == 0
        assert ses.read("link_reconnects") == 0

        def prog2(comm):
            if comm.rank == 0:
                FaultyTransport(comm._t, link_reset_midframe_every=1)
                with pytest.raises(TransportError,
                                   match="healing disabled"):
                    comm.send(np.ones(4), dest=1, tag=1)
            return True

        assert run_socket_world(prog2, 2)[0]
    finally:
        mpit.cvar_write("link_retry_timeout_s", old)


# -- membership interplay (satellite: purge on slot replacement) --------------


def test_membership_invalidate_purges_per_dest_link_state():
    def prog(comm):
        t = comm._t
        if comm.rank == 0:
            for i in range(3):
                comm.send(np.ones(8) * i, dest=1, tag=2)
            # slot 1 is replaced under epoch 1: the corpse's retained
            # window, seq state, and delivery marks must all die with
            # it — a rejoiner starts at seq 1 and must never see a
            # stale replay
            from mpi_tpu import membership

            membership.survivor_transition(t, 1, [1])
            assert t._link.retained_bytes(1) == 0
            assert t._link.delivered(1) == 0
            assert t._link.tx_retain(1, 7, b"x") == 1
        return True

    # 2-rank world; rank 1 exits immediately (it only exists so rank
    # 0's sends have a live acceptor before the transition)
    def prog_wrap(comm):
        if comm.rank == 1:
            for i in range(3):
                comm.recv(source=0, tag=2)
            return True
        return prog(comm)

    assert all(run_socket_world(prog_wrap, 2))


# -- epoch grace cvar (satellite) --------------------------------------------


def test_epoch_grace_cvar_writes_both_transports():
    from mpi_tpu.transport import shm as shm_mod
    from mpi_tpu.transport import socket as socket_mod

    old = mpit.cvar_read("epoch_grace_s")
    try:
        mpit.cvar_write("epoch_grace_s", 0.123)
        assert socket_mod._EPOCH_GRACE_S == 0.123
        assert shm_mod._EPOCH_GRACE_S == 0.123
        with pytest.raises(ValueError):
            mpit.cvar_write("epoch_grace_s", -1)
    finally:
        mpit.cvar_write("epoch_grace_s", old)


@pytest.mark.parametrize("catches_up", [True, False])
def test_epoch_grace_tolerates_laggy_catchup_only(catches_up):
    """A peer one epoch AHEAD is tolerated while we catch up within the
    grace (the broadcast-transition race) — but a genuinely stale
    straggler (epoch never catches up) still raises EpochSkewError."""
    old = mpit.cvar_read("epoch_grace_s")
    mpit.cvar_write("epoch_grace_s", 1.5 if catches_up else 0.3)
    try:
        def prog(comm):
            t = comm._t
            if comm.rank == 1:
                t.epoch = 1  # already transitioned
                if catches_up:
                    comm.recv(source=0, tag=3)
                else:
                    time.sleep(2.5)  # outlive the connector's grace
                return True
            # rank 0 lags: its own bump lands mid-grace (or never)
            if catches_up:
                def bump():
                    time.sleep(0.4)
                    t.epoch = 1

                threading.Thread(target=bump, daemon=True).start()
                comm.send(np.ones(4), dest=1, tag=3)  # heals mid-grace
                return True
            with pytest.raises(EpochSkewError):
                comm.send(np.ones(4), dest=1, tag=3)
            return True

        assert all(run_socket_world(prog, 2, timeout=30))
    finally:
        mpit.cvar_write("epoch_grace_s", old)


# -- client connect retry (satellite) ----------------------------------------


def test_retry_connect_waits_out_refused_then_raises_others():
    # reserve a port that is NOT yet listening
    probe = _socketlib.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    def late_server():
        time.sleep(0.5)
        srv = _socketlib.socket()
        srv.setsockopt(_socketlib.SOL_SOCKET, _socketlib.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        conn.close()
        srv.close()

    th = threading.Thread(target=late_server, daemon=True)
    th.start()
    t0 = time.monotonic()
    sock = retry_connect(
        lambda: _socketlib.create_connection(("127.0.0.1", port),
                                             timeout=5.0))
    sock.close()
    th.join(5.0)
    assert time.monotonic() - t0 >= 0.3  # it actually waited out a refusal

    # a zero budget restores first-failure raise
    probe = _socketlib.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(ConnectionRefusedError):
        retry_connect(
            lambda: _socketlib.create_connection(("127.0.0.1", dead_port),
                                                 timeout=5.0),
            timeout_s=0.0)


def test_server_client_connects_to_delayed_server():
    """satellite: mpi_tpu.connect()/ServerClient survive the
    server-still-binding race instead of first-failure raising."""
    from mpi_tpu import serve

    probe = _socketlib.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    started = []

    def late_pool():
        time.sleep(0.6)
        srv = serve.WorldServer(pool_size=2, backend="socket",
                                port=port, detect_timeout_s=2.0,
                                heartbeat_s=0.2)
        srv.start()
        started.append(srv)

    th = threading.Thread(target=late_pool, daemon=True)
    th.start()
    try:
        client = serve.connect(("127.0.0.1", port))
        got = client.run(serve.job_allreduce, 64, nranks=2, timeout=30.0)
        assert got == 3.0
        client.close()
    finally:
        th.join(10.0)
        for srv in started:
            srv.stop()


# -- serve: leases ride healed links (satellite) ------------------------------


def test_serve_lease_rides_healed_links():
    from mpi_tpu import serve

    ses = mpit.session_create()
    ses.reset_all()
    with serve.WorldServer(pool_size=2, backend="socket",
                           detect_timeout_s=2.0, heartbeat_s=0.2,
                           world_lease_timeout_s=30.0) as srv:
        client = serve.connect(srv)
        got = client.run(serve.job_allreduce_link_chaos, 256, 2,
                         nranks=2, timeout=30.0)
        assert got == 3.0
        # and the pool is still healthy for a plain lease afterwards
        assert client.run(serve.job_allreduce, 64, nranks=2,
                          timeout=30.0) == 3.0
        client.close()


# -- refcounted buffer ownership (ISSUE 11: mpi_tpu/bufpool.py) ---------------


def test_bufref_touch_snapshots_before_mutation():
    """Copy-on-write unit contract: touch() snapshots every overlapping
    retained ref BEFORE the caller's write lands, exactly once, priced
    by the cow pvars (never payload_copies)."""
    from mpi_tpu import bufpool

    ses = mpit.session_create()
    ses.reset_all()
    arr = np.arange(64, dtype=np.float64)
    ref = bufpool.BufRef([b"head", arr])
    assert bufpool.live_refs() == 1
    want = ref.tobytes()
    assert bufpool.touch(arr) == 1        # snapshot BEFORE the write
    arr[:] = -1.0
    assert ref.tobytes() == want          # a replay stays bit-exact
    assert bufpool.touch(arr) == 0        # second write: nothing to do
    assert bufpool.live_refs() == 0       # snapshotted refs leave the index
    assert ses.read("link_cow_snapshots") == 1
    assert ses.read("link_cow_bytes") == len(want)
    assert ses.read("payload_copies") == 0  # the decoupling
    ref.release()
    assert ref.tobytes() == b""


def test_bufref_pin_defers_release_and_skips_replay():
    from mpi_tpu import bufpool

    arr = np.ones(8, np.uint8)
    ref = bufpool.BufRef([arr])
    views = ref.pin()
    assert views and views[0].nbytes == 8
    ref.release()               # acked while a replay streams the views
    assert ref.pin() is None    # later replays skip the frame (dedup'd)
    ref.unpin()                 # the last pin actually frees
    assert ref.tobytes() == b""
    assert bufpool.live_refs() == 0


def test_retention_by_reference_zero_copies_on_no_reuse():
    """The ISSUE 11 decoupling: a no-reuse send stream retains every
    frame (link_bytes_retained prices the replay bound) with ZERO
    retention-attributed copies — no cow snapshots, payload_copies
    untouched by retention."""
    ses = mpit.session_create()
    ses.reset_all()

    def prog(comm):
        out = None
        if comm.rank == 0:
            for i in range(6):
                comm.send(np.full(2048, float(i)), dest=1, tag=3)
            out = (ses.read("link_bytes_retained"),
                   ses.read("link_cow_snapshots"),
                   ses.read("link_cow_bytes"))
        else:
            for i in range(6):
                got = comm.recv(source=0, tag=3)
                assert float(got[0]) == float(i)
        comm.barrier()
        return out

    res = run_socket_world(prog, 2)
    retained, snaps, cow_bytes = res[0]
    assert retained >= 6 * 2048 * 8
    assert snaps == 0 and cow_bytes == 0


def test_buffer_reuse_under_resets_is_bit_exact_with_cow():
    """The chaos leg the ISSUE names: ONE buffer reused across sends
    (note_write per the borrow contract before each off-op mutation)
    while link_reset_every tears connections — every replay must be
    bit-exact against the content AT SEND TIME, and the cow pvars must
    show reuse actually forced snapshots."""
    from mpi_tpu import bufpool

    ses = mpit.session_create()
    ses.reset_all()
    base = np.arange(4096.0)

    def prog(comm):
        inj = None
        if comm.rank == 0:
            inj = FaultyTransport(comm._t, link_reset_every=3)
            buf = np.empty(4096, np.float64)
            for i in range(10):
                bufpool.note_write(buf)   # the documented borrow contract
                buf[:] = base + float(i)
                comm.send(buf, dest=1, tag=5)
        else:
            for i in range(10):
                got = comm.recv(source=0, tag=5)
                assert np.array_equal(got, base + float(i)), i
        comm.barrier()
        return 0 if inj is None else inj.link_resets

    res = run_socket_world(prog, 2, timeout=90)
    assert res[0] >= 2                            # resets really fired
    assert ses.read("link_reconnects") >= res[0]  # ... and healed
    assert ses.read("link_cow_snapshots") >= 1    # reuse forced copies


def test_sendmsg_batches_whole_frame_into_one_syscall():
    """Vectored sends: a multi-segment raw frame (header + meta + 6
    segment bodies = 8 wire parts) goes out in ONE sendmsg syscall —
    the fewer-syscalls-per-frame acceptance, counter-asserted via the
    link_send_syscalls pvar."""
    ses = mpit.session_create()
    ses.reset_all()
    segs = [np.arange(256.0) + i for i in range(6)]

    def prog(comm):
        out = None
        if comm.rank == 0:
            before = mpit.pvar_read("link_send_syscalls")
            for i in range(4):
                comm.send([s * (i + 1) for s in segs], dest=1, tag=2)
            out = mpit.pvar_read("link_send_syscalls") - before
        else:
            for i in range(4):
                got = comm.recv(source=0, tag=2)
                assert len(got) == 6
                assert np.array_equal(got[0], segs[0] * (i + 1))
        comm.barrier()
        return out

    res = run_socket_world(prog, 2)
    assert res[0] == 4, f"expected 1 syscall per frame, saw {res[0]}/4"


# -- idle-link keepalive (ISSUE 11 satellite: PR-10 residual (b)) -------------


def test_idle_link_keepalive_heals_before_next_send():
    """A link torn while IDLE (the remote endpoint hard-reset, as a
    SIGKILL of the peer's old incarnation would) is discovered and
    healed by the keepalive probe — link_reconnects ticks with NO send
    in flight — so the next real send finds a live link instead of
    paying the reconnect spike."""
    import struct as _struct

    ses = mpit.session_create()
    ses.reset_all()
    old = mpit.cvar_read("link_keepalive_s")
    mpit.cvar_write("link_keepalive_s", 0.25)
    sent = threading.Event()
    torn = threading.Event()
    try:
        def prog(comm):
            if comm.rank == 0:
                comm.send(np.arange(32.0), dest=1, tag=1)
                sent.set()
                assert torn.wait(10.0)
                deadline = time.monotonic() + 8.0
                while (ses.read("link_reconnects") < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                healed_first = ses.read("link_reconnects") >= 1
                comm.send(np.arange(64.0), dest=1, tag=2)
                return healed_first
            got = comm.recv(source=0, tag=1)
            assert np.array_equal(got, np.arange(32.0))
            sent.wait(10.0)
            # hard-reset the REMOTE END of rank 0's (now idle) link:
            # rank 0's cached connection is a corpse from here on
            with comm._t._conn_lock:
                conns = list(comm._t._reader_conns.get(0, []))
            for c in conns:
                try:
                    c.setsockopt(_socketlib.SOL_SOCKET,
                                 _socketlib.SO_LINGER,
                                 _struct.pack("ii", 1, 0))
                    c.close()
                except OSError:
                    pass
            torn.set()
            got2 = comm.recv(source=0, tag=2)
            assert np.array_equal(got2, np.arange(64.0))
            return True

        res = run_socket_world(prog, 2, timeout=60)
        assert res[0], "the idle probe never healed the torn link"
    finally:
        mpit.cvar_write("link_keepalive_s", old)
