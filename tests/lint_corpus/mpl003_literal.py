"""Seeded bug: typed receive count smaller than the matched send's."""


def main(comm, buf, b, dt):
    if comm.rank == 0:
        MPI_Send(buf, dest=1, datatype=dt, count=8)
    if comm.rank == 1:
        return MPI_Recv(source=0, datatype=dt, buf=b, count=4)
    return None
