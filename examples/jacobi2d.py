"""2-D Jacobi stencil on a Cartesian process grid (MPI_Cart_create +
Cart-shift halo exchanges; SURVEY.md §2 component #14 / §3.5 generalized to
the 2-D decomposition).

The global domain is tiled over a ``pr x pc`` Cartesian topology
(``dims_create`` balances the factorization).  Each iteration exchanges
one-row/one-column halos with all four neighbors — ``cart.exchange`` is a
sendrecv pair per direction on the CPU backends and exactly one
``lax.ppermute`` per direction on the SPMD backend — then sweeps the 5-point
stencil.  The hot global top edge is 1.0, every other edge 0.0 (the same
boundary problem as examples/jacobi.py, so the two decompositions can be
cross-checked).

    python -m mpi_tpu.launcher -n 4 examples/jacobi2d.py
    python examples/jacobi2d.py --backend local -n 4
    python examples/jacobi2d.py --backend tpu -n 8
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np

from mpi_tpu import CartComm, dims_create, ops


def jacobi2d_step(cart: CartComm, local):
    """One 4-direction halo exchange + 5-point sweep on this rank's tile."""
    pr, pc = cart.dims
    row, col = cart.coords  # ints on CPU backends, traced scalars on SPMD
    # dim 0 = rows of the process grid: my bottom row goes down (+1), the
    # neighbor's bottom row arrives from above; and vice versa.
    north = cart.exchange(local[-1], dim=0, disp=1, fill=0.0)
    north = jnp.where(row == 0, jnp.ones_like(north), north)  # hot top edge
    south = cart.exchange(local[0], dim=0, disp=-1, fill=0.0)
    west = cart.exchange(local[:, -1], dim=1, disp=1, fill=0.0)
    east = cart.exchange(local[:, 0], dim=1, disp=-1, fill=0.0)
    padded = jnp.concatenate([north[None], local, south[None]], axis=0)
    padded = jnp.concatenate(
        [jnp.concatenate([jnp.zeros((1,), padded.dtype), west,
                          jnp.zeros((1,), padded.dtype)])[:, None],
         padded,
         jnp.concatenate([jnp.zeros((1,), padded.dtype), east,
                          jnp.zeros((1,), padded.dtype)])[:, None]],
        axis=1)
    new = 0.25 * (padded[:-2, 1:-1] + padded[2:, 1:-1]
                  + padded[1:-1, :-2] + padded[1:-1, 2:])
    # global side walls stay fixed at 0 on boundary tiles
    keep_w = jnp.where(col == 0, 0.0, 1.0)
    keep_e = jnp.where(col == pc - 1, 0.0, 1.0)
    new = new.at[:, 0].mul(keep_w).at[:, -1].mul(keep_e)
    return new


def jacobi2d_program(comm, tile_rows: int = 8, tile_cols: int = 8,
                     iters: int = 100, dims=None):
    """Returns (final local tile, global max-residual of the last sweep)."""
    dims = dims or dims_create(comm.size, 2)
    cart = CartComm(comm, dims)
    local = jnp.zeros((tile_rows, tile_cols), jnp.float32)
    prev = local
    for _ in range(iters):
        new = jacobi2d_step(cart, local)
        local, prev = new, local
    residual = comm.allreduce(jnp.max(jnp.abs(local - prev)), op=ops.MAX)
    return local, residual


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--rows", type=int, default=8, help="rows per tile")
    ap.add_argument("--cols", type=int, default=8, help="cols per tile")
    ap.add_argument("--iters", type=int, default=100)
    args = ap.parse_args()

    out = mpi_tpu.run(jacobi2d_program, backend=args.backend, nranks=args.nranks,
                      tile_rows=args.rows, tile_cols=args.cols, iters=args.iters)
    if isinstance(out, list):
        res = float(np.asarray(out[0][1]))
    else:
        res = float(np.ravel(np.asarray(jax.device_get(out[1])))[0])
    print(f"jacobi2d: {args.iters} iters, last-sweep max residual {res:.3e}")


if __name__ == "__main__":
    main()
