"""Long-context strategies over the framework's primitives, checked against
single-device full-attention oracles (ring attention = ppermute ring;
Ulysses = all-to-all), plus the DP training demo."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from examples.data_parallel import dp_train_program
from examples.ring_attention import ring_attention, ring_attention_program
from examples.ulysses_attention import ulysses_attention, ulysses_program
from mpi_tpu.tpu import run_spmd
from mpi_tpu.transport.local import run_local


def _full_attention(q, k, v):
    scores = (q @ k.T) / np.sqrt(q.shape[-1])
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return p @ v


def test_ring_attention_matches_full_attention_tpu():
    P, s, d = 8, 16, 8
    out = run_spmd(ring_attention_program, nranks=P, seq_per_rank=s, d=d)
    o = np.asarray(out[0]).reshape(P * s, d)
    q = np.asarray(out[1]).reshape(P * s, d)
    k = np.asarray(out[2]).reshape(P * s, d)
    v = np.asarray(out[3]).reshape(P * s, d)
    np.testing.assert_allclose(o, _full_attention(q, k, v), rtol=1e-4, atol=1e-5)


def test_ring_attention_kernel_variant_matches_oracle():
    """The example's ``kernel=True`` path (the fused Pallas RDMA ring
    attention, round-4) produces the same attention as the shift-based
    loop and the dense oracle — same program, hot-path spelling."""
    import warnings

    P, s, d = 4, 16, 128
    with warnings.catch_warnings():
        # check_vma defaults on under run_spmd → loud ppermute fallback
        warnings.simplefilter("ignore", RuntimeWarning)
        out = run_spmd(ring_attention_program, nranks=P, seq_per_rank=s,
                       d=d, kernel=True)
    o = np.asarray(out[0]).reshape(P * s, d)
    q = np.asarray(out[1]).reshape(P * s, d)
    k = np.asarray(out[2]).reshape(P * s, d)
    v = np.asarray(out[3]).reshape(P * s, d)
    np.testing.assert_allclose(o, _full_attention(q, k, v), rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_matches_on_local_backend():
    P, s, d = 4, 8, 4
    res = run_local(ring_attention_program, P, kwargs={"seq_per_rank": s, "d": d})
    o = np.concatenate([np.asarray(r[0]) for r in res])
    q = np.concatenate([np.asarray(r[1]) for r in res])
    k = np.concatenate([np.asarray(r[2]) for r in res])
    v = np.concatenate([np.asarray(r[3]) for r in res])
    np.testing.assert_allclose(o, _full_attention(q, k, v), rtol=1e-4, atol=1e-5)


def test_ulysses_matches_full_attention_tpu():
    P, s, H, d = 8, 8, 8, 4
    out = run_spmd(ulysses_program, nranks=P, seq_per_rank=s, heads=H, d=d)
    o = np.asarray(out[0]).reshape(P * s, H, d)
    q = np.asarray(out[1]).reshape(P * s, H, d)
    k = np.asarray(out[2]).reshape(P * s, H, d)
    v = np.asarray(out[3]).reshape(P * s, H, d)
    for h in range(H):
        np.testing.assert_allclose(
            o[:, h], _full_attention(q[:, h], k[:, h], v[:, h]),
            rtol=1e-4, atol=1e-5)


def test_ulysses_matches_on_local_backend():
    P, s, H, d = 4, 4, 4, 4
    res = run_local(ulysses_program, P, kwargs={"seq_per_rank": s, "heads": H, "d": d})
    o = np.concatenate([np.asarray(r[0]) for r in res])
    q = np.concatenate([np.asarray(r[1]) for r in res])
    k = np.concatenate([np.asarray(r[2]) for r in res])
    v = np.concatenate([np.asarray(r[3]) for r in res])
    for h in range(H):
        np.testing.assert_allclose(
            o[:, h], _full_attention(q[:, h], k[:, h], v[:, h]),
            rtol=1e-4, atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    def prog(comm):
        q = jnp.zeros((4, 6, 2))
        return ulysses_attention(comm, q, q, q)

    with pytest.raises(Exception, match="divisible"):
        run_spmd(prog, nranks=4)


def test_dp_training_loss_decreases_and_backends_agree():
    # backends must follow the same trajectory (comm.localize keeps TPU
    # gradients local, so the explicit allreduce is the only sync point on
    # every backend); tolerance covers jit-vs-eager fp reassociation only
    tpu_out = run_spmd(dp_train_program, nranks=4, steps=3)
    tpu_loss = float(np.ravel(np.asarray(tpu_out[0]))[0])
    tpu_ck = float(np.ravel(np.asarray(tpu_out[1]))[0])

    local = run_local(dp_train_program, 4, kwargs={"steps": 3})
    local_loss = float(np.asarray(local[0][0]))
    local_ck = float(np.asarray(local[0][1]))

    np.testing.assert_allclose(local_loss, tpu_loss, rtol=1e-4)
    np.testing.assert_allclose(local_ck, tpu_ck, rtol=1e-4)

    # and training actually trains
    long = run_spmd(dp_train_program, nranks=4, steps=40)
    assert float(np.ravel(np.asarray(long[0]))[0]) < tpu_loss


def test_ring_attention_causal_both_spellings_match_oracle():
    """--causal on both the shift loop and the kernel variant equals a
    dense causal oracle (global-position masking across blocks)."""
    import warnings

    P, s, d = 4, 8, 128

    def causal_full(q, k, v):
        sc = (q @ k.T) / np.sqrt(q.shape[-1])
        n = sc.shape[0]
        sc = np.where(np.tril(np.ones((n, n), bool)), sc, -np.inf)
        p = np.exp(sc - sc.max(axis=-1, keepdims=True))
        p /= p.sum(axis=-1, keepdims=True)
        return p @ v

    for kernel in (False, True):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            out = run_spmd(ring_attention_program, nranks=P, seq_per_rank=s,
                           d=d, kernel=kernel, causal=True)
        o = np.asarray(out[0]).reshape(P * s, d)
        q = np.asarray(out[1]).reshape(P * s, d)
        k = np.asarray(out[2]).reshape(P * s, d)
        v = np.asarray(out[3]).reshape(P * s, d)
        np.testing.assert_allclose(o, causal_full(q, k, v), rtol=2e-4,
                                   atol=2e-5)


# -- long-context TRAINING through the fused ring kernels (round 5) ----------


def test_long_context_training_matches_dense_oracle():
    """One transformer-block training step over an 8-way sp-sharded
    mesh — causal ring attention on the FUSED Pallas kernels (forward
    K/V circulation AND the [K,V,dK,dV] backward ring) — produces the
    same loss and weight gradients as the identical block trained on
    one device with dense attention."""
    from jax.sharding import PartitionSpec as P

    from examples.long_context_training import (dense_train_step,
                                                init_params,
                                                sharded_train_step)
    from mpi_tpu.tpu import default_mesh

    Pn, s, d = 8, 16, 128
    S = Pn * s
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(S, d), jnp.float32)
    y = jnp.asarray(rng.randn(S, d), jnp.float32)
    params = init_params(d, 2 * d)
    mesh = default_mesh(Pn, axis_name="sp")

    jstep = jax.jit(jax.shard_map(
        sharded_train_step(Pn, interpret=True), mesh=mesh,
        in_specs=(P(), P("sp"), P("sp")), out_specs=(P(), P()),
        check_vma=False))
    loss_s, grads_s = jstep(params, x, y)
    loss_d, grads_d = jax.jit(dense_train_step())(params, x, y)

    np.testing.assert_allclose(float(loss_s), float(loss_d),
                               rtol=1e-5, atol=1e-6)
    for name in grads_d:
        np.testing.assert_allclose(
            np.asarray(grads_s[name]), np.asarray(grads_d[name]),
            rtol=5e-4, atol=5e-5, err_msg=name)


def test_long_context_training_tiled_budget():
    """The same training step with a VMEM budget that forces BOTH
    attention folds onto their tiled paths — long-context shapes —
    still matches the dense oracle's gradients."""
    from jax.sharding import PartitionSpec as P

    from examples.long_context_training import (dense_train_step,
                                                init_params,
                                                sharded_train_step)
    from mpi_tpu.tpu import default_mesh
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    Pn, s, d, limit = 4, 32, 128, 120_000
    assert attention_vmem_plan(s, d, 1, 1, jnp.float32,
                               vmem_limit_bytes=limit)[0] == "tiled"
    assert attention_vmem_plan(s, d, 1, 1, jnp.float32,
                               vmem_limit_bytes=limit,
                               for_backward=True)[0] == "tiled"
    S = Pn * s
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(S, d), jnp.float32)
    y = jnp.asarray(rng.randn(S, d), jnp.float32)
    params = init_params(d, 2 * d, seed=4)
    mesh = default_mesh(Pn, axis_name="sp")

    jstep = jax.jit(jax.shard_map(
        sharded_train_step(Pn, interpret=True,
                           vmem_limit_bytes=limit), mesh=mesh,
        in_specs=(P(), P("sp"), P("sp")), out_specs=(P(), P()),
        check_vma=False))
    loss_s, grads_s = jstep(params, x, y)
    loss_d, grads_d = jax.jit(dense_train_step())(params, x, y)
    np.testing.assert_allclose(float(loss_s), float(loss_d),
                               rtol=1e-5, atol=1e-6)
    for name in grads_d:
        np.testing.assert_allclose(
            np.asarray(grads_s[name]), np.asarray(grads_d[name]),
            rtol=5e-4, atol=5e-5, err_msg=name)
