#!/usr/bin/env python
"""OSU-style host data-plane size sweep (1KB -> 64MB) over real rank
processes — the artifact trail for the segmented collective engine.

Runs the 2-rank allreduce sweep on BOTH host transports (socket, shm)
with both hand-scheduled algorithms (ring, recursive_halving), plus the
1KB latency legs that ground the shm-vs-socket small-message inversion
diagnosis (VERDICT r5 weak #1 / next-round #7).  From the allreduce rows
it re-derives the ring/halving crossover that backs the
``allreduce_ring_crossover_bytes`` mpit cvar.

Each (transport, band) combination is ONE launcher invocation of
benchmarks/osu.py, so the measured program is exactly the shipping
benchmark, not a private reimplementation.

Usage::

    python benchmarks/host_sweep.py --label pre  --out benchmarks/results/host_sweep_pre.json
    python benchmarks/host_sweep.py --label post --out benchmarks/results/host_sweep_post.json
    python bench.py --sweep        # the post-change spelling used by CI
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# iters shrink as sizes grow: at 64MB one allreduce moves ~64MB per rank
# per call, so a handful of samples already averages thousands of ring
# segments; at 1KB the per-call noise needs the larger population.
BANDS = [
    ("1KB,4KB,16KB,64KB", 40, 5),
    ("256KB,1MB,4MB", 12, 2),
    ("16MB,64MB", 5, 1),
]
TRANSPORTS = ("socket", "shm")
ALGOS = ("ring", "recursive_halving")


def _osu_rows(backend: str, bench: str, sizes: str, algos: Optional[str],
              iters: int, warmup: int,
              env_extra: Optional[Dict[str, str]] = None) -> List[Dict]:
    from mpi_tpu.launcher import launch

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "rows.jsonl")
        argv = [os.path.join(REPO, "benchmarks", "osu.py"),
                "--bench", bench, "--backend", backend, "-n", "2",
                "--sizes", sizes, "--iters", str(iters),
                "--warmup", str(warmup), "--out", out]
        if algos:
            argv += ["--algorithms", algos]
        rc = launch(2, argv, env_extra=dict(env_extra or {}),
                    timeout=1800.0, backend=backend)
        if rc != 0:
            raise RuntimeError(f"{backend} {bench} sweep leg exited {rc}")
        with open(out) as f:
            return [json.loads(line) for line in f if line.strip()]


def allreduce_sweep() -> List[Dict]:
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        for sizes, iters, warmup in BANDS:
            rows += _osu_rows(backend, "allreduce", sizes, ",".join(ALGOS),
                              iters, warmup)
    return rows


def latency_diagnosis_legs() -> List[Dict]:
    """1KB ping-pong p50 on socket, shm(default spin), shm(spin off) and
    shm(long spin): separates the futex-wakeup cost (the spin knob removes
    it when a spare core can run the sender) from everything else."""
    legs = []
    for backend, env, label in (
        ("socket", None, "socket"),
        ("shm", None, "shm_default"),
        ("shm", {"MPI_TPU_SHM_SPIN_US": "0"}, "shm_spin_off"),
        ("shm", {"MPI_TPU_SHM_SPIN_US": "300"}, "shm_spin_300us"),
    ):
        try:
            rows = _osu_rows(backend, "latency", "1KB", None, 200, 20,
                             env_extra=env)
            for r in rows:
                r["leg"] = label
            legs += rows
        except Exception as e:  # noqa: BLE001 - a diag leg must not kill the sweep
            legs.append({"leg": label, "error": str(e)[:200]})
    return legs


def derive_crossover(rows: List[Dict]) -> Dict:
    """Per transport: the smallest size from which ring's p50 stays at or
    below recursive halving's for every larger measured size (the point
    the ``auto`` policy should switch); None if halving never loses."""
    out: Dict = {}
    for backend in TRANSPORTS:
        by_size: Dict[int, Dict[str, float]] = {}
        for r in rows:
            if r.get("backend") == backend and "p50_us" in r:
                by_size.setdefault(r["bytes"], {})[r["algorithm"]] = r["p50_us"]
        sizes = sorted(by_size)
        crossover = None
        for i, s in enumerate(sizes):
            if all("ring" in by_size[t] and "recursive_halving" in by_size[t]
                   and by_size[t]["ring"] <= by_size[t]["recursive_halving"]
                   for t in sizes[i:]):
                crossover = s
                break
        out[backend] = {"crossover_bytes": crossover,
                        "table": {str(s): by_size[s] for s in sizes}}
    return out


def run_sweep(label: str) -> Dict:
    t0 = time.time()
    rows = allreduce_sweep()
    lat = latency_diagnosis_legs()
    result = {
        "label": label,
        "nranks": 2,
        "cpus": os.cpu_count(),
        "allreduce_rows": rows,
        "latency_1kb_legs": lat,
        "crossover": derive_crossover(rows),
        "wall_s": round(time.time() - t0, 1),
    }
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--label", default="post")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    result = run_sweep(args.label)
    text = json.dumps(result, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
