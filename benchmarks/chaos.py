#!/usr/bin/env python
"""Chaos smoke: FaultyTransport drop/delay/duplicate sweep over the
collective family, asserting DIAGNOSE-DON'T-HANG.

The failure story's CI tripwire (ISSUE 3 satellite): every cell runs one
in-process local world through a fault-injecting transport and records
the outcome.  A cell may *succeed* (the fault was absorbed — e.g. a
delay, or a duplicate the matching engine never mismatched) or *fail
diagnosably* (RecvTimeout / ProcFailedError / TransportError naming the
stuck channel) — what it may never do is HANG: a run_local deadlock
timeout fails the sweep.  That is exactly the library's failure-semantics
contract (README "Failure semantics"), checked across every collective
algorithm gate rather than argued about.

Duplicate-injection cells additionally record result corruption
(``wrong_result``) honestly instead of asserting it away: a duplicated
internal frame can legally mis-fold a later collective on the same
channel — the sweep documents which schedules are sensitive, it does not
promise they aren't.

Usage::

    python benchmarks/chaos.py            # full sweep, JSON to stdout
    python benchmarks/chaos.py --quick    # tier-1 smoke (fewer cells)
    python bench.py --chaos [--quick]     # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_tpu import mpit  # noqa: E402
from mpi_tpu.errors import ProcFailedError, RevokedError  # noqa: E402
from mpi_tpu.transport.base import RecvTimeout, TransportError  # noqa: E402
from mpi_tpu.transport.faulty import FaultyTransport  # noqa: E402
from mpi_tpu.transport.local import run_local  # noqa: E402

NRANKS = 4  # pow2: exercises halving/doubling gates too
RECV_TIMEOUT_S = 2.0  # the diagnosis bound a dropped message hits
WORLD_TIMEOUT_S = 30.0  # run_local deadlock ceiling = the HANG verdict

# (name, per-rank collective call).  Payloads are small (latency-path
# schedules) — chaos probes control-flow robustness, not bandwidth.
COLLECTIVES = [
    ("bcast", lambda c: c.bcast(np.arange(8.0), root=0)),
    ("reduce", lambda c: c.reduce(np.ones(8), root=0)),
    ("allreduce-ring", lambda c: c.allreduce(np.ones(8), algorithm="ring")),
    ("allreduce-halving", lambda c: c.allreduce(
        np.ones(8), algorithm="recursive_halving")),
    ("allreduce-rabenseifner", lambda c: c.allreduce(
        np.ones(8), algorithm="rabenseifner")),
    ("allgather-ring", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="ring")),
    ("allgather-doubling", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="doubling")),
    ("alltoall", lambda c: c.alltoall([np.full(2, c.rank)] * c.size)),
    ("reduce_scatter", lambda c: c.reduce_scatter(np.ones((c.size, 4)))),
    ("scatter", lambda c: c.scatter(
        [np.full(2, d) for d in range(c.size)] if c.rank == 0 else None,
        root=0)),
    ("gather", lambda c: c.gather(np.full(2, c.rank), root=0)),
    ("scan", lambda c: c.scan(np.ones(4))),
    ("barrier", lambda c: c.barrier()),
]

FAULTS = [
    ("drop", dict(drop_every=5)),
    ("delay", dict(delay_s=0.01)),
    ("duplicate", dict(duplicate_every=5)),
]

QUICK_COLLECTIVES = ("allreduce-ring", "alltoall", "reduce_scatter",
                     "barrier")


def _oracle(name: str, comm_size: int):
    """Expected fault-free result per rank (None = don't check)."""
    if name.startswith("allreduce"):
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(8, float(comm_size)))
    if name == "scan":
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(4, float(r + 1)))
    return None


def run_cell(coll_name: str, call, fault_kw: Dict) -> Dict:
    wrapper = FaultyTransport.wrapper(**fault_kw)
    check = _oracle(coll_name, NRANKS)

    def fn(comm):
        got = call(comm)
        if check is not None and not check(comm.rank, got):
            return "wrong_result"
        return "ok"

    t0 = time.monotonic()
    try:
        res = run_local(fn, NRANKS, transport_wrapper=wrapper,
                        recv_timeout=RECV_TIMEOUT_S, timeout=WORLD_TIMEOUT_S)
        outcome = ("wrong_result" if "wrong_result" in res else "ok")
    except TimeoutError as e:
        outcome = f"HANG: {e}"  # the one unacceptable verdict
    except RuntimeError as e:
        # run_local wraps the first rank error; classify its cause
        cause = e.__cause__
        if isinstance(cause, (RecvTimeout, ProcFailedError, RevokedError,
                              TransportError)):
            outcome = f"diagnosed:{type(cause).__name__}"
        else:
            outcome = f"error:{type(cause).__name__}: {str(cause)[:120]}"
    return {"collective": coll_name, "fault": dict(fault_kw),
            "outcome": outcome,
            "wall_ms": round((time.monotonic() - t0) * 1e3, 1)}


def run_chaos(quick: bool = False) -> Dict:
    t0 = time.time()
    ses = mpit.session_create()
    ses.reset_all()
    colls = [(n, c) for n, c in COLLECTIVES
             if not quick or n in QUICK_COLLECTIVES]
    cells: List[Dict] = []
    for fault_name, fault_kw in FAULTS:
        for coll_name, call in colls:
            cell = run_cell(coll_name, call, fault_kw)
            cell["fault_name"] = fault_name
            cells.append(cell)
    hangs = [c for c in cells if c["outcome"].startswith("HANG")]
    return {
        "quick": quick,
        "nranks": NRANKS,
        "recv_timeout_s": RECV_TIMEOUT_S,
        "cells": cells,
        "hangs": hangs,
        "injected": {"dropped": ses.read("faulty_dropped"),
                     "duplicated": ses.read("faulty_duplicated")},
        "ok": not hangs,
        "wall_s": round(time.time() - t0, 1),
    }


def run_serve_chaos(quick: bool = False, backend: str = "socket") -> Dict:
    """The resident-pool chaos leg (ISSUE 7 satellite): continuous
    ``SIGKILL`` against a live world server while a client churns
    lease → allreduce → release cycles.  The contract under fire:

    * every lease either COMPLETES (with the correct result) or raises
      a NAMED error (ProcFailedError / RevokedError / the lease-timeout
      TimeoutError) — never a hang, never an anonymous crash;
    * worlds/sec never reaches zero: each observation window must
      complete at least one world (the pool self-heals faster than the
      killer drains it);
    * the pool ends the run healed (full strength, epoch advanced, and
      a final full-pool allreduce is correct).
    """
    import random
    import signal as _signal

    from mpi_tpu import serve
    from mpi_tpu.errors import EpochSkewError

    pool = 3
    duration_s = 8.0 if quick else 20.0
    kill_every_s = 2.0 if quick else 2.5
    window_s = 4.0
    rng = random.Random(1234)
    t0 = time.time()
    outcomes: List[Dict] = []
    kills = 0
    stop = [False]
    with serve.WorldServer(pool_size=pool, backend=backend,
                           detect_timeout_s=1.5, heartbeat_s=0.2,
                           world_lease_timeout_s=10.0,
                           rejoin_timeout_s=15.0) as srv:

        def killer():
            nonlocal kills
            while not stop[0]:
                time.sleep(kill_every_s)
                if stop[0]:
                    return
                with srv._lock:
                    live = [w.proc for w in srv._workers.values()
                            if w.proc is not None
                            and w.proc.poll() is None]
                if live:
                    try:
                        os.kill(rng.choice(live).pid, _signal.SIGKILL)
                        kills += 1
                    except OSError:
                        pass

        import threading

        kth = threading.Thread(target=killer, daemon=True)
        kth.start()
        client = serve.connect(srv)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            t_cycle = time.monotonic()
            try:
                lease = client.acquire(2, timeout=6.0)
                try:
                    got = lease.run(serve.job_allreduce, 256,
                                    timeout=8.0)
                    if got != 3.0:
                        outcome = f"wrong_result:{got}"
                    else:
                        outcome = "ok"
                finally:
                    lease.release()
            except (ProcFailedError, RevokedError, EpochSkewError,
                    RecvTimeout, TransportError, TimeoutError) as e:
                outcome = f"diagnosed:{type(e).__name__}"
            except Exception as e:  # noqa: BLE001 - the failing verdict
                outcome = f"error:{type(e).__name__}: {str(e)[:120]}"
            outcomes.append({"at_s": round(time.monotonic()
                                           - (deadline - duration_s), 2),
                             "outcome": outcome,
                             "wall_ms": round((time.monotonic()
                                               - t_cycle) * 1e3, 1)})
        stop[0] = True
        kth.join(timeout=5.0)
        # the pool must HEAL once the killing stops...
        heal_deadline = time.monotonic() + 30.0
        healed = False
        while time.monotonic() < heal_deadline:
            st = client.stats()
            if st["idle"] == pool and not st["healing"]:
                healed = True
                break
            time.sleep(0.3)
        # ... and serve a correct full-pool world again
        final_ok = False
        if healed:
            try:
                final_ok = client.run(serve.job_allreduce, 256,
                                      nranks=pool, timeout=15.0) == 6.0
            except Exception:  # noqa: BLE001 - recorded below
                final_ok = False
        stats = client.stats()
    completed = [o for o in outcomes if o["outcome"] == "ok"]
    bad = [o for o in outcomes
           if o["outcome"].startswith(("wrong_result", "error"))]
    # worlds/sec never zero: every window must complete >= 1 world
    nwin = max(1, int(duration_s // window_s))
    windows = [0] * nwin
    for o in completed:
        windows[min(nwin - 1, int(o["at_s"] // window_s))] += 1
    return {
        "quick": quick, "backend": backend, "pool_size": pool,
        "duration_s": duration_s, "kills": kills,
        "cycles": len(outcomes), "completed_worlds": len(completed),
        "worlds_per_s": round(len(completed) / duration_s, 2),
        "windows_completed": windows,
        # worlds churn at O(100)/s: keep the full record only for the
        # abnormal cycles (diagnosed + failed), not thousands of "ok"s
        "outcomes_abnormal": [o for o in outcomes
                              if o["outcome"] != "ok"][:200],
        "unnamed_failures": bad,
        "healed": healed, "final_allreduce_ok": final_ok,
        "final_epoch": stats["epoch"],
        "heals_completed": stats["heals_completed"],
        "oversubscribed": (pool + 2) > (os.cpu_count() or 1),
        "ok": (not bad and healed and final_ok and kills > 0
               and all(w > 0 for w in windows)),
        "wall_s": round(time.time() - t0, 1),
    }


# -- link-fault chaos (ISSUE 10): connection resets, not process death --------

_LINKS_PROG = '''
import hashlib, json, os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import ProcFailedError, RevokedError
from mpi_tpu.transport.faulty import FaultyTransport

mpit.cvar_write("fault_detect_timeout_s", 2.5)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
# the link budget stays BELOW the detect bound (the masked-hang guard);
# 0 disables healing entirely — the honest "pre" leg
mpit.cvar_write("link_retry_timeout_s",
                float(os.environ.get("MPI_TPU_LINKS_RETRY_S", "2.0")))
comm = mpi_tpu.init()   # MPI_TPU_FT=1: heartbeat files + detector
P, R = comm.size, comm.rank
iters = int(os.environ.get("MPI_TPU_LINKS_ITERS", "4"))
reset_every = int(os.environ.get("MPI_TPU_LINKS_RESET_EVERY", "0"))
mid_every = int(os.environ.get("MPI_TPU_LINKS_MIDFRAME_EVERY", "0"))
kill_rank = int(os.environ.get("MPI_TPU_LINKS_KILL_RANK", "-1"))
inj = None
if reset_every or mid_every:
    # installs connection-level hooks INTO the live world transport;
    # the communicator keeps using the inner transport directly
    inj = FaultyTransport(comm._t, link_reset_every=reset_every,
                          link_reset_midframe_every=mid_every)


def vec(n, it, r, k=1):
    # exact small-integer f64 payloads: every reduction order is exact,
    # so bit-parity with an uninjected run is a legitimate assertion
    return ((np.arange(n) * (7 * it + 3 * r + k) + r) % 1000).astype(
        np.float64)


digest = hashlib.sha256()


def note(x):
    if isinstance(x, list):
        for a in x:
            note(a)
    elif isinstance(x, np.ndarray):
        digest.update(np.ascontiguousarray(x).tobytes())
    else:
        digest.update(repr(x).encode())


detect = float(mpit.cvar_read("fault_detect_timeout_s"))
BOUND = 3.0 * detect + (25.0 if (os.cpu_count() or 1) < 4 else 8.0)
t0 = time.monotonic()
colls = 0


def run_mix():
    global colls
    for it in range(iters):
        if R == kill_rank and it == max(1, iters // 3):
            os._exit(43)   # SIGKILL-alike: no cleanup, no goodbye
        n = 257 if it % 2 else 4099
        root = it % P
        out = comm.allreduce(vec(n, it, R), algorithm="ring")
        assert np.array_equal(out, np.sum([vec(n, it, r) for r in
                                           range(P)], axis=0)), "allreduce"
        note(out)
        out = comm.allreduce(vec(n, it, R, 2), algorithm="rabenseifner")
        assert np.array_equal(out, np.sum([vec(n, it, r, 2) for r in
                                           range(P)], axis=0)), "rabenseifner"
        note(out)
        out = comm.bcast(vec(n, it, root) if R == root else None,
                         root=root)
        assert np.array_equal(out, vec(n, it, root)), "bcast"
        note(out)
        out = comm.allgather(vec(64, it, R), algorithm="ring")
        for r in range(P):
            assert np.array_equal(out[r], vec(64, it, r)), "allgather"
        note(out)
        out = comm.alltoall([vec(32, it, R, d + 3) for d in range(P)])
        for s in range(P):
            assert np.array_equal(out[s], vec(32, it, s, R + 3)), "alltoall"
        note(out)
        out = comm.reduce_scatter(
            np.stack([vec(128, it, R, b + 5) for b in range(P)]))
        assert np.array_equal(out, np.sum(
            [vec(128, it, r, R + 5) for r in range(P)], axis=0)), "rs"
        note(out)
        out = comm.scan(vec(96, it, R, 9))
        assert np.array_equal(out, np.sum(
            [vec(96, it, r, 9) for r in range(R + 1)], axis=0)), "scan"
        note(out)
        # one POOL-CLASS payload (1MB doubles, above the recv-pool
        # floor): the recycled receive buffers and the rendezvous
        # steering path both run under the reset storm, and the digest
        # proves them bit-exact (ISSUE 17)
        out = comm.allreduce(vec(1 << 17, it, R, 13), algorithm="ring")
        assert np.array_equal(out, np.sum([vec(1 << 17, it, r, 13) for r
                                           in range(P)], axis=0)), "pool"
        note(out)
        got = comm.sendrecv(vec(48, it, R, 11), dest=(R + 1) % P,
                            source=(R - 1) % P, sendtag=5, recvtag=5)
        assert np.array_equal(got, vec(48, it, (R - 1) % P, 11)), "sendrecv"
        note(got)
        comm.barrier()
        colls += 10


try:
    run_mix()
    comm.barrier()
    outcome = "ok"
except ProcFailedError as e:
    took = time.monotonic() - t0
    if kill_rank < 0:
        outcome = "failed:ProcFailedError:" + str(e)[:160]
    else:
        assert kill_rank in e.failed, (kill_rank, e.failed)
        assert took < BOUND, f"detection took {{took:.1f}}s (> {{BOUND}}s)"
        outcome = "diagnosed:ProcFailedError"
        try:
            comm.revoke()   # unblock survivors not talking to the corpse
        except Exception:
            pass
except RevokedError:
    took = time.monotonic() - t0
    if kill_rank < 0:
        outcome = "failed:RevokedError"
    else:
        assert took < BOUND, f"revoke took {{took:.1f}}s (> {{BOUND}}s)"
        outcome = "diagnosed:RevokedError"
except Exception as e:  # noqa: BLE001 - recorded, classified by driver
    outcome = f"failed:{{type(e).__name__}}:{{str(e)[:160]}}"

print(json.dumps({{
    "rank": R, "outcome": outcome, "colls": colls,
    "digest": digest.hexdigest(),
    "resets_injected": ((inj.link_resets + inj.link_midframe_resets)
                        if inj is not None else 0),
    "link_reconnects": mpit.pvar_read("link_reconnects"),
    "link_frames_replayed": mpit.pvar_read("link_frames_replayed"),
    "link_faults_masked": mpit.pvar_read("link_faults_masked"),
    "link_bytes_retained": mpit.pvar_read("link_bytes_retained"),
    "link_cow_snapshots": mpit.pvar_read("link_cow_snapshots"),
    "link_torn_frames": mpit.pvar_read("link_torn_frames"),
    "recv_pool_rendezvous": mpit.pvar_read("recv_pool_rendezvous"),
    "recv_bytes_steered": mpit.pvar_read("recv_bytes_steered"),
    "recv_pool_hits": mpit.pvar_read("recv_pool_hits"),
    "recv_pool_misses": mpit.pvar_read("recv_pool_misses"),
    "proc_failures_detected": mpit.pvar_read("proc_failures_detected"),
}}), flush=True)
sys.exit(0 if outcome.startswith(("ok", "diagnosed")) else 3)
'''


def _run_links_world(script_path: str, env_extra: Dict,
                     nranks: int = 3, timeout: float = 120.0) -> List[Dict]:
    """Spawn one 3-rank socket world of the links program; returns one
    record per rank: the parsed JSON report (or exit diagnostics)."""
    import subprocess

    from mpi_tpu import membership

    rdv = membership.new_rendezvous_dir()
    procs = []
    try:
        for r in range(nranks):
            env = dict(os.environ)
            env.update({"MPI_TPU_RANK": str(r),
                        "MPI_TPU_SIZE": str(nranks),
                        "MPI_TPU_RDV": rdv,
                        "MPI_TPU_BACKEND": "socket",
                        "MPI_TPU_FT": "1", "JAX_PLATFORMS": "cpu"})
            env.update(env_extra)
            procs.append(subprocess.Popen(
                [sys.executable, script_path], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        out = []
        for r, p in enumerate(procs):
            try:
                stdout, stderr = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                stdout, stderr = p.communicate()
                out.append({"rank": r, "outcome": "HANG",
                            "stderr": stderr[-400:]})
                continue
            rec = None
            for line in reversed(stdout.strip().splitlines()):
                try:
                    rec = json.loads(line)
                    break
                except ValueError:
                    continue
            if rec is None:
                rec = {"rank": r,
                       "outcome": f"no-report:rc={p.returncode}",
                       "stderr": stderr[-400:]}
            rec["returncode"] = p.returncode
            out.append(rec)
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        membership.cleanup_rendezvous(rdv)


def run_links_chaos(quick: bool = False, healing: bool = True,
                    trace_dir: str = None) -> Dict:
    """The link-fault chaos leg (ISSUE 10 acceptance): a 3-rank socket
    world under FT runs a mixed-collective stream with per-rank
    oracle checks while connection resets (between frames AND
    mid-frame) are injected into established links.  Contract:

    * the injected run completes with BIT-IDENTICAL per-rank digests vs
      an uninjected run of the same program — zero ``ProcFailedError``,
      zero wrong results, ``link_reconnects`` >= resets injected (every
      reset healed, none escalated to a process-death verdict);
    * under the SAME harness, a genuine mid-run death (rank 1
      ``os._exit``) still surfaces ``MPI_ERR_PROC_FAILED`` on the
      survivors within the cvar-derived detection bound — healing must
      never mask real death;
    * with ``healing=False`` (``link_retry_timeout_s = 0``, the honest
      "pre" leg) the same resets are terminal — committed as
      chaos_links_pre.json so the healed run has a measured baseline.

    ``trace_dir`` (ISSUE 13 satellite) additionally runs the INJECTED
    leg under the flight recorder (``MPI_TPU_TRACE=1``): each rank
    exports a Chrome trace into ``trace_dir``, tools/tracecat.py merges
    them into ``<trace_dir>/chaos_links_trace.json``, and the result
    records how many reset→reconnect→replay events the merged timeline
    carries — the "name the war story in minutes" artifact.
    """
    import tempfile

    t0 = time.time()
    iters = 4 if quick else 24
    reset_every = 9 if quick else 25
    mid_every = 13 if quick else 40
    with tempfile.TemporaryDirectory(prefix="mpi_tpu_links_") as td:
        script = os.path.join(td, "links.py")
        with open(script, "w") as f:
            f.write(_LINKS_PROG.format(repo=REPO))
        base_env = {"MPI_TPU_LINKS_ITERS": str(iters),
                    "MPI_TPU_LINKS_RETRY_S": "2.0" if healing else "0"}
        inject_env = dict(base_env,
                          MPI_TPU_LINKS_RESET_EVERY=str(reset_every),
                          MPI_TPU_LINKS_MIDFRAME_EVERY=str(mid_every))
        if trace_dir:
            # the injected leg ONLY: the baseline/kill worlds reuse the
            # dir across legs and would mix their rank files in
            os.makedirs(trace_dir, exist_ok=True)
            # exports are pid-suffixed, so a PREVIOUS run's rank files
            # survive here and would alias this run's (src, dst, seq)
            # triples in the merge — same garbled-offsets failure as
            # tracing the kill leg
            import glob as _glob

            for stale in _glob.glob(os.path.join(trace_dir,
                                                 "trace.r*.json")):
                os.unlink(stale)
            inject_env = dict(inject_env, MPI_TPU_TRACE="1",
                              MPI_TPU_TRACE_DIR=os.path.abspath(
                                  trace_dir))
        baseline = _run_links_world(script, base_env)
        injected = _run_links_world(script, inject_env)
        # the kill-contrast leg keeps the injection ONLY while healing
        # is on (healing must not mask real death UNDER fire); with
        # healing off the first reset is itself terminal and would
        # shadow the kill — the classification check runs clean there
        kill_env = dict(inject_env if healing else base_env,
                        MPI_TPU_LINKS_KILL_RANK="1")
        # never traced: its survivors would export into trace_dir and
        # the merge would alias two runs' (src, dst, seq) triples
        kill_env.pop("MPI_TPU_TRACE", None)
        kill_env.pop("MPI_TPU_TRACE_DIR", None)
        kill = _run_links_world(script, kill_env)

    resets = sum(r.get("resets_injected", 0) for r in injected)
    reconnects = sum(r.get("link_reconnects", 0) for r in injected)
    replayed = sum(r.get("link_frames_replayed", 0) for r in injected)
    masked = sum(r.get("link_faults_masked", 0) for r in injected)
    # ISSUE 11 retention-by-reference, observed under chaos: the
    # retained window prices real bytes with no eager snapshot, and
    # the mix's genuine reuse sites (scan folds into its just-sent
    # accumulator) fire copy-on-write — the bit-parity assertion below
    # is then LIVE proof the snapshots land BEFORE the folds, or every
    # replayed scan frame would carry post-fold bytes.  The zero-reuse
    # zero-copy contract is asserted where reuse is absent
    # (benchmarks/hotpath.py's ring leg + tests/test_resilience.py).
    retained = sum(r.get("link_bytes_retained", 0) for r in injected)
    cow_snaps = sum(r.get("link_cow_snapshots", 0) for r in injected)
    # ISSUE 17 receive-side observability: the injected leg runs the
    # recycled recv-pool and rendezvous steering UNDER the reset storm
    # (the mix's 1MB leg is pool-class), so the digest parity above is
    # also the pooled/steered receive path's bit-exactness proof
    torn = sum(r.get("link_torn_frames", 0) for r in injected)
    rendezvous = sum(r.get("recv_pool_rendezvous", 0) for r in injected)
    steered = sum(r.get("recv_bytes_steered", 0) for r in injected)
    pool_hits = sum(r.get("recv_pool_hits", 0) for r in injected)
    pool_misses = sum(r.get("recv_pool_misses", 0) for r in injected)
    parity = all(
        b.get("digest") and b.get("digest") == i.get("digest")
        for b, i in zip(baseline, injected))
    clean = (all(r.get("outcome") == "ok" for r in baseline + injected)
             and all(r.get("proc_failures_detected", 1) == 0
                     for r in injected))
    kill_ok = (
        kill[1].get("returncode") == 43
        and all(kill[r].get("outcome", "").startswith("diagnosed")
                for r in (0, 2)))
    min_resets = 6 if quick else 20
    result = {
        "quick": quick, "healing": healing, "nranks": 3,
        "collectives_per_rank": iters * 10,
        "resets_injected": resets,
        "link_reconnects": reconnects,
        "link_frames_replayed": replayed,
        "link_faults_masked": masked,
        "link_bytes_retained": retained,
        "link_cow_snapshots": cow_snaps,
        "link_torn_frames": torn,
        "recv_pool_rendezvous": rendezvous,
        "recv_bytes_steered": steered,
        "recv_pool_hits": pool_hits,
        "recv_pool_misses": pool_misses,
        "retention_by_reference": (retained > 0 if healing
                                   else retained == 0),
        "bit_parity_vs_uninjected": parity,
        "zero_proc_failed": clean,
        "kill_still_diagnosed": kill_ok,
        "baseline": baseline, "injected": injected, "kill": kill,
        "oversubscribed": 4 > (os.cpu_count() or 1),
        "ok": (parity and clean and kill_ok and resets >= min_resets
               and reconnects >= resets),
        "wall_s": round(time.time() - t0, 1),
    }
    if not healing:
        # the pre leg's contract is the CONTRAST: with healing off the
        # FIRST reset is terminal (so only ~1 ever fires) — the
        # injected run must NOT survive (else the layer under test was
        # never load-bearing) and the clean kill leg must still
        # diagnose (classification never depended on healing)
        result["ok"] = (kill_ok and resets >= 1
                        and not all(r.get("outcome") == "ok"
                                    for r in injected))
    if trace_dir:
        result["trace"] = _merge_links_trace(trace_dir)
    return result


def _merge_links_trace(trace_dir: str) -> Dict:
    """Merge the injected leg's per-rank traces (tools/tracecat.py) and
    summarize the fault-story events the merged timeline carries."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import tracecat
    finally:
        sys.path.pop(0)
    out = os.path.join(trace_dir, "chaos_links_trace.json")
    doc = tracecat.merge_paths([trace_dir], out)
    counts: Dict[str, int] = {}
    for e in doc["traceEvents"]:
        if e.get("cat") in ("link", "coll", "frame", "ft"):
            key = f"{e['cat']}.{e['name']}"
            counts[key] = counts.get(key, 0) + 1
    return {
        "merged": out,
        "ranks": len(doc["mpi_tpu"]["ranks"]),
        "events": len(doc["traceEvents"]),
        "offsets_us": doc["mpi_tpu"]["offsets_us"],
        "negative_latency_frames":
            doc["mpi_tpu"]["negative_latency_frames"],
        "link_events": {k: v for k, v in sorted(counts.items())
                        if k.startswith("link.")},
        "coll_events": sum(v for k, v in counts.items()
                           if k.startswith("coll.")),
        "frame_events": sum(v for k, v in counts.items()
                            if k.startswith("frame.")),
    }


# -- federated serve fabric chaos (ISSUE 15): server kill-storms --------------


def _spawn_fed_server(idx: int, ns: str, logdir: str, pool: int,
                      federated: bool = True,
                      max_pending: int = 32,
                      env_extra: Dict = None) -> Dict:
    """One ``launcher serve`` subprocess (real process: the storm
    SIGKILLs it).  Returns {proc, addr_file, log, id}."""
    import subprocess

    addr_file = os.path.join(logdir, f"server{idx}.addr")
    log = open(os.path.join(logdir, f"server{idx}.log"), "wb")
    argv = [sys.executable, "-m", "mpi_tpu.launcher", "serve",
            "--pool-size", str(pool), "--addr-file", addr_file,
            "--detect-timeout", "1.5", "--heartbeat", "0.2",
            "--lease-timeout", "6", "--rejoin-timeout", "15",
            "--max-pending", str(max_pending),
            "--server-id", f"srv{idx}"]
    if federated:
        argv += ["--federation", ns, "--fed-lease-timeout", "2.0",
                 "--orphan-timeout", "30"]
    proc = subprocess.Popen(argv, cwd=REPO,
                            env=dict(os.environ, JAX_PLATFORMS="cpu",
                                     **(env_extra or {})),
                            stdout=log, stderr=log)
    return {"proc": proc, "addr_file": addr_file, "log": log,
            "id": f"srv{idx}"}


_FED_NAMED = None  # lazily-built tuple of acceptable named error classes


def _fed_named_errors():
    global _FED_NAMED
    if _FED_NAMED is None:
        from mpi_tpu.errors import (EpochSkewError, NoQuorumError,
                                    ServerBusyError)
        from mpi_tpu.serve import ServerLostError

        # deliberately NO blanket OSError: ServerClient wraps raw
        # socket errors into ServerLostError, and a raw
        # ConnectionResetError leaking through is exactly the
        # anonymous-crash class this gate exists to catch
        _FED_NAMED = (ProcFailedError, RevokedError, EpochSkewError,
                      RecvTimeout, ServerLostError, TransportError,
                      TimeoutError, ServerBusyError, NoQuorumError)
    return _FED_NAMED


def _fed_client_loop(make_client, deadline: float, t0: float,
                     outcomes: List[Dict], lock, rng,
                     think_s: float) -> None:
    """One open-loop client: its OWN connect() handle, cycling
    acquire → allreduce → release until the deadline; every cycle's
    outcome recorded (ok / diagnosed:<named> / error:<unnamed>).
    Open-loop approximation: a fixed per-client think time independent
    of completions — offered load does not back off when the fabric
    degrades, which is exactly what exposes an unbounded queue."""
    from mpi_tpu import serve as _serve

    client = None
    while time.monotonic() < deadline:
        t_cycle = time.monotonic()
        try:
            if client is None:
                client = make_client()
            got = client.run(_serve.job_allreduce, 128, nranks=1,
                             timeout=6.0)
            outcome = "ok" if got == 1.0 else f"wrong_result:{got}"
        except _fed_named_errors() as e:
            outcome = f"diagnosed:{type(e).__name__}"
            try:
                if client is not None:
                    client.close()
            except Exception:  # noqa: BLE001 - teardown of a dead handle
                pass
            client = None
        except Exception as e:  # noqa: BLE001 - the failing verdict
            outcome = f"error:{type(e).__name__}: {str(e)[:120]}"
        with lock:
            outcomes.append(
                {"at_s": round(t_cycle - t0, 2), "outcome": outcome})
        time.sleep(rng.uniform(0.2, 1.0) * think_s)
    if client is not None:
        try:
            client.close()
        except Exception:  # noqa: BLE001
            pass


def run_federation_chaos(quick: bool = False, pre: bool = False) -> Dict:
    """The federated-serve kill-storm leg (ISSUE 15 acceptance):
    N >= 2 ``launcher serve --federation NS`` subprocess servers, an
    open-loop fleet of concurrent ``connect()`` clients churning
    1-rank leases, and SIGKILL fired into the server set mid-run.
    Contract (post):

    * aggregate worlds/s NEVER reaches zero — every observation window
      completes >= 1 world (clients fail over to survivors while the
      leader reassigns the dead server's pool);
    * every client-visible failure is a NAMED error — ServerLostError /
      TransportError / TimeoutError / ServerBusyError / the FT family —
      never an anonymous crash or hang;
    * the dead server's orphaned workers RE-REGISTER with a survivor
      (the survivor's stats shows the adopted pool populated, and the
      namespace roll-up converges back to every worker idle);
    * the leader-interval log shows NO authority overlap (the
      split-brain assertion), and a final cross-server lease completes
      correctly.

    ``pre=True`` is the honest baseline: ONE non-federated server under
    the same load, killed mid-run — throughput goes to zero and stays
    there (windows after the kill complete nothing), which is exactly
    the SPOF this PR removes.  Committed as
    benchmarks/results/federation_{pre,post}.json."""
    import shutil
    import signal as _signal
    import tempfile
    import threading

    from mpi_tpu import federation as _federation
    from mpi_tpu import serve as _serve

    nservers = 1 if pre else (2 if quick else 3)
    pool = 2
    nclients = 6 if quick else 24
    duration_s = 10.0 if quick else 24.0
    window_s = 2.5 if quick else 4.0
    think_s = 0.25
    # kill times (fractions of the run); always leave >= 1 survivor in
    # the post leg — the pre leg's whole point is killing the only one
    kill_at = [0.3] if (quick or pre) else [0.25, 0.55]
    rng = __import__("random").Random(4321)
    t_start = time.time()
    ns = tempfile.mkdtemp(prefix="mpi_tpu_fed_ns_")
    logdir = tempfile.mkdtemp(prefix="mpi_tpu_fed_log_")
    servers = [_spawn_fed_server(i, ns, logdir, pool,
                                 federated=not pre)
               for i in range(nservers)]
    outcomes: List[Dict] = []
    out_lock = threading.Lock()
    result: Dict = {
        "quick": quick, "leg": "pre" if pre else "post",
        "servers": nservers, "pool_per_server": pool,
        "clients": nclients, "duration_s": duration_s,
        "open_loop_think_s": think_s,
        "oversubscribed":
            (nservers * (pool + 1) + 2) > (os.cpu_count() or 1),
    }
    try:
        # wait for every server to publish its address (and, post leg,
        # its federation endpoint record)
        deadline_up = time.monotonic() + 120.0
        addrs = []
        for s in servers:
            while not os.path.exists(s["addr_file"]):
                if s["proc"].poll() is not None:
                    raise RuntimeError(
                        f"server {s['id']} died at startup")
                if time.monotonic() > deadline_up:
                    raise RuntimeError("servers never published addrs")
                time.sleep(0.1)
            with open(s["addr_file"]) as f:
                addrs.append(f.read().strip())
        if not pre:
            while len([r for r in
                       _federation.read_server_records(ns).values()
                       if _federation.record_live(r)]) < nservers:
                if time.monotonic() > deadline_up:
                    raise RuntimeError("servers never joined namespace")
                time.sleep(0.1)

        def make_client():
            if pre:
                return _federation.FederatedClient(
                    addrs=list(addrs), failover_timeout_s=4.0)
            return _federation.FederatedClient(
                namespace=ns, failover_timeout_s=4.0)

        t0 = time.monotonic()
        deadline = t0 + duration_s
        threads = [threading.Thread(
            target=_fed_client_loop,
            args=(make_client, deadline, t0, outcomes, out_lock,
                  __import__("random").Random(1000 + i), think_s),
            daemon=True) for i in range(nclients)]
        for th in threads:
            th.start()
        kills = []
        for frac in kill_at:
            wait = t0 + frac * duration_s - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            live = [s for s in servers if s["proc"].poll() is None]
            if len(live) > (0 if pre else 1):
                victim = rng.choice(live if pre else live[:-1])
                try:
                    os.kill(victim["proc"].pid, _signal.SIGKILL)
                    kills.append({"id": victim["id"],
                                  "at_s": round(time.monotonic() - t0,
                                                2)})
                except OSError:
                    pass
        for th in threads:
            th.join(timeout=max(5.0, deadline - time.monotonic() + 30.0))
        result["kills"] = kills

        completed = [o for o in outcomes if o["outcome"] == "ok"]
        bad = [o for o in outcomes
               if o["outcome"].startswith(("wrong_result", "error"))]
        nwin = max(1, int(duration_s // window_s))
        windows = [0] * nwin
        for o in completed:
            windows[min(nwin - 1, int(o["at_s"] // window_s))] += 1
        result.update({
            "cycles": len(outcomes),
            "completed_worlds": len(completed),
            "worlds_per_s": round(len(completed) / duration_s, 2),
            "windows_completed": windows,
            "diagnosed": sorted({o["outcome"] for o in outcomes
                                 if o["outcome"].startswith("diagnosed")}),
            "unnamed_failures": bad[:50],
        })

        if pre:
            # the baseline's contract is the CONTRAST: the kill drains
            # throughput to zero and it never comes back
            kill_t = kills[0]["at_s"] if kills else duration_s
            dead_windows = [w for i, w in enumerate(windows)
                            if i * window_s > kill_t + window_s]
            result.update({
                "windows_after_kill_zero":
                    bool(dead_windows) and all(w == 0
                                               for w in dead_windows),
                "ok": (not bad and bool(kills) and bool(dead_windows)
                       and all(w == 0 for w in dead_windows)),
            })
            return result

        # post: the fabric must CONVERGE — orphans re-registered with a
        # survivor, every worker idle again, and a cross-server lease
        # correct.  Poll the namespace roll-up.
        expect_workers = nservers * pool
        heal_deadline = time.monotonic() + 45.0
        healed = False
        rollup = {}
        while time.monotonic() < heal_deadline:
            rollup = _federation.federation_stats(ns)
            if rollup.get("workers") == expect_workers \
                    and rollup.get("idle") == expect_workers:
                healed = True
                break
            time.sleep(0.5)
        orphans = 0
        adopted_pools = 0
        final_ok = False
        try:
            with make_client() as client:
                st = client.stats()
                for sid, rec in (st.get("federation", {})
                                 .get("servers", {})).items():
                    if rec.get("live") and rec.get("pools", 0) > 1:
                        adopted_pools += rec["pools"] - 1
                orphans = st.get("orphans_reregistered", 0)
                final_ok = client.run(_serve.job_allreduce, 128,
                                      nranks=2, timeout=15.0) == 3.0
        except Exception as e:  # noqa: BLE001 - recorded below
            result["final_error"] = f"{type(e).__name__}: {str(e)[:200]}"
        overlap_ok, overlap_err = True, None
        try:
            _federation.assert_no_leader_overlap(ns)
        except AssertionError as e:
            overlap_ok, overlap_err = False, str(e)
        result.update({
            "healed_to_full_strength": healed,
            "rollup": {k: rollup.get(k) for k in
                       ("servers_live", "workers", "idle", "pools",
                        "leader")},
            "adopted_pools_visible": adopted_pools,
            "orphans_reregistered_on_polled_server": orphans,
            "final_cross_server_allreduce_ok": final_ok,
            "no_leader_overlap": overlap_ok,
            "leader_overlap_error": overlap_err,
            "ok": (not bad and bool(kills) and healed and final_ok
                   and overlap_ok and adopted_pools >= 1
                   and all(w > 0 for w in windows)),
        })
        return result
    finally:
        for s in servers:
            if s["proc"].poll() is None:
                s["proc"].kill()
        for s in servers:
            try:
                s["proc"].wait(10.0)
            except Exception:  # noqa: BLE001
                pass
            s["log"].close()
        result["wall_s"] = round(time.time() - t_start, 1)
        shutil.rmtree(ns, ignore_errors=True)
        shutil.rmtree(logdir, ignore_errors=True)


def _free_ports(n: int) -> List[int]:
    """Reserve n distinct loopback ports (bind-then-close: a short race
    window, acceptable for a chaos harness)."""
    import socket as _socket

    socks, ports = [], []
    for _ in range(n):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _raft_node_stats(store, raft_addrs: List[str]) -> List[Dict]:
    """Chaos-RPC stats from every reachable raft node (dead/partitioned
    nodes recorded as {"unreachable": ...})."""
    out = []
    for a in raft_addrs:
        try:
            out.append(store.chaos(a, {"op": "stats"})["stats"])
        except Exception as e:  # noqa: BLE001 - dead node is data here
            out.append({"unreachable": f"{type(e).__name__}"})
    return out


def run_federation_partition(quick: bool = False,
                             pre: bool = False) -> Dict:
    """The replicated-store partition leg (ISSUE 18 acceptance): a
    3-server federation whose namespace is a RaftStore fabric — every
    server embeds one raft node (``--federation raft:IDX@a0,a1,a2``),
    NO shared directory — under an open-loop client fleet, with a
    store-level network partition injected mid-run (chaos RPC, gated
    on MPI_TPU_STORE_CHAOS=1) that isolates the raft LEADER's node.
    Contract (post):

    * the minority-side server REFUSES new leases with the named
      :class:`NoQuorumError` (admission fence) — probed directly
      against its serve endpoint, over the wire;
    * the majority side keeps serving: aggregate worlds/s never
      reaches zero in any observation window;
    * on heal, the deposed leader's uncommitted lease intents are
      DISCARDED (``truncated_entries`` > 0 across the fabric), not
      replayed — and the leader-interval log shows no authority
      overlap;
    * a subsequent SIGKILL of the serve leader (2-of-3 raft quorum
      preserved) still heals to full strength with a correct final
      cross-server lease — partition tolerance and crash tolerance
      compose.

    ``pre=True`` is the honest baseline: the SAME fabric with the
    admission fence disabled (MPI_TPU_SERVE_STORE_FENCE=0) — the
    minority server happily grants leases it has no replicated
    authority to grant (recorded as ``stale_grant_succeeded``), which
    is exactly the split-brain hazard the fence closes.  Committed as
    benchmarks/results/federation_partition_{pre,post}.json."""
    import shutil
    import signal as _signal
    import tempfile
    import threading

    from mpi_tpu import federation as _federation
    from mpi_tpu import federation_store as _fstore
    from mpi_tpu import serve as _serve
    from mpi_tpu.errors import NoQuorumError

    nservers = 3  # raft wants an odd fabric; 2-of-3 is the quorum story
    pool = 2
    nclients = 4 if quick else 12
    duration_s = 16.0 if quick else 28.0
    window_s = 4.0
    think_s = 0.25
    part_frac, heal_frac, kill_frac = 0.15, 0.5, 0.7
    rng = __import__("random").Random(8421)
    t_start = time.time()
    logdir = tempfile.mkdtemp(prefix="mpi_tpu_fedpart_log_")
    ports = _free_ports(nservers)
    raft_addrs = [f"127.0.0.1:{p}" for p in ports]
    addrs_str = ",".join(raft_addrs)
    cspec = f"raft:{addrs_str}"  # client spec: no embedded node
    env_extra = {"MPI_TPU_STORE_CHAOS": "1"}
    if pre:
        env_extra["MPI_TPU_SERVE_STORE_FENCE"] = "0"
    servers = [_spawn_fed_server(i, f"raft:{i}@{addrs_str}", logdir,
                                 pool, env_extra=env_extra)
               for i in range(nservers)]
    outcomes: List[Dict] = []
    out_lock = threading.Lock()
    result: Dict = {
        "quick": quick, "leg": "pre" if pre else "post",
        "servers": nservers, "pool_per_server": pool,
        "clients": nclients, "duration_s": duration_s,
        "store": cspec, "fence": not pre,
        "oversubscribed":
            (nservers * (pool + 1) + 2) > (os.cpu_count() or 1),
    }
    try:
        deadline_up = time.monotonic() + 120.0
        serve_addrs = []
        for s in servers:
            while not os.path.exists(s["addr_file"]):
                if s["proc"].poll() is not None:
                    raise RuntimeError(
                        f"server {s['id']} died at startup")
                if time.monotonic() > deadline_up:
                    raise RuntimeError("servers never published addrs")
                time.sleep(0.1)
            with open(s["addr_file"]) as f:
                serve_addrs.append(f.read().strip())
        while len([r for r in
                   _federation.read_server_records(cspec).values()
                   if _federation.record_live(r)]) < nservers:
            if time.monotonic() > deadline_up:
                raise RuntimeError("servers never joined namespace")
            time.sleep(0.1)
        store = _fstore.resolve_store(cspec)

        def make_client():
            return _federation.FederatedClient(
                namespace=cspec, failover_timeout_s=4.0)

        t0 = time.monotonic()
        deadline = t0 + duration_s
        threads = [threading.Thread(
            target=_fed_client_loop,
            args=(make_client, deadline, t0, outcomes, out_lock,
                  __import__("random").Random(2000 + i), think_s),
            daemon=True) for i in range(nclients)]
        for th in threads:
            th.start()

        # -- phase 1: partition the raft leader's node away ------------
        wait = t0 + part_frac * duration_s - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        stats0 = _raft_node_stats(store, raft_addrs)
        leaders = [i for i, st in enumerate(stats0)
                   if st.get("role") == "leader"]
        lid = leaders[-1] if leaders else 0  # highest term wins ties
        pmap = {i: (1 if i == lid else 0) for i in range(nservers)}
        for a in raft_addrs:
            store.chaos(a, {"op": "partition", "map": pmap})
        result["partition"] = {"isolated_node": lid, "map": pmap,
                               "at_s": round(time.monotonic() - t0, 2)}

        # probe the minority server's serve endpoint DIRECTLY: the
        # fence must refuse with the named error over the wire (post);
        # with the fence off it grants a lease its lapsed authority
        # cannot back (pre)
        probe_deadline = t0 + heal_frac * duration_s - 1.0
        refused_named = False
        stale_grant = False
        probe_err = None
        time.sleep(2.0)  # let the isolated node notice its acks stale
        while time.monotonic() < probe_deadline \
                and not (refused_named or stale_grant):
            pc = None
            try:
                pc = _serve.connect(serve_addrs[lid], timeout=4.0)
                got = pc.run(_serve.job_allreduce, 128, nranks=1,
                             timeout=4.0)
                stale_grant = (got == 1.0)
            except NoQuorumError as e:
                refused_named = True
                probe_err = str(e)[:200]
            except Exception as e:  # noqa: BLE001 - recorded below
                probe_err = f"{type(e).__name__}: {str(e)[:120]}"
            finally:
                if pc is not None:
                    try:
                        pc.close()
                    except Exception:  # noqa: BLE001
                        pass
            time.sleep(0.3)
        result["minority_probe"] = {
            "refused_with_noquorum": refused_named,
            "stale_grant_succeeded": stale_grant,
            "last_error": probe_err,
        }
        result["stats_partitioned"] = _raft_node_stats(store, raft_addrs)

        # -- heal: the deposed leader rejoins; its unreplicated lease
        # intents must be truncated away, not replayed ----------------
        wait = t0 + heal_frac * duration_s - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        for a in raft_addrs:
            store.chaos(a, {"op": "partition", "map": None})
        result["healed_at_s"] = round(time.monotonic() - t0, 2)
        time.sleep(2.0)  # reconvergence: AppendEntries truncates

        kills = []
        if not pre:
            # -- phase 2: SIGKILL the serve leader (keeps 2-of-3 raft
            # quorum — crash tolerance on top of partition tolerance)
            wait = t0 + kill_frac * duration_s - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            rollup = _federation.federation_stats(cspec)
            victim_sid = rollup.get("leader") or "srv0"
            victim = next((s for s in servers
                           if s["id"] == victim_sid), servers[0])
            if victim["proc"].poll() is None:
                os.kill(victim["proc"].pid, _signal.SIGKILL)
                kills.append({"id": victim["id"],
                              "at_s": round(time.monotonic() - t0, 2)})
        result["kills"] = kills

        for th in threads:
            th.join(timeout=max(5.0, deadline - time.monotonic() + 30.0))

        completed = [o for o in outcomes if o["outcome"] == "ok"]
        bad = [o for o in outcomes
               if o["outcome"].startswith(("wrong_result", "error"))]
        nwin = max(1, int(duration_s // window_s))
        windows = [0] * nwin
        for o in completed:
            windows[min(nwin - 1, int(o["at_s"] // window_s))] += 1
        stats_final = _raft_node_stats(store, raft_addrs)
        truncated = sum(st.get("truncated_entries", 0)
                        for st in stats_final)
        dropped = sum(st.get("partition_dropped", 0)
                      for st in stats_final
                      + result["stats_partitioned"])
        result.update({
            "cycles": len(outcomes),
            "completed_worlds": len(completed),
            "worlds_per_s": round(len(completed) / duration_s, 2),
            "windows_completed": windows,
            "diagnosed": sorted({o["outcome"] for o in outcomes
                                 if o["outcome"].startswith("diagnosed")}),
            "unnamed_failures": bad[:50],
            "stats_final": stats_final,
            "truncated_entries": truncated,
            "partition_frames_dropped": dropped,
        })

        if pre:
            # honest baseline: with the fence off the minority server
            # granted a lease its lapsed authority cannot back — and
            # nothing anywhere said "no quorum"
            result["ok"] = (stale_grant and not refused_named
                            and not bad
                            and all(w > 0 for w in windows))
            return result

        # post: refusal named, majority never stalled, stale intents
        # discarded on heal, kill-after-heal still converges
        expect_workers = nservers * pool
        heal_deadline = time.monotonic() + 45.0
        healed = False
        rollup = {}
        while time.monotonic() < heal_deadline:
            rollup = _federation.federation_stats(cspec)
            if rollup.get("workers") == expect_workers \
                    and rollup.get("idle") == expect_workers:
                healed = True
                break
            time.sleep(0.5)
        final_ok = False
        try:
            with make_client() as client:
                final_ok = client.run(_serve.job_allreduce, 128,
                                      nranks=2, timeout=15.0) == 3.0
        except Exception as e:  # noqa: BLE001 - recorded below
            result["final_error"] = f"{type(e).__name__}: {str(e)[:200]}"
        overlap_ok, overlap_err = True, None
        try:
            _federation.assert_no_leader_overlap(cspec)
        except AssertionError as e:
            overlap_ok, overlap_err = False, str(e)
        result.update({
            "healed_to_full_strength": healed,
            "rollup": {k: rollup.get(k) for k in
                       ("servers_live", "workers", "idle", "pools",
                        "leader")},
            "final_cross_server_allreduce_ok": final_ok,
            "no_leader_overlap": overlap_ok,
            "leader_overlap_error": overlap_err,
            "ok": (refused_named and not bad and bool(kills)
                   and truncated > 0 and healed and final_ok
                   and overlap_ok
                   and all(w > 0 for w in windows)),
        })
        return result
    finally:
        for s in servers:
            if s["proc"].poll() is None:
                s["proc"].kill()
        for s in servers:
            try:
                s["proc"].wait(10.0)
            except Exception:  # noqa: BLE001
                pass
            s["log"].close()
        result["wall_s"] = round(time.time() - t_start, 1)
        shutil.rmtree(logdir, ignore_errors=True)


def run_federation_saturation(quick: bool = False) -> Dict:
    """The admission-control leg (ISSUE 15 acceptance): offered load
    beyond capacity against ONE server with a SMALL bounded admission
    queue.  Contract: queue depth never exceeds the bound, the excess
    is rejected with NAMED ServerBusyError (no unbounded latency), and
    an in-bound prioritized client keeps completing leases at its fair
    share throughout the flood."""
    import threading

    from mpi_tpu import serve as _serve
    from mpi_tpu.errors import ServerBusyError

    pool, max_pending = 2, 3
    duration_s = 5.0 if quick else 10.0
    nflood = 8
    t0_wall = time.time()
    counts = {"flood_ok": 0, "flood_busy": 0, "flood_timeout": 0,
              "good_ok": 0, "good_busy": 0}
    max_waiting_seen = [0]
    lock = threading.Lock()
    with _serve.WorldServer(pool_size=pool, backend="socket",
                            detect_timeout_s=1.5, heartbeat_s=0.2,
                            world_lease_timeout_s=8.0,
                            max_pending=max_pending) as srv:
        stop = [False]

        def flood():
            client = _serve.connect(srv)
            while not stop[0]:
                try:
                    lease = client.acquire(1, timeout=1.5)
                    try:
                        lease.run(_serve.job_sleep, 0.15, timeout=6.0)
                        with lock:
                            counts["flood_ok"] += 1
                    finally:
                        lease.release()
                except ServerBusyError:
                    with lock:
                        counts["flood_busy"] += 1
                    time.sleep(0.05)
                except TimeoutError:
                    with lock:
                        counts["flood_timeout"] += 1
                except Exception:  # noqa: BLE001 - teardown race
                    if stop[0]:
                        break
                    raise
            client.close()

        def good():
            client = _serve.connect(srv, priority=1)
            while not stop[0]:
                try:
                    lease = client.acquire(1, timeout=6.0)
                    try:
                        lease.run(_serve.job_sleep, 0.02, timeout=6.0)
                        with lock:
                            counts["good_ok"] += 1
                    finally:
                        lease.release()
                except ServerBusyError:
                    with lock:
                        counts["good_busy"] += 1
                    time.sleep(0.05)
                except Exception:  # noqa: BLE001 - teardown race
                    if stop[0]:
                        break
                    raise
                time.sleep(0.05)
            client.close()

        def sampler():
            while not stop[0]:
                st = srv.stats()
                with lock:
                    max_waiting_seen[0] = max(max_waiting_seen[0],
                                              st["waiting"])
                time.sleep(0.05)

        threads = [threading.Thread(target=flood, daemon=True)
                   for _ in range(nflood)]
        threads += [threading.Thread(target=good, daemon=True),
                    threading.Thread(target=sampler, daemon=True)]
        for th in threads:
            th.start()
        time.sleep(duration_s)
        stop[0] = True
        for th in threads:
            th.join(timeout=20.0)
        st = srv.stats()
    # the fair-share floor: the prioritized client must keep landing
    # leases while 8 flooders hammer a 2-slot pool (each good cycle is
    # ~0.1s of work; 1/s is far below its entitled share but far above
    # the zero a starved client would show)
    good_floor = max(2, int(duration_s * 1.0))
    result = {
        "quick": quick, "pool": pool, "max_pending": max_pending,
        "flood_clients": nflood, "duration_s": duration_s,
        **counts,
        "busy_rejected_total": st["busy_rejected"],
        "max_waiting_seen": max_waiting_seen[0],
        "good_client_floor": good_floor,
        "oversubscribed": (pool + 2) > (os.cpu_count() or 1),
        "ok": (st["busy_rejected"] > 0
               and max_waiting_seen[0] <= max_pending
               and counts["good_ok"] >= good_floor),
        "wall_s": round(time.time() - t0_wall, 1),
    }
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: a subset of collectives per fault")
    ap.add_argument("--serve", action="store_true",
                    help="resident-pool leg: continuous SIGKILL against "
                         "a live world server; asserts worlds/sec never "
                         "reaches zero and every lease completes or "
                         "raises a named FT error")
    ap.add_argument("--links", action="store_true",
                    help="link-fault leg: connection resets (between "
                         "frames and mid-frame) against a 3-rank socket "
                         "world; asserts bit-parity with an uninjected "
                         "run, zero ProcFailedError, and that a real "
                         "kill is still diagnosed")
    ap.add_argument("--no-healing", action="store_true",
                    help="(with --links) disable link healing "
                         "(link_retry_timeout_s=0): the honest 'pre' "
                         "leg where the same resets are terminal")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="(with --links) run the injected leg under "
                         "the flight recorder and merge the per-rank "
                         "Chrome traces into DIR/chaos_links_trace."
                         "json (tools/tracecat.py)")
    ap.add_argument("--federation", action="store_true",
                    help="federated-serve leg (ISSUE 15): SIGKILL "
                         "servers of an N-server federation under an "
                         "open-loop client fleet; asserts worlds/s "
                         "never zero, every failure named, orphaned "
                         "workers adopted by a survivor, and no "
                         "leader-authority overlap — plus the "
                         "beyond-capacity saturation/admission leg")
    ap.add_argument("--partition", action="store_true",
                    help="(with --federation) the replicated-store "
                         "partition leg (ISSUE 18): a 3-server raft "
                         "fabric (no shared dir) with a store-level "
                         "partition isolating the raft leader — the "
                         "minority server refuses leases with the "
                         "named NoQuorumError, the majority keeps "
                         "serving, heal discards the deposed leader's "
                         "uncommitted intents, and a SIGKILL after "
                         "heal still converges")
    ap.add_argument("--pre", action="store_true",
                    help="(with --federation) the honest baseline: ONE "
                         "non-federated server under the same load, "
                         "killed mid-run — throughput dies to zero "
                         "(with --partition: the same fabric with the "
                         "admission fence off — the minority grants "
                         "stale leases)")
    ap.add_argument("--backend", choices=("socket", "shm"),
                    default="socket")
    args = ap.parse_args(argv)
    if args.federation and args.partition:
        result = run_federation_partition(quick=args.quick,
                                          pre=args.pre)
    elif args.federation:
        result = run_federation_chaos(quick=args.quick, pre=args.pre)
        if not args.pre:
            result["saturation"] = run_federation_saturation(
                quick=args.quick)
            result["ok"] = result["ok"] and result["saturation"]["ok"]
    elif args.links:
        result = run_links_chaos(quick=args.quick,
                                 healing=not args.no_healing,
                                 trace_dir=args.trace_dir)
    elif args.serve:
        result = run_serve_chaos(quick=args.quick, backend=args.backend)
    else:
        result = run_chaos(quick=args.quick)
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
