"""Refcounted buffer ownership at the codec seam (ISSUE 11 tentpole).

The resilient socket link (mpi_tpu/resilience.py) must be able to
REPLAY any unacked frame after a connection reset, which is why ISSUE 10
snapshotted every frame body into the retained window — a full memcpy
per frame on the default (healing-on) hot path.  The UCX
registration-cache / NCCL buffer-pool designs show the cheaper shape:
**own buffers by reference, copy only on proven reuse**.  This module is
that ownership layer:

* :class:`BufRef` — one retained frame body as a list of buffer views
  (the header/meta ``bytes`` plus memoryviews of the caller's arrays),
  refcounted two ways: a **pin count** held while a thread is streaming
  the views onto a socket (first transmission or replay), and
  registration in the module-wide live-range index while any view is
  still mutable caller memory.
* **Copy-on-write** — :func:`touch` consults the live-range index (the
  same address-interval overlap rule the runtime verifier's
  buffer-overlap lint uses for pending nonblocking buffers) and
  SNAPSHOTS every overlapping un-snapshotted ref — one flat ``bytes``
  copy, made BEFORE the caller's write lands, so a later replay is
  bit-exact.  Every in-place mutation path inside mpi_tpu notifies:
  ``ReduceOp.combine_into`` (all fold sites), the segmented engine's
  copy-into-working-buffer sites, ``isendrecv_replace``'s completion
  refill, and the verifier's write-buffer registration.  A caller that
  mutates a sent buffer OUTSIDE any mpi_tpu operation must call
  :func:`note_write` first (the documented borrow contract), or set the
  ``link_retain_copy`` cvar to restore ISSUE 10's eager-copy semantics
  wholesale.
* The **reuse-on-send** trigger — sending a region that overlaps a
  still-retained (unacked) frame also snapshots the older frames, so
  repeated sends from one buffer never share mutable views.

Pricing: ``link_bytes_retained`` keeps counting every retained body
byte (retention is still the resilience price — it pins memory and
bounds replay), but the no-reuse path now takes ZERO copies;
``link_cow_snapshots`` / ``link_cow_bytes`` price exactly the copies
that reuse forced.  ``payload_copies`` deliberately does NOT tick for
CoW: it is the codec plane's number, and CoW firing depends on ack
timing, which would make exact-copy-count tests nondeterministic.

Pinning rule: a snapshot must never race a thread that is mid-
``sendmsg`` over the same views (the wire would carry half-mutated
bytes).  ``pin()`` marks the views in use; :func:`touch` waits for the
pin count to drain before snapshotting.  ``release()`` (ack prune /
membership purge) defers freeing until the last pin drops, and a
replay that finds its ref already released simply skips the frame —
an acked frame was delivered, so the receiver dedups it anyway.

Everything here is transport-agnostic bookkeeping; transport/socket.py
does the wire work and mpi_tpu/resilience.py owns the window.
"""

from __future__ import annotations

import bisect
import threading
from typing import List, Optional, Sequence, Tuple

from . import mpit as _mpit

# One process-wide condition guards every ref's pins/parts AND the
# live-range index: CoW, pinning, and release are rare enough that
# registry-level sharding would buy nothing, and a single lock makes
# the wait-for-pins protocol trivially correct.  ``_NLIVE`` is the
# lock-free fast-path gate — the count of range-bearing live refs —
# read without the lock by touch()/active() so the common case (no
# socket retention anywhere in the process: shm/local worlds, healing
# off, everything acked) costs one int compare per fold.
#
# The index itself is a SORTED-INTERVAL structure (ISSUE 17, PR-11
# residual c): ``_starts`` holds every registered [start, end) range's
# start in sorted order with ``_ivals`` the parallel (start, end, ref)
# records, and ``_maxlen`` bounds the longest registered interval so a
# point query only scans entries whose start lies in
# [qstart - _maxlen, qend) — O(log n + hits) instead of the old flat
# O(live) sweep per fold.  ``_maxlen`` is grow-only while the index is
# non-empty (an exact running max would need a heap for nothing) and
# resets to 0 whenever the index drains, which it does every time the
# retained window is fully acked.
_cv = threading.Condition()
_live: dict = {}   # id(ref) -> ref, refs that still hold mutable ranges
_NLIVE = 0
_starts: List[int] = []                          # sorted interval starts
_ivals: List[Tuple[int, int, "BufRef"]] = []     # parallel (s, e, ref)
_maxlen = 0


def _addr_range(arr) -> Optional[Tuple[int, int]]:
    """[start, end) of an ndarray's backing bytes, or None for payloads
    with no stable buffer address (the same guard the verifier's
    buffer-overlap lint uses)."""
    try:
        start = int(arr.__array_interface__["data"][0])
        nbytes = int(arr.nbytes)
    except (AttributeError, KeyError, TypeError):
        return None
    return (start, start + nbytes)


class BufRef:
    """One retained frame body, by reference until acked or copied."""

    __slots__ = ("_iov", "_owners", "ranges", "nbytes", "_pins",
                 "snapshotted", "_released")

    def __init__(self, parts: Sequence, register: bool = True) -> None:
        iov: List[memoryview] = []
        owners = []
        ranges: List[Tuple[int, int]] = []
        nbytes = 0
        for p in parts:
            if isinstance(p, (bytes, bytearray, memoryview)):
                mv = memoryview(p)
                if mv.nbytes:
                    iov.append(mv if mv.format == "B" and mv.ndim == 1
                               else mv.cast("B"))
                    nbytes += mv.nbytes
                continue
            # an ndarray (contiguous — codec compacted it): keep the
            # OWNER alive too, which is what vetoes codec.RECV_POOL
            # recycling a pooled array that is still retained here
            if not p.nbytes:
                continue
            iov.append(memoryview(p).cast("B"))
            owners.append(p)
            r = _addr_range(p)
            if r is not None:
                ranges.append(r)
            nbytes += int(p.nbytes)
        self._iov = iov
        self._owners = tuple(owners)
        self.ranges = tuple(ranges)
        self.nbytes = nbytes
        self._pins = 0
        self.snapshotted = not self.ranges  # immutable bodies need no CoW
        self._released = False
        if register and self.ranges:
            _register(self)

    # -- streaming (transport/socket.py) -----------------------------------

    def pin(self) -> Optional[List[memoryview]]:
        """Borrow the views for one streaming pass (sendmsg/sendall);
        None when the ref was already released (frame acked mid-replay:
        safe to skip — the receiver delivered it and dedups a replay).
        Pair with :meth:`unpin`."""
        with _cv:
            if self._released:
                return None
            self._pins += 1
            return list(self._iov)

    def unpin(self) -> None:
        with _cv:
            self._pins -= 1
            if self._released and self._pins == 0:
                self._clear_locked()
            _cv.notify_all()

    # -- ownership transitions ---------------------------------------------

    def snapshot(self) -> None:
        """Eager-copy spelling (the ``link_retain_copy`` cvar and pickle
        bodies): one flat bytes, counted as retention only — policy,
        not reuse, so the CoW pvars stay a pure reuse signal."""
        with _cv:
            self._snapshot_locked(count_cow=False)

    def _snapshot_locked(self, count_cow: bool = True) -> None:
        if self.snapshotted or self._released:
            return
        while self._pins:
            # a sender is streaming these exact views: copying under a
            # concurrent sendmsg is fine, but the CALLER of touch() is
            # about to MUTATE them — it must not proceed until the
            # in-flight pass is off the buffer
            _cv.wait(0.05)
            if self.snapshotted or self._released:
                return
        blob = b"".join(bytes(mv) for mv in self._iov)
        self._iov = [memoryview(blob)]
        self._owners = ()
        self.snapshotted = True
        _unregister_locked(self)
        if count_cow:
            _mpit.count(link_cow_snapshots=1, link_cow_bytes=len(blob))

    def release(self) -> None:
        """Ack prune / membership purge / window teardown: drop the
        ranges from the index now; free the views once unpinned."""
        with _cv:
            if self._released:
                return
            self._released = True
            _unregister_locked(self)
            if self._pins == 0:
                self._clear_locked()
            _cv.notify_all()

    def _clear_locked(self) -> None:
        self._iov = []
        self._owners = ()

    def tobytes(self) -> bytes:
        """Flat body content (tests / diagnostics)."""
        with _cv:
            return b"".join(bytes(mv) for mv in self._iov)


def _register(ref: BufRef) -> None:
    global _NLIVE, _maxlen
    with _cv:
        _live[id(ref)] = ref
        for (s, e) in ref.ranges:
            i = bisect.bisect_right(_starts, s)
            _starts.insert(i, s)
            _ivals.insert(i, (s, e, ref))
            if e - s > _maxlen:
                _maxlen = e - s
        _NLIVE = len(_live)


def _unregister_locked(ref: BufRef) -> None:
    global _NLIVE, _maxlen
    if _live.pop(id(ref), None) is not None:
        for (s, e) in ref.ranges:
            i = bisect.bisect_left(_starts, s)
            while i < len(_starts) and _starts[i] == s:
                if _ivals[i][2] is ref and _ivals[i][1] == e:
                    del _starts[i]
                    del _ivals[i]
                    break
                i += 1
        if not _ivals:
            _maxlen = 0
    _NLIVE = len(_live)


def live_refs() -> int:
    """Range-bearing retained refs process-wide (test introspection)."""
    with _cv:
        return len(_live)


def touch_ranges(ranges: Sequence[Tuple[int, int]],
                 exclude: Optional[BufRef] = None) -> int:
    """Copy-on-write core: snapshot every live retained ref overlapping
    any of ``ranges`` (address intervals), BEFORE the caller's write or
    conflicting send proceeds.  Returns snapshots taken.

    Two-phase under the lock: COLLECT the overlapping refs from the
    sorted-interval index first (a snapshot mutates the index, and
    ``_snapshot_locked`` may drop the lock waiting for pins), THEN
    snapshot each — ``_snapshot_locked`` re-checks its own state so a
    concurrent ack prune or duplicate hit is benign."""
    if not _NLIVE or not ranges:
        return 0
    took = 0
    with _cv:
        hits: List[BufRef] = []
        seen: set = set()
        for (qs, qe) in ranges:
            i = bisect.bisect_left(_starts, qs - _maxlen)
            n = len(_starts)
            while i < n and _starts[i] < qe:
                s, e, ref = _ivals[i]
                if (e > qs and ref is not exclude
                        and not ref.snapshotted and id(ref) not in seen):
                    seen.add(id(ref))
                    hits.append(ref)
                i += 1
        for ref in hits:
            if not ref.snapshotted:
                ref._snapshot_locked()
                took += 1
    return took


def touch(arr) -> int:
    """Notify the ownership layer that ``arr``'s bytes are about to be
    WRITTEN in place.  Called by every internal mutation site (fold
    sites via ``ReduceOp.combine_into``, the segmented engine's
    copy-into-buffer sites, ``isendrecv_replace``'s refill, the
    verifier's write-buffer registration); snapshot-copies any retained
    unacked frame still referencing the region.  Near-free when nothing
    is retained (one int compare)."""
    if not _NLIVE:
        return 0
    r = _addr_range(arr)
    if r is None:
        return 0
    return touch_ranges((r,))


def note_write(arr) -> int:
    """Public spelling of :func:`touch` — the borrow contract's hook for
    user code that mutates a just-sent buffer outside any mpi_tpu
    operation (see README "Buffer ownership").  Returns the number of
    retained frames snapshotted."""
    return touch(arr)
