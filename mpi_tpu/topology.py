"""Cartesian process topologies — MPI_Cart_create / shift / sub [S].

SURVEY.md §2 component #14 motivates this: the Jacobi stencil's natural
decomposition is an N-D grid of ranks with halo exchanges along each
dimension.  MPI spells that MPI_Cart_create + MPI_Cart_shift + Sendrecv; the
TPU-native spelling of the same shift is ONE ``lax.ppermute`` whose pairs are
a *static* permutation of the mesh axis.  ``CartComm`` therefore reduces
every topology operation to two portable Communicator primitives —
``exchange(obj, pairs, fill)`` (static-pattern p2p) and
``split_by_rank(color_fn, key_fn)`` (host-computable split) — and works
unchanged over the socket, thread, and SPMD backends.

Rank-to-coordinate numbering is row-major (C order), matching MPI's
MPI_Cart_coords convention [S].
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

from .communicator import Communicator

Pair = Tuple[int, int]


def dims_create(nnodes: int, ndims: int) -> List[int]:
    """MPI_Dims_create [S]: factor ``nnodes`` into ``ndims`` balanced,
    non-increasing dimensions."""
    if nnodes <= 0 or ndims <= 0:
        raise ValueError("nnodes and ndims must be positive")
    dims = [1] * ndims
    n = nnodes
    # repeatedly peel the largest prime factor onto the smallest dimension
    factors: List[int] = []
    f = 2
    while f * f <= n:
        while n % f == 0:
            factors.append(f)
            n //= f
        f += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return sorted(dims, reverse=True)


class CartComm:
    """A communicator with an attached N-D Cartesian topology.

    Wraps (never mutates) an existing communicator whose size must equal
    ``prod(dims)`` — MPI_Cart_create's "allow fewer ranks" escape hatch is
    not portable to SPMD, where every device runs the program.
    """

    def __init__(self, comm: Communicator, dims: Sequence[int],
                 periods: Optional[Sequence[bool]] = None):
        dims = tuple(int(d) for d in dims)
        if any(d <= 0 for d in dims):
            raise ValueError(f"dims must be positive, got {dims}")
        if math.prod(dims) != comm.size:
            raise ValueError(
                f"prod(dims)={math.prod(dims)} must equal comm.size={comm.size}")
        periods = (tuple(bool(p) for p in periods) if periods is not None
                   else (False,) * len(dims))
        if len(periods) != len(dims):
            raise ValueError("periods must have one entry per dimension")
        self.comm = comm
        self.dims = dims
        self.periods = periods
        # row-major strides: stride[i] = prod(dims[i+1:])
        self._strides = tuple(
            math.prod(dims[i + 1:]) for i in range(len(dims)))

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        return self.comm.rank

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def ndims(self) -> int:
        return len(self.dims)

    @property
    def coords(self):
        """This rank's coordinates.  Plain ints on process backends; traced
        scalars on the SPMD backend (pure arithmetic on the traced rank)."""
        r = self.comm.rank
        return tuple((r // s) % d for s, d in zip(self._strides, self.dims))

    # -- pure coordinate math (host-side, any rank) ------------------------

    def coords_of(self, rank: int) -> Tuple[int, ...]:
        """MPI_Cart_coords [S]."""
        if not (0 <= rank < self.size):
            raise ValueError(f"rank {rank} out of range for size {self.size}")
        return tuple((rank // s) % d for s, d in zip(self._strides, self.dims))

    def rank_of(self, coords: Sequence[int]) -> Optional[int]:
        """MPI_Cart_rank [S]: periodic dimensions wrap; out-of-range
        coordinates on non-periodic dimensions return None (MPI_PROC_NULL)."""
        if len(coords) != self.ndims:
            raise ValueError(f"need {self.ndims} coordinates, got {len(coords)}")
        rank = 0
        for c, d, p, s in zip(coords, self.dims, self.periods, self._strides):
            c = int(c)
            if p:
                c %= d
            elif not (0 <= c < d):
                return None
            rank += c * s
        return rank

    def shift(self, dim: int, disp: int = 1) -> Tuple[Optional[int], Optional[int]]:
        """MPI_Cart_shift [S]: (source, dest) ranks for a displacement along
        ``dim`` — the ranks this rank receives-from / sends-to.  None is
        MPI_PROC_NULL.  Needs a concrete integer rank, so on the SPMD backend
        (traced rank) use ``exchange`` / ``shift_perm`` instead."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        r = self.comm.rank
        if not isinstance(r, int):
            raise TypeError(
                "CartComm.shift needs a concrete rank; inside an SPMD trace "
                "the rank is traced — use cart.exchange(obj, dim, disp) "
                "(the whole-mesh halo exchange) instead")
        me = list(self.coords_of(r))
        me[dim] += disp
        dest = self.rank_of(me)
        me = list(self.coords_of(r))
        me[dim] -= disp
        src = self.rank_of(me)
        return src, dest

    def shift_perm(self, dim: int, disp: int = 1) -> List[Pair]:
        """The full static (src, dst) permutation of a shift along ``dim`` —
        exactly the pairs of the one ``lax.ppermute`` the exchange lowers to."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        pairs: List[Pair] = []
        for r in range(self.size):
            c = list(self.coords_of(r))
            c[dim] += disp
            dst = self.rank_of(c)
            if dst is not None:
                pairs.append((r, dst))
        return pairs

    # -- communication -----------------------------------------------------

    def exchange(self, obj: Any, dim: int, disp: int = 1, fill: Any = None) -> Any:
        """Halo exchange along one dimension: every rank sends ``obj`` to its
        ``+disp`` neighbor and returns the payload from its ``-disp``
        neighbor; boundary holes (non-periodic) are ``fill``."""
        return self.comm.exchange(obj, self.shift_perm(dim, disp), fill=fill)

    def sendrecv_shift(self, obj: Any, dim: int, disp: int = 1,
                       fill: Any = None) -> Any:
        """Alias of :meth:`exchange` under its MPI name (Cart_shift +
        Sendrecv fused)."""
        return self.exchange(obj, dim, disp, fill)

    # -- neighborhood collectives [S: MPI-3 MPI_Neighbor_*] ----------------

    def neighbors_of(self, rank: int) -> List[Optional[int]]:
        """Neighbor ranks of ``rank`` in MPI's Cartesian neighbor order:
        for each dimension, the −1 neighbor then the +1 neighbor
        (None = MPI_PROC_NULL at a non-periodic boundary)."""
        out: List[Optional[int]] = []
        for dim in range(self.ndims):
            for disp in (-1, +1):
                c = list(self.coords_of(rank))
                c[dim] += disp
                out.append(self.rank_of(c))
        return out

    def neighbor_allgather(self, obj: Any, fill: Any = None) -> List[Any]:
        """MPI_Neighbor_allgather [S]: every rank contributes ``obj``; each
        rank returns ``[from −dim0, from +dim0, from −dim1, ...]`` — one
        entry per neighbor (``fill`` at non-periodic boundaries).  Lowers to
        2·ndims ppermutes on the SPMD backend."""
        out: List[Any] = []
        for dim in range(self.ndims):
            # receive from the −dim neighbor = everyone ships one hop +dim
            out.append(self.exchange(obj, dim, +1, fill=fill))
            out.append(self.exchange(obj, dim, -1, fill=fill))
        return out

    def neighbor_alltoall(self, objs: Sequence[Any], fill: Any = None) -> List[Any]:
        """MPI_Neighbor_alltoall [S]: ``objs`` holds one distinct payload per
        neighbor in neighbor order (−dim0, +dim0, −dim1, ...); returns the
        payloads received from each neighbor, same order.  The item you
        address to your +dim neighbor arrives there as its −dim item."""
        if len(objs) != 2 * self.ndims:
            raise ValueError(
                f"need one payload per neighbor (2·ndims = {2 * self.ndims}), "
                f"got {len(objs)}")
        out: List[Any] = []
        for dim in range(self.ndims):
            # my item for the +dim neighbor rides the +1 shift; what lands
            # here on that shift is the −dim neighbor's +dim item
            out.append(self.exchange(objs[2 * dim + 1], dim, +1, fill=fill))
            out.append(self.exchange(objs[2 * dim], dim, -1, fill=fill))
        return out

    # -- topology management ----------------------------------------------

    def sub(self, remain_dims: Sequence[bool]) -> "CartComm":
        """MPI_Cart_sub [S]: drop the dimensions where ``remain_dims`` is
        False; ranks sharing the dropped coordinates form each new
        communicator, which keeps the remaining dimensions' topology."""
        remain = tuple(bool(k) for k in remain_dims)
        if len(remain) != self.ndims:
            raise ValueError(f"need {self.ndims} remain flags, got {len(remain)}")
        kept = [i for i, k in enumerate(remain) if k]
        dropped = [i for i, k in enumerate(remain) if not k]

        def color(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in dropped:
                out = out * self.dims[i] + c[i]
            return out

        def key(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in kept:
                out = out * self.dims[i] + c[i]
            return out

        sub = self.comm.split_by_rank(color, key)
        return CartComm(sub,
                        [self.dims[i] for i in kept] or [1],
                        [self.periods[i] for i in kept] or [False])

    def dup(self) -> "CartComm":
        return CartComm(self.comm.dup(), self.dims, self.periods)


def cart_create(comm: Communicator, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None) -> CartComm:
    """MPI_Cart_create [S] (reorder is meaningless here: ranks are mesh
    positions already)."""
    return CartComm(comm, dims, periods)
