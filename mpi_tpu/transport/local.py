"""In-process transport: rank = thread, channel = shared mailbox.

Not present in the reference (SURVEY.md §2 lists socket/pickle as its only
transport [B]); added here because a thread transport makes every semantic
test of the Communicator layer run in milliseconds on one host, and because
it is the substrate for fault injection and the comm-op recorder (both enter
via ``run_local``'s ``transport_wrapper`` hook).  Message semantics are kept honest:
payloads are deep-copied by default so ranks cannot share mutable state
through a 'message' the way threads otherwise could.
"""

from __future__ import annotations

import copy
import sys
import threading
import traceback
from typing import Any, Callable, List, Optional, Sequence

from .base import Mailbox, Transport


class LocalWorld:
    """Shared state for one in-process world of ``size`` ranks."""

    def __init__(self, size: int, copy_payloads: bool = True) -> None:
        self.size = size
        self.copy_payloads = copy_payloads
        self.mailboxes = [Mailbox() for _ in range(size)]


class LocalTransport(Transport):
    # Tuned-dispatch table key (mpi_tpu/tuning): lets tests pin a
    # "local" table row against in-process worlds; tools/tune.py only
    # sweeps the real host transports.
    tuning_transport = "local"

    def __init__(self, world: LocalWorld, rank: int) -> None:
        super().__init__(rank, world.size)
        self._world = world
        self.mailbox = world.mailboxes[rank]
        self.aliases_payloads = not world.copy_payloads

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        if not (0 <= dest < self.world_size):
            raise ValueError(f"dest {dest} out of range for world size {self.world_size}")
        if self._world.copy_payloads:
            payload = copy.deepcopy(payload)
        vc = self.verify_clock
        stamp = vc.tick_send() if vc is not None else None
        self._world.mailboxes[dest].deliver(self.world_rank, ctx, tag,
                                            payload, stamp)

    def close(self) -> None:
        self.mailbox.close()


KILLED = object()  # result-slot sentinel: this rank died by injection


def run_local(
    fn: Callable,
    nranks: int,
    args: Sequence = (),
    kwargs: Optional[dict] = None,
    timeout: float = 120.0,
    copy_payloads: bool = True,
    transport_wrapper: Optional[Callable[[Transport], Transport]] = None,
    recv_timeout: Optional[float] = None,
    fault_tolerance: bool = False,
    verify: bool = False,
    progress: Optional[str] = None,
    tuning_table: Optional[str] = None,
    trace: bool = False,
) -> List[Any]:
    """Run ``fn(comm, *args, **kwargs)`` on ``nranks`` in-process ranks;
    return the per-rank results as a list indexed by rank.

    ``transport_wrapper`` lets tests interpose (fault injection, tracing) at
    the plugin boundary without touching the Communicator.

    ``fault_tolerance=True`` enables the ULFM layer (mpi_tpu/ft.py) on
    every rank over one shared in-memory liveness table: a rank killed by
    FaultyTransport injection (KilledRankError) records :data:`KILLED` in
    its result slot and — unlike a real error — does NOT close the other
    mailboxes, so survivors exercise detection/revoke/shrink exactly as
    they would against a dead process.  A rank whose ``fn`` returns stops
    heartbeating, so long-running survivors eventually see it as failed —
    keep the detection timeout above the straggler spread.

    ``verify=True`` enables the runtime correctness verifier
    (mpi_tpu/verify) on every rank over one shared in-memory pending-op
    board: deadlocks raise DeadlockError instead of hanging, divergent
    collectives raise CollectiveMismatchError, and the request/buffer
    lints land in ``mpi_tpu.verify.take_report()`` + ``verify_*`` pvars.
    A rank whose ``fn`` returns publishes 'exited', so a peer blocked on
    it is diagnosed rather than stuck until the run_local timeout.

    ``progress="thread"`` starts one async progress engine per rank
    (mpi_tpu/progress.py): posted irecvs complete in the background and
    pure-polling drain loops join deadlock detection.  ``None`` defers
    to the MPI_TPU_PROGRESS environment variable / ``progress`` cvar;
    ``"none"`` forces it off.

    ``tuning_table`` activates a tuned-dispatch table (mpi_tpu/tuning)
    for the run: ``algorithm="auto"`` consults its measured rows before
    the built-in constants.  Process-wide state, like the cvar it sets
    — restored to the previous table when the world completes.  ``None``
    leaves the current process configuration (MPI_TPU_TUNING_TABLE /
    the ``tuning_table_path`` cvar) alone.

    ``trace=True`` enables the flight recorder (mpi_tpu/telemetry) for
    the run: one process-wide ring buffer (rank threads are told apart
    by tid), left ACTIVE afterwards so the caller can inspect/export —
    ``mpi_tpu.telemetry.recorder().dump()`` /
    ``telemetry.export_chrome(path)``; call ``telemetry.disable()``
    when done.  ``False`` changes nothing (an already-enabled recorder
    keeps recording).
    """
    from .. import progress as _progress
    from .. import tuning as _tuning
    from ..communicator import P2PCommunicator

    if trace:
        from .. import telemetry as _telemetry

        _telemetry.enable()
    progress_mode = _progress.resolve_mode(progress)
    prev_table = None
    if tuning_table is not None:
        prev_table = _tuning.table_path()
        _tuning.set_table_path(tuning_table)
    kwargs = kwargs or {}
    world = LocalWorld(nranks, copy_payloads=copy_payloads)
    results: List[Any] = [None] * nranks
    errors: List[tuple] = []
    lock = threading.Lock()
    liveness = None
    if fault_tolerance:
        from .. import ft as _ft

        liveness = _ft.MemoryLiveness(nranks)
    board = None
    if verify:
        from ..verify import MemoryBoard

        board = MemoryBoard(nranks)

    def runner(r: int) -> None:
        ft_state = None
        v_state = None
        engine = None
        try:
            t: Transport = LocalTransport(world, r)
            if transport_wrapper is not None:
                t = transport_wrapper(t)
            comm = P2PCommunicator(t, range(nranks),
                                   recv_timeout=recv_timeout)
            comm._mark_generation()  # the world comm: shrink bumps epoch
            if liveness is not None:
                from .. import ft as _ft

                ft_state = _ft.enable(comm, liveness=liveness)._ft
            if board is not None:
                from .. import verify as _verify

                v_state = _verify.enable(comm, board=board)._verify
            if progress_mode == "thread":
                engine = _progress.enable(comm)._progress
            results[r] = fn(comm, *args, **kwargs)
            if v_state is not None:
                v_state.world.mark_exited()
        except BaseException as e:  # noqa: BLE001 - propagated to caller below
            from .faulty import KilledRankError

            if isinstance(e, KilledRankError):
                # simulated crash-stop: the rank is gone but the WORLD
                # lives on — survivors must detect/recover on their own
                results[r] = KILLED
                return
            with lock:
                errors.append((r, e, traceback.format_exc()))
            # unblock peers waiting on this rank
            for mb in world.mailboxes:
                mb.close()
        finally:
            if ft_state is not None:
                ft_state.world.stop()
            if engine is not None:
                engine.stop()

    threads = [
        threading.Thread(target=runner, args=(r,), name=f"mpi-tpu-rank-{r}", daemon=True)
        for r in range(nranks)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
    finally:
        if prev_table is not None:
            try:
                _tuning.set_table_path(prev_table or None)
            except _tuning.TuningTableError:
                _tuning.set_table_path(None)  # prior table went away
    stuck = [t for t in threads if t.is_alive()]
    if stuck:
        # snapshot where each stuck rank is blocked before unblocking them —
        # this is the actionable part of a deadlock report
        import traceback as _tb

        frames = sys._current_frames()
        where = []
        for t in stuck:
            frame = frames.get(t.ident)
            if frame is not None:
                loc = _tb.extract_stack(frame)[-1]
                where.append(f"{t.name} at {loc.filename}:{loc.lineno} in {loc.name}")
            else:
                where.append(t.name)
        for mb in world.mailboxes:
            mb.close()
        raise TimeoutError(
            f"ranks did not finish within {timeout}s (likely deadlock): {where}"
        )
    if errors:
        r, e, tb = errors[0]
        raise RuntimeError(f"rank {r} failed:\n{tb}") from e
    return results
