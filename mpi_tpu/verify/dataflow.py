"""Dataflow engine under mpilint v2 (ISSUE 20 tentpole).

The v1 linter (PR 5) pattern-matched literal ``if c.rank == 0:`` tests;
it was blind the moment a rank landed in a variable, a helper function,
or a loop bound.  This module is the small analysis engine the v2 rules
are grounded on:

* **Per-function walk over ``ast``** with an explicit guard stack: every
  MPI operation (:class:`Op`) records the chain of branch conditions it
  sits under, each with a snapshot of the variable environment at that
  point.  Early ``return``/``raise`` in a branch contributes the
  *negated* test to the statements after the ``if`` (the leader-pattern
  ``if c.rank != 0: return`` shape).
* **Constant / rank propagation**: assignments bind names to
  :class:`Sym` closures (expression + environment snapshot); evaluation
  (:func:`eval_expr`) substitutes a concrete ``(rank, size)`` pair and
  constant-folds, so ``r = comm.rank; if r == 0:`` or ``left = (comm.rank
  - 1) % comm.size`` resolve exactly.  Anything the folder cannot decide
  evaluates to ``None`` — callers treat that as *undecidable* and stay
  silent (the linter's findings must survive review, so unknown never
  fires a rule).
* **One-level call graph**: a call to a module-level function with a
  communicator argument splices the callee's operations into the caller
  (parameters bound to the caller's argument expressions), so
  ``def leader_only(c): if c.rank == 0: c.bcast(...)`` resolves at its
  call sites.  One level only — calls inside a spliced callee are not
  resolved further.
* **Request flow** (:func:`request_flow`): a may-analysis over the
  statement CFG tracking nonblocking requests from creation to a
  completion call.  Branch joins union the maybe-live sets, so a request
  waited on only one side of an ``if`` is still live "along some CFG
  path" (MPL005); writes to a live request's buffer surface as MPL006
  evidence.  Any escape (stored, passed, returned, appended) discharges
  the request — the analysis only flags the shapes it can prove.

The whole-tree send/recv/collective matching on top of these facts lives
in :mod:`mpi_tpu.verify.commgraph`; the rule wiring and the public
``lint_source`` API stay in :mod:`mpi_tpu.verify.lint`.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

# Evaluation depth cap: Sym chains are acyclic by construction (each
# snapshot only references older bindings) but splices and long copy
# chains can nest; past this depth we give up and return "undecidable".
_MAX_DEPTH = 32

# Names that spell the wildcards in any of the supported dialects.
_WILDCARD_NAMES = frozenset({
    "ANY_SOURCE", "MPI_ANY_SOURCE", "ANY_TAG", "MPI_ANY_TAG",
})

# Nonblocking request constructors (methods on a comm, or MPI_* call
# forms).  Persistent *_init requests are deliberately excluded from the
# request-flow rules: their lifecycle is start/wait cycles ended by
# free(), not a single wait.
NONBLOCKING_METHODS = frozenset({
    "isend", "irecv", "isendrecv", "isendrecv_replace",
    "ibarrier", "ibcast", "iallreduce", "ireduce", "igather",
    "iallgather", "iscatter", "ialltoall", "ireduce_scatter",
    "iscan", "iexscan",
})
NONBLOCKING_FUNCS = frozenset({"MPI_Isend", "MPI_Irecv"})

# Calls that complete (or otherwise account for) a request.
_COMPLETION_METHODS = frozenset({"wait", "test", "free", "cancel"})
_COMPLETION_FUNCS = frozenset({
    "MPI_Wait", "MPI_Test", "MPI_Waitall", "MPI_Waitany", "MPI_Waitsome",
    "MPI_Testall", "MPI_Testany",
})


class Sym(NamedTuple):
    """A deferred expression: AST node + the environment it closed over."""
    node: ast.AST
    env: Dict[str, "Sym"]


class Guard(NamedTuple):
    """One branch condition an operation sits under."""
    test: ast.AST
    env: Dict[str, Sym]
    polarity: bool  # True: taken when test is truthy


class Op(NamedTuple):
    """One MPI operation with its resolved context."""
    comm: str                 # canonical communicator key (source text)
    kind: str                 # 'coll' | 'send' | 'recv' | 'nb'
    name: str                 # method / function name
    line: int
    guards: Tuple[Guard, ...]
    env: Dict[str, Sym]       # environment at the call
    peer: Optional[ast.AST]   # dest (sends) / source (recvs)
    tag: Optional[ast.AST]    # None: the API default
    count: Optional[ast.AST]
    in_rank_loop: bool        # enclosing loop trip count is rank-dependent


class RankLoopColl(NamedTuple):
    """MPL008 evidence: a collective inside a rank-dependent loop."""
    comm: str
    name: str
    line: int
    loop_line: int


class RootOps(NamedTuple):
    """Operations of one analysis root (module body or uncalled function,
    with one level of callee splicing)."""
    name: str
    ops: List[Op]


class ReqIssue(NamedTuple):
    """MPL005/006 evidence from the request-flow analysis."""
    code: str       # 'MPL005' | 'MPL006'
    line: int       # report line (creation for 005, the write for 006)
    op_line: int    # request creation line
    op_name: str
    buf: Optional[str]


# The collective vocabulary (shared with lint.py via import there).
COLLECTIVES = frozenset({
    "bcast", "reduce", "allreduce", "allgather", "allgatherv", "alltoall",
    "alltoallv", "barrier", "scan", "exscan", "reduce_scatter", "scatter",
    "scatterv", "gather", "gatherv", "maxloc", "minloc",
})


# -- expression evaluation ---------------------------------------------------

def resolve_comm(expr: ast.AST, env: Dict[str, Sym],
                 depth: int = 0) -> Optional[str]:
    """Canonical communicator key for a receiver expression: follow
    name-to-name bindings (so a spliced callee's parameter resolves to
    the caller's argument), then use the source text.  Returns None for
    expressions that cannot name a communicator."""
    if depth > _MAX_DEPTH:
        return None
    if isinstance(expr, ast.Name):
        bound = env.get(expr.id)
        if bound is not None and isinstance(bound.node, (ast.Name,
                                                         ast.Attribute)):
            return resolve_comm(bound.node, bound.env, depth + 1)
        return expr.id
    if isinstance(expr, ast.Attribute):
        try:
            return ast.unparse(expr)
        except Exception:  # pragma: no cover - malformed tree
            return None
    return None


def eval_expr(node: Optional[ast.AST], env: Dict[str, Sym],
              comm: Optional[str], rank: int, size: int,
              depth: int = 0) -> Optional[Any]:
    """Constant-fold ``node`` with ``<comm>.rank`` := rank and
    ``<comm>.size`` := size (``comm=None`` treats ANY receiver's
    rank/size that way — used for rank-dependence probes).  Returns an
    int/bool, or None when undecidable."""
    if node is None or depth > _MAX_DEPTH:
        return None
    if isinstance(node, ast.Constant):
        v = node.value
        return v if isinstance(v, (int, bool)) else None
    if isinstance(node, ast.Name):
        if node.id in _WILDCARD_NAMES:
            return -1
        bound = env.get(node.id)
        if bound is None:
            return None
        return eval_expr(bound.node, bound.env, comm, rank, size, depth + 1)
    if isinstance(node, ast.Attribute):
        if node.attr in _WILDCARD_NAMES:
            return -1
        if node.attr in ("rank", "world_rank", "size", "world_size"):
            base = resolve_comm(node.value, env)
            if base is None or (comm is not None and base != comm):
                return None
            return rank if node.attr in ("rank", "world_rank") else size
        return None
    if isinstance(node, ast.UnaryOp):
        v = eval_expr(node.operand, env, comm, rank, size, depth + 1)
        if v is None:
            return None
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return +v
        if isinstance(node.op, ast.Not):
            return not v
        return None
    if isinstance(node, ast.BinOp):
        a = eval_expr(node.left, env, comm, rank, size, depth + 1)
        b = eval_expr(node.right, env, comm, rank, size, depth + 1)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Mod):
                return a % b
            if isinstance(node.op, ast.Pow) and abs(b) < 32:
                return a ** b
        except (ZeroDivisionError, ValueError):
            return None
        return None
    if isinstance(node, ast.Compare):
        left = eval_expr(node.left, env, comm, rank, size, depth + 1)
        if left is None:
            return None
        for op, rhs in zip(node.ops, node.comparators):
            right = eval_expr(rhs, env, comm, rank, size, depth + 1)
            if right is None:
                return None
            if isinstance(op, ast.Eq):
                ok = left == right
            elif isinstance(op, ast.NotEq):
                ok = left != right
            elif isinstance(op, ast.Lt):
                ok = left < right
            elif isinstance(op, ast.LtE):
                ok = left <= right
            elif isinstance(op, ast.Gt):
                ok = left > right
            elif isinstance(op, ast.GtE):
                ok = left >= right
            else:
                return None
            if not ok:
                return False
            left = right
        return True
    if isinstance(node, ast.BoolOp):
        vals = [eval_expr(v, env, comm, rank, size, depth + 1)
                for v in node.values]
        if isinstance(node.op, ast.And):
            if any(v is False for v in vals):
                return False
            if any(v is None for v in vals):
                return None
            return True
        if any(v is True for v in vals):
            return True
        if any(v is None for v in vals):
            return None
        return False
    if isinstance(node, ast.IfExp):
        t = eval_expr(node.test, env, comm, rank, size, depth + 1)
        if t is None:
            return None
        pick = node.body if t else node.orelse
        return eval_expr(pick, env, comm, rank, size, depth + 1)
    return None


def mentions_rank(node: Optional[ast.AST], env: Dict[str, Sym],
                  depth: int = 0) -> bool:
    """Syntactic rank-dependence probe: does the expression reach a
    ``.rank`` attribute, directly or through bindings?"""
    if node is None or depth > _MAX_DEPTH:
        return False
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("rank", "world_rank"):
            return True
        if isinstance(n, ast.Name):
            bound = env.get(n.id)
            if bound is not None and mentions_rank(bound.node, bound.env,
                                                   depth + 1):
                return True
    return False


def rank_dependent(node: Optional[ast.AST], env: Dict[str, Sym]) -> bool:
    """True when the expression's value provably varies with the rank
    (evaluates to different values at different ranks), or mentions rank
    in a way the folder cannot resolve."""
    if node is None:
        return False
    vals = [eval_expr(node, env, None, r, 5) for r in range(4)]
    known = [v for v in vals if v is not None]
    if len(known) >= 2 and any(v != known[0] for v in known[1:]):
        return True
    if known and len(known) == len(vals):
        return False  # fully evaluated, identical at every rank
    return mentions_rank(node, env)


# -- call helpers ------------------------------------------------------------

def _attr_call(call: ast.Call) -> Optional[Tuple[ast.AST, str]]:
    if isinstance(call.func, ast.Attribute):
        return call.func.value, call.func.attr
    return None


def _arg(call: ast.Call, kw: str, pos: Optional[int]) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


# Method-call argument slots: (peer kw, peer pos, tag default handling).
# send(obj, dest, tag=0) / recv(source=ANY_SOURCE, tag=ANY_TAG).
_P2P_SLOTS = {
    "send": ("send", "dest", 1),
    "ssend": ("send", "dest", 1),
    "isend": ("send", "dest", 1),
    "recv": ("recv", "source", 0),
    "irecv": ("recv", "source", 0),
}
_FUNC_SLOTS = {
    "MPI_Send": ("send", "dest", 1),
    "MPI_Isend": ("send", "dest", 1),
    "MPI_Recv": ("recv", "source", 0),
    "MPI_Irecv": ("recv", "source", 0),
}


# -- the operation collector -------------------------------------------------

class _Loop(NamedTuple):
    line: int
    rank_dep: bool


class OpCollector:
    """Walk one root (module body or function) collecting :class:`Op`
    records with guard chains, plus MPL008 loop evidence.  ``funcs`` is
    the module's top-level function table for one-level splicing."""

    def __init__(self, funcs: Dict[str, ast.FunctionDef]) -> None:
        self.funcs = funcs
        self.ops: List[Op] = []
        self.rank_loops: List[RankLoopColl] = []

    # .. statement walk ......................................................

    def walk_root(self, body: Sequence[ast.stmt]) -> None:
        self._walk_block(body, {}, [], [], splice=True)

    def _walk_block(self, body: Sequence[ast.stmt], env: Dict[str, Sym],
                    guards: List[Guard], loops: List[_Loop],
                    splice: bool) -> bool:
        """Walk a statement sequence; returns True when the block
        terminates (return/raise on every path through its tail)."""
        extra: List[Guard] = []
        for stmt in body:
            g = guards + extra
            if isinstance(stmt, (ast.Return, ast.Raise)):
                self._scan_exprs(stmt, env, g, loops, splice)
                return True
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs execute on their own schedule
            if isinstance(stmt, ast.If):
                self._scan_exprs(stmt.test, env, g, loops, splice)
                genv = dict(env)
                t_end = self._walk_block(
                    stmt.body, dict(env),
                    g + [Guard(stmt.test, genv, True)], loops, splice)
                f_end = self._walk_block(
                    stmt.orelse, dict(env),
                    g + [Guard(stmt.test, genv, False)], loops, splice)
                if t_end and f_end and stmt.orelse:
                    return True
                if t_end and not f_end:
                    extra = extra + [Guard(stmt.test, genv, False)]
                elif f_end and not t_end:
                    extra = extra + [Guard(stmt.test, genv, True)]
                # branch assignments are not merged back (env stays the
                # pre-branch snapshot): a post-branch read of a
                # branch-assigned name evaluates as undecidable, which
                # is the conservative direction
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                trip = stmt.iter
                if (isinstance(trip, ast.Call)
                        and isinstance(trip.func, ast.Name)
                        and trip.func.id == "range"):
                    dep = any(rank_dependent(a, env) for a in trip.args)
                else:
                    dep = rank_dependent(trip, env)
                self._scan_exprs(stmt.iter, env, g, loops, splice)
                lenv = dict(env)
                for t in ast.walk(stmt.target):
                    if isinstance(t, ast.Name):
                        lenv.pop(t.id, None)  # loop var: unknown value
                self._walk_block(stmt.body, lenv, list(g),
                                 loops + [_Loop(stmt.lineno, dep)], splice)
                self._walk_block(stmt.orelse, dict(env), list(g), loops,
                                 splice)
                continue
            if isinstance(stmt, ast.While):
                dep = rank_dependent(stmt.test, env)
                self._scan_exprs(stmt.test, env, g, loops, splice)
                self._walk_block(stmt.body, dict(env), list(g),
                                 loops + [_Loop(stmt.lineno, dep)], splice)
                self._walk_block(stmt.orelse, dict(env), list(g), loops,
                                 splice)
                continue
            if isinstance(stmt, ast.Try):
                ended = self._walk_block(stmt.body, dict(env), list(g),
                                         loops, splice)
                for h in stmt.handlers:
                    self._walk_block(h.body, dict(env), list(g), loops,
                                     splice)
                self._walk_block(stmt.orelse, dict(env), list(g), loops,
                                 splice)
                self._walk_block(stmt.finalbody, dict(env), list(g), loops,
                                 splice)
                del ended  # a try's reachability is not modeled
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_exprs(item.context_expr, env, g, loops,
                                     splice)
                self._walk_block(stmt.body, env, list(g), loops, splice)
                continue
            # simple statement: collect ops from its expressions, then
            # update the environment for assignments
            self._scan_exprs(stmt, env, g, loops, splice)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                env[stmt.targets[0].id] = Sym(stmt.value, dict(env))
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                env[stmt.target.id] = Sym(stmt.value, dict(env))
            elif isinstance(stmt, ast.AugAssign) \
                    and isinstance(stmt.target, ast.Name):
                env.pop(stmt.target.id, None)  # x += ...: give up on x
        return False

    # .. expression scan (op extraction + one-level splicing) ................

    def _scan_exprs(self, node: ast.AST, env: Dict[str, Sym],
                    guards: List[Guard], loops: List[_Loop],
                    splice: bool) -> None:
        stack: List[ast.AST] = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                self._handle_call(n, env, guards, loops, splice)
            stack.extend(ast.iter_child_nodes(n))

    def _handle_call(self, call: ast.Call, env: Dict[str, Sym],
                     guards: List[Guard], loops: List[_Loop],
                     splice: bool) -> None:
        mc = _attr_call(call)
        if mc is not None:
            recv_expr, meth = mc
            comm = resolve_comm(recv_expr, env)
            if comm is None:
                return
            in_rank_loop = any(lp.rank_dep for lp in loops)
            if meth in COLLECTIVES:
                if in_rank_loop:
                    dep_line = next(lp.line for lp in loops if lp.rank_dep)
                    self.rank_loops.append(
                        RankLoopColl(comm, meth, call.lineno, dep_line))
                self.ops.append(Op(
                    comm, "coll", meth, call.lineno, tuple(guards),
                    dict(env), None, None, None, in_rank_loop))
            elif meth in _P2P_SLOTS:
                kind, peer_kw, peer_pos = _P2P_SLOTS[meth]
                self.ops.append(Op(
                    comm, "nb" if meth.startswith("i") else kind,
                    meth, call.lineno, tuple(guards), dict(env),
                    _arg(call, peer_kw, peer_pos), _arg(call, "tag", None),
                    _arg(call, "count", None), in_rank_loop))
            return
        if isinstance(call.func, ast.Name):
            fname = call.func.id
            if fname in _FUNC_SLOTS:
                kind, peer_kw, peer_pos = _FUNC_SLOTS[fname]
                comm_arg = _arg(call, "comm", None)
                comm = (resolve_comm(comm_arg, env)
                        if comm_arg is not None else "<world>")
                if comm is None:
                    comm = "<world>"
                self.ops.append(Op(
                    comm, "nb" if "I" in fname else kind, fname,
                    call.lineno, tuple(guards), dict(env),
                    _arg(call, peer_kw, peer_pos), _arg(call, "tag", None),
                    _arg(call, "count", None),
                    any(lp.rank_dep for lp in loops)))
                return
            if splice and fname in self.funcs:
                self._splice(self.funcs[fname], call, env, guards, loops)

    def _splice(self, fn: ast.FunctionDef, call: ast.Call,
                env: Dict[str, Sym], guards: List[Guard],
                loops: List[_Loop]) -> None:
        """One-level call-graph resolution: walk the callee's body with
        its parameters bound to the caller's argument expressions."""
        params = [a.arg for a in fn.args.args]
        callee_env: Dict[str, Sym] = {}
        for i, p in enumerate(params):
            a = _arg(call, p, i)
            if a is not None:
                callee_env[p] = Sym(a, dict(env))
        defaults = fn.args.defaults
        if defaults:
            for p, d in zip(params[-len(defaults):], defaults):
                callee_env.setdefault(p, Sym(d, {}))
        self._walk_block(fn.body, callee_env, list(guards), list(loops),
                         splice=False)


# -- module-level driver -----------------------------------------------------

def _top_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for n in tree.body:
        if isinstance(n, ast.FunctionDef):
            out[n.name] = n
    return out


def _called_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            out.add(n.func.id)
    return out


def all_functions(tree: ast.Module):
    """Every function/method in the module (for the per-function local
    rules)."""
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def collect_roots(tree: ast.Module) -> Tuple[List[RootOps],
                                             List[RankLoopColl]]:
    """Comm-graph analysis roots: the module body plus every top-level or
    method function that is NOT called from within this module (called
    helpers are analyzed spliced into their callers, so a rank-guarded
    helper whose caller supplies the matching branch stays clean)."""
    funcs = _top_functions(tree)
    called = _called_names(tree)
    roots: List[RootOps] = []
    rank_loops: List[RankLoopColl] = []

    col = OpCollector(funcs)
    col._walk_block(
        [s for s in tree.body
         if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))],
        {}, [], [], splice=True)
    roots.append(RootOps("<module>", col.ops))
    rank_loops.extend(col.rank_loops)

    for fn in all_functions(tree):
        if fn.name in called:
            continue
        col = OpCollector(funcs)
        col.walk_root(fn.body)
        roots.append(RootOps(fn.name, col.ops))
        rank_loops.extend(col.rank_loops)

    # called helpers still contribute MPL008 evidence standalone (a
    # rank-dependent collective loop is a local property)
    for fn in all_functions(tree):
        if fn.name not in called:
            continue
        col = OpCollector(funcs)
        col.walk_root(fn.body)
        rank_loops.extend(col.rank_loops)

    seen = set()
    uniq: List[RankLoopColl] = []
    for rl in rank_loops:
        key = (rl.line, rl.name)
        if key not in seen:
            seen.add(key)
            uniq.append(rl)
    return roots, uniq


# -- request flow (MPL005 / MPL006) ------------------------------------------

class _Req(NamedTuple):
    line: int
    name: str
    buf: Optional[str]


def _req_creation(stmt: ast.stmt) -> Optional[Tuple[Optional[str], _Req]]:
    """(target-name-or-None, request) when the statement creates a
    nonblocking request; an Expr statement that discards the handle
    returns target None."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        target, value = stmt.targets[0].id, stmt.value
    elif isinstance(stmt, ast.Expr):
        target, value = None, stmt.value
    else:
        return None
    if not isinstance(value, ast.Call):
        return None
    name = None
    mc = _attr_call(value)
    if mc is not None and mc[1] in NONBLOCKING_METHODS:
        name = mc[1]
    elif isinstance(value.func, ast.Name) \
            and value.func.id in NONBLOCKING_FUNCS:
        name = value.func.id
    if name is None:
        return None
    buf = None
    lowered = name.lower()
    if "recv" in lowered and "send" not in lowered:
        b = _arg(value, "buf", None)
    else:
        b = _arg(value, "buf", 0) if lowered.startswith(("isend", "mpi_i")) \
            else _arg(value, "buf", None)
    if isinstance(b, ast.Name):
        buf = b.id
    return target, _Req(stmt.lineno, name, buf)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _completion_targets(stmt: ast.AST) -> Set[str]:
    """Variable names this statement completes: ``v.wait()``-style calls
    and names passed (directly or in a list literal) to MPI_Wait*."""
    out: Set[str] = set()
    for n in ast.walk(stmt):
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute) \
                and n.func.attr in _COMPLETION_METHODS \
                and isinstance(n.func.value, ast.Name):
            out.add(n.func.value.id)
        elif isinstance(n.func, ast.Name) \
                and n.func.id in _COMPLETION_FUNCS:
            for a in n.args:
                out.update(_names_in(a))
    return out


def _buffer_writes(stmt: ast.stmt) -> Set[Tuple[str, int]]:
    """(name, line) for every subscript/augmented store through a plain
    name in the statement — the buffer-mutation shapes MPL006 prices."""
    out: Set[Tuple[str, int]] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, ast.AugAssign):
        targets = [stmt.target]
    elif isinstance(stmt, ast.AnnAssign):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name):
                out.add((n.value.id, stmt.lineno))
            elif isinstance(stmt, ast.AugAssign) and isinstance(n, ast.Name) \
                    and n is stmt.target:
                out.add((n.id, stmt.lineno))
    return out


class _ReqFlow:
    def __init__(self) -> None:
        self.issues: List[ReqIssue] = []
        self._flagged006: Set[Tuple[str, int]] = set()

    def run(self, body: Sequence[ast.stmt]) -> None:
        state: Dict[str, _Req] = {}
        exits: List[Dict[str, _Req]] = []
        end = self._block(body, state, exits)
        if end is not None:
            exits.append(end)
        leaked: Dict[str, _Req] = {}
        for snap in exits:
            for v, req in snap.items():
                leaked.setdefault(v, req)
        for v, req in sorted(leaked.items(), key=lambda kv: kv[1].line):
            self.issues.append(ReqIssue("MPL005", req.line, req.line,
                                        req.name, req.buf))

    def _block(self, body: Sequence[ast.stmt], state: Dict[str, _Req],
               exits: List[Dict[str, _Req]]) -> Optional[Dict[str, _Req]]:
        """Forward may-analysis; returns the fall-through state, or None
        when the block always terminates."""
        for stmt in body:
            if isinstance(stmt, ast.Return):
                self._uses(stmt, state)
                exits.append(dict(state))
                return None
            if isinstance(stmt, ast.Raise):
                # error path: request accounting is moot there
                return None
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                self._uses(stmt.test, state)
                s1 = self._block(stmt.body, dict(state), exits)
                s2 = self._block(stmt.orelse, dict(state), exits)
                if s1 is None and s2 is None:
                    return None
                state = self._merge(s1, s2)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                head = stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                    else stmt.test
                self._uses(head, state)
                s = dict(state)
                for _ in range(2):  # loop body twice: fixpoint for joins
                    out = self._block(stmt.body, dict(s), exits)
                    s = self._merge(s, out)
                state = self._merge(
                    s, self._block(stmt.orelse, dict(s), exits))
                if state is None:
                    return None
                continue
            if isinstance(stmt, ast.Try):
                s1 = self._block(stmt.body, dict(state), exits)
                merged = self._merge(state, s1)
                for h in stmt.handlers:
                    merged = self._merge(
                        merged, self._block(h.body, dict(state), exits))
                merged = self._merge(
                    merged, self._block(stmt.orelse,
                                        dict(merged or state), exits))
                fin = self._block(stmt.finalbody,
                                  dict(merged or state), exits)
                state = fin if stmt.finalbody else (merged or {})
                if state is None:
                    return None
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._uses(item.context_expr, state)
                s = self._block(stmt.body, state, exits)
                if s is None:
                    return None
                state = s
                continue
            # simple statement
            created = _req_creation(stmt)
            self._uses(stmt, state, skip_value=(
                created is not None))
            self._writes(stmt, state)
            if created is not None:
                target, req = created
                key = target if target is not None \
                    else f"<discarded@{req.line}>"
                state[key] = req
        return state

    @staticmethod
    def _merge(a: Optional[Dict[str, _Req]],
               b: Optional[Dict[str, _Req]]) -> Optional[Dict[str, _Req]]:
        if a is None:
            return None if b is None else dict(b)
        if b is None:
            return dict(a)
        out = dict(a)
        for k, v in b.items():
            out.setdefault(k, v)
        return out

    def _uses(self, node: ast.AST, state: Dict[str, _Req],
              skip_value: bool = False) -> None:
        """Apply completions, then escape-discharge any OTHER mention of
        a live request var (stored, passed, returned: the analysis can no
        longer prove anything, so it stays silent)."""
        if not state:
            return
        done = _completion_targets(node)
        for v in list(state):
            if v in done:
                state.pop(v, None)
        if skip_value:
            return
        mentioned = _names_in(node)
        for v in list(state):
            if v in mentioned:
                state.pop(v, None)  # escaped: conservatively discharged

    def _writes(self, stmt: ast.stmt, state: Dict[str, _Req]) -> None:
        if not state:
            return
        writes = _buffer_writes(stmt)
        if not writes:
            return
        for v, req in list(state.items()):
            if req.buf is None:
                continue
            for name, line in writes:
                if name == req.buf:
                    key = (req.buf, req.line)
                    if key not in self._flagged006:
                        self._flagged006.add(key)
                        self.issues.append(ReqIssue(
                            "MPL006", line, req.line, req.name, req.buf))
                    state.pop(v, None)
                    break


def request_flow(body: Sequence[ast.stmt]) -> List[ReqIssue]:
    """MPL005/006 evidence for one function body (or module body)."""
    flow = _ReqFlow()
    flow.run(body)
    return flow.issues
