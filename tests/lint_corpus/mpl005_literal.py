"""Seeded bug: a nonblocking send whose request is simply dropped."""


def main(comm):
    req = comm.isend(b"payload", 1, tag=0)
    comm.barrier()
