"""Checkpoint / resume — the slice-restart half of the fault story.

SURVEY.md §5: the reference has no checkpoint capability (socket EOF ⇒
crash); the TPU-native failure model is *slice restart + checkpoint* —
detection surfaces through ``recv_timeout`` / ``FaultyTransport`` (see
transport/faulty.py), and recovery is relaunch + restore.  Two surfaces:

* process backends — ``save(path, state, comm)`` / ``load(path, comm)``:
  each rank owns ``rank{r}/`` under ``path`` (numpy + pickle payloads);
  save is collective (barrier'd, manifest written once) so a checkpoint
  directory is either complete or detectably partial.
* SPMD/TPU backend — ``save_sharded`` / ``load_sharded`` wrap orbax
  (async-capable, TPU-native sharded IO): global jax Arrays are written
  per-shard by the process that owns them and restored to the SAME
  sharding layout, so a pod-scale training state round-trips without
  ever being gathered to one host.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Optional

import numpy as np

_MANIFEST = "manifest.json"
_STATE = "state.pkl"


def save(path: str, state: Any, comm=None) -> None:
    """Collective checkpoint on a process-backend communicator: every rank
    writes its own state pytree; rank 0 commits the manifest LAST, so a
    directory with a manifest is complete."""
    from . import init

    comm = comm or init()
    # re-saving over an existing checkpoint: invalidate it FIRST, so a
    # crash mid-save can never leave an old manifest blessing mixed
    # old/new rank states (the manifest == completeness contract)
    if comm.rank == 0 and os.path.exists(os.path.join(path, _MANIFEST)):
        os.unlink(os.path.join(path, _MANIFEST))
    comm.barrier()
    rank_dir = os.path.join(path, f"rank{comm.rank}")
    os.makedirs(rank_dir, exist_ok=True)
    with open(os.path.join(rank_dir, _STATE), "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    comm.barrier()  # every rank's state is on disk
    if comm.rank == 0:
        tmp = os.path.join(path, "." + _MANIFEST)
        with open(tmp, "w") as f:
            json.dump({"nranks": comm.size, "format": 1}, f)
        os.replace(tmp, os.path.join(path, _MANIFEST))
    comm.barrier()  # nobody returns before the checkpoint is committed


def exists(path: str) -> bool:
    """True iff ``path`` holds a COMPLETE checkpoint (manifest present)."""
    return os.path.exists(os.path.join(path, _MANIFEST))


def load(path: str, comm=None) -> Any:
    """Restore this rank's state from a complete checkpoint; raises
    FileNotFoundError on a missing/partial one, ValueError on a world-size
    mismatch (a resumed job must match the checkpoint's geometry)."""
    from . import init

    comm = comm or init()
    mpath = os.path.join(path, _MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"no complete checkpoint at {path!r} (manifest missing — the "
            f"save was interrupted before commit)")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest["nranks"] != comm.size:
        raise ValueError(
            f"checkpoint was taken with {manifest['nranks']} ranks; this "
            f"world has {comm.size}")
    with open(os.path.join(path, f"rank{comm.rank}", _STATE), "rb") as f:
        return pickle.load(f)


# ---- SPMD / sharded (orbax) ----------------------------------------------


def save_sharded(path: str, state: Any) -> None:
    """Write a pytree of (possibly sharded, possibly multi-host) jax
    Arrays via orbax; call OUTSIDE jit, same args on every process."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckpt:
        ckpt.save(os.path.abspath(path), state, force=True)


def load_sharded(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_sharded`.  ``template`` is a
    pytree of arrays or jax.ShapeDtypeStruct(shape, dtype, sharding=...)
    giving the target shardings — restored shards land directly on the
    right devices (no host-side gather)."""
    import jax
    import orbax.checkpoint as ocp

    abstract_tree = jax.tree.map(
        lambda x: (x if isinstance(x, jax.ShapeDtypeStruct)
                   else jax.ShapeDtypeStruct(
                       np.shape(x), np.asarray(x).dtype if not hasattr(x, "dtype")
                       else x.dtype,
                       sharding=getattr(x, "sharding", None))),
        template)
    with ocp.StandardCheckpointer() as ckpt:
        return ckpt.restore(os.path.abspath(path), abstract_tree)
