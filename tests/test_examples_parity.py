"""Source-compatibility / parity tests (SURVEY.md §4 item 4): the SAME
example program, byte-for-byte, runs on the CPU backends and the TPU SPMD
backend and produces matching results; Jacobi additionally matches a serial
numpy oracle."""

import numpy as np
import pytest

from examples.jacobi import jacobi_program
from examples.pi import pi_program
from mpi_tpu.tpu import run_spmd
from mpi_tpu.transport.local import run_local

NR = 4


def _serial_jacobi(nrows, cols, iters):
    grid = np.zeros((nrows + 2, cols), np.float32)
    grid[0] = 1.0  # hot top edge (the rank-0 halo in the distributed version)
    cur = grid.copy()
    for _ in range(iters):
        new = cur.copy()
        inner = 0.25 * (cur[:-2] + cur[2:]
                        + np.pad(cur[1:-1, :-1], ((0, 0), (1, 0)))
                        + np.pad(cur[1:-1, 1:], ((0, 0), (0, 1))))
        inner[:, 0] = 0.0
        inner[:, -1] = 0.0
        new[1:-1] = inner
        prev, cur = cur, new
    return cur[1:-1], np.max(np.abs(cur[1:-1] - prev[1:-1]))


def test_pi_local_vs_tpu_identical():
    local = run_local(pi_program, NR, kwargs={"n_per_rank": 5000})
    tpu = np.ravel(np.asarray(run_spmd(pi_program, nranks=NR, n_per_rank=5000)))
    # same rank-seeded RNG, same reduction → identical estimates
    for r in range(NR):
        np.testing.assert_allclose(float(np.asarray(local[r])), tpu[r], rtol=1e-6)
    assert abs(tpu[0] - np.pi) < 0.1


def test_jacobi_local_vs_tpu_vs_serial():
    rows, cols, iters = 4, 16, 40
    local = run_local(jacobi_program, NR,
                      kwargs={"rows_per_rank": rows, "cols": cols, "iters": iters})
    blocks_l = np.concatenate([np.asarray(b) for b, _ in local])
    res_l = float(np.asarray(local[0][1]))

    blocks_t, res_t = run_spmd(jacobi_program, nranks=NR, rows_per_rank=rows,
                               cols=cols, iters=iters)
    blocks_t = np.asarray(blocks_t).reshape(NR * rows, cols)
    res_t = float(np.asarray(res_t).ravel()[0])

    oracle_grid, oracle_res = _serial_jacobi(NR * rows, cols, iters)

    np.testing.assert_allclose(blocks_l, blocks_t, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(blocks_l, oracle_grid, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(res_l, res_t, rtol=1e-4)
    np.testing.assert_allclose(res_l, oracle_res, rtol=1e-3, atol=1e-7)


@pytest.mark.slow
def test_jacobi_socket_parity():
    """The socket backend (the reference's transport) runs the same program
    with the same numbers — the BASELINE.json:7 CPU config."""
    from test_socket_backend import run_socket_world

    rows, cols, iters = 4, 16, 20
    res = run_socket_world(
        lambda comm: jacobi_program(comm, rows_per_rank=rows, cols=cols, iters=iters),
        2,
    )
    blocks_s = np.concatenate([np.asarray(b) for b, _ in res])
    blocks_t, _ = run_spmd(jacobi_program, nranks=2, rows_per_rank=rows,
                           cols=cols, iters=iters)
    np.testing.assert_allclose(
        blocks_s, np.asarray(blocks_t).reshape(2 * rows, cols), rtol=1e-5, atol=1e-7
    )


def test_master_worker_matches_serial_oracle():
    """The dynamic task farm (tags + Waitany, self-balancing) returns every
    task's result exactly once, equal to the serial computation."""
    from examples.master_worker import _task, run as mw_run

    NT = 25
    res = run_local(lambda c: mw_run(c, NT), 4)
    oracle = [_task(i) for i in range(NT)]
    got = res[0]
    assert len(got) == NT and all(r is not None for r in got)
    np.testing.assert_allclose(got, oracle)
    # workers return None
    assert res[1] is None and res[3] is None


def test_master_worker_single_rank_degenerates():
    from examples.master_worker import _task, run as mw_run

    res = run_local(lambda c: mw_run(c, 7), 1)
    np.testing.assert_allclose(res[0], [_task(i) for i in range(7)])


def test_master_worker_more_workers_than_tasks():
    """Surplus workers are stopped at priming and get no dangling irecv."""
    from examples.master_worker import _task, run as mw_run

    res = run_local(lambda c: mw_run(c, 2), 6)  # 5 workers, 2 tasks
    np.testing.assert_allclose(res[0], [_task(0), _task(1)])
