"""Shared-memory transport: the native same-host data plane.

Same plugin seam as the socket transport (SURVEY.md §1 L1), different
substrate: one native SPSC byte ring in POSIX shared memory per directed
rank pair (mpi_tpu/native/shmring.cpp), no syscalls on the data path —
a `memcpy` into the ring replaces the TCP stack.  Frames are
``<u64 flags|length>`` + body; the body is either a pickle envelope or a
raw-array frame (meta + raw bytes, no pickle on the hot payload — see
transport/codec.py).  Contiguous numpy arrays therefore move with exactly
TWO memcpys end to end: sender's buffer → ring → receiver's result array.
The C side streams in chunks, so frames larger than the ring capacity
flow without deadlock.

Topology/ownership: every rank CREATES its P−1 incoming rings plus one
futex *doorbell* at startup (consumer-owned; stale segments from crashed
runs are unlinked first) and signals readiness through the rendezvous dir;
senders open the peer's ring + doorbell on first send and ring the bell
once the frame header is visible (see ``send`` for why the bell cannot
wait for the full frame).

Progress model: INLINE, like an MPI progress engine — whichever thread is
blocked in ``recv``/``probe`` drains the rings itself, sleeping directly on
the futex doorbell when they are empty.  A message therefore takes exactly
one kernel wakeup (sender → receiving thread), with no intermediate reader
thread hop; that is the latency edge over the socket transport, whose
receiver pays reader-thread → condvar → user thread.  Threads that lose
the progress-lock race fall back to waiting on the shared Mailbox, which
the progressing thread feeds — matching semantics stay identical to every
other CPU transport.

Bandwidth root-cause note (the round-2 "shm loses at 16MB" finding): the
ring itself streams 16MB frames cross-process at >5 GB/s on this 1-core
box; the transport's measured 1.6 GB/s was the RECEIVER faulting in every
page of each message's freshly-mmapped destination array (48.8k minor
faults / 84ms system time per 192MB — glibc munmaps large frees, so the
warm pages never came back).  The fix is transport-agnostic: recv
destinations come from ``codec.RECV_POOL``, which recycles large buffers
once they are provably unaliased.  With pooled destinations the 16MB
windowed bandwidth is ~6.4 GB/s vs the socket path's ~2.5 (kernel-copy
bound), i.e. the zero-copy thesis of this module holds once the
page-fault tax is removed; see benchmarks/shm_bw_probe.py for the
measurement harness.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import bufpool as _bufpool
from .. import mpit as _mpit
from .. import recvpool as _recvpool
from .. import telemetry as _telemetry
from ..errors import EpochSkewError
from ..native import load_shmring
from . import codec
from .base import ANY_SOURCE, Mailbox, RecvTimeout, Transport, TransportError

_LEN = struct.Struct("<Q")
_RING_BYTES = int(os.environ.get("MPI_TPU_SHM_RING_BYTES", 4 << 20))
_OPEN_TIMEOUT = 60.0
_WRITE_TIMEOUT = 120.0  # max time with NO progress before declaring a peer dead
_PROGRESS_SLICE = 0.25  # max doorbell nap; re-checks deadline/closing
_SMALL = 8192  # frames up to this commit in one ring write (atomic + 1 bell)
# Bounded poll-spin before the futex nap: with spare cores the sender runs
# concurrently, so a short spin catches the frame without paying the futex
# wakeup + context switch.  On a 1-core box the sender CANNOT progress while
# we spin (measured: yield-spinning made p50 ~20µs worse there), so the
# default is off unless there are ≥2 CPUs.  MPI_TPU_SHM_SPIN_US overrides;
# 0 disables.
_SPIN_S = float(os.environ.get(
    "MPI_TPU_SHM_SPIN_US",
    "100" if (os.cpu_count() or 1) > 1 else "0")) * 1e-6
# Grace window before an ahead-of-us readiness stamp is declared a
# SKEW (see transport/socket.py _EPOCH_GRACE_S — same rationale: a
# broadcast epoch transition reaches peers at slightly different
# times, and only a genuinely ousted straggler stays behind).
# mpit cvar: epoch_grace_s (one knob writes both transports' globals);
# env default: MPI_TPU_EPOCH_GRACE_S.
_EPOCH_GRACE_S = float(os.environ.get("MPI_TPU_EPOCH_GRACE_S", "2.0"))


class _PeerDeadMidFrame(TransportError):
    """A frame from ``src`` truncated because the failure detector
    declared the sender dead: the CHANNEL is desynced (unknown bytes of
    a frame are missing) but the rest of the transport is healthy —
    _drain_once quarantines the one ring (``_dead_srcs``) instead of
    closing the whole mailbox, and an epoch transition recreates it for
    the slot's replacement (membership_invalidate)."""

    def __init__(self, msg: str, src: int) -> None:
        super().__init__(msg)
        self.src = src


def _addr(buf) -> int:
    """Raw address of a bytes-like's buffer (zero-copy; caller must keep
    ``buf`` alive across the native call)."""
    return np.frombuffer(buf, dtype=np.uint8).ctypes.data


def shm_prefix(session: str) -> str:
    """Common /dev/shm name prefix of every segment of one session — the
    launcher's crash-path cleanup globs on this, so the naming scheme lives
    in exactly one place."""
    return f"mt_{session}_"


def _ring_name(session: str, src: int, dst: int) -> bytes:
    # /dev/shm names: <=255 chars, one leading slash
    return f"/{shm_prefix(session)}{src}_{dst}".encode()


def _db_name(session: str, rank: int) -> bytes:
    return f"/{shm_prefix(session)}db_{rank}".encode()


class ShmTransport(Transport):
    # The ring is a fixed _RING_BYTES (4MB) allocation per directed pair:
    # the collective engine's in-flight credit (window * segment, see
    # communicator._SEG_WINDOW) must stay well inside it or a symmetric
    # exchange stalls on the periodic drainer.  256KB * window 4 = 1MB —
    # a quarter ring — keeps the futex fast path hot at every sweep size.
    coll_segment_hint = 256 << 10

    # Ranks of one shm world share /dev/shm: communicators over this
    # transport may map a coll/sm collective arena (mpi_tpu/coll_sm.py);
    # the handles register in _coll_arenas and close() tears them down.
    supports_coll_sm = True

    # Tuned-dispatch table key (mpi_tpu/tuning): rows measured on this
    # data plane.  Wrapper transports (FaultyTransport) deliberately
    # carry no name, so chaos legs bypass the table.
    tuning_transport = "shm"

    # Receive-side rendezvous steering (ISSUE 19): the ring drain can
    # land a large raw frame's body DIRECTLY in a posted receive's
    # buffer — one ring->destination memcpy, no intermediate array.
    # Ring frames carry no (gen, seq); the reader synthesizes both
    # per source (see _read_frame / membership_invalidate) so the
    # registry's watermark and purge fences carry over unchanged.
    recv_steering = True

    def __init__(self, rank: int, size: int, rdv_dir: str,
                 ring_bytes: int = _RING_BYTES,
                 connect_timeout: float = _OPEN_TIMEOUT,
                 epoch: int = 0) -> None:
        super().__init__(rank, size)
        self.epoch = epoch  # a rejoiner is BORN into the current epoch
        self._lib = load_shmring()
        self._session = os.path.basename(rdv_dir.rstrip("/"))
        self._rdv = rdv_dir
        self._connect_timeout = connect_timeout
        self._ring_bytes = ring_bytes
        self._closing = False

        # inbound channels quarantined mid-frame (their sender died with
        # a frame half-written — the byte stream is desynced); skipped
        # by _drain_once until an epoch transition recreates the ring
        self._dead_srcs: set = set()
        # Rendezvous steering (ISSUE 19): the registry the ring drain
        # consults, plus the per-source synthesized stream position the
        # registry's watermark is keyed on.  The ring is a reliable
        # in-order byte stream, so every frame read is by construction
        # the next fresh frame of the current generation — seq is just
        # a counter, and gen bumps when membership_invalidate recreates
        # a slot's ring (fencing old-incarnation pairings exactly like
        # the socket link's stream generation).  Both dicts are touched
        # only under the progress lock.
        self.recv_registry = _recvpool.PostedRecvRegistry()
        self._rx_seq: Dict[int, int] = {}
        self._rx_gen: Dict[int, int] = {}
        # consumer side: create my incoming rings + doorbell, then publish
        self._in_rings: Dict[int, int] = {}
        for src in range(size):
            if src == rank:
                continue
            name = _ring_name(self._session, src, rank)
            ring = self._lib.shmring_create(name, ring_bytes)
            if not ring:
                raise TransportError(
                    f"rank {rank}: shmring_create({name!r}) failed")
            self._in_rings[src] = ring
        self._in_items = list(self._in_rings.items())
        self._db = self._lib.shmdb_create(_db_name(self._session, rank))
        if not self._db:
            raise TransportError(f"rank {rank}: doorbell create failed")
        # readiness file content = the membership epoch these rings were
        # created under (mpi_tpu/membership.py): a rejoiner replacing a
        # dead slot re-publishes this file atomically, and openers check
        # the stamp so neither a survivor nor a stale straggler can map
        # the wrong generation's segments (see _out_ring_locked)
        self._write_readiness()

        # producer side: outgoing rings + doorbells open lazily on first send
        self._out_rings: Dict[int, int] = {}
        self._out_dbs: Dict[int, int] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._state_lock = threading.Lock()
        # exactly one thread runs the progress engine at a time
        self._progress_lock = threading.Lock()
        # Our own doorbell mapping is NEVER munmapped (close() only unlinks
        # the name; the 1-page mapping is reclaimed at process exit), so
        # ring/read/wait on it need no lock against teardown — any thread
        # may touch it at any time and close() just has to wake sleepers.
        # Helper drainer: guarantees the buffered-send invariant
        # (communicator.py: "transports buffer sends and drain receives on
        # dedicated threads") even when NO thread of this rank is in recv —
        # e.g. two ranks symmetric-sendrecv'ing frames bigger than the free
        # ring space would otherwise deadlock in their sends.  It defers to
        # user threads: it only drains when the progress lock is free.
        self._user_waiters = 0  # user threads inside _blocking_match
        self._waiters_lock = threading.Lock()  # += is not atomic under GIL
        self._helper = threading.Thread(
            target=self._helper_loop, name=f"mpi-tpu-shm-helper-{rank}",
            daemon=True)
        self._helper.start()

    # -- progress engine (incoming) ----------------------------------------

    def _helper_loop(self) -> None:
        while not self._closing:
            # Last-resort drainer only: while any user thread is receiving,
            # IT owns the progress engine (one-wakeup latency path) and the
            # helper must stand down entirely.  The helper deliberately
            # does NOT wait on the doorbell: it would share the futex with
            # real receive waiters, so every delivery would wake one extra
            # thread — a whole extra context switch per message on a
            # 1-core box.  A 20Hz ring poll is plenty for its only job
            # (the no-receiver symmetric-send overload case) and costs the
            # hot path nothing.
            time.sleep(0.05)
            if self._closing:
                return
            if (self._user_waiters == 0
                    and self._progress_lock.acquire(blocking=False)):
                try:
                    if self._closing:
                        return
                    if self._user_waiters == 0:
                        self._drain_once()
                except TransportError:
                    # _drain_once closed the mailbox, so every blocked
                    # receiver sees the diagnosis; the helper's job here
                    # is done — a dead peer means no more progress.
                    return
                finally:
                    self._progress_lock.release()

    def _read_exact(self, ring: int, addr: int, n: int, src: int) -> None:
        """Stream exactly ``n`` bytes from ``ring`` to the buffer at
        ``addr``, in short native slices so teardown (``_closing``) and a
        dead peer (no progress for _WRITE_TIMEOUT) are noticed promptly —
        never one multi-minute block inside C (the round-1 advisor's
        close()-hangs-2-minutes finding).  Caller holds the progress lock
        and keeps the buffer's owner alive."""
        done = 0
        stall = time.monotonic() + _WRITE_TIMEOUT
        while done < n:
            got = self._lib.shmring_read_some(
                ring, addr + done, n - done, _PROGRESS_SLICE)
            if got:
                done += got
                stall = time.monotonic() + _WRITE_TIMEOUT
                continue
            if self._closing:
                raise TransportError(
                    f"rank {self.world_rank}: transport closed mid-frame "
                    f"from {src}")
            if self._peer_suspected(src):
                # quarantine THIS channel only (the mailbox and every
                # other channel stay live — a pool survivor must remain
                # usable after a peer dies mid-frame); a blocked
                # receiver on the corpse is unblocked by the detector
                # (comm-level sliced waits raise ProcFailedError)
                raise _PeerDeadMidFrame(
                    f"rank {self.world_rank}: frame from {src} truncated "
                    f"mid-stream: the failure detector declared rank "
                    f"{src} dead", src)
            if time.monotonic() > stall:
                self.mailbox.close()  # failure must reach blocked recvs
                raise TransportError(
                    f"rank {self.world_rank}: truncated frame from {src} "
                    f"(no data for {_WRITE_TIMEOUT}s — is the sender alive?)")

    def _note_counted(self, src: int, ctx, tag: int, plan):
        """Count one in-order ring frame on its steering channel; returns
        (posted destination to steer into or None, counted?).  The ring
        delivers reliably in order, so every frame IS the next fresh
        frame of the current generation — the freshness gate the socket
        reader gets from ``rx_fresh`` is the ring's structure here.
        Internal tags always count; user tags only once an
        ``irecv(buf=...)`` activated the channel (reg.user_active).
        Caller holds the progress lock (the seq dict is engine state)."""
        reg = self.recv_registry
        if tag >= 0 and not (reg.user_count
                             and reg.user_active(src, ctx, tag)):
            return None, False
        seq = self._rx_seq.get(src, 0) + 1
        self._rx_seq[src] = seq
        return reg.note_frame(src, ctx, tag, seq,
                              self._rx_gen.get(src, 0), plan), True

    def _read_frame(self, src: int, ring: int) -> Tuple[Any, int, Any, Any]:
        """Read one complete frame (header already known present);
        returns (ctx, tag, payload, vclock-stamp-or-None).

        Small frames (body ≤ _SMALL) are pulled in exactly TWO native
        calls — header word, then the whole body into one buffer parsed
        host-side — because on the latency path ctypes call overhead
        (~1-3µs each) dwarfs an extra ≤8KB memcpy.  Only large raw frames
        take the streamed zero-copy read into the final array — a POSTED
        destination when steering pairs one (ring -> the very view the
        fold site or user owns), else a pooled fallback allocation."""
        hdr = ctypes.create_string_buffer(_LEN.size)
        self._read_exact(ring, ctypes.addressof(hdr), _LEN.size, src)
        (word,) = _LEN.unpack(hdr.raw)
        body = word & codec.LEN_MASK
        vc = self.verify_clock
        stamp = None
        try:
            if word & codec.RAW_FLAG:
                if body <= _SMALL:
                    buf = ctypes.create_string_buffer(body)
                    self._read_exact(ring, ctypes.addressof(buf), body, src)
                    ctx, tag, out = codec.parse_raw_body(buf.raw)
                    if vc is not None:
                        ctx, stamp = vc.unwrap(ctx)
                    # small frames never steer (the whole-body read
                    # already happened) but still count, so the
                    # frame/consumer pairing stays aligned
                    self._note_counted(src, ctx, tag, None)
                    return ctx, tag, out, stamp
                mbuf = ctypes.create_string_buffer(codec.META.size)
                self._read_exact(ring, ctypes.addressof(mbuf),
                                 codec.META.size, src)
                (mlen,) = codec.META.unpack(mbuf.raw)
                meta = ctypes.create_string_buffer(mlen)
                self._read_exact(ring, ctypes.addressof(meta), mlen, src)
                ctx, tag, plan = codec.parse_raw_meta(meta.raw)
                if vc is not None:
                    # unwrap BEFORE the steering consult: the posted-recv
                    # registry keys on the real ctx
                    ctx, stamp = vc.unwrap(ctx)
                total = codec.plan_nbytes(plan)
                if codec.META.size + mlen + total != body:
                    raise ValueError(
                        f"raw frame length mismatch: header says {body}, "
                        f"meta implies {codec.META.size + mlen + total}")
                # steering first refusal: a posted receive of matching
                # geometry takes the ring bytes DIRECTLY (ISSUE 19 —
                # the shm edition of the socket reader's rendezvous)
                out, counted = self._note_counted(src, ctx, tag, plan)
                rec = _telemetry.REC
                if out is not None:
                    dests = codec.raw_destinations(out)
                    # CoW-protect any retained frame still referencing
                    # the destination region BEFORE scribbling on it —
                    # a replay must stay bit-exact (mpi_tpu/bufpool.py)
                    for a in dests:
                        _bufpool.touch(a)
                    try:
                        # the single receive-side copy: ring -> the
                        # posted view(s), one streamed read per segment
                        for a in dests:
                            if a.nbytes:
                                self._read_exact(ring, a.ctypes.data,
                                                 a.nbytes, src)
                    except TransportError:
                        # torn mid-steer (peer died / teardown): the
                        # view never reaches the mailbox — drop the
                        # user aliasing guard so the buffer can re-arm;
                        # the owner's fallback refill overwrites any
                        # partial bytes
                        if tag >= 0:
                            self.recv_registry.steer_abort(out)
                        raise
                    if tag >= 0:
                        self.recv_registry.steer_done(out)
                    _mpit.count(recv_pool_rendezvous=1,
                                recv_bytes_steered=total)
                    if rec is not None:
                        rec.emit("recvpool", "steer",
                                 attrs={"src": src, "tag": tag,
                                        "nbytes": total,
                                        "transport": "shm"})
                    return ctx, tag, out, stamp
                out = codec.alloc_raw(plan)
                if counted and plan[0] in ("arr", "segs") \
                        and rec is not None:
                    rec.emit("recvpool", "fallback",
                             attrs={"src": src, "tag": tag,
                                    "nbytes": total, "transport": "shm"})
                for a in codec.raw_destinations(out):
                    if a.nbytes:
                        self._read_exact(ring, a.ctypes.data, a.nbytes, src)
                return ctx, tag, out, stamp
            payload = ctypes.create_string_buffer(body) if body else b""
            if body:
                self._read_exact(ring, ctypes.addressof(payload), body, src)
            ctx, tag, obj = pickle.loads(payload.raw if body else b"")
            if vc is not None:
                ctx, stamp = vc.unwrap(ctx)
            # pickle frames on counted channels still count (never
            # steerable) so the frame/consumer pairing stays aligned
            self._note_counted(src, ctx, tag, None)
            return ctx, tag, obj, stamp
        except TransportError:
            raise
        except Exception as e:  # noqa: BLE001 - deliver the diagnosis
            self.mailbox.close()
            raise TransportError(
                f"rank {self.world_rank}: bad frame from {src}: {e}")

    def _drain_once(self) -> bool:
        """Pull every complete-or-started frame out of the rings into the
        Mailbox.  Returns True if anything was delivered.  Caller holds the
        progress lock."""
        lib = self._lib
        progressed = False
        for src, ring in self._in_items:
            if src in self._dead_srcs:
                # desynced mid-frame channel: quarantined until an
                # epoch transition recreates the ring
                continue
            try:
                while lib.shmring_avail(ring) >= _LEN.size:
                    ctx, tag, obj, stamp = self._read_frame(src, ring)
                    self.mailbox.deliver(src, ctx, tag, obj, stamp)
                    progressed = True
            except _PeerDeadMidFrame:
                self._dead_srcs.add(src)
                continue  # other channels keep draining
        if progressed:
            # Local delivery-ring: threads that lost the progress-lock race
            # wait on the doorbell (not the mailbox cv), so tell them their
            # message may have landed.  One futex op, only on delivery.
            self._lib.shmdb_ring(self._db)
        return progressed

    def _progress_wait(self, slice_s: float) -> None:
        """One blocking progress step: drain; if nothing, nap on the
        doorbell (seqlock pattern: snapshot bell → re-scan → wait, so a
        frame landing between scan and wait still wakes us).  Caller holds
        the progress lock AND has checked _closing after acquiring it —
        close() tears the RING mappings down under this lock, so a stale
        call here would hand freed ring pointers to C (the doorbell mapping
        itself outlives close(); see __init__)."""
        lib = self._lib
        # Seqlock order: snapshot the bell BEFORE scanning, so a frame that
        # lands after the scan has already bumped the bell past `seen` and
        # shmdb_wait returns immediately — one ring scan, no lost wakeup.
        seen = lib.shmdb_read(self._db)
        if self._drain_once():
            return
        if _SPIN_S > 0.0:
            spin_deadline = time.monotonic() + min(_SPIN_S, slice_s)
            while time.monotonic() < spin_deadline:
                os.sched_yield()  # 1-core friendly: lets the sender run
                if self._drain_once():
                    return
                if self._closing:
                    return
        lib.shmdb_wait(self._db, seen, slice_s)
        # Drain whatever the bell announced BEFORE handing the lock back:
        # a user thread queued behind the lock in a doorbell wait would
        # otherwise sit on an undrained ring until its next poll.
        self._drain_once()

    def _blocking_match(self, op: str, source: int, ctx, tag: int,
                        timeout: Optional[float],
                        consume: bool) -> Tuple[Any, int, int]:
        """Shared recv/probe loop: match from the Mailbox, progressing the
        rings inline while we wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._waiters_lock:
            self._user_waiters += 1
        try:
            return self._match_loop(op, source, ctx, tag, timeout, deadline,
                                    consume)
        finally:
            with self._waiters_lock:
                self._user_waiters -= 1

    def _match_loop(self, op, source, ctx, tag, timeout, deadline, consume):
        while True:
            if consume:
                hit = self.mailbox.poll(source, ctx, tag)
            else:
                pk = self.mailbox.peek_nowait(source, ctx, tag)
                # probe hits reuse the payload slot for the byte count
                hit = None if pk is None else (pk[2], pk[0], pk[1])
            if hit is not None:
                return hit
            if self._closing:
                raise TransportError(
                    f"transport closed while waiting for {op}"
                    f"(source={source}, ctx={ctx}, tag={tag})")
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise RecvTimeout(
                    f"{op}(source={source}, ctx={ctx}, tag={tag}) timed out "
                    f"after {timeout}s; pending={self.mailbox.pending_summary()}")
            slice_s = _PROGRESS_SLICE
            if remaining is not None:
                slice_s = min(slice_s, remaining)
            if self._progress_lock.acquire(blocking=False):
                try:
                    if self._closing:  # close() may have won the lock race
                        continue       # loop re-raises via the check above
                    self._progress_wait(slice_s)
                finally:
                    self._progress_lock.release()
            else:
                # Another thread holds the progress engine.  Wait on the
                # DOORBELL, not the mailbox cv: the bell rings both on
                # remote arrival and on local delivery (_drain_once), so we
                # wake for either — never stranded for a full nap slice.
                # Seqlock: snapshot, re-poll the mailbox, then wait.  No
                # teardown lock needed — our doorbell mapping outlives
                # close() (see __init__), and close() rings it to pop us
                # out of the nap into the _closing check above.
                if self._closing:
                    continue  # loop re-raises via the check above
                seen = self._lib.shmdb_read(self._db)
                if consume:
                    hit = self.mailbox.poll(source, ctx, tag)
                    if hit is not None:
                        return hit
                else:
                    pk = self.mailbox.peek_nowait(source, ctx, tag)
                    if pk is not None:
                        return pk[2], pk[0], pk[1]
                self._lib.shmdb_wait(self._db, seen, slice_s)
                continue

    def progress_park(self, timeout: float) -> bool:
        """Progress-engine park hook (mpi_tpu/progress.py): the shm
        rings need a consumer to PULL frames, so the engine's park IS a
        progress step — take the progress lock for ONE drain pass, then
        nap on the doorbell with the lock RELEASED.  This is what
        replaces the helper thread's 20Hz last-resort cadence with ~µs
        doorbell latency while every thread of this rank is computing
        or stuck in a ring-full send: without it a symmetric exchange
        larger than the ring advances in 50ms quanta (the measured 16MB
        ialltoall stall the overlap bench prices).  User receivers keep
        their one-wakeup inline-drain priority — when one is waiting,
        the engine stands down onto the doorbell like the helper does.

        The lock must NOT be held across the nap (PR-6 residual (c)): a
        blocking user receive that arrives mid-park would lose the
        progress-lock race and have to wait for the ENGINE to wake,
        drain and re-ring the bell — one extra thread hop on every such
        receive.  With the lock free during the nap the receiver takes
        the engine inline immediately (asserted by
        tests/test_progress.py test_park_releases_progress_lock)."""
        if self._closing:
            raise TransportError(
                f"rank {self.world_rank}: transport closed while parked")
        before = self.mailbox.deliveries
        # Seqlock order (see _progress_wait): snapshot the bell BEFORE
        # the drain scan, so a frame landing between scan and nap has
        # already bumped it past `seen` and shmdb_wait returns at once.
        seen = self._lib.shmdb_read(self._db)
        drained = False
        if (self._user_waiters == 0
                and self._progress_lock.acquire(blocking=False)):
            try:
                if self._closing:
                    raise TransportError(
                        f"rank {self.world_rank}: transport closed while "
                        f"parked")
                drained = self._drain_once()
            finally:
                self._progress_lock.release()
        if not drained and self.mailbox.deliveries == before:
            # Lock-free spin before the futex nap (same 1-core rationale
            # as _progress_wait's _SPIN_S phase): senders ring OUR
            # doorbell on every frame, so polling the bell word catches
            # a frame landing microseconds after the drain pass without
            # paying a futex sleep/wake round-trip — and without the
            # progress lock, which must stay free for user receivers.
            if _SPIN_S > 0.0:
                spin_deadline = time.monotonic() + min(_SPIN_S, timeout)
                while (time.monotonic() < spin_deadline
                       and not self._closing
                       and self._lib.shmdb_read(self._db) == seen):
                    os.sched_yield()
            if (not self._closing
                    and self._lib.shmdb_read(self._db) == seen):
                self._lib.shmdb_wait(self._db, seen, timeout)
            # the bell rang (or the slice expired): pull whatever
            # arrived before reporting, unless a user receiver already
            # owns the engine — their inline drain delivers it
            if (self._user_waiters == 0
                    and self._progress_lock.acquire(blocking=False)):
                try:
                    if not self._closing:
                        self._drain_once()
                finally:
                    self._progress_lock.release()
        return self.mailbox.deliveries != before

    # -- Transport interface (incoming) ------------------------------------

    def recv(self, source: int, ctx, tag: int,
             timeout: Optional[float] = None) -> Tuple[Any, int, int]:
        return self._blocking_match("recv", source, ctx, tag, timeout, True)

    def poll(self, source: int, ctx, tag: int):
        if self._progress_lock.acquire(blocking=False):
            try:
                if not self._closing:
                    self._drain_once()
            finally:
                self._progress_lock.release()
        return self.mailbox.poll(source, ctx, tag)

    def peek(self, source: int, ctx, tag: int,
             timeout: Optional[float] = None):
        n, s, t = self._blocking_match("probe", source, ctx, tag, timeout,
                                       False)
        return s, t, n

    def peek_nowait(self, source: int, ctx, tag: int):
        if self._progress_lock.acquire(blocking=False):
            try:
                if not self._closing:
                    self._drain_once()
            finally:
                self._progress_lock.release()
        return self.mailbox.peek_nowait(source, ctx, tag)

    # -- outgoing ----------------------------------------------------------

    def _send_lock(self, dest: int) -> threading.Lock:
        with self._state_lock:
            if self._closing:
                raise TransportError(
                    f"rank {self.world_rank}: send on a closed transport")
            lock = self._send_locks.get(dest)
            if lock is None:
                lock = self._send_locks[dest] = threading.Lock()
            return lock

    def _peer_epoch_once(self, dest: int) -> Optional[int]:
        """Epoch stamped in the peer's shm readiness file, or None when
        not (yet) published.  Pre-epoch files ('ready') read as 0."""
        try:
            with open(os.path.join(self._rdv, f"shm.{dest}")) as f:
                text = f.read().strip()
        except OSError:
            return None
        try:
            return int(text)
        except ValueError:
            return 0

    def _out_ring_locked(self, dest: int) -> int:
        with self._state_lock:
            ring = self._out_rings.get(dest)
        if ring is not None:
            return ring
        # Wait for the peer to have created its incoming rings — at an
        # acceptable membership epoch.  Three readiness-stamp cases:
        # newer than ours = WE were shrunk out (EpochSkewError, the
        # diagnosed straggler); below min_peer_epoch[dest] = the STALE
        # incarnation's leftover file on a replaced slot (keep polling
        # for the rejoiner's republish — mapping the old segment would
        # stream bytes into a corpse's ring); otherwise open.
        need = self.min_peer_epoch.get(dest, 0)
        deadline = time.monotonic() + self._connect_timeout
        skew_since = None
        while True:
            fe = self._peer_epoch_once(dest)
            if fe is not None:
                if fe > self.epoch:
                    # grace before the skew verdict (mirrors the socket
                    # hello): our own epoch bump may be milliseconds
                    # behind a broadcast transition — self.epoch is
                    # re-read every poll round.  A genuinely ousted
                    # straggler never catches up and still raises.
                    if skew_since is None:
                        skew_since = time.monotonic()
                    if time.monotonic() - skew_since > _EPOCH_GRACE_S:
                        _mpit.count(epoch_skews=1)
                        raise EpochSkewError(
                            f"rank {self.world_rank}: peer {dest} "
                            f"published shm endpoints at membership "
                            f"epoch {fe}, this process at {self.epoch} "
                            f"— this process was shrunk out of the "
                            f"world (stale-epoch straggler)",
                            local_epoch=self.epoch, peer_epoch=fe,
                            peer=dest)
                elif fe >= need:
                    break
                else:
                    skew_since = None
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rank {self.world_rank}: peer {dest} did not publish "
                    f"shm readiness at epoch >= {need} within "
                    f"{self._connect_timeout}s")
            time.sleep(0.005)
        name = _ring_name(self._session, self.world_rank, dest)
        ring = self._lib.shmring_open(name, self._connect_timeout)
        if not ring:
            raise TransportError(
                f"rank {self.world_rank}: shmring_open({name!r}) failed")
        db = self._lib.shmdb_open(_db_name(self._session, dest),
                                  self._connect_timeout)
        if not db:
            raise TransportError(
                f"rank {self.world_rank}: doorbell open for {dest} failed")
        with self._state_lock:
            self._out_rings[dest] = ring
            self._out_dbs[dest] = db
        return ring

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        if not (0 <= dest < self.world_size):
            raise ValueError(
                f"dest {dest} out of range for world size {self.world_size}")
        if self._closing:
            raise TransportError(
                f"rank {self.world_rank}: send on a closed transport")
        if dest == self.world_rank:
            # count the delivery on its steering channel first: loopback
            # traffic on a counted envelope consumes posted slots like
            # any other arrival (its own (self, ctx, tag) channel —
            # never interleaved with a peer ring's frame order)
            reg = self.recv_registry
            if tag < 0 or (reg.user_count
                           and reg.user_active(dest, ctx, tag)):
                reg.note_local(dest, ctx, tag)
            vc = self.verify_clock
            stamp = vc.tick_send() if vc is not None else None
            self.mailbox.deliver(dest, ctx, tag, codec.value_copy(payload),
                                 stamp)
            # ring our own bell: a thread parked in _match_loop's
            # doorbell-wait branch (lost the progress-lock race) waits on
            # the bell, not the mailbox cv — without this it would sleep
            # its full nap slice before noticing the local delivery
            self._lib.shmdb_ring(self._db)
            return
        vc = self.verify_clock
        if vc is not None:
            # stamp rides inside the frame (the ctx slot of the meta /
            # pickle body); the ring reader unwraps right after parse
            ctx = vc.wrap(ctx)
        frame = codec.pack_raw_frame(ctx, tag, payload)
        if frame is not None:
            head, bufs = frame
            body = len(head) + sum(b.nbytes for b in bufs)
            header = _LEN.pack(codec.RAW_FLAG | body)
            with self._send_lock(dest):
                if self._closing:  # close() may have held this lock first
                    raise TransportError(
                        f"rank {self.world_rank}: send on a closed transport")
                ring = self._out_ring_locked(dest)
                if body <= _SMALL:
                    frame = header + head + b"".join(
                        b.tobytes() for b in bufs)
                    self._write_all(ring, frame, len(frame), dest)
                    self._lib.shmdb_ring(self._out_dbs[dest])
                    return
                # big frame: header+meta, bell, then the raw bytes straight
                # from each array's own buffer — the single send-side copy
                # is the in-C memcpy into the ring (see send() pickle path
                # below for why the bell precedes the body)
                prefix = header + head
                self._write_all(ring, prefix, len(prefix), dest)
                self._lib.shmdb_ring(self._out_dbs[dest])
                for b in bufs:
                    if b.nbytes:
                        self._write_all(ring, b.ctypes.data, b.nbytes, dest)
            return
        blob = codec.pack_pickle_body(ctx, tag, payload)
        with self._send_lock(dest):
            if self._closing:  # close() may have held this lock before us
                raise TransportError(
                    f"rank {self.world_rank}: send on a closed transport")
            ring = self._out_ring_locked(dest)
            if len(blob) <= _SMALL:
                # tiny: concat header+blob — one C call beats a second
                # call's overhead, the whole frame commits atomically, and
                # the bell fires with the frame complete
                frame = _LEN.pack(len(blob)) + blob
                self._write_all(ring, frame, len(frame), dest)
                self._lib.shmdb_ring(self._out_dbs[dest])
                return
            # Larger frames: header first, then the bell, THEN the body.
            # The bell wakes the receiver before the body write so (a) a
            # frame bigger than the ring streams against a live reader
            # (ringing only after a full-frame write would deadlock until
            # the receiver's nap timeout) and (b) a body-write timeout
            # leaves a reader mid-frame, not an orphaned header silently
            # misframing the stream.  The body-read futex-handshakes with
            # the streaming write per chunk (in-ring wseq/rseq futexes),
            # so no further bell is needed.
            header = _LEN.pack(len(blob))
            self._write_all(ring, header, len(header), dest)
            self._lib.shmdb_ring(self._out_dbs[dest])
            self._write_all(ring, blob, len(blob), dest)

    def _peer_suspected(self, peer: int) -> bool:
        """True once the ULFM detector (mpi_tpu/ft.py, attached to this
        transport by ft.enable) has declared ``peer`` dead.  Consulted
        between native wait slices on BOTH no-progress paths — a sender
        stuck mid-frame in a dead consumer's full ring, and a reader
        stuck mid-frame from a dead producer — so the data plane gives
        up within the detection bound instead of spinning out the full
        ``shm_write_timeout_s`` stall constant (FT residual (a))."""
        ft = getattr(self, "_ft_world", None)
        return ft is not None and peer in ft.failed

    def _write_all(self, ring: int, buf, n: int, dest: int) -> None:
        """Stream exactly ``n`` bytes into ``ring`` in short native slices
        (same teardown/dead-peer rationale as _read_exact).  ``buf`` is
        bytes (passed straight to C — the common whole-frame-fits case
        costs ONE ctypes call) or a raw int address; the resume path
        switches to address+offset arithmetic.  The caller keeps the
        buffer's owner alive across the call."""
        done = self._lib.shmring_write_some(ring, buf, n, _PROGRESS_SLICE)
        if done == n:
            return
        addr = buf if isinstance(buf, int) else _addr(buf)
        stall = time.monotonic() + _WRITE_TIMEOUT
        while done < n:
            if self._closing:
                raise TransportError(
                    f"rank {self.world_rank}: transport closed during send "
                    f"to {dest}")
            if self._peer_suspected(dest):
                raise TransportError(
                    f"rank {self.world_rank}: send to {dest} aborted "
                    f"mid-frame ({done}/{n} bytes): the failure detector "
                    f"declared rank {dest} dead (its ring will never "
                    f"drain)")
            if time.monotonic() > stall:
                raise TransportError(
                    f"rank {self.world_rank}: send to {dest} timed out "
                    f"({n} bytes; ring full for {_WRITE_TIMEOUT}s — is the "
                    f"receiver alive?)")
            got = self._lib.shmring_write_some(
                ring, addr + done, n - done, _PROGRESS_SLICE)
            if got:
                done += got
                stall = time.monotonic() + _WRITE_TIMEOUT

    # -- membership (mpi_tpu/membership.py) --------------------------------

    def _write_readiness(self) -> None:
        """Atomically publish ``shm.<rank>`` containing the CURRENT
        epoch — the one spelling shared by startup and epoch-transition
        republish (the stamp format must never diverge between them)."""
        tmp = os.path.join(self._rdv, f".shm.{self.world_rank}.tmp")
        with open(tmp, "w") as f:
            f.write(str(self.epoch))
        os.replace(tmp, os.path.join(self._rdv,
                                     f"shm.{self.world_rank}"))

    def membership_republish(self) -> None:
        """Re-stamp this rank's readiness file with the CURRENT epoch
        (called by survivor_transition after an epoch bump): shm has no
        per-connection hello, so the readiness stamp is where a stale
        straggler doing a fresh ring-open against a survivor reads the
        skew and raises EpochSkewError instead of mapping segments of a
        world that moved on.  It is ALSO the replacement's green light:
        a rejoiner requires every peer's stamp to reach its epoch
        before opening rings (membership.rejoin_transport), which is
        what guarantees it never appends to an inbound ring this
        survivor has not yet recreated (membership_invalidate below)."""
        try:
            self._write_readiness()
        except OSError:
            pass  # rendezvous dir tearing down — world is exiting

    def membership_invalidate(self, dead) -> None:
        """Epoch transition, shm edition.  Two halves per replaced slot:

        * OUTGOING rings/doorbells are dropped: their segments belong
          to the dead incarnation (the rejoiner recreates its own
          inbound side under the new epoch).  Takes each per-dest send
          lock — a sender still streaming into the old ring must exit
          first (the _peer_suspected check bounds that to the
          detection timeout) before its mapping is unmapped.
        * INBOUND rings from the slot are RECREATED (close + fresh
          shmring_create, which unlinks the stale segment): the corpse
          may have died mid-frame, leaving the byte stream desynced
          (quarantined in ``_dead_srcs``), and the replacement must
          never append to that garbage — it only opens our rings after
          our readiness file shows the new epoch (membership_republish
          runs after this, see survivor_transition).  The swap holds
          the progress lock: the drain loop iterates these rings.
        """
        for dest in dead:
            try:
                lock = self._send_lock(dest)
            except TransportError:
                return  # transport closing: close() tears everything down
            with lock:
                with self._state_lock:
                    ring = self._out_rings.pop(dest, None)
                    db = self._out_dbs.pop(dest, None)
                if ring is not None:
                    self._lib.shmring_close(ring)
                if db is not None:
                    self._lib.shmdb_close(db)
        with self._progress_lock:
            if self._closing:
                return
            for src in dead:
                # fence the steering registry to the slot's NEXT stream
                # generation before (re)creating anything: the purged
                # ring's in-flight frames died with it, and the bumped
                # gen keeps the replacement's fresh stream from ever
                # pairing against old-incarnation counts (the shm
                # edition of the socket link's purge_peer + purge_src)
                gen = self._rx_gen.get(int(src), 0) + 1
                self._rx_gen[int(src)] = gen
                self._rx_seq[int(src)] = 0
                self.recv_registry.purge_src(int(src), gen)
                old = self._in_rings.pop(int(src), None)
                if old is None:
                    continue
                self._lib.shmring_close(old)
                name = _ring_name(self._session, int(src),
                                  self.world_rank)
                ring = self._lib.shmring_create(name, self._ring_bytes)
                if ring:
                    self._in_rings[int(src)] = ring
                    self._dead_srcs.discard(int(src))
                # creation failure leaves the channel out of the scan:
                # sends from the replacement would time out loudly
            self._in_items = list(self._in_rings.items())

    # -- shutdown ----------------------------------------------------------

    def close(self) -> None:
        self._closing = True
        # coll/sm arenas of every communicator over this transport: close
        # the mapping (the owning rank also unlinks the name).  Arena
        # waits re-check _closed each slice, so a straggler blocked in a
        # flag wait surfaces a TransportError instead of touching a
        # freed mapping.
        for arena in list(getattr(self, "_coll_arenas", {}).values()):
            # pooled lease arenas (ISSUE 11/12): every closing handle
            # unlinks — their creator may be a dead worker whose close
            # never ran, and a name nobody unlinks outlives the process
            arena.close(force_unlink=getattr(arena, "_pooled", False))
        if self._db:
            self._lib.shmdb_ring(self._db)  # pop any thread out of its nap
        if self._helper.is_alive():
            self._helper.join(timeout=2.0)
        # exclude in-flight receivers (progress lock) AND in-flight senders
        # (every per-dest send lock) before unmapping anything a concurrent
        # memcpy could still be streaming into
        with self._state_lock:
            send_locks = list(self._send_locks.values())
        for lock in send_locks:
            lock.acquire()
        try:
            with self._progress_lock:
                with self._state_lock:
                    for ring in self._out_rings.values():
                        self._lib.shmring_close(ring)
                    for db in self._out_dbs.values():
                        self._lib.shmdb_close(db)
                    self._out_rings.clear()
                    self._out_dbs.clear()
                for src, ring in self._in_rings.items():
                    self._lib.shmring_close(ring)
                    self._lib.shmring_unlink(
                        _ring_name(self._session, src, self.world_rank))
                self._in_rings.clear()
                self._in_items = []
                # unlink the doorbell NAME but keep the mapping alive: a
                # waiter may still be inside shmdb_wait on it, and the
                # 1-page mapping is reclaimed at process exit anyway
                self._lib.shmdb_unlink(
                    _db_name(self._session, self.world_rank))
        finally:
            for lock in send_locks:
                lock.release()
        self.mailbox.close()
