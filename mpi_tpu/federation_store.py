"""Replicated namespace store under the federation fabric (ISSUE 18).

PR 15 federated ``launcher serve`` over a shared namespace DIRECTORY —
single-host/NFS scope, with one accepted race in the leader-lease
takeover (the re-stat → unlink gap).  This module converts both in one
move: the namespace becomes a pluggable :class:`NamespaceStore`
(get / put / compare-and-swap / scan / watch, plus the append-only
interval logs the split-brain assertion reads), with two backends:

* :class:`FileStore` — today's directory, with CAS made ATOMIC.  Each
  key's state lives in versioned files ``<key>.v<N>.json``; a write
  publishes a fully-written temp file onto ``<key>.v<N+1>.json`` with
  ``os.link`` (O_EXCL no-clobber semantics — the atomic-rename family
  member that FAILS instead of overwriting), so the version slot
  itself is the arbiter.  Two racing writers both target the SAME slot
  and exactly one link succeeds; a holder frozen (SIGSTOP) between its
  read and its publish loses the slot to the takeover and its thawed
  publish fails with EEXIST — the PR-15 re-stat→unlink window is
  structurally closed, not shrunk.  Deletes publish a tombstone
  version (same arbitration); readers take the highest parseable
  version.

* :class:`RaftStore` — N store nodes (one embedded in each federation
  server) running a Raft-shaped consensus (Ongaro & Ousterhout 2014):
  terms, randomized election timeouts, majority-vote leader election
  with the log up-to-date check, an append-only replicated log with
  quorum-acked commit, conflict truncation, and snapshot compaction.
  Every mutation is a log command applied DETERMINISTICALLY on every
  node (a CAS is decided at apply time; the new version IS the log
  index), with an applied-nonce table making client retries
  exactly-once.  Node links ride the PR-10 resilience primitives
  (``retry_connect`` + jittered ``backoff_delays``) with monotone
  per-peer sequence stamping (a receiver drops seq regressions, so a
  reconnect's overlap window cannot re-deliver); loss across
  reconnects is healed by Raft's own heartbeat retransmission, and
  duplication is idempotent by term/index checks plus the nonce table.

Partition semantics (the Chubby-shaped degradation): a node that
cannot commit (minority side, or no elected leader) raises the NAMED
:class:`~mpi_tpu.errors.NoQuorumError` from every mutation, and
reports ``healthy() == False`` — which is what makes the federation
tier refuse leader authority and fail client admissions on the
minority side while the majority keeps serving.  Reads are served
from local applied state, stale-but-honest (endpoint discovery must
keep working on both sides so orphans re-converge after heal).

Fault injection: :meth:`RaftNode.install_partition` installs a
``{node_id: group}`` map into the LIVE store (the
``install_link_faults`` idiom) — node-to-node messages crossing
groups are dropped on both send and receive (``store_partition_
dropped`` pvar + trace instants); control-RPC connections are exempt
(they model the operator's out-of-band console, which is how
``bench.py --chaos --federation --partition`` installs and heals the
partition from outside).  ``MPI_TPU_STORE_CHAOS=1`` additionally
exposes partition install/heal + node stats over the store's RPC
port for subprocess fabrics.

Deliberate non-goals (honest residuals, see ROADMAP): static
membership (no joint-consensus reconfiguration), no durable raft
state across a node restart (a SIGKILLed server's store node does not
rejoin the group in-term), interval logs are compacted into snapshots
whole (memory grows with reign churn), and wall-clock lease stamps
assume NTP-grade skew between real hosts.
"""

from __future__ import annotations

import json
import os
import pickle
import queue
import random
import re
import socket
import struct
import threading
import time
import uuid
import weakref
from collections import deque, namedtuple
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import mpit as _mpit
from . import resilience as _resilience
from . import telemetry as _telemetry
from .errors import NoQuorumError

__all__ = [
    "Rec", "NamespaceStore", "FileStore", "RaftNode", "RaftStore",
    "RaftClientStore", "Watcher", "NoQuorumError",
    "resolve_store", "resolve_member_store", "client_spec",
    "parse_member_spec", "install_store_partition", "store_gauge",
]

#: One committed record: ``value`` (a JSON-able dict), ``ver`` (the CAS
#: token — FileStore: the version-slot number; RaftStore: the log index
#: of the committing command) and ``stamp`` (writer wall time, what
#: lease staleness is judged from).
Rec = namedtuple("Rec", ("value", "ver", "stamp"))

_FRAME = struct.Struct("!I")

# Raft timing (seconds).  Election timeout is randomized in
# [T, 2T); heartbeats at T/4.  Defaults keep elections well under the
# federation lease bound (2-3s in the chaos legs) while staying lazy
# enough for a loaded 2-core CI box; override per-fabric via env.
_ELECT_S = float(os.environ.get("MPI_TPU_STORE_ELECT_S", "0.6"))
_PROPOSE_TIMEOUT_S = float(os.environ.get(
    "MPI_TPU_STORE_PROPOSE_S", str(max(2.0, 4 * _ELECT_S))))
# log length that triggers snapshot compaction (small enough that the
# committed chaos artifact proves compaction fired mid-run)
_SNAP_THRESHOLD = int(os.environ.get("MPI_TPU_STORE_SNAP_N", "256"))
_WATCH_POLL_S = 0.1
_TOMBSTONE_GC_S = 60.0

# live RaftNodes in this process (the store_term / store_commit_index
# gauge pvars in mpit.py read the max over these)
_NODES: "weakref.WeakSet[RaftNode]" = weakref.WeakSet()


def store_gauge(field: str) -> int:
    """Max of ``field`` over this process's live store nodes (gauge
    pvar hook — 0 with no node, so the off-mode pvar contract holds)."""
    best = 0
    for node in list(_NODES):
        best = max(best, int(getattr(node, field, 0)))
    return best


def install_store_partition(mapping: Optional[Dict[int, int]]) -> int:
    """Install (or heal, with None) a partition map into every live
    store node of THIS process — the ``install_link_faults`` idiom at
    the store tier.  Returns the number of nodes updated."""
    n = 0
    for node in list(_NODES):
        node.install_partition(mapping)
        n += 1
    return n


# -- the interface ------------------------------------------------------------


class NamespaceStore:
    """What the federation tier needs from a namespace: a small
    versioned KV with atomic compare-and-swap (the lease primitive),
    prefix scan/watch (endpoint + ownership records), and per-key
    append-only logs (the leader authority intervals).  ``ver`` tokens
    are opaque ints: pass a read's ``ver`` back to :meth:`cas`;
    ``expect_ver=None`` means "only if absent" (the O_EXCL-create
    shape).  Implementations raise :class:`NoQuorumError` from
    mutations they cannot commit — callers treat that as "authority
    refused", never as success or plain failure."""

    def get(self, key: str) -> Optional[Rec]:
        raise NotImplementedError

    def cas(self, key: str, expect_ver: Optional[int],
            value: dict) -> Optional[Rec]:
        """Atomic: write ``value`` iff the key's current version is
        ``expect_ver`` (None = absent).  Returns the new Rec, or None
        on a lost race / stale expectation."""
        raise NotImplementedError

    def put(self, key: str, value: dict) -> Rec:
        """Unconditional upsert (bounded internal CAS retry)."""
        for _ in range(64):
            cur = self.get(key)
            rec = self.cas(key, None if cur is None else cur.ver, value)
            if rec is not None:
                return rec
        raise OSError(f"store put({key!r}): persistent CAS contention")

    def delete(self, key: str, expect_ver: Optional[int] = None) -> bool:
        raise NotImplementedError

    def scan(self, prefix: str) -> Dict[str, Rec]:
        raise NotImplementedError

    def append(self, key: str, record: dict) -> None:
        raise NotImplementedError

    def log_scan(self, prefix: str) -> Dict[str, List[dict]]:
        raise NotImplementedError

    def watch(self, prefix: str) -> "Watcher":
        return Watcher(lambda: self.scan(prefix))

    def healthy(self) -> bool:
        """Can a mutation commit right now?  FileStore: always (the
        directory IS the quorum); RaftStore: quorum reachability."""
        return True

    def describe(self) -> str:
        return type(self).__name__

    def close(self) -> None:
        pass


class Watcher:
    """Polling change feed over a prefix scan: a daemon thread diffs
    versions each ``_WATCH_POLL_S`` and queues ``(key, rec_or_None)``
    events (None = deleted).  Uniform across backends — RaftStore
    local state and FileStore directories poll equally well at
    federation cadences."""

    def __init__(self, poll: Callable[[], Dict[str, Rec]],
                 interval: float = _WATCH_POLL_S) -> None:
        self._poll = poll
        self._interval = interval
        self._events: "queue.Queue[Tuple[str, Optional[Rec]]]" = \
            queue.Queue()
        self._stop = threading.Event()
        self._seen: Dict[str, int] = {k: r.ver
                                      for k, r in poll().items()}
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="store-watch")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                cur = self._poll()
            except (OSError, NoQuorumError):
                continue  # store tearing down / partitioned: re-poll
            for k, r in cur.items():
                if self._seen.get(k) != r.ver:
                    self._seen[k] = r.ver
                    self._events.put((k, r))
            for k in [k for k in self._seen if k not in cur]:
                del self._seen[k]
                self._events.put((k, None))

    def next(self, timeout: Optional[float] = None
             ) -> Optional[Tuple[str, Optional[Rec]]]:
        try:
            return self._events.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()


# -- file backend -------------------------------------------------------------


_VER_RE = re.compile(r"^(?P<key>.+)\.v(?P<ver>\d+)\.json$")


class FileStore(NamespaceStore):
    """The namespace directory, with ATOMIC CAS (see module docstring
    for the version-slot arbitration that closes the PR-15 takeover
    race).  Stateless per instance — any number of processes/handles
    on one directory compose; the directory is the shared truth."""

    #: test seam (SIGSTOP-in-the-window regression): called between the
    #: current-version read and the publish link of every cas()
    _test_mid_cas: Optional[Callable[[str], None]] = None

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- internals --

    def _versions(self, names: List[str]) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for name in names:
            m = _VER_RE.match(name)
            if m:
                out.setdefault(m.group("key"), []).append(
                    int(m.group("ver")))
        for vers in out.values():
            vers.sort(reverse=True)
        return out

    def _names(self) -> List[str]:
        try:
            return os.listdir(self.root)
        except OSError:
            return []

    def _read_ver(self, key: str, ver: int) -> Optional[dict]:
        try:
            with open(os.path.join(self.root,
                                   f"{key}.v{ver}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # vanished (GC) or mid-write: caller falls back

    def _current(self, key: str,
                 vers: Optional[List[int]] = None
                 ) -> Tuple[Optional[dict], int]:
        """(wrapper, ver) of the highest parseable version; (None, 0)
        for a key with no versions at all.  A tombstone wrapper is
        returned as-is — callers distinguish deleted from absent."""
        if vers is None:
            vers = self._versions(self._names()).get(key, [])
        for v in vers:
            w = self._read_ver(key, v)
            if w is not None:
                return w, v
        return None, 0

    # -- interface --

    def get(self, key: str) -> Optional[Rec]:
        w, v = self._current(key)
        if w is None or w.get("dead"):
            return None
        return Rec(w.get("v"), v, float(w.get("stamp", 0.0)))

    def cas(self, key: str, expect_ver: Optional[int],
            value: Optional[dict], _dead: bool = False
            ) -> Optional[Rec]:
        vers = self._versions(self._names()).get(key, [])
        w, cur = self._current(key, vers)
        live = w is not None and not w.get("dead")
        if expect_ver is None:
            if live:
                return None
        elif not live or cur != expect_ver:
            return None
        if self._test_mid_cas is not None:
            self._test_mid_cas(key)
        # epoch check (ISSUE 19 satellite): the successor slot must top
        # EVERY existing slot NUMBER, not just the highest parseable
        # one — inside the tombstone-GC window a recreate can observe a
        # chain of truncated placeholders (cur == 0) whose names are
        # still on disk; cur + 1 would collide with (EEXIST) or recycle
        # one of them, handing a straggler frozen on the dead chain a
        # silent win over the recreated key
        new_ver = max(vers[0] if vers else 0, cur) + 1
        stamp = time.time()
        wrapper = {"v": value, "stamp": stamp}
        if _dead:
            wrapper["dead"] = True
        tmp = os.path.join(self.root,
                           f".tmp.{uuid.uuid4().hex}")
        final = os.path.join(self.root, f"{key}.v{new_ver}.json")
        with open(tmp, "w") as f:
            json.dump(wrapper, f)
        try:
            # the atomic arbitration: link() is create-exclusive — the
            # FIRST writer owns slot v<N+1>, every straggler (including
            # a SIGSTOP-thawed holder whose read predates the winner's
            # publish) gets EEXIST and reports the lost race
            os.link(tmp, final)
        except FileExistsError:
            return None
        except OSError:
            return None  # namespace tearing down
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        # superseded-version GC: keep one predecessor's CONTENT as the
        # readers' mid-publish fallback; older slots are truncated to
        # empty placeholders but NEVER unlinked — the slot NAME is the
        # arbitration token, and a recycled name would hand a straggler
        # frozen past two generations a silent win over a newer commit
        # (the lost-update variant of the PR-15 window).  The walk
        # stops at the first already-empty slot, so it is amortized
        # O(1); empty placeholders parse-fail in _read_ver and cost
        # readers nothing on the happy path.
        for v in range(new_ver - 2, 0, -1):
            p = os.path.join(self.root, f"{key}.v{v}.json")
            try:
                if os.path.getsize(p) == 0:
                    break
                os.truncate(p, 0)
            except OSError:
                break
        return Rec(value, new_ver, stamp)

    def delete(self, key: str, expect_ver: Optional[int] = None) -> bool:
        for _ in range(64):
            w, cur = self._current(key)
            live = w is not None and not w.get("dead")
            if not live:
                return expect_ver is None  # already gone
            if expect_ver is not None and cur != expect_ver:
                return False
            if self.cas(key, cur, None, _dead=True) is not None:
                return True
            if expect_ver is not None:
                return False
        return False

    def scan(self, prefix: str) -> Dict[str, Rec]:
        names = self._names()
        out: Dict[str, Rec] = {}
        now = time.time()
        for key, vers in self._versions(names).items():
            if not key.startswith(prefix):
                continue
            w, v = self._current(key, vers)
            if w is None:
                continue
            if w.get("dead"):
                # opportunistic tombstone GC: a long-dead key's version
                # chain is garbage once every reader has moved on
                if now - float(w.get("stamp", now)) > _TOMBSTONE_GC_S:
                    # unlink ASCENDING so the tombstone (the highest
                    # slot) goes LAST: a GC interrupted mid-chain
                    # leaves the key still visibly dead — removing the
                    # tombstone first would resurrect the stale
                    # predecessor value for every reader racing the
                    # delete/recreate window (ISSUE 19 satellite)
                    for vv in reversed(vers):
                        try:
                            os.unlink(os.path.join(
                                self.root, f"{key}.v{vv}.json"))
                        except OSError:
                            pass
                continue
            out[key] = Rec(w.get("v"), v, float(w.get("stamp", 0.0)))
        return out

    def append(self, key: str, record: dict) -> None:
        # one writer per log key (leader.log.<id>) + O_APPEND: the
        # same appender contract the PR-15 interval logs shipped with
        with open(os.path.join(self.root, key), "a") as f:
            f.write(json.dumps(record) + "\n")

    def log_scan(self, prefix: str) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        for name in self._names():
            if not name.startswith(prefix) or _VER_RE.match(name) \
                    or name.startswith(".tmp."):
                continue
            entries: List[dict] = []
            try:
                with open(os.path.join(self.root, name)) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            entries.append(json.loads(line))
            except (OSError, ValueError):
                continue
            out[name] = entries
        return out

    def describe(self) -> str:
        return self.root


# -- raft backend -------------------------------------------------------------


def _send_frame(sock: socket.socket, lock: Optional[threading.Lock],
                msg: dict) -> None:
    blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _FRAME.pack(len(blob)) + blob
    if lock is None:
        sock.sendall(frame)
    else:
        with lock:
            sock.sendall(frame)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    from .transport.socket import _recv_exact

    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        return None
    (n,) = _FRAME.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


class _PeerLink:
    """Outbound half of one node→peer link: a bounded queue drained by
    a daemon thread that (re)dials with the PR-10 resilience
    primitives and stamps a per-peer monotone ``seq`` on every frame
    (never reset across reconnects, so the receiver's monotone filter
    dedups any reconnect-overlap delivery).  Send-side losses are NOT
    retransmitted here — Raft's heartbeat cycle is the retransmission
    layer; this link only guarantees ordering and no-duplication."""

    def __init__(self, me: int, peer: int, addr: str) -> None:
        self.me, self.peer, self.addr = me, peer, addr
        self._q: "deque[dict]" = deque(maxlen=256)
        self._has = threading.Event()
        self._stop = threading.Event()
        self._seq = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"store-link-{me}->{peer}")
        self._thread.start()

    def send(self, msg: dict) -> None:
        with self._lock:
            self._seq += 1
            self._q.append({**msg, "seq": self._seq, "from": self.me})
        self._has.set()

    def _loop(self) -> None:
        sock: Optional[socket.socket] = None
        while not self._stop.is_set():
            if not self._has.wait(timeout=0.5):
                continue
            if sock is None:
                host, _, port = self.addr.rpartition(":")
                try:
                    sock = _resilience.retry_connect(
                        lambda: socket.create_connection(
                            (host, int(port)), timeout=2.0),
                        timeout_s=2.0)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    _send_frame(sock, None, {"t": "peer",
                                             "from": self.me})
                except OSError:
                    sock = None
                    # peer down: drop what queued (raft re-offers on
                    # its heartbeat cadence) and back off one beat
                    with self._lock:
                        self._q.clear()
                        self._has.clear()
                    self._stop.wait(0.25)
                    continue
            while True:
                with self._lock:
                    if not self._q:
                        self._has.clear()
                        break
                    msg = self._q.popleft()
                try:
                    _send_frame(sock, None, msg)
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                    break
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        self._has.set()


class _Future:
    __slots__ = ("_ev", "_res")

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._res: Any = None

    def set(self, res: Any) -> None:
        self._res = res
        self._ev.set()

    def wait(self, timeout: float) -> Optional[Any]:
        return self._res if self._ev.wait(timeout) else None


class RaftNode:
    """One member of the replicated store group (see module docstring
    for scope and the honest non-goals).  All state is guarded by one
    RLock; message handling never blocks on the network (sends go
    through :class:`_PeerLink` queues)."""

    def __init__(self, node_id: int, addrs: List[str],
                 elect_timeout_s: float = _ELECT_S,
                 snap_threshold: int = _SNAP_THRESHOLD) -> None:
        if not (0 <= node_id < len(addrs)):
            raise ValueError(
                f"store node id {node_id} outside addrs[{len(addrs)}]")
        self.nid = node_id
        self.addrs = list(addrs)
        self.n = len(addrs)
        self.majority = self.n // 2 + 1
        self._elect_s = float(elect_timeout_s)
        self._snap_threshold = int(snap_threshold)
        self._lock = threading.RLock()
        self._rng = random.Random(0x5710 + node_id)
        # raft state (volatile: no durable term/vote — restart = fresh
        # identity, a documented non-goal)
        self.term = 0
        self.voted_for: Optional[int] = None
        self.role = "follower"
        self.leader_id: Optional[int] = None
        self.log: List[dict] = []          # {"term": t, "cmd": {...}}
        self.base_index = 0                # last snapshot-covered index
        self.base_term = 0
        self.commit_index = 0
        self.applied_index = 0
        self._votes: set = set()
        self._next: Dict[int, int] = {}
        self._match: Dict[int, int] = {}
        # state machine
        self.kv: Dict[str, Tuple[Any, int, float]] = {}
        self.logs: Dict[str, List[dict]] = {}
        self._nonces: Dict[str, Any] = {}
        self._nonce_order: deque = deque()
        self._pending: Dict[str, _Future] = {}
        # liveness bookkeeping
        now = time.monotonic()
        self._last_heard = now
        self._last_ack: Dict[int, float] = {}
        self._deadline = now + self._rand_elect()
        self._last_hb = 0.0
        # fault injection + evidence counters
        self._partition: Optional[Dict[int, int]] = None
        self.elections = 0
        self.truncated_entries = 0
        self.snapshots = 0
        self.partition_dropped = 0
        self._rx_seq: Dict[int, int] = {}
        # wiring
        host, _, port = self.addrs[node_id].rpartition(":")
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(16)
        if int(port) == 0:
            a = self._listener.getsockname()
            self.addrs[node_id] = "%s:%d" % (a[0], a[1])
        self._stop = threading.Event()
        self._peers = {p: _PeerLink(node_id, p, self.addrs[p])
                       for p in range(self.n) if p != node_id}
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"store-accept-{node_id}"),
            threading.Thread(target=self._timer_loop, daemon=True,
                             name=f"store-timer-{node_id}"),
        ]
        for t in self._threads:
            t.start()
        _NODES.add(self)

    # -- helpers --

    def _rand_elect(self) -> float:
        return self._elect_s * (1.0 + self._rng.random())

    @property
    def addr(self) -> str:
        return self.addrs[self.nid]

    def _last_index(self) -> int:
        return self.base_index + len(self.log)

    def _term_at(self, idx: int) -> int:
        if idx == self.base_index:
            return self.base_term
        return self.log[idx - self.base_index - 1]["term"]

    def _entry(self, idx: int) -> dict:
        return self.log[idx - self.base_index - 1]

    def _blocked(self, peer: int) -> bool:
        p = self._partition
        if p is None:
            return False
        return p.get(self.nid) != p.get(peer)

    def install_partition(self,
                          mapping: Optional[Dict[int, int]]) -> None:
        """Install/heal the partition map (None heals).  Takes effect
        on the next frame in either direction — live injection."""
        with self._lock:
            self._partition = dict(mapping) if mapping else None
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("store", "partition_installed",
                     attrs={"node": self.nid,
                            "map": mapping or "healed"})

    def _send(self, peer: int, msg: dict) -> None:
        if self._blocked(peer):
            self.partition_dropped += 1
            _mpit.count(store_partition_dropped=1)
            return
        self._peers[peer].send(msg)

    def _broadcast(self, msg: dict) -> None:
        for p in self._peers:
            self._send(p, msg)

    # -- inbound --

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True,
                             name=f"store-conn-{self.nid}").start()

    def _conn_loop(self, conn: socket.socket) -> None:
        try:
            first = _recv_frame(conn)
            if first is None:
                return
            if first.get("t") == "peer":
                peer = int(first["from"])
                while True:
                    msg = _recv_frame(conn)
                    if msg is None:
                        return
                    self._on_peer_msg(peer, msg)
            else:
                # client RPC connection: request/reply, pipelined
                msg: Optional[dict] = first
                lock = threading.Lock()
                while msg is not None:
                    reply = self._rpc(msg)
                    try:
                        _send_frame(conn, lock, reply)
                    except OSError:
                        return
                    msg = _recv_frame(conn)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _on_peer_msg(self, peer: int, msg: dict) -> None:
        with self._lock:
            if self._blocked(peer):
                # inbound half of the injection: frames already in
                # flight when the map landed must not leak through
                self.partition_dropped += 1
                _mpit.count(store_partition_dropped=1)
                return
            seq = int(msg.get("seq", 0))
            if seq and seq <= self._rx_seq.get(peer, 0):
                return  # reconnect-overlap duplicate: sequenced drop
            if seq:
                self._rx_seq[peer] = seq
            t = msg.get("t")
            if t == "rv":
                self._on_request_vote(peer, msg)
            elif t == "rv_r":
                self._on_vote_reply(peer, msg)
            elif t == "ae":
                self._on_append_entries(peer, msg)
            elif t == "ae_r":
                self._on_append_reply(peer, msg)
            elif t == "snap":
                self._on_snapshot(peer, msg)
            elif t == "prop":
                self._on_propose_fwd(msg)

    # -- elections --

    def _step_down(self, term: int) -> None:
        self.term = term
        self.role = "follower"
        self.voted_for = None
        self._votes = set()
        self._deadline = time.monotonic() + self._rand_elect()

    def _timer_loop(self) -> None:
        while not self._stop.wait(0.05):
            with self._lock:
                now = time.monotonic()
                if self.role == "leader":
                    if now - self._last_hb >= self._elect_s / 4:
                        self._last_hb = now
                        for p in self._peers:
                            self._send_ae(p)
                elif now >= self._deadline:
                    self._start_election()
                self._maybe_snapshot()

    def _start_election(self) -> None:
        self.term += 1
        self.role = "candidate"
        self.voted_for = self.nid
        self._votes = {self.nid}
        self.leader_id = None
        self.elections += 1
        self._deadline = time.monotonic() + self._rand_elect()
        _mpit.count(store_elections=1)
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("store", "election_started",
                     attrs={"node": self.nid, "term": self.term})
        if self.n == 1:
            self._become_leader()
            return
        self._broadcast({"t": "rv", "term": self.term,
                         "cand": self.nid,
                         "lli": self._last_index(),
                         "llt": self._term_at(self._last_index())})

    def _on_request_vote(self, peer: int, msg: dict) -> None:
        if msg["term"] > self.term:
            self._step_down(msg["term"])
        granted = False
        if msg["term"] == self.term \
                and self.voted_for in (None, msg["cand"]):
            # the up-to-date check: never elect a leader whose log
            # would discard committed entries
            my_lli = self._last_index()
            my_llt = self._term_at(my_lli)
            if (msg["llt"], msg["lli"]) >= (my_llt, my_lli):
                granted = True
                self.voted_for = msg["cand"]
                self._deadline = time.monotonic() + self._rand_elect()
        self._send(peer, {"t": "rv_r", "term": self.term,
                          "granted": granted})

    def _on_vote_reply(self, peer: int, msg: dict) -> None:
        if msg["term"] > self.term:
            self._step_down(msg["term"])
            return
        if self.role != "candidate" or msg["term"] != self.term:
            return
        if msg.get("granted"):
            self._votes.add(peer)
            if len(self._votes) >= self.majority:
                self._become_leader()

    def _become_leader(self) -> None:
        self.role = "leader"
        self.leader_id = self.nid
        last = self._last_index()
        self._next = {p: last + 1 for p in self._peers}
        self._match = {p: 0 for p in self._peers}
        self._last_ack = {}
        self._last_hb = time.monotonic()
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("store", "leader_elected",
                     attrs={"node": self.nid, "term": self.term})
        for p in self._peers:
            self._send_ae(p)
        self._advance_commit()

    # -- replication --

    def _send_ae(self, peer: int) -> None:
        ni = self._next.get(peer, self._last_index() + 1)
        if ni <= self.base_index:
            self._send(peer, {"t": "snap", "term": self.term,
                              "lead": self.nid,
                              "idx": self.base_index,
                              "sterm": self.base_term,
                              "kv": dict(self.kv),
                              "logs": {k: list(v) for k, v
                                       in self.logs.items()},
                              "nonces": dict(self._nonces)})
            return
        prev = ni - 1
        entries = self.log[prev - self.base_index:
                           prev - self.base_index + 64]
        self._send(peer, {"t": "ae", "term": self.term,
                          "lead": self.nid, "pli": prev,
                          "plt": self._term_at(prev),
                          "ent": entries, "ci": self.commit_index})

    def _on_append_entries(self, peer: int, msg: dict) -> None:
        if msg["term"] < self.term:
            self._send(peer, {"t": "ae_r", "term": self.term,
                              "ok": False, "match": 0,
                              "hint": self._last_index()})
            return
        if msg["term"] > self.term or self.role != "follower":
            self._step_down(msg["term"])
        self.leader_id = msg["lead"]
        self._last_heard = time.monotonic()
        self._deadline = self._last_heard + self._rand_elect()
        pli, plt = int(msg["pli"]), int(msg["plt"])
        if pli < self.base_index or pli > self._last_index() \
                or self._term_at(pli) != plt:
            self._send(peer, {"t": "ae_r", "term": self.term,
                              "ok": False, "match": 0,
                              "hint": min(self._last_index(),
                                          max(self.base_index, pli))})
            return
        idx = pli
        for ent in msg["ent"]:
            idx += 1
            if idx <= self._last_index():
                if self._term_at(idx) == ent["term"]:
                    continue
                # conflict: truncate OUR uncommitted suffix — these
                # are the minority's stale intents being discarded
                dropped = self._last_index() - idx + 1
                del self.log[idx - self.base_index - 1:]
                self.truncated_entries += dropped
                _mpit.count(store_entries_truncated=dropped)
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("store", "log_truncated",
                             attrs={"node": self.nid, "at": idx,
                                    "dropped": dropped})
            self.log.append(ent)
        self.commit_index = max(self.commit_index,
                                min(int(msg["ci"]), self._last_index()))
        self._apply_ready()
        self._send(peer, {"t": "ae_r", "term": self.term, "ok": True,
                          "match": idx})

    def _on_append_reply(self, peer: int, msg: dict) -> None:
        if msg["term"] > self.term:
            self._step_down(msg["term"])
            return
        if self.role != "leader":
            return
        self._last_ack[peer] = time.monotonic()
        if msg.get("ok"):
            self._match[peer] = max(self._match.get(peer, 0),
                                    int(msg["match"]))
            self._next[peer] = self._match[peer] + 1
            if self._next[peer] <= self._last_index():
                self._send_ae(peer)  # keep streaming the backlog
            self._advance_commit()
        else:
            hint = int(msg.get("hint", 0))
            self._next[peer] = max(self.base_index,
                                   min(self._next.get(peer, 1) - 1,
                                       hint + 1))
            self._send_ae(peer)

    def _advance_commit(self) -> None:
        for idx in range(self._last_index(), self.commit_index, -1):
            if self._term_at(idx) != self.term:
                break  # only own-term entries commit by counting [Raft §5.4.2]
            acks = 1 + sum(1 for p in self._peers
                           if self._match.get(p, 0) >= idx)
            if acks >= self.majority:
                self.commit_index = idx
                self._apply_ready()
                break

    def _on_snapshot(self, peer: int, msg: dict) -> None:
        if msg["term"] < self.term:
            return
        if msg["term"] > self.term or self.role != "follower":
            self._step_down(msg["term"])
        self.leader_id = msg["lead"]
        self._last_heard = time.monotonic()
        self._deadline = self._last_heard + self._rand_elect()
        if int(msg["idx"]) <= self.base_index:
            return  # stale snapshot
        self.kv = dict(msg["kv"])
        self.logs = {k: list(v) for k, v in msg["logs"].items()}
        self._nonces = dict(msg["nonces"])
        self._nonce_order = deque(self._nonces)
        self.base_index = int(msg["idx"])
        self.base_term = int(msg["sterm"])
        self.log = []
        self.commit_index = max(self.commit_index, self.base_index)
        self.applied_index = self.base_index
        self._send(peer, {"t": "ae_r", "term": self.term, "ok": True,
                          "match": self.base_index})

    def _maybe_snapshot(self) -> None:
        if self.applied_index - self.base_index < self._snap_threshold:
            return
        drop = self.applied_index - self.base_index
        self.base_term = self._term_at(self.applied_index)
        del self.log[:drop]
        self.base_index = self.applied_index
        self.snapshots += 1
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("store", "snapshot_compacted",
                     attrs={"node": self.nid,
                            "through": self.base_index})

    # -- the state machine --

    def _apply_ready(self) -> None:
        while self.applied_index < self.commit_index:
            self.applied_index += 1
            ent = self._entry(self.applied_index)
            res = self._apply_cmd(ent["cmd"], self.applied_index)
            fut = self._pending.pop(ent["cmd"]["nonce"], None)
            if fut is not None:
                fut.set(res)

    def _apply_cmd(self, cmd: dict, idx: int) -> tuple:
        nonce = cmd["nonce"]
        if nonce in self._nonces:
            return self._nonces[nonce]  # exactly-once under retry
        op = cmd["op"]
        key = cmd.get("key")
        stamp = float(cmd.get("stamp", 0.0))
        cur = self.kv.get(key)
        if op == "cas":
            ev = cmd["ev"]
            if (ev is None) == (cur is None) \
                    and (cur is None or cur[1] == ev):
                self.kv[key] = (cmd["val"], idx, stamp)
                res = ("ok", idx, stamp)
            else:
                res = ("fail",)
        elif op == "put":
            self.kv[key] = (cmd["val"], idx, stamp)
            res = ("ok", idx, stamp)
        elif op == "del":
            ev = cmd["ev"]
            if cur is None:
                res = ("ok",) if ev is None else ("fail",)
            elif ev is None or cur[1] == ev:
                del self.kv[key]
                res = ("ok",)
            else:
                res = ("fail",)
        elif op == "append":
            self.logs.setdefault(key, []).append(cmd["rec"])
            res = ("ok",)
        else:
            res = ("fail",)
        self._nonces[nonce] = res
        self._nonce_order.append(nonce)
        while len(self._nonce_order) > 8192:
            self._nonces.pop(self._nonce_order.popleft(), None)
        return res

    # -- the write path --

    def propose(self, cmd: dict,
                timeout: float = _PROPOSE_TIMEOUT_S) -> tuple:
        """Commit one command through the group; returns the applied
        result.  Raises :class:`NoQuorumError` when no quorum commits
        it within ``timeout`` — the named minority verdict."""
        nonce = uuid.uuid4().hex
        cmd = {**cmd, "nonce": nonce, "stamp": time.time()}
        fut = _Future()
        deadline = time.monotonic() + timeout
        sent_to: Optional[Tuple[str, int]] = None
        last_send = 0.0
        with self._lock:
            self._pending[nonce] = fut
        try:
            while True:
                now = time.monotonic()
                with self._lock:
                    route = (("self", self.term)
                             if self.role == "leader"
                             else ("fwd%d" % self.leader_id, self.term)
                             if self.leader_id is not None
                             and self.leader_id != self.nid
                             else None)
                    if route is not None and (
                            route != sent_to or now - last_send > 0.6):
                        sent_to, last_send = route, now
                        if self.role == "leader":
                            self.log.append({"term": self.term,
                                             "cmd": cmd})
                            if self.n == 1:
                                self._advance_commit()
                            else:
                                for p in self._peers:
                                    self._send_ae(p)
                        else:
                            self._send(self.leader_id,
                                       {"t": "prop", "cmd": cmd})
                res = fut.wait(min(0.1, max(0.0, deadline - now)))
                if res is not None:
                    return res
                if time.monotonic() >= deadline:
                    raise NoQuorumError(
                        f"store node {self.nid}: no quorum committed "
                        f"the {cmd['op']}({cmd.get('key')!r}) within "
                        f"{timeout:.1f}s (role {self.role}, term "
                        f"{self.term}, leader {self.leader_id}) — "
                        f"minority side of a partition, or no elected "
                        f"store leader")
        finally:
            with self._lock:
                self._pending.pop(nonce, None)

    def _on_propose_fwd(self, msg: dict) -> None:
        cmd = msg["cmd"]
        if self.role == "leader":
            self.log.append({"term": self.term, "cmd": cmd})
            for p in self._peers:
                self._send_ae(p)
        elif self.leader_id is not None and self.leader_id != self.nid:
            self._send(self.leader_id, msg)  # one-hop re-forward

    # -- liveness / introspection --

    def healthy(self) -> bool:
        """Quorum reachability from THIS node: a leader with fresh
        majority acks, or a follower with fresh leader contact.  What
        the serve tier's admission fence and the LeaderLease consult —
        the minority side turns unhealthy within one election bound."""
        with self._lock:
            if self.n == 1:
                return True
            now = time.monotonic()
            window = 2.5 * self._elect_s
            if self.role == "leader":
                fresh = 1 + sum(1 for t in self._last_ack.values()
                                if now - t < window)
                return fresh >= self.majority
            return (self.leader_id is not None
                    and now - self._last_heard < window)

    def stats(self) -> dict:
        with self._lock:
            return {"node": self.nid, "addr": self.addr,
                    "role": self.role, "term": self.term,
                    "leader": self.leader_id,
                    "commit_index": self.commit_index,
                    "applied_index": self.applied_index,
                    "base_index": self.base_index,
                    "log_len": len(self.log),
                    "elections": self.elections,
                    "snapshots": self.snapshots,
                    "truncated_entries": self.truncated_entries,
                    "partition_dropped": self.partition_dropped,
                    "healthy": None,  # filled below, outside the lock
                    "keys": len(self.kv)}

    # -- client RPC --

    def _rpc(self, msg: dict) -> dict:
        t = msg.get("t")
        try:
            if t == "read":
                return self._rpc_read(msg)
            if t == "write":
                return self._rpc_write(msg)
            if t == "chaos":
                if os.environ.get("MPI_TPU_STORE_CHAOS") != "1":
                    return {"err": "ValueError",
                            "msg": "chaos RPC disabled "
                                   "(MPI_TPU_STORE_CHAOS != 1)"}
                return self._rpc_chaos(msg)
            return {"err": "ValueError", "msg": f"unknown rpc {t!r}"}
        except NoQuorumError as e:
            return {"err": "NoQuorumError", "msg": str(e)}
        except Exception as e:  # noqa: BLE001 - shipped to the client
            return {"err": type(e).__name__, "msg": str(e)[:300]}

    def _rpc_read(self, msg: dict) -> dict:
        op = msg["op"]
        with self._lock:
            if op == "get":
                return {"ok": True, "rec": self.kv.get(msg["key"])}
            if op == "scan":
                pre = msg["prefix"]
                return {"ok": True,
                        "recs": {k: v for k, v in self.kv.items()
                                 if k.startswith(pre)}}
            if op == "log_scan":
                pre = msg["prefix"]
                return {"ok": True,
                        "logs": {k: list(v)
                                 for k, v in self.logs.items()
                                 if k.startswith(pre)}}
            if op == "health":
                pass  # healthy() takes the lock itself, fall through
        if op == "health":
            return {"ok": True, "healthy": self.healthy()}
        return {"err": "ValueError", "msg": f"unknown read {op!r}"}

    def _rpc_write(self, msg: dict) -> dict:
        res = self.propose({k: msg[k] for k in
                            ("op", "key", "ev", "val", "rec")
                            if k in msg})
        return {"ok": True, "res": res}

    def _rpc_chaos(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "partition":
            self.install_partition(msg.get("map"))
            return {"ok": True}
        if op == "stats":
            st = self.stats()
            st["healthy"] = self.healthy()
            return {"ok": True, "stats": st}
        return {"err": "ValueError", "msg": f"unknown chaos {op!r}"}

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for link in self._peers.values():
            link.close()


def _rec_from_tuple(t: Optional[Tuple[Any, int, float]]
                    ) -> Optional[Rec]:
    return None if t is None else Rec(t[0], t[1], t[2])


class RaftStore(NamespaceStore):
    """Member-mode store handle: wraps this server's embedded
    :class:`RaftNode`.  Reads are local applied state (stale-ok);
    mutations are quorum commits that raise the named
    :class:`NoQuorumError` on the minority side."""

    def __init__(self, node: RaftNode, owns_node: bool = True) -> None:
        self.node = node
        self._owns = owns_node

    def get(self, key: str) -> Optional[Rec]:
        with self.node._lock:
            return _rec_from_tuple(self.node.kv.get(key))

    def cas(self, key: str, expect_ver: Optional[int],
            value: dict) -> Optional[Rec]:
        res = self.node.propose({"op": "cas", "key": key,
                                 "ev": expect_ver, "val": value})
        if res[0] != "ok":
            return None
        return Rec(value, res[1], res[2])

    def put(self, key: str, value: dict) -> Rec:
        res = self.node.propose({"op": "put", "key": key,
                                 "val": value})
        return Rec(value, res[1], res[2])

    def delete(self, key: str, expect_ver: Optional[int] = None) -> bool:
        res = self.node.propose({"op": "del", "key": key,
                                 "ev": expect_ver})
        return res[0] == "ok"

    def scan(self, prefix: str) -> Dict[str, Rec]:
        with self.node._lock:
            return {k: _rec_from_tuple(v)
                    for k, v in self.node.kv.items()
                    if k.startswith(prefix)}

    def append(self, key: str, record: dict) -> None:
        res = self.node.propose({"op": "append", "key": key,
                                 "rec": record})
        if res[0] != "ok":  # pragma: no cover - append never CAS-fails
            raise OSError(f"store append({key!r}) failed")

    def log_scan(self, prefix: str) -> Dict[str, List[dict]]:
        with self.node._lock:
            return {k: list(v) for k, v in self.node.logs.items()
                    if k.startswith(prefix)}

    def healthy(self) -> bool:
        return self.node.healthy()

    def describe(self) -> str:
        return f"raft:{self.node.nid}@{','.join(self.node.addrs)}"

    def close(self) -> None:
        if self._owns:
            self.node.close()


class RaftClientStore(NamespaceStore):
    """Membership-less store handle over the nodes' RPC port (workers
    resolving pool owners, namespace clients resolving endpoints).
    Reads come from whichever node answers first — possibly a stale
    minority during a partition, by design (discovery must work on
    both sides); mutations are forwarded through that node's quorum
    path and raise :class:`NoQuorumError` when it has none."""

    def __init__(self, addrs: List[str]) -> None:
        if not addrs:
            raise ValueError("RaftClientStore needs node addresses")
        self.addrs = list(addrs)
        self._sock: Optional[socket.socket] = None
        self._rr = 0
        self._lock = threading.Lock()

    def _rpc(self, msg: dict) -> dict:
        with self._lock:
            last: Optional[BaseException] = None
            for i in range(len(self.addrs) * 2):
                if self._sock is None:
                    addr = self.addrs[(self._rr + i) % len(self.addrs)]
                    host, _, port = addr.rpartition(":")
                    try:
                        self._sock = socket.create_connection(
                            (host, int(port)), timeout=2.0)
                        self._sock.settimeout(
                            max(5.0, _PROPOSE_TIMEOUT_S + 2.0))
                        self._rr += i + 1
                    except OSError as e:
                        last = e
                        continue
                try:
                    _send_frame(self._sock, None, msg)
                    reply = _recv_frame(self._sock)
                    if reply is None:
                        raise OSError("store rpc connection closed")
                    return reply
                except OSError as e:
                    last = e
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
            raise OSError(f"no store node reachable "
                          f"({self.addrs}): {last}")

    @staticmethod
    def _check(reply: dict) -> dict:
        err = reply.get("err")
        if err == "NoQuorumError":
            raise NoQuorumError(reply.get("msg", "no quorum"))
        if err:
            raise OSError(f"store rpc failed: {err}: "
                          f"{reply.get('msg')}")
        return reply

    def get(self, key: str) -> Optional[Rec]:
        r = self._check(self._rpc({"t": "read", "op": "get",
                                   "key": key}))
        return _rec_from_tuple(r.get("rec"))

    def cas(self, key: str, expect_ver: Optional[int],
            value: dict) -> Optional[Rec]:
        r = self._check(self._rpc({"t": "write", "op": "cas",
                                   "key": key, "ev": expect_ver,
                                   "val": value}))
        res = r["res"]
        return None if res[0] != "ok" else Rec(value, res[1], res[2])

    def put(self, key: str, value: dict) -> Rec:
        r = self._check(self._rpc({"t": "write", "op": "put",
                                   "key": key, "val": value}))
        res = r["res"]
        return Rec(value, res[1], res[2])

    def delete(self, key: str, expect_ver: Optional[int] = None) -> bool:
        r = self._check(self._rpc({"t": "write", "op": "del",
                                   "key": key, "ev": expect_ver}))
        return r["res"][0] == "ok"

    def scan(self, prefix: str) -> Dict[str, Rec]:
        r = self._check(self._rpc({"t": "read", "op": "scan",
                                   "prefix": prefix}))
        return {k: _rec_from_tuple(v) for k, v in r["recs"].items()}

    def append(self, key: str, record: dict) -> None:
        self._check(self._rpc({"t": "write", "op": "append",
                               "key": key, "rec": record}))

    def log_scan(self, prefix: str) -> Dict[str, List[dict]]:
        r = self._check(self._rpc({"t": "read", "op": "log_scan",
                                   "prefix": prefix}))
        return r["logs"]

    def healthy(self) -> bool:
        try:
            r = self._check(self._rpc({"t": "read", "op": "health"}))
        except (OSError, NoQuorumError):
            return False
        return bool(r.get("healthy"))

    def chaos(self, node_addr: str, msg: dict) -> dict:
        """Send a chaos RPC to ONE SPECIFIC node (partition install /
        stats) — a fresh connection, so the sticky read socket keeps
        its node affinity."""
        host, _, port = node_addr.rpartition(":")
        with socket.create_connection((host, int(port)),
                                      timeout=5.0) as s:
            s.settimeout(10.0)
            _send_frame(s, None, {"t": "chaos", **msg})
            reply = _recv_frame(s)
        if reply is None:
            raise OSError("chaos rpc connection closed")
        return self._check(reply)

    def describe(self) -> str:
        return "raft:" + ",".join(self.addrs)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


# -- spec resolution ----------------------------------------------------------
#
# A federation "namespace" is now a SPEC string:
#   /path/to/dir                → FileStore (PR-15 compatible)
#   raft:<idx>@h0:p0,h1:p1,...  → member: embedded RaftNode idx
#   raft:h0:p0,h1:p1,...        → client: RPC to any node
# Client stores are cached per addr-set (workers resolve owners every
# 100ms — one sticky connection, not one dial per poll).

_CLIENT_CACHE: Dict[Tuple[str, ...], RaftClientStore] = {}
_FILE_CACHE: Dict[str, FileStore] = {}
_CLIENT_CACHE_LOCK = threading.Lock()


def parse_member_spec(spec: str) -> Tuple[int, List[str]]:
    body = spec[len("raft:"):]
    head, _, rest = body.partition("@")
    if not rest:
        raise ValueError(
            f"member spec needs raft:<idx>@addr,...: {spec!r}")
    return int(head), [a.strip() for a in rest.split(",") if a.strip()]


def resolve_store(spec: Any) -> NamespaceStore:
    """Spec → a READ/CLIENT-capable store handle (a member spec
    resolves to a client store over the same group — workers and
    clients never embed a node)."""
    if isinstance(spec, NamespaceStore):
        return spec
    s = str(spec)
    if not s.startswith("raft:"):
        with _CLIENT_CACHE_LOCK:
            store = _FILE_CACHE.get(s)
            if store is None:
                store = _FILE_CACHE[s] = FileStore(s)
            return store
    body = s[len("raft:"):]
    if "@" in body:
        _, addrs = parse_member_spec(s)
    else:
        addrs = [a.strip() for a in body.split(",") if a.strip()]
    key = tuple(addrs)
    with _CLIENT_CACHE_LOCK:
        store = _CLIENT_CACHE.get(key)
        if store is None:
            store = _CLIENT_CACHE[key] = RaftClientStore(addrs)
        return store


def resolve_member_store(spec: Any) -> Tuple[NamespaceStore, bool]:
    """Spec → (store, owns): the server-side resolve.  A ``raft:``
    member spec STARTS this server's embedded node (owns=True: the
    FederationMember's stop() shuts it down); a directory is a shared
    FileStore (owns=False)."""
    if isinstance(spec, NamespaceStore):
        return spec, False
    s = str(spec)
    if s.startswith("raft:"):
        idx, addrs = parse_member_spec(s)
        return RaftStore(RaftNode(idx, addrs)), True
    return FileStore(s), False


def client_spec(spec: Any) -> str:
    """The spec workers/clients should use for the same namespace
    (member raft spec → client raft spec; a dir stays a dir)."""
    if isinstance(spec, NamespaceStore):
        return spec.describe()
    s = str(spec)
    if s.startswith("raft:") and "@" in s:
        _, addrs = parse_member_spec(s)
        return "raft:" + ",".join(addrs)
    return s
