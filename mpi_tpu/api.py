"""Flat MPI_* function layer (L4 of SURVEY.md §1; BASELINE.json:5 API surface).

Thin wrappers over the world communicator so classic MPI-style programs read
naturally::

    from mpi_tpu.api import *
    MPI_Init()
    rank = MPI_Comm_rank()
    if rank == 0:
        MPI_Send(data, dest=1)
    ...
    MPI_Finalize()
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from . import datatypes, errors, ops
from . import communicator as _comm
from .communicator import Communicator, Status
from .group import Group
from .transport.base import ANY_SOURCE, ANY_TAG

__all__ = [
    "MPI_Init", "MPI_Finalize", "MPI_Initialized", "MPI_COMM_WORLD",
    "MPI_Comm_rank", "MPI_Comm_size", "MPI_Send", "MPI_Recv", "MPI_Sendrecv",
    "MPI_Isendrecv", "MPI_Isendrecv_replace",
    "MPI_Bcast", "MPI_Reduce", "MPI_Allreduce", "MPI_Allgather", "MPI_Alltoall",
    "MPI_Barrier", "MPI_Comm_split", "MPI_Comm_dup", "MPI_Scatter", "MPI_Gather",
    "MPI_Scan", "MPI_Reduce_scatter", "MPI_Isend", "MPI_Irecv", "MPI_Wait",
    "MPI_Test", "MPI_Waitall", "MPI_Waitany", "MPI_Waitsome", "MPI_Testall",
    "MPI_Testany", "MPI_Probe", "MPI_Iprobe", "MPI_Wtime",
    "MPI_Mprobe", "MPI_Improbe", "MPI_Mrecv",
    "MPI_Send_init", "MPI_Recv_init", "MPI_Start", "MPI_Startall",
    "MPI_Ibcast", "MPI_Ireduce", "MPI_Iallreduce", "MPI_Iallgather",
    "MPI_Ialltoall", "MPI_Ibarrier", "MPI_Iscatter", "MPI_Igather",
    "MPI_Get_processor_name", "MPI_Get_version", "MPI_Get_library_version", "MPI_Abort",
    "MPI_Wtick", "MPI_Sendrecv_replace",
    "MPI_Exscan", "MPI_Op_create", "MPI_Maxloc", "MPI_Minloc",
    "MPI_Gatherv", "MPI_Scatterv", "MPI_Allgatherv", "MPI_Alltoallv",
    "MPI_Cart_create", "MPI_Dims_create", "MPI_Cart_coords", "MPI_Cart_rank",
    "MPI_Graph_create", "MPI_Dist_graph_create_adjacent",
    "MPI_Intercomm_create", "MPI_Intercomm_merge",
    "MPI_Comm_remote_size", "MPI_Comm_test_inter",
    "MPI_Cart_shift", "MPI_Cart_sub",
    "MPI_Neighbor_allgather", "MPI_Neighbor_alltoall",
    "MPI_Comm_group", "MPI_Comm_create", "MPI_Comm_create_group",
    "MPI_Win_create", "MPI_Win_fence", "MPI_Win_free",
    "MPI_Win_lock", "MPI_Win_unlock",
    "MPI_Win_post", "MPI_Win_start", "MPI_Win_complete", "MPI_Win_wait",
    "MPI_Win_test", "MPI_Fetch_and_op", "MPI_Compare_and_swap",
    "MPI_Win_flush", "MPI_Comm_split_type", "MPI_COMM_TYPE_SHARED",
    "MPI_Win_lock_all", "MPI_Win_unlock_all", "MPI_Win_flush_all",
    "MPI_Win_flush_local", "MPI_Get_accumulate",
    "MPI_Rput", "MPI_Rget", "MPI_Raccumulate", "MPI_Comm_idup",
    "MPI_Type_create_hvector", "MPI_Type_create_hindexed",
    "MPI_Win_allocate_shared", "MPI_Win_shared_query", "MPI_Win_sync",
    "MPI_Win_create_dynamic", "MPI_Win_attach", "MPI_Win_detach",
    "MPI_T_cvar_list", "MPI_T_cvar_read", "MPI_T_cvar_write",
    "MPI_T_pvar_list", "MPI_T_pvar_read", "MPI_T_pvar_session_create",
    "MPI_Bcast_init", "MPI_Allreduce_init", "MPI_Reduce_init",
    "MPI_Allgather_init", "MPI_Alltoall_init", "MPI_Barrier_init",
    "MPI_Reduce_scatter_init",
    "MPI_Session_init", "MPI_Session_finalize", "MPI_Session_get_num_psets",
    "MPI_Session_get_nth_pset", "MPI_Session_get_info",
    "MPI_Group_from_session_pset", "MPI_Comm_create_from_group",
    "MPI_Psend_init", "MPI_Precv_init", "MPI_Pready", "MPI_Pready_range",
    "MPI_Parrived",
    "MPI_Put", "MPI_Get", "MPI_Accumulate",
    "MPI_Group_incl", "MPI_Group_excl", "MPI_Group_union",
    "MPI_Group_intersection", "MPI_Group_difference", "MPI_Group_size",
    "MPI_Group_rank", "MPI_Group_translate_ranks", "Group",
    "MPI_Type_contiguous", "MPI_Type_vector", "MPI_Type_indexed",
    "MPI_Type_create_subarray", "MPI_Type_create_struct",
    "MPI_Type_create_resized", "MPI_Type_commit", "MPI_Type_free",
    "MPI_Type_size", "MPI_Type_get_extent",
    "MPI_Pack", "MPI_Unpack", "MPI_Pack_size", "Datatype",
    "MPI_Pack_external", "MPI_Unpack_external",
    "MPI_COMM_SELF", "MPI_Get_count", "MPI_Get_elements",
    "MPI_SUCCESS", "MPI_ERRORS_ARE_FATAL", "MPI_ERRORS_RETURN",
    "MPI_Error_class", "MPI_Error_string", "ErrorCode",
    "MPI_Comm_set_errhandler", "MPI_Comm_get_errhandler",
    "MPI_ERR_PROC_FAILED", "MPI_ERR_REVOKED",
    "MPIX_Comm_revoke", "MPIX_Comm_shrink", "MPIX_Comm_agree",
    "MPIX_Comm_failure_ack", "MPIX_Comm_failure_get_acked",
    "MPIX_Comm_get_failed",
    "MPIX_Comm_accept_rejoin", "MPIX_Comm_rejoin", "MPIX_Comm_get_epoch",
    "MPI_Errhandler_create",
    "MPI_Comm_create_keyval", "MPI_Comm_free_keyval", "MPI_COMM_DUP_FN",
    "MPI_COMM_NULL_COPY_FN", "MPI_NO_COPY", "Keyval",
    "MPI_Comm_set_attr", "MPI_Comm_get_attr", "MPI_Comm_delete_attr",
    "MPI_Comm_spawn", "MPI_Comm_spawn_multiple", "MPI_Comm_get_parent",
    "MPI_Open_port", "MPI_Close_port", "MPI_Comm_accept", "MPI_Comm_connect",
    "MPI_Publish_name", "MPI_Unpublish_name", "MPI_Lookup_name",
    "MPI_File_open", "MPI_File_close", "MPI_File_delete",
    "MPI_File_read_at", "MPI_File_write_at",
    "MPI_File_read_at_all", "MPI_File_write_at_all",
    "MPI_File_seek", "MPI_File_get_position", "MPI_File_read", "MPI_File_write",
    "MPI_File_read_shared", "MPI_File_write_shared", "MPI_File_seek_shared",
    "MPI_File_write_ordered", "MPI_File_read_ordered",
    "Info", "MPI_INFO_NULL", "MPI_Info_create", "MPI_Info_set",
    "MPI_Info_get", "MPI_Info_delete", "MPI_Info_dup", "MPI_Info_free",
    "MPI_Info_get_nkeys",
    "MPI_File_set_view", "MPI_File_get_view", "MPI_Register_datarep",
    "MPI_File_get_size", "MPI_File_set_size", "MPI_File_preallocate",
    "MPI_File_sync",
    "MPI_MODE_RDONLY", "MPI_MODE_WRONLY", "MPI_MODE_RDWR", "MPI_MODE_CREATE",
    "MPI_MODE_EXCL", "MPI_MODE_APPEND", "MPI_MODE_DELETE_ON_CLOSE",
    "MPI_SEEK_SET", "MPI_SEEK_CUR", "MPI_SEEK_END",
    "ANY_SOURCE", "ANY_TAG", "SUM", "PROD", "MAX", "MIN",
    "LAND", "LOR", "LXOR", "BAND", "BOR", "BXOR", "Status",
]

SUM, PROD, MAX, MIN = ops.SUM, ops.PROD, ops.MAX, ops.MIN
LAND, LOR, LXOR = ops.LAND, ops.LOR, ops.LXOR
BAND, BOR, BXOR = ops.BAND, ops.BOR, ops.BXOR
MPI_Op_create = ops.make_op


def _world(comm: Optional[Communicator]) -> Communicator:
    if comm is not None:
        return comm
    from . import init

    return init()


def _call(comm: Optional[Communicator], method: str, *args: Any, **kwargs: Any) -> Any:
    """Invoke a communicator method under its error handler (MPI-1 §7,
    mpi_tpu/errors.py): ERRORS_ARE_FATAL propagates the exception,
    ERRORS_RETURN yields an ErrorCode in place of the result, a callable
    handler decides.  This boundary is the MPI_* layer only — the object
    API stays exception-raising (pythonic)."""
    c = _world(comm)
    try:
        return getattr(c, method)(*args, **kwargs)
    except Exception as exc:  # noqa: BLE001 - classified by the handler
        return errors.invoke_handler(c, exc)


def MPI_Init(backend: Optional[str] = None) -> Communicator:
    from . import init

    return init(backend)


def MPI_Initialized() -> bool:
    from . import is_initialized

    return is_initialized()


def MPI_Finalize() -> None:
    from . import finalize

    finalize()


def MPI_COMM_WORLD() -> Communicator:
    return _world(None)


def MPI_Comm_rank(comm: Optional[Communicator] = None) -> int:
    return _world(comm).rank


def MPI_Comm_size(comm: Optional[Communicator] = None) -> int:
    return _world(comm).size


def MPI_Send(obj: Any, dest: int, tag: int = 0, comm: Optional[Communicator] = None,
             datatype: Optional[datatypes.Datatype] = None, count: int = 1) -> None:
    """With ``datatype=``, ``obj`` is the typed base buffer and the wire
    payload is ``datatype.pack(obj, count)`` — the MPI typed-send spelling
    (strided columns, halo faces, structs; mpi_tpu/datatypes.py)."""
    c = _world(comm)
    try:
        payload = datatype.pack(obj, count) if datatype is not None else obj
        return c.send(payload, dest, tag)
    except Exception as exc:  # noqa: BLE001 - pack errors honor the handler too
        return errors.invoke_handler(c, exc)


def MPI_Recv(source: int = ANY_SOURCE, tag: int = ANY_TAG,
             comm: Optional[Communicator] = None,
             status: Optional[Status] = None,
             datatype: Optional[datatypes.Datatype] = None,
             buf: Optional[Any] = None, count: int = 1) -> Any:
    """With ``datatype=`` and ``buf=``, the received contiguous payload is
    scattered into ``buf`` in-place (the typed-recv spelling); ``buf`` is
    returned."""
    c = _world(comm)
    try:
        if (buf is None) != (datatype is None):
            raise ValueError("typed MPI_Recv needs BOTH datatype= and buf= "
                             "(one without the other would silently drop the "
                             "layout or leave buf unfilled)")
        obj = c.recv(source, tag, status)
        if datatype is not None:
            return datatype.unpack(obj, buf, count)
        return obj
    except Exception as exc:  # noqa: BLE001 - unpack errors honor the handler;
        # a handler's fallback value is returned as-is, never unpacked into buf
        return errors.invoke_handler(c, exc)


def MPI_Sendrecv(sendobj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG,
                 comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "sendrecv", sendobj, dest, source, sendtag, recvtag)


def MPI_Isendrecv(sendobj: Any, dest: int, source: int = ANY_SOURCE,
                  sendtag: int = 0, recvtag: int = ANY_TAG,
                  comm: Optional[Communicator] = None):
    """MPI-4 nonblocking combined send+receive; the request completes
    with the received payload."""
    return _call(comm, "isendrecv", sendobj, dest, source, sendtag, recvtag)


def MPI_Isendrecv_replace(buf: Any, dest: int, source: int = ANY_SOURCE,
                          sendtag: int = 0, recvtag: int = ANY_TAG,
                          comm: Optional[Communicator] = None):
    """MPI-4 nonblocking sendrecv_replace: ndarray ``buf`` is refilled
    in place when the request completes."""
    return _call(comm, "isendrecv_replace", buf, dest, source, sendtag,
                 recvtag)


def MPI_Bcast(obj: Any, root: int = 0, comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "bcast", obj, root)


def MPI_Reduce(obj: Any, op: ops.ReduceOp = ops.SUM, root: int = 0,
               comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "reduce", obj, op, root)


def MPI_Allreduce(obj: Any, op: ops.ReduceOp = ops.SUM, algorithm: str = "auto",
                  comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "allreduce", obj, op, algorithm)


def MPI_Allgather(obj: Any, comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "allgather", obj)


def MPI_Alltoall(objs: Sequence[Any], comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "alltoall", objs)


def MPI_Barrier(comm: Optional[Communicator] = None) -> None:
    return _call(comm, "barrier")  # None, or ErrorCode under ERRORS_RETURN


def MPI_Comm_split(color: Optional[int], key: int = 0,
                   comm: Optional[Communicator] = None) -> Optional[Communicator]:
    return _call(comm, "split", color, key)


def MPI_Comm_dup(comm: Optional[Communicator] = None) -> Communicator:
    return _call(comm, "dup")


def MPI_Scatter(objs: Optional[Sequence[Any]], root: int = 0,
                comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "scatter", objs, root)


def MPI_Gather(obj: Any, root: int = 0, comm: Optional[Communicator] = None) -> Any:
    """On the SPMD backend the replicated result costs O(size × payload)
    HBM per device and warns above the ``gather_replicated_warn_bytes``
    mpit cvar; large payloads should use the backend-specific
    ``comm.gather(obj, sharded=True)`` spelling (zero wire traffic,
    O(payload) per device — see TpuCommunicator.gather).  The sharded
    slice is branded vma-VARYING over the axis, so composing it with a
    non-sharded out_spec fails the vma typecheck loudly instead of
    silently yielding a [1, ...] slice (under ``check_vma=False`` the
    composition remains the caller's burden)."""
    return _call(comm, "gather", obj, root)


def MPI_Isend(obj: Any, dest: int, tag: int = 0,
              comm: Optional[Communicator] = None):
    return _call(comm, "isend", obj, dest, tag)


def MPI_Irecv(source: int = ANY_SOURCE, tag: int = ANY_TAG,
              comm: Optional[Communicator] = None):
    return _call(comm, "irecv", source, tag)


def MPI_Wait(request) -> Any:
    return request.wait()


def MPI_Test(request):
    return request.test()


def MPI_Waitall(requests) -> list:
    return [r.wait() for r in requests]


def _retired(r) -> bool:
    return getattr(r, "_retired", False)


def MPI_Waitany(requests):
    """Block until some request completes; returns (index, value).

    MPI sets completed requests to MPI_REQUEST_NULL so the next Waitany
    moves on; the analogue here: a request RETURNED by Waitany/Waitsome/
    Testany is marked retired and skipped by later calls on the same set
    — a ``for _ in range(len(reqs)): MPI_Waitany(reqs)`` drain loop visits
    every request exactly once.  When every request is retired, returns
    (None, None) (MPI_UNDEFINED).

    Implementation: round-robin test() polling (the transports complete
    in background threads), with the inter-sweep sleep backing off to
    1ms — i.e. at most ~1000 sweeps/s while nothing is ready.  A poll
    loop is the honest Waitany over independent requests: blocking on
    any single request could miss an earlier completion on another."""
    import time as _time

    if not requests:
        raise ValueError("MPI_Waitany needs at least one request")
    # Scope the progress engine's stalled-poll publication to THIS
    # call's request list: when the verifier publishes on the drain
    # loop's behalf, the OR-set names exactly these requests' pending
    # sources, not the union over every posted request in the world.
    eng = None
    for r in requests:
        c = getattr(r, "_comm", None)
        if c is not None:
            eng = getattr(c._t, "_progress_engine", None)
            break
    prev_scope = eng.enter_poll_scope(requests) if eng is not None else None
    try:
        delay = 0.0
        while True:
            live = False
            for i, r in enumerate(requests):
                if _retired(r):
                    continue
                live = True
                done, value = r.test()
                if done:
                    r._retired = True
                    return i, value
            if not live:
                return None, None  # MPI_UNDEFINED: no active requests
            _time.sleep(delay)
            delay = min(0.001, delay + 0.0001)
    finally:
        if eng is not None:
            eng.exit_poll_scope(prev_scope)


def MPI_Waitsome(requests):
    """Block until at least one un-retired request completes; returns
    (indices, values) of ALL requests complete at that moment, retiring
    them (see MPI_Waitany).  ``(None, None)`` when nothing is active."""
    i0, v0 = MPI_Waitany(requests)
    if i0 is None:
        return None, None
    idx, vals = [i0], [v0]
    for i, r in enumerate(requests):
        if i == i0 or _retired(r):
            continue
        done, value = r.test()
        if done:
            r._retired = True
            idx.append(i)
            vals.append(value)
    order = sorted(range(len(idx)), key=lambda k: idx[k])
    return [idx[k] for k in order], [vals[k] for k in order]


def MPI_Testall(requests):
    """(all_done, values) — values is None unless every request is done
    (matching MPI's flag semantics).  Completed requests keep their value
    across re-polls (and Testall does NOT retire anything: its contract
    is a snapshot of the whole set, repeatable by design)."""
    results = [r.test() for r in requests]
    if all(done for done, _ in results):
        return True, [v for _, v in results]
    return False, None


def MPI_Testany(requests):
    """(done, index, value) of the first completed un-retired request
    (which it retires, see MPI_Waitany), else (False, None, None)."""
    for i, r in enumerate(requests):
        if _retired(r):
            continue
        done, value = r.test()
        if done:
            r._retired = True
            return True, i, value
    return False, None, None


def MPI_Probe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
              comm: Optional[Communicator] = None, status=None) -> None:
    return _call(comm, "probe", source, tag, status)


def MPI_Iprobe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
               comm: Optional[Communicator] = None, status=None) -> bool:
    return _call(comm, "iprobe", source, tag, status)


def MPI_Wtime() -> float:
    import time

    return time.perf_counter()


def MPI_Scan(obj: Any, op: ops.ReduceOp = ops.SUM,
             comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "scan", obj, op)


def MPI_Reduce_scatter(blocks: Any, op: ops.ReduceOp = ops.SUM,
                       comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "reduce_scatter", blocks, op)


def MPI_Exscan(obj: Any, op: ops.ReduceOp = ops.SUM,
               comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "exscan", obj, op)


def MPI_Allgatherv(obj: Any, counts: Sequence[int],
                   comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "allgatherv", obj, counts)


def MPI_Gatherv(obj: Any, counts: Sequence[int], root: int = 0,
                comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "gatherv", obj, counts, root)


def MPI_Scatterv(obj: Any, counts: Sequence[int], root: int = 0,
                 comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "scatterv", obj, counts, root)


def MPI_Alltoallv(blocks: Any, counts: Sequence[Sequence[int]],
                  comm: Optional[Communicator] = None) -> Any:
    return _call(comm, "alltoallv", blocks, counts)


def MPI_Maxloc(obj: Any, comm: Optional[Communicator] = None):
    """Allreduce with MPI_MAXLOC semantics: (max value, lowest rank with it)."""
    return _call(comm, "maxloc", obj)


def MPI_Minloc(obj: Any, comm: Optional[Communicator] = None):
    """Allreduce with MPI_MINLOC semantics: (min value, lowest rank with it)."""
    return _call(comm, "minloc", obj)


def MPI_Cart_create(dims: Sequence[int], periods: Optional[Sequence[bool]] = None,
                    comm: Optional[Communicator] = None):
    from .topology import cart_create

    return cart_create(_world(comm), dims, periods)


def MPI_Intercomm_create(group_a, group_b,
                         comm: Optional[Communicator] = None):
    """Two-group intercommunicator from explicit disjoint parent-rank
    groups (the host-side spelling of the leader/bridge protocol — see
    mpi_tpu/intercomm.py); returns None on non-member ranks."""
    from .intercomm import create_intercomm

    return create_intercomm(_world(comm), group_a, group_b)


def MPI_Intercomm_merge(intercomm, high: bool = False):
    return intercomm.merge(high)


def MPI_Comm_remote_size(intercomm) -> int:
    return intercomm.remote_size


def MPI_Comm_test_inter(comm) -> bool:
    return getattr(comm, "is_inter", False)


def MPI_Graph_create(edges, comm: Optional[Communicator] = None):
    """Arbitrary directed process graph from the global edge list [S]."""
    from .topology import graph_create

    return graph_create(_world(comm), edges)


def MPI_Dist_graph_create_adjacent(sources, destinations,
                                   comm: Optional[Communicator] = None):
    from .topology import dist_graph_create_adjacent

    return dist_graph_create_adjacent(_world(comm), sources, destinations)


def MPI_Dims_create(nnodes: int, ndims: int) -> list:
    from .topology import dims_create

    return dims_create(nnodes, ndims)


def MPI_Cart_coords(cart, rank: int):
    return cart.coords_of(rank)


def MPI_Cart_rank(cart, coords: Sequence[int]):
    return cart.rank_of(coords)


def MPI_Cart_shift(cart, direction: int, disp: int = 1):
    return cart.shift(direction, disp)


def MPI_Cart_sub(cart, remain_dims: Sequence[bool]):
    return cart.sub(remain_dims)


def MPI_Comm_group(comm: Optional[Communicator] = None):
    return _world(comm).group()


def MPI_Comm_create(group, comm: Optional[Communicator] = None):
    return _world(comm).create(group)


# MPI-3 spells the non-collective-over-comm variant MPI_Comm_create_group;
# our create() is already group-collective-only in spirit, so they coincide.
MPI_Comm_create_group = MPI_Comm_create


def MPI_Group_incl(group, positions: Sequence[int]):
    return group.incl(positions)


def MPI_Group_excl(group, positions: Sequence[int]):
    return group.excl(positions)


def MPI_Group_union(a, b):
    return a.union(b)


def MPI_Group_intersection(a, b):
    return a.intersection(b)


def MPI_Group_difference(a, b):
    return a.difference(b)


def MPI_Group_size(group) -> int:
    return group.size


def MPI_Group_rank(group, comm: Optional[Communicator] = None):
    """This process's position in ``group`` (None = MPI_UNDEFINED)."""
    return group.rank_of(_world(comm).rank)


def MPI_Group_translate_ranks(group, positions: Sequence[int], other):
    return group.translate(positions, other)


# -- one-sided (RMA) -------------------------------------------------------


def MPI_Win_create(init: Any, comm: Optional[Communicator] = None):
    """Expose ``init`` (copied) as this rank's RMA window [S: MPI-2]."""
    return _world(comm).win_create(init)


def MPI_Win_fence(win) -> None:
    win.fence()


def MPI_Put(win, data: Any, target, loc: Any = None) -> None:
    win.put(data, target, loc=loc)


def MPI_Get(win, target, fill: Any = 0, loc: Any = None):
    """Returns a GetFuture; ``.value`` is defined after the closing fence.
    ``fill`` resolves ranks with no source in a pattern-form get."""
    return win.get(target, fill=fill, loc=loc)


def MPI_Accumulate(win, data: Any, target, op=ops.SUM, loc: Any = None) -> None:
    win.accumulate(data, target, op=op, loc=loc)


def MPI_Win_lock(win, rank: int, exclusive: bool = True) -> None:
    """MPI_Win_lock [S]: passive-target epoch (process backends)."""
    win.lock(rank, exclusive)


def MPI_Win_unlock(win, rank: int) -> None:
    win.unlock(rank)


def MPI_Win_free(win) -> None:
    win.free()


# -- neighborhood collectives ----------------------------------------------


def MPI_Neighbor_allgather(cart, obj: Any, fill: Any = None):
    return cart.neighbor_allgather(obj, fill=fill)


def MPI_Neighbor_alltoall(cart, objs: Sequence[Any], fill: Any = None):
    return cart.neighbor_alltoall(objs, fill=fill)


# -- persistent requests ---------------------------------------------------


def MPI_Send_init(buf: Any, dest: int, tag: int = 0,
                  comm: Optional[Communicator] = None):
    return _call(comm, "send_init", buf, dest, tag)


def MPI_Recv_init(source: int = ANY_SOURCE, tag: int = ANY_TAG,
                  buf: Any = None, comm: Optional[Communicator] = None):
    return _call(comm, "recv_init", source, tag, buf=buf)


def MPI_Start(request):
    return request.start()


def MPI_Startall(requests: Sequence[Any]):
    from .communicator import startall

    return startall(requests)


# -- nonblocking collectives [S: MPI-3] ------------------------------------


def MPI_Ibcast(obj: Any, root: int = 0, comm: Optional[Communicator] = None):
    return _call(comm, "ibcast", obj, root)


def MPI_Ireduce(obj: Any, op=ops.SUM, root: int = 0,
                comm: Optional[Communicator] = None):
    return _call(comm, "ireduce", obj, op, root)


def MPI_Iallreduce(obj: Any, op=ops.SUM, algorithm: str = "auto",
                   comm: Optional[Communicator] = None):
    return _call(comm, "iallreduce", obj, op, algorithm)


def MPI_Iallgather(obj: Any, comm: Optional[Communicator] = None):
    return _call(comm, "iallgather", obj)


def MPI_Ialltoall(objs: Sequence[Any], comm: Optional[Communicator] = None):
    return _call(comm, "ialltoall", objs)


def MPI_Ibarrier(comm: Optional[Communicator] = None):
    return _call(comm, "ibarrier")


def MPI_Iscatter(objs, root: int = 0, comm: Optional[Communicator] = None):
    return _call(comm, "iscatter", objs, root)


def MPI_Igather(obj: Any, root: int = 0, comm: Optional[Communicator] = None):
    return _call(comm, "igather", obj, root)


# -- environment inquiry & abort -------------------------------------------


def MPI_Get_processor_name() -> str:
    import socket

    return socket.gethostname()


def MPI_Get_version():
    """(major, minor) of the MPI standard this library *conforms to*.

    MPI-3.0 as of round 3.  MPI-1 and MPI-2 are complete (p2p,
    collectives, groups, topology, derived datatypes incl. h-variants +
    Pack/Unpack + external32, error handlers, attribute caching,
    COMM_SELF, Get_count; RMA with all three sync modes, dynamic
    processes incl. spawn + ports + name service, MPI-IO with views/
    shared pointers/ordered + two-phase collective I/O,
    intercommunicators).  The MPI-3 additions present: nonblocking
    collectives, neighborhood collectives on cartesian AND
    distributed-graph topologies, matched probe (Mprobe/Mrecv),
    request-set ops, RMA atomics (Fetch_and_op/Compare_and_swap/
    Get_accumulate) with lock_all/flush/flush_all and request-based
    Rput/Rget/Raccumulate, Comm_split_type, Comm_idup,
    Comm_create_group, Win_allocate_shared/shared_query/Win_sync
    (true load/store shared-memory windows over /dev/shm mmap on the
    process backends), Win_create_dynamic/attach/detach (key-addressed
    runtime regions), and an MPI_T tool interface (mpit.py: real cvars
    steering the library + exact transport-level pvar counters).
    MPI_Register_datarep is implemented (user file representations
    honored by set_view and all typed IO, io.py).  Remaining MPI-3 gap:
    large-count bindings only (meaningless — Python ints are
    unbounded).  MPI-4 previews beyond that: persistent collectives,
    partitioned communication, and sessions (mpi_tpu/mpi4.py)."""
    return (3, 0)


def MPI_Get_library_version() -> str:
    from .version import __version__

    return f"mpi_tpu {__version__} (TPU-native: XLA/ICI collectives + " \
           f"socket/shm process transports)"


def MPI_Abort(code: int = 1, comm: Optional[Communicator] = None) -> None:
    """Terminate this rank immediately with ``code``; under the launcher
    the nonzero exit propagates and the remaining ranks are killed (the
    L0 kill-all contract, SURVEY.md §2 component #1)."""
    import os
    import sys

    sys.stderr.write(f"MPI_Abort(code={code})\n")
    sys.stderr.flush()
    os._exit(code)


def MPI_Wtick() -> float:
    import time

    return time.get_clock_info("perf_counter").resolution


def MPI_Sendrecv_replace(obj: Any, dest: int, source: int = ANY_SOURCE,
                         sendtag: int = 0, recvtag: int = ANY_TAG,
                         comm: Optional[Communicator] = None):
    """MPI_Sendrecv_replace [S]: same buffer for send and receive — in this
    library's value semantics, simply returns the received payload."""
    return _call(comm, "sendrecv", obj, dest, source, sendtag, recvtag)


# -- derived datatypes (MPI-1 ch.3; mpi_tpu/datatypes.py) -------------------

MPI_Type_contiguous = datatypes.type_contiguous
MPI_Type_vector = datatypes.type_vector
MPI_Type_indexed = datatypes.type_indexed
MPI_Type_create_subarray = datatypes.type_create_subarray
MPI_Type_create_struct = datatypes.type_create_struct
MPI_Type_create_resized = datatypes.type_create_resized
MPI_Pack = datatypes.pack
MPI_Pack_external = datatypes.pack_external
MPI_Unpack_external = datatypes.unpack_external
MPI_Unpack = datatypes.unpack
MPI_Pack_size = datatypes.pack_size
Datatype = datatypes.Datatype


def MPI_Type_commit(datatype: datatypes.Datatype) -> datatypes.Datatype:
    return datatype.commit()


def MPI_Type_free(datatype: datatypes.Datatype) -> None:
    datatype.free()


def MPI_Type_size(datatype: datatypes.Datatype) -> int:
    return datatype.size


def MPI_Type_get_extent(datatype: datatypes.Datatype):
    """(lower bound, extent) in bytes."""
    return (datatype.lb * datatype.base_dtype.itemsize, datatype.extent_bytes)


def MPI_COMM_SELF() -> Communicator:
    """The size-1 communicator containing only this process [S]."""
    import mpi_tpu

    return mpi_tpu.comm_self()


def _datatype_bytes(datatype) -> int:
    if isinstance(datatype, datatypes.Datatype):
        return datatype.size
    import numpy as np

    return np.dtype(datatype).itemsize


def MPI_Get_count(status: Status, datatype) -> Optional[int]:
    """Instances of ``datatype`` in the received payload; None
    (MPI_UNDEFINED) when the payload was an opaque object, the status
    came from a probe (envelope only), or the size is not a whole
    multiple of the datatype.  ``datatype`` is a Datatype or dtype-like."""
    nbytes = _datatype_bytes(datatype)
    if status.count_bytes is None or nbytes == 0 or \
            status.count_bytes % nbytes:
        return None
    return status.count_bytes // nbytes


def MPI_Get_elements(status: Status, datatype) -> Optional[int]:
    """Base-element count of the received payload (MPI_Get_elements:
    counts primitive elements even when a partial instance arrived)."""
    if isinstance(datatype, datatypes.Datatype):
        item = datatype.base_dtype.itemsize
    else:
        item = _datatype_bytes(datatype)
    if status.count_bytes is None or item == 0 or status.count_bytes % item:
        return None
    return status.count_bytes // item


# -- error handling (MPI-1 ch.7; mpi_tpu/errors.py) -------------------------

MPI_SUCCESS = errors.MPI_SUCCESS
MPI_ERRORS_ARE_FATAL = errors.ERRORS_ARE_FATAL
MPI_ERRORS_RETURN = errors.ERRORS_RETURN
MPI_Error_class = errors.error_class
MPI_Error_string = errors.error_string
ErrorCode = errors.ErrorCode


def MPI_Comm_set_errhandler(handler, comm: Optional[Communicator] = None) -> None:
    """ERRORS_ARE_FATAL (default), ERRORS_RETURN, or ``handler(comm, exc)``."""
    _world(comm).set_errhandler(handler)


def MPI_Comm_get_errhandler(comm: Optional[Communicator] = None):
    return _world(comm).get_errhandler()


def MPI_Errhandler_create(fn):
    """MPI_Errhandler_create: any ``fn(comm, exc)`` callable IS a handler."""
    return fn


# -- fault tolerance (ULFM proposal; mpi_tpu/ft.py) --------------------------

MPI_ERR_PROC_FAILED = errors.MPI_ERR_PROC_FAILED
MPI_ERR_REVOKED = errors.MPI_ERR_REVOKED


def MPIX_Comm_revoke(comm: Optional[Communicator] = None):
    """Revoke the communicator everywhere (not collective): every rank's
    pending and future operations on it raise RevokedError /
    MPI_ERR_REVOKED — the survivor-unblocking half of the failure story."""
    return _call(comm, "revoke")


def MPIX_Comm_shrink(comm: Optional[Communicator] = None):
    """Survivors agree on the failed set and return a dense
    sub-communicator of them (collective among survivors; valid on a
    revoked communicator)."""
    return _call(comm, "shrink")


def MPIX_Comm_agree(value: bool = True,
                    comm: Optional[Communicator] = None):
    """Fault-tolerant agreement on the AND of every live rank's value;
    ERR_PROC_FAILED (after agreeing) while dead members are
    unacknowledged."""
    return _call(comm, "agree", value)


def MPIX_Comm_failure_ack(comm: Optional[Communicator] = None):
    """Acknowledge all currently known failures (re-arms ANY_SOURCE
    receives and agreement); returns the acknowledged ranks."""
    return _call(comm, "failure_ack")


def MPIX_Comm_failure_get_acked(comm: Optional[Communicator] = None):
    return _call(comm, "failure_get_acked")


def MPIX_Comm_get_failed(comm: Optional[Communicator] = None):
    """Comm ranks this process currently believes dead (sorted)."""
    return _call(comm, "get_failed")


# -- elastic membership (mpi_tpu/membership.py) ------------------------------


def MPIX_Comm_accept_rejoin(comm: Optional[Communicator] = None,
                            timeout: Optional[float] = None):
    """Survivor-side grow-back (collective on the SHRUNKEN
    communicator): announce the vacant world slots under the post-shrink
    membership epoch, admit replacement claims (refusing an
    ousted-but-live incarnation until failure_ack), and return the
    full-size communicator under the new epoch."""
    return _call(comm, "accept_rejoin", timeout=timeout)


def MPIX_Comm_rejoin(rdv_dir: Optional[str] = None,
                     timeout: Optional[float] = None, **kwargs):
    """Joiner-side grow-back, from a FRESH process (no communicator
    yet): claim a vacant slot from the newest vacancy announcement on
    the rendezvous dir and return the full-size world communicator
    under the announced epoch (mpi_tpu.membership.rejoin)."""
    from . import membership

    return membership.rejoin(rdv_dir=rdv_dir, timeout=timeout, **kwargs)


def MPIX_Comm_get_epoch(comm: Optional[Communicator] = None) -> int:
    """The communicator's monotone membership epoch (0 at world
    creation; bumped by every shrink / healing transition)."""
    return _world(comm).membership_epoch


# -- attribute caching (MPI-1 ch.5.7 keyvals) -------------------------------

MPI_Comm_create_keyval = _comm.create_keyval
MPI_COMM_DUP_FN = _comm.dup_fn
MPI_COMM_NULL_COPY_FN = None
MPI_NO_COPY = _comm.NO_COPY
Keyval = _comm.Keyval


def MPI_Comm_free_keyval(keyval) -> None:
    """The keyval object is the handle; freeing is garbage collection."""


def MPI_Comm_set_attr(keyval, value, comm: Optional[Communicator] = None) -> None:
    _world(comm).set_attr(keyval, value)


def MPI_Comm_get_attr(keyval, comm: Optional[Communicator] = None):
    return _world(comm).get_attr(keyval)


def MPI_Comm_delete_attr(keyval, comm: Optional[Communicator] = None) -> None:
    _world(comm).delete_attr(keyval)


# -- dynamic process management (MPI-2 ch.5; mpi_tpu/spawn.py) --------------


def MPI_Comm_spawn(command: Sequence[str], maxprocs: int, root: int = 0,
                   comm: Optional[Communicator] = None, info=None):
    """Spawn ``maxprocs`` ranks of ``python command...`` as a new world;
    returns the parent-child intercommunicator."""
    from .spawn import comm_spawn

    return comm_spawn(command, maxprocs, comm, root, info=info)


def MPI_Comm_spawn_multiple(segments, root: int = 0,
                            comm: Optional[Communicator] = None):
    from .spawn import comm_spawn_multiple

    return comm_spawn_multiple(segments, comm, root)


def MPI_Comm_get_parent():
    from .spawn import comm_get_parent

    return comm_get_parent()


def MPI_Open_port() -> str:
    from .spawn import open_port

    return open_port()


def MPI_Close_port(port_name: str) -> None:
    from .spawn import close_port

    close_port(port_name)


def MPI_Comm_accept(port_name: str, root: int = 0,
                    comm: Optional[Communicator] = None):
    from .spawn import comm_accept

    return comm_accept(port_name, comm, root)


def MPI_Comm_connect(port_name: str, root: int = 0,
                     comm: Optional[Communicator] = None):
    from .spawn import comm_connect

    return comm_connect(port_name, comm, root)


def MPI_Publish_name(service_name: str, port_name: str) -> None:
    from .spawn import publish_name

    publish_name(service_name, port_name)


def MPI_Unpublish_name(service_name: str) -> None:
    from .spawn import unpublish_name

    unpublish_name(service_name)


def MPI_Lookup_name(service_name: str) -> str:
    from .spawn import lookup_name

    return lookup_name(service_name)


# -- MPI-IO (MPI-2 ch.9; mpi_tpu/io.py) -------------------------------------

from . import io as _io  # noqa: E402 - grouped with its API block

MPI_MODE_RDONLY = _io.MODE_RDONLY
MPI_MODE_WRONLY = _io.MODE_WRONLY
MPI_MODE_RDWR = _io.MODE_RDWR
MPI_MODE_CREATE = _io.MODE_CREATE
MPI_MODE_EXCL = _io.MODE_EXCL
MPI_MODE_APPEND = _io.MODE_APPEND
MPI_MODE_DELETE_ON_CLOSE = _io.MODE_DELETE_ON_CLOSE
MPI_SEEK_SET, MPI_SEEK_CUR, MPI_SEEK_END = _io.SEEK_SET, _io.SEEK_CUR, _io.SEEK_END
MPI_File_delete = _io.file_delete


def MPI_File_open(path: str, amode: int = _io.MODE_RDWR,
                  comm: Optional[Communicator] = None,
                  shared: bool = False, info=None) -> "_io.File":
    return _io.file_open(_world(comm), path, amode, shared, info)


def MPI_File_close(fh: "_io.File") -> None:
    fh.close()


def MPI_File_read_at(fh, offset: int, count: int):
    return fh.read_at(offset, count)


def MPI_File_write_at(fh, offset: int, data: Any) -> int:
    return fh.write_at(offset, data)


def MPI_File_read_at_all(fh, offset: int, count: int):
    return fh.read_at_all(offset, count)


def MPI_File_write_at_all(fh, offset: int, data: Any) -> int:
    return fh.write_at_all(offset, data)


def MPI_File_seek(fh, offset: int, whence: int = _io.SEEK_SET) -> None:
    fh.seek(offset, whence)


def MPI_File_get_position(fh) -> int:
    return fh.get_position()


def MPI_File_read(fh, count: int):
    return fh.read(count)


def MPI_File_write(fh, data: Any) -> int:
    return fh.write(data)


def MPI_File_read_shared(fh, count: int):
    return fh.read_shared(count)


def MPI_File_write_shared(fh, data: Any) -> int:
    return fh.write_shared(data)


def MPI_File_seek_shared(fh, offset: int) -> None:
    fh.seek_shared(offset)


def MPI_File_set_view(fh, disp: int = 0, etype: Any = None,
                      filetype=None, datarep: str = "native") -> None:
    import numpy as _np

    fh.set_view(disp, etype if etype is not None else _np.uint8, filetype,
                datarep)


def MPI_Register_datarep(datarep: str, read_conversion_fn,
                         write_conversion_fn, dtype_file_extent_fn=None,
                         extra_state=None) -> None:
    """Register a user file-data representation for MPI_File_set_view
    (callback shapes: mpi_tpu/io.py Datarep)."""
    from . import io as _io

    _io.register_datarep(datarep, read_conversion_fn, write_conversion_fn,
                         dtype_file_extent_fn, extra_state)


def MPI_File_get_view(fh):
    return fh.get_view()


def MPI_File_get_size(fh) -> int:
    return fh.get_size()


def MPI_File_set_size(fh, size: int) -> None:
    fh.set_size(size)


def MPI_File_preallocate(fh, size: int) -> None:
    fh.preallocate(size)


def MPI_File_sync(fh) -> None:
    fh.sync()


def MPI_File_write_ordered(fh, data: Any) -> int:
    return fh.write_ordered(data)


def MPI_File_read_ordered(fh, count: int):
    return fh.read_ordered(count)


# -- Info objects (MPI-2) ----------------------------------------------------
# An Info is a string-keyed hint dictionary; this library's spelling IS a
# dict (the docstring of MPI_Get_version used to name this as the gap).

class Info(dict):
    """MPI_Info: string key/value hints.  ``MPI_File_open(..., info=)``
    and ``MPI_Comm_spawn(..., info=)`` accept one (advisory no-ops
    currently); exists so MPI-2 code ports without surgery."""


MPI_INFO_NULL = None


def MPI_Info_create() -> Info:
    return Info()


def MPI_Info_set(info: Info, key: str, value: str) -> None:
    info[str(key)] = str(value)


def MPI_Info_get(info: Info, key: str, default: Optional[str] = None):
    return info.get(key, default)


def MPI_Info_delete(info: Info, key: str) -> None:
    info.pop(key, None)


def MPI_Info_dup(info: Info) -> Info:
    return Info(info)


def MPI_Info_free(info: Info) -> None:
    info.clear()


def MPI_Info_get_nkeys(info: Info) -> int:
    return len(info)


def MPI_Mprobe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
               comm: Optional[Communicator] = None,
               status: Optional[Status] = None):
    """Matched probe (MPI-3): returns an MPI_Message no other receive can
    steal; consume with MPI_Mrecv."""
    return _call(comm, "mprobe", source, tag, status)


def MPI_Improbe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
                comm: Optional[Communicator] = None,
                status: Optional[Status] = None):
    return _call(comm, "improbe", source, tag, status)


def MPI_Mrecv(message, status: Optional[Status] = None):
    try:
        return message.recv(status)
    except Exception as exc:  # noqa: BLE001 - same boundary as every MPI_*
        c = getattr(message, "_comm", None)
        if c is None:
            raise
        return errors.invoke_handler(c, exc)


def MPI_Win_allocate_shared(nelems: int, dtype=None,
                            comm: Optional[Communicator] = None):
    """Collectively allocate a host-shared load/store window; query
    any rank's region with win.remote(rank) (MPI_Win_shared_query)."""
    import numpy as _np

    from .shmwin import win_allocate_shared

    return win_allocate_shared(comm, nelems,
                               dtype if dtype is not None else _np.float64)


def MPI_Win_shared_query(win, rank: int):
    """(size_in_elements, the live shared view) of ``rank``'s region."""
    view = win.remote(rank)
    return view.size, view


def MPI_Win_sync(win) -> None:
    win.sync()


def MPI_Win_post(win, group) -> None:
    """PSCW exposure epoch: expose ``win`` to origin ranks ``group``."""
    win.post(group)


def MPI_Win_start(win, group) -> None:
    win.start(group)


def MPI_Win_complete(win) -> None:
    win.complete()


def MPI_Win_wait(win) -> None:
    win.wait()


def MPI_Win_test(win) -> bool:
    return win.test()


def MPI_Fetch_and_op(win, data: Any, target: int, op=ops.SUM, loc: Any = None):
    """MPI-3 atomic: combine ``data`` into the target window, return the
    previous value (one round-trip; the distributed-counter primitive)."""
    return win.fetch_and_op(target, data, op, loc)


def MPI_Compare_and_swap(win, compare: Any, new: Any, target: int,
                         loc: Any = None):
    return win.compare_and_swap(target, compare, new, loc)


def MPI_Win_flush(win, target: int) -> None:
    win.flush(target)


MPI_COMM_TYPE_SHARED = "shared"


def MPI_Comm_split_type(split_type=MPI_COMM_TYPE_SHARED, key: int = 0,
                        comm: Optional[Communicator] = None):
    """MPI_Comm_split_type(COMM_TYPE_SHARED): ranks that share memory.
    Process worlds are single-host (the launcher forks locally) → the
    whole communicator; multi-host SPMD communicators split by jax
    process (TpuCommunicator.split_type, ADVICE r3 #4)."""
    return _call(comm, "split_type", split_type, key)


MPI_Type_create_hvector = datatypes.type_create_hvector
MPI_Type_create_hindexed = datatypes.type_create_hindexed


def MPI_Win_lock_all(win) -> None:
    win.lock_all()


def MPI_Win_unlock_all(win) -> None:
    win.unlock_all()


def MPI_Win_flush_all(win) -> None:
    win.flush_all()


def MPI_Win_flush_local(win, target: int) -> None:
    win.flush_local(target)


def MPI_Get_accumulate(win, data: Any, target: int, op=ops.SUM,
                       loc: Any = None):
    return win.get_accumulate(target, data, op, loc)


def MPI_Rput(win, data: Any, target: int, loc: Any = None):
    return win.rput(target, data, loc)


def MPI_Rget(win, target: int, loc: Any = None):
    return win.rget(target, loc)


def MPI_Raccumulate(win, data: Any, target: int, op=ops.SUM,
                    loc: Any = None):
    return win.raccumulate(target, data, op, loc)


def MPI_Comm_idup(comm: Optional[Communicator] = None):
    """MPI_Comm_idup: dup is synchronous here, so the request completes
    at creation carrying the new communicator."""
    from .communicator import _CompletedRequest

    return _CompletedRequest(_world(comm).dup())


# -- MPI-4 previews (mpi_tpu/mpi4.py) ---------------------------------------


def MPI_Bcast_init(obj: Any, root: int = 0,
                   comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "bcast", obj, root)


def MPI_Allreduce_init(obj: Any, op=ops.SUM,
                       comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "allreduce", obj, op)


def MPI_Reduce_init(obj: Any, op=ops.SUM, root: int = 0,
                    comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "reduce", obj, op, root)


def MPI_Allgather_init(obj: Any, comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "allgather", obj)


def MPI_Alltoall_init(objs: Any, comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "alltoall", objs)


def MPI_Reduce_scatter_init(blocks: Any, op=ops.SUM,
                            comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "reduce_scatter", blocks, op)


def MPI_Barrier_init(comm: Optional[Communicator] = None):
    from .mpi4 import persistent_collective

    return persistent_collective(_world(comm), "barrier")


def MPI_Psend_init(buf: Any, partitions: int, dest: int, tag: int = 0,
                   comm: Optional[Communicator] = None):
    from .mpi4 import psend_init

    return psend_init(_world(comm), buf, partitions, dest, tag)


def MPI_Precv_init(partitions: int, source: int, tag: int = 0,
                   comm: Optional[Communicator] = None):
    from .mpi4 import precv_init

    return precv_init(_world(comm), partitions, source, tag)


def MPI_Pready(request, partition: int) -> None:
    request.pready(partition)


def MPI_Pready_range(request, lo: int, hi: int) -> None:
    request.pready_range(lo, hi)


def MPI_Parrived(request, partition: int) -> bool:
    return request.parrived(partition)


# -- MPI-4 sessions (mpi_tpu/mpi4.py Session) -------------------------------


def MPI_Session_init(info: Optional[dict] = None, errhandler=None):
    from .mpi4 import session_init

    return session_init(info, errhandler)


def MPI_Session_finalize(session) -> None:
    session.finalize()


def MPI_Session_get_num_psets(session, info: Optional[dict] = None) -> int:
    return session.get_num_psets(info)


def MPI_Session_get_nth_pset(session, n: int,
                             info: Optional[dict] = None) -> str:
    return session.get_nth_pset(n, info)


def MPI_Session_get_info(session) -> dict:
    return session.get_info()


def MPI_Group_from_session_pset(session, pset_name: str):
    return session.group_from_pset(pset_name)


def MPI_Comm_create_from_group(group, stringtag: str = "",
                               info: Optional[dict] = None,
                               errhandler=None, session=None):
    """The group carries no session in this implementation's Group type,
    so the session is an explicit (keyword) argument; omitting it uses a
    fresh default-runtime session — the common spelling."""
    if session is None:
        from .mpi4 import session_init

        session = session_init()
    return session.comm_create_from_group(group, stringtag, info,
                                          errhandler)


def MPI_Win_create_dynamic(comm: Optional[Communicator] = None):
    return _world(comm).win_create_dynamic()


def MPI_Win_attach(win, key: str, array: Any):
    return win.attach(key, array)


def MPI_Win_detach(win, key: str):
    return win.detach(key)


# -- MPI_T tool interface (mpi_tpu/mpit.py) ---------------------------------

from . import mpit as _mpit  # noqa: E402 - grouped with its API block

MPI_T_cvar_list = _mpit.cvar_list
MPI_T_cvar_read = _mpit.cvar_read
MPI_T_cvar_write = _mpit.cvar_write
MPI_T_pvar_list = _mpit.pvar_list
MPI_T_pvar_read = _mpit.pvar_read
MPI_T_pvar_session_create = _mpit.session_create
