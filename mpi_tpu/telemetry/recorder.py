"""The flight recorder: a fixed-size, lock-light ring of binary events.

One :class:`Recorder` per process (module-level singleton in
``mpi_tpu.telemetry.__init__``); every instrumentation seam in the
library tests the singleton for ``None`` and returns — the established
off-mode contract (ft/verify/progress all gate the same way), asserted
mechanically by the ``trace_events`` pvar staying 0 and the
``bench.py --verify-overhead --trace`` leg.

An event is one tuple ``(t_ns, dur_ns, kind, name, tid, attrs)``:

* ``t_ns`` — ``time.perf_counter_ns()`` at emit (monotonic; the
  recorder stores a (wall, mono) anchor pair taken at enable so export
  maps every event onto the wall clock);
* ``dur_ns`` — 0 for instant events, the span length for completed
  spans (collective begin/end, link heal, lease job, blocked wait);
* ``kind``/``name`` — the event class and the specific event
  (``("coll", "allreduce")``, ``("link", "heal")``, ...);
* ``tid`` — the emitting thread (local-backend ranks are threads; the
  progress engine / fold pool / reader threads get their own rows in
  the trace viewer);
* ``attrs`` — a small dict (algorithm, bytes, seq, peer, ...) or None.

The ring OVERWRITES oldest-first once full (``dropped`` counts what was
lost — a flight recorder keeps the newest history, like its namesake);
capacity comes from ``MPI_TPU_TRACE_EVENTS`` (default 65536/rank,
~4MB).  Emission is one tuple build + one index bump under a plain
lock — "lock-light" here means the critical section is two statements,
not that it is lock-free; at the event rates this library produces
(thousands/s, not millions/s) a futex-free fancy structure would buy
noise.

Export is Chrome-trace / Perfetto JSON (``chrome://tracing`` or
https://ui.perfetto.dev): span events as ``ph: "X"``, instants as
``ph: "i"``, one process per rank, one track per thread.  Cross-rank
merging + clock-offset refinement live in ``tools/tracecat.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import mpit as _mpit
from ..profiling import CommStats

_DEFAULT_CAPACITY = int(os.environ.get("MPI_TPU_TRACE_EVENTS", "65536"))

# Span kinds the Chrome export renders as complete ("X") events; every
# other kind is an instant.  A kind may still emit dur=0 spans (a
# sub-microsecond collective) — they render fine.
_SPAN_KINDS = frozenset({"coll", "wait", "link", "lease", "sm", "heal"})

# Blocked-wait spans below this duration are NOT recorded: a healthy
# recv that hit its message on the first slice would otherwise emit one
# event per receive and drown the trace in noise.  1ms ~= 20 FT poll
# slices of headroom above a same-box delivery.
WAIT_MIN_NS = 1_000_000


class Recorder:
    """Fixed-size ring of timestamped events + per-op comm counters."""

    def __init__(self, capacity: int = 0, rank: Optional[int] = None,
                 trace_dir: Optional[str] = None) -> None:
        self.capacity = int(capacity) or _DEFAULT_CAPACITY
        if self.capacity < 1:
            raise ValueError("trace capacity must be >= 1")
        self.rank = rank
        self.trace_dir = trace_dir
        self.pid = os.getpid()
        # the clock anchor pair: every event timestamp is monotonic;
        # export maps mono -> wall through this pair, so single-host
        # multi-process traces land on one shared timeline (refined
        # further by tracecat's message-matching offset estimation)
        self.wall_anchor_ns = time.time_ns()
        self.mono_anchor_ns = time.perf_counter_ns()
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._n = 0  # total events ever emitted (ring index = n % cap)
        self._lock = threading.Lock()
        self._tls = threading.local()  # per-thread open-collective stack
        # ISSUE 13 satellite: profiling.CommStats finally has a live
        # producer — per-collective op/byte counters filled by every
        # traced collective (profiling.comm_stats() reads them)
        self.stats = CommStats()

    # -- emission ----------------------------------------------------------

    def emit(self, kind: str, name: str, dur_ns: int = 0,
             attrs: Optional[dict] = None) -> None:
        evt = (time.perf_counter_ns() - dur_ns, dur_ns, kind, name,
               threading.get_ident(), attrs)
        with self._lock:
            self._buf[self._n % self.capacity] = evt
            self._n += 1
        _mpit.count(trace_events=1)

    # -- collective spans (communicator.py seam) ---------------------------

    def coll_begin(self, name: str, algorithm: Optional[str],
                   nbytes: Optional[int]) -> list:
        """Open a collective span on this thread; returns the mutable
        span cell (``_resolve_algorithm`` rewrites slot 1 with the
        RESOLVED algorithm via :meth:`note_algorithm`)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        cell = [name, algorithm, nbytes, time.perf_counter_ns()]
        stack.append(cell)
        return cell

    def note_algorithm(self, algorithm: str) -> None:
        """Record the resolved algorithm into the innermost open
        collective span (the ``_resolve_algorithm`` seam — one line at
        the single gate every host collective already passes)."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack[-1][1] = algorithm

    def coll_end(self, cell: list, error: Optional[str] = None) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is cell:
            stack.pop()
        name, algorithm, nbytes, t0 = cell
        dur = time.perf_counter_ns() - t0
        attrs: Dict[str, Any] = {}
        if algorithm is not None:
            attrs["algorithm"] = algorithm
        if nbytes is not None:
            attrs["nbytes"] = int(nbytes)
        if error is not None:
            attrs["error"] = error
        self.emit("coll", name, dur_ns=dur, attrs=attrs or None)
        with self._lock:
            # local-backend rank threads share this recorder: the
            # CommStats dict bumps need the same lock emit holds
            self.stats.record(name, int(nbytes or 0))
        _mpit.hist_record("coll_latency_s", dur / 1e9)

    # -- introspection -----------------------------------------------------

    @property
    def events_total(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def dump(self) -> List[dict]:
        """Events oldest-first as dicts (tests / ad-hoc inspection)."""
        with self._lock:
            n = self._n
            if n <= self.capacity:
                raw = self._buf[:n]
            else:
                cut = n % self.capacity
                raw = self._buf[cut:] + self._buf[:cut]
        return [{"t_ns": t, "dur_ns": d, "kind": k, "name": nm,
                 "tid": tid, "attrs": a or {}}
                for (t, d, k, nm, tid, a) in raw]

    def find(self, kind: str, name: Optional[str] = None) -> List[dict]:
        return [e for e in self.dump()
                if e["kind"] == kind and (name is None or e["name"] == name)]

    # -- Chrome-trace export -----------------------------------------------

    def _wall_us(self, t_ns: int) -> float:
        return (self.wall_anchor_ns + (t_ns - self.mono_anchor_ns)) / 1e3

    def chrome_trace(self) -> dict:
        """The Perfetto/chrome://tracing document for THIS rank.  The
        ``mpi_tpu`` metadata block carries what tracecat.py needs for
        cross-rank alignment (anchors, rank, drop count)."""
        pid = self.pid if self.rank is None else self.rank
        events: List[dict] = [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": (f"rank {self.rank}" if self.rank is not None
                               else f"pid {self.pid}")}},
        ]
        for e in self.dump():
            rec = {"pid": pid, "tid": e["tid"],
                   "name": e["name"], "cat": e["kind"],
                   "ts": self._wall_us(e["t_ns"]),
                   "args": e["attrs"]}
            if e["kind"] in _SPAN_KINDS:
                rec["ph"] = "X"
                rec["dur"] = e["dur_ns"] / 1e3
            else:
                rec["ph"] = "i"
                rec["s"] = "t"
            events.append(rec)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "mpi_tpu": {
                "rank": self.rank, "pid": self.pid,
                "wall_anchor_ns": self.wall_anchor_ns,
                "mono_anchor_ns": self.mono_anchor_ns,
                "events_total": self.events_total,
                "dropped": self.dropped,
                "capacity": self.capacity,
            },
        }

    def export_chrome(self, path: str) -> str:
        """Write the Chrome-trace JSON atomically; returns the path."""
        doc = self.chrome_trace()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def export_to_dir(self, trace_dir: Optional[str] = None
                      ) -> Optional[str]:
        """Standard per-rank export: ``<dir>/trace.r<rank>.<pid>.json``
        (pid-suffixed — serve workers and relaunched worlds share trace
        dirs across process generations).  None when no dir configured."""
        d = trace_dir or self.trace_dir
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        tag = "x" if self.rank is None else str(self.rank)
        return self.export_chrome(
            os.path.join(d, f"trace.r{tag}.{self.pid}.json"))
