"""MUST-style runtime verifier + static linter (ISSUE 5 tentpole).

The acceptance matrix: each of the six seeded bug classes — cross
send-send deadlock, rank-divergent collective order, root mismatch,
truncating recv (divergent vector counts / reduce geometry), leaked
request, overlapping nonblocking buffers — produces the PRECISE
diagnostic naming the ranks and operations involved, with no test
hanging; clean programs (including the segmented engine under forced
multi-segment pipelining) produce none; and verify=False leaves the
zero-copy hot path's pvar contracts untouched.
"""

import gc
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from mpi_tpu import checker, ft, mpit, verify  # noqa: E402
from mpi_tpu import communicator as _comm_mod  # noqa: E402
from mpi_tpu.errors import (CollectiveMismatchError, DeadlockError,  # noqa: E402
                            MPI_ERR_OTHER, MPI_ERR_PENDING, error_class)
from mpi_tpu.transport.local import run_local  # noqa: E402

STALL = 0.4  # tight stall bound so deadlock tests converge in ~1s


@pytest.fixture(autouse=True)
def _fast_stall_and_clean_report():
    old = mpit.cvar_read("verify_stall_timeout_s")
    mpit.cvar_write("verify_stall_timeout_s", STALL)
    gc.collect()
    verify.finalize_report()  # drain leftovers from earlier tests
    yield
    mpit.cvar_write("verify_stall_timeout_s", old)
    gc.collect()
    verify.finalize_report()


def _run(fn, nranks=2, **kw):
    kw.setdefault("timeout", 30.0)
    kw.setdefault("verify", True)
    return run_local(fn, nranks, **kw)


# -- deadlock detection ------------------------------------------------------

def test_cross_send_deadlock_is_diagnosed_not_hung():
    """Seeded bug #1: both ranks recv before their sends can ever be
    posted — the classic head-to-head cycle.  DeadlockError (not a
    run_local timeout) naming BOTH ranks, their pending recvs, and the
    user call sites."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            comm.recv(source=1, tag=7)   # blocks forever: 1 never sends
            comm.send("a", 1, tag=7)
        else:
            comm.recv(source=0, tag=7)
            comm.send("b", 0, tag=7)

    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    took = time.monotonic() - t0
    assert took < 20.0, f"diagnosis took {took:.1f}s (should be ~1s)"
    cause = ei.value.__cause__
    assert isinstance(cause, DeadlockError), cause
    msg = str(cause)
    assert "rank 0" in msg and "rank 1" in msg
    assert "recv(source=1, tag=7)" in msg and "recv(source=0, tag=7)" in msg
    assert "test_verify.py" in msg  # the call sites
    assert sorted(cause.ranks) == [0, 1]
    assert ses.read("verify_deadlocks_detected") >= 1
    assert error_class(cause) == MPI_ERR_PENDING


def test_wait_on_exited_rank_is_diagnosed():
    """A rank blocked on a peer whose program already RETURNED is stuck
    forever too — the 'waiting for a terminated process' diagnosis."""

    def fn(comm):
        if comm.rank == 0:
            comm.recv(source=1, tag=3)  # rank 1 exits without sending

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, DeadlockError)
    assert "exited" in str(cause)
    assert "rank 0" in str(cause)


def test_any_source_knot_detected_but_live_peer_prevents_false_positive():
    """OR semantics: an ANY_SOURCE recv deadlocks only when EVERY
    potential sender is provably stuck (a knot); one computing peer
    keeps the picture open and the message eventually lands."""

    def live(comm):
        if comm.rank == 0:
            return comm.recv(source=-1, tag=1)
        # peer 'computes' well past the stall bound, then delivers
        time.sleep(3 * STALL)
        comm.send("late", 0, tag=1)

    out = _run(live)
    assert out[0] == "late"

    def knot(comm):
        if comm.rank == 0:
            comm.recv(source=-1, tag=1)  # OR over {1}; 1 is AND on 0
        else:
            comm.recv(source=0, tag=2)

    with pytest.raises(RuntimeError) as ei:
        _run(knot)
    assert isinstance(ei.value.__cause__, DeadlockError)


def test_unmatched_tag_deadlock_reports_queued_messages():
    """The wrong-tag case: bytes ARE queued but can never match — the
    diagnostic lists the unmatched pending messages, which is the line
    a user needs to spot the tag typo."""

    def fn(comm):
        if comm.rank == 0:
            comm.send("x", 1, tag=5)
            comm.recv(source=1, tag=6)
        else:
            comm.send("y", 0, tag=5)
            comm.recv(source=0, tag=6)

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, DeadlockError)
    assert "unmatched message" in str(cause)


def test_find_deadlock_and_or_semantics():
    """The pure AND-OR analysis (checker.find_deadlock): cycles, knots,
    exited ranks, and the no-false-positive guarantees."""
    # 2-cycle
    assert checker.find_deadlock(
        {0: ("AND", [1]), 1: ("AND", [0])}, range(2)) == [0, 1]
    # a running third rank doesn't change the cycle
    assert checker.find_deadlock(
        {0: ("AND", [1]), 1: ("AND", [0])}, range(3)) == [0, 1]
    # blocked on a running rank: no deadlock
    assert checker.find_deadlock({0: ("AND", [2])}, range(3)) == []
    # OR with one live target: open
    assert checker.find_deadlock(
        {0: ("OR", [1, 2]), 1: ("AND", [0])}, range(3)) == []
    # OR knot: every target stuck
    assert checker.find_deadlock(
        {0: ("OR", [1, 2]), 1: ("AND", [0]), 2: ("AND", [1])},
        range(3)) == [0, 1, 2]
    # waiting on an exited rank is hopeless
    assert checker.find_deadlock(
        {0: ("AND", [1])}, range(2), exited=[1]) == [0]
    # waitall (AND set): one stuck member dooms it, one live one doesn't
    assert checker.find_deadlock(
        {0: ("AND", [1, 2]), 1: ("AND", [0])}, range(3)) == [0, 1]
    assert checker.find_deadlock(
        {0: ("OR", [1, 2]), 1: ("AND", [0])}, range(3)) == []
    # unknown wait targets: conservative, never reported
    assert checker.find_deadlock({0: ("AND", [])}, range(2)) == []


def test_poll_slice_matches_ft():
    """The verifier rides the FT slice-poll plumbing: one constant."""
    assert _comm_mod._FT_POLL_S == ft.POLL_S == ft._POLL_S


# -- collective matching -----------------------------------------------------

def test_divergent_collective_order():
    """Seeded bug #2: rank 0 enters bcast while rank 1 enters allreduce.
    Both raise CollectiveMismatchError naming both ranks, both
    signatures (collective names), and both call sites — before either
    schedule exchanges a byte."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            comm.bcast(1, root=0)  # mpilint: ok (deliberate divergence)
        else:
            comm.allreduce(np.ones(2))  # mpilint: ok

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    msg = str(cause)
    assert "bcast" in msg and "allreduce" in msg
    assert "rank 0" in msg and "rank 1" in msg
    assert "test_verify.py" in msg
    assert sorted(cause.ranks) == [0, 1]
    assert len(cause.signatures) == 2 and len(cause.sites) == 2
    assert ses.read("verify_collective_mismatches") >= 1
    assert error_class(cause) == MPI_ERR_OTHER


def test_root_mismatch():
    """Seeded bug #3: same collective, different roots."""

    def fn(comm):
        comm.bcast(np.ones(2), root=comm.rank)

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    assert "root=0" in str(cause) and "root=1" in str(cause)


def test_reduce_geometry_mismatch():
    """Seeded bug #4a: mismatched reduce geometry — rank 1's allreduce
    payload is half the size (the truncating-reduce case)."""

    def fn(comm):
        comm.allreduce(np.ones(8 if comm.rank == 0 else 4, np.float32))

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    assert "(8,)" in str(cause) and "(4,)" in str(cause)


def test_reduce_op_and_dtype_mismatch():
    def op_fn(comm):
        from mpi_tpu import ops

        comm.allreduce(np.ones(4), op=ops.SUM if comm.rank == 0
                       else ops.MAX)

    with pytest.raises(RuntimeError) as ei:
        _run(op_fn)
    assert isinstance(ei.value.__cause__, CollectiveMismatchError)
    assert "op=sum" in str(ei.value.__cause__)

    def dt_fn(comm):
        comm.allreduce(np.ones(4, np.float32 if comm.rank == 0
                               else np.float64))

    with pytest.raises(RuntimeError) as ei:
        _run(dt_fn)
    assert isinstance(ei.value.__cause__, CollectiveMismatchError)


def test_allgatherv_counts_divergence_truncation():
    """Seeded bug #4b: truncating recv counts — rank 1 declares fewer
    rows for rank 1's contribution than rank 1 actually sends."""

    def fn(comm):
        counts = [2, 2] if comm.rank == 0 else [2, 1]
        return comm.allgatherv(np.ones((2, 3)), counts)

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    assert "counts=[2, 2]" in str(cause) and "counts=[2, 1]" in str(cause)


def test_collective_count_divergence_deadlock_names_collective():
    """Rank 1 calls ONE collective fewer (falls off the end): rank 0's
    signature exchange can never complete — diagnosed as a deadlock
    naming the enclosing collective, not a silent hang."""

    def fn(comm):
        comm.barrier()
        if comm.rank == 0:
            comm.barrier()  # mpilint: ok (deliberate divergence)

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, DeadlockError)
    assert "barrier" in str(cause)


# -- request / buffer / comm lints -------------------------------------------

def test_leaked_requests_reported():
    """Seeded bug #5: an isend GC'd unwaited and an irecv dropped
    unwaited both land in the finalize report with rank, op, peer, tag,
    and site."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            comm.isend(np.ones(4), 1, tag=3)   # never waited
            comm.recv(source=1, tag=5)
        else:
            comm.send(1, 0, tag=5)
            comm.irecv(source=0, tag=3)        # never waited
        gc.collect()

    _run(fn)
    report = verify.finalize_report()
    leaks = [r for r in report if "leaked request" in r]
    assert any("isend(peer=1, tag=3)" in r and "rank 0" in r for r in leaks), \
        report
    assert any("irecv(peer=0, tag=3)" in r and "rank 1" in r for r in leaks), \
        report
    assert ses.read("verify_requests_leaked") >= 2


def test_waited_requests_not_reported():
    def fn(comm):
        peer = 1 - comm.rank
        req = comm.irecv(source=peer, tag=2)
        comm.isend(comm.rank, peer, tag=2).wait()
        return req.wait()

    out = _run(fn)
    assert out == [1, 0]
    gc.collect()
    assert not [r for r in verify.finalize_report()
                if "leaked request" in r]


def test_double_wait_lint():
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            comm.send(np.ones(2), 1, tag=1)
        else:
            r = comm.irecv(source=0, tag=1)
            r.wait()
            r.wait()   # second wait on a completed request

    _run(fn)
    report = verify.finalize_report()
    assert any("double-wait" in r for r in report), report
    assert ses.read("verify_double_waits") >= 1


def test_overlapping_nonblocking_buffers():
    """Seeded bug #6: two pending receives into overlapping slices of
    one buffer — the message race.  Diagnostic names both ops/sites."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        buf = np.zeros(8)
        peer = 1 - comm.rank
        r1 = comm.recv_init(source=peer, tag=2, buf=buf).start()
        r2 = comm.recv_init(source=peer, tag=2, buf=buf[2:6]).start()
        comm.send(np.arange(8.0), peer, tag=2)
        comm.send(np.arange(4.0), peer, tag=2)
        r1.wait()
        r2.wait()

    _run(fn)
    report = verify.finalize_report()
    overlaps = [r for r in report if "overlapping live buffers" in r]
    assert overlaps and "recv_init" in overlaps[0], report
    assert "test_verify.py" in overlaps[0]
    assert ses.read("verify_buffer_overlaps") >= 1


def test_disjoint_buffers_not_reported():
    def fn(comm):
        buf = np.zeros(8)
        peer = 1 - comm.rank
        r1 = comm.recv_init(source=peer, tag=2, buf=buf[:4]).start()
        r2 = comm.recv_init(source=peer, tag=2, buf=buf[4:]).start()
        comm.send(np.arange(4.0), peer, tag=2)
        comm.send(np.arange(4.0) + 4, peer, tag=2)
        r1.wait()
        r2.wait()
        return buf.sum()

    out = _run(fn)
    assert out == [28.0, 28.0]
    assert not [r for r in verify.finalize_report() if "overlapping" in r]


def test_unfreed_comm_lint_and_freed_comm_clean():
    ses = mpit.session_create()
    ses.reset_all()

    def leaky(comm):
        sub = comm.split(0)
        sub.barrier()

    _run(leaky)
    report = verify.finalize_report()
    assert any("never freed" in r and "split()" in r for r in report), report
    assert ses.read("verify_comms_unfreed") >= 1

    def clean(comm):
        sub = comm.dup()
        sub.barrier()
        sub.free()

    _run(clean)
    assert not [r for r in verify.finalize_report() if "never freed" in r]


# -- clean programs produce no diagnostics -----------------------------------

def test_clean_program_full_collective_family():
    """The whole collective family + p2p under verify=True: correct
    results, empty report, zero verify-event pvars."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        from mpi_tpu import ops

        r, p = comm.rank, comm.size
        out = []
        out.append(float(np.sum(comm.bcast(np.arange(4.0), root=0))))
        out.append(float(comm.allreduce(np.float64(r + 1), op=ops.SUM)))
        out.append(float(np.sum(comm.allgather(np.full(2, r))[r])))
        red = comm.reduce(np.ones(3), root=1)
        out.append(float(red.sum()) if r == 1 else None)
        comm.barrier()
        out.append(float(np.asarray(
            comm.alltoall([np.full(1, r * p + d) for d in range(p)])).sum()))
        out.append(float(np.asarray(comm.scan(np.ones(2))).sum()))
        out.append(float(np.asarray(
            comm.reduce_scatter(np.ones((p, 2)))).sum()))
        got = comm.sendrecv(r, (r + 1) % p, (r - 1) % p, 9, 9)
        out.append(got)
        req = comm.ibarrier()
        req.wait()
        return out

    results = _run(fn, nranks=3)
    assert results[0][1] == 6.0  # allreduce sum 1+2+3
    gc.collect()
    assert verify.finalize_report() == []
    for p in mpit.pvar_list():
        # verify_clock_bytes is a COST counter, nonzero by design while
        # verify mode piggybacks vector clocks; every verify EVENT pvar
        # (deadlocks, mismatches, races, ...) must stay 0 on clean runs
        if p.startswith("verify_") and p != "verify_clock_bytes":
            assert ses.read(p) == 0, (p, ses.read(p))
    assert ses.read("verify_clock_bytes") > 0  # the clocks actually ran


def test_clean_segmented_engine_under_verify():
    """The zero-copy segmented engine with FORCED multi-segment
    pipelining (tiny collective_segment_bytes) under verify=True: the
    pipelined internal irecvs must neither trip the request lints nor
    the deadlock detector, and parity holds."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)
    try:
        def fn(comm):
            arr = np.arange(256.0, dtype=np.float64) + comm.rank
            ring = comm.allreduce(arr, algorithm="ring")
            raben = comm.allreduce(arr, algorithm="rabenseifner")
            rs = comm.reduce_scatter(
                np.tile(arr, (comm.size, 1)) + comm.rank)
            return float(ring.sum()), float(raben.sum()), float(rs.sum())

        out = _run(fn)
        assert out[0][0] == out[1][0] == pytest.approx(out[0][1])
    finally:
        mpit.cvar_write("collective_segment_bytes", old)
    gc.collect()
    assert verify.finalize_report() == []


def test_verify_with_fault_tolerance_coexists():
    """FT and the verifier share the slice loop: both enabled, a clean
    program stays clean and correct."""

    def fn(comm):
        return float(comm.allreduce(np.ones(8)).sum())

    out = run_local(fn, 2, fault_tolerance=True, verify=True, timeout=30.0)
    assert out == [16.0, 16.0]
    assert verify.finalize_report() == []


def test_verify_run_runtime_verify_fold():
    """The folded seed: trace-based matching verification + the runtime
    verifier in one call (mpi_tpu.trace.verify_run)."""
    from mpi_tpu.trace import verify_run

    def clean(comm):
        peer = 1 - comm.rank
        comm.send(comm.rank, peer, tag=1)
        return comm.recv(source=peer, tag=1)

    results, problems = verify_run(clean, 2, runtime_verify=True)
    assert results == [1, 0]
    assert problems == []

    def leaky(comm):
        peer = 1 - comm.rank
        comm.send(comm.rank, peer, tag=1)   # never received: match leak
        gc.collect()

    _, problems = verify_run(leaky, 2, runtime_verify=True)
    assert any("never received" in p for p in problems), problems


# -- off-mode zero cost ------------------------------------------------------

def test_verify_off_leaves_hot_path_pvar_contracts():
    """The acceptance contract: verify=False keeps the segmented ring's
    zero-copy accounting bit-identical — 0 pickled array bytes, the
    engine's exact payload_copies — and no verify machinery runs."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        return comm.allreduce(np.ones(1 << 12, np.float32),
                              algorithm="ring")

    run_local(fn, 2, timeout=30.0)  # verify OFF (default)
    assert ses.read("bytes_pickled_sent") == 0
    assert ses.read("payload_copies") == 0
    for p in mpit.pvar_list():
        if p.startswith("verify_"):
            assert ses.read(p) == 0, p


def test_verify_off_requests_untracked():
    def fn(comm):
        if comm.rank == 0:
            comm.isend(1, 1, tag=0)          # leaked — but verify is OFF
        else:
            comm.irecv(source=0, tag=0)
        gc.collect()

    run_local(fn, 2, timeout=30.0)
    gc.collect()
    assert verify.finalize_report() == []


def test_verify_overhead_quick_smoke():
    """bench.py --verify-overhead: the leg runs green and its off-mode
    assertions (0 pickled bytes, 0 verify events) hold."""
    from benchmarks import verify_overhead

    assert verify_overhead.main(["--quick"]) == 0


# -- process worlds (FileBoard) ----------------------------------------------


def test_fileboard_summary_compaction(tmp_path):
    """ISSUE 6 satellite (PR-5 FileBoard residual): at ≥8 ranks read_all
    consults the compacted ``pending.summary.json`` first and re-reads
    ONLY per-rank files whose stat identity moved — correctness
    unchanged (entries, ages, staleness) with O(changed) parses instead
    of O(P)."""
    from mpi_tpu.verify.state import FileBoard

    size = 10
    rdv = str(tmp_path)
    boards = [FileBoard(rdv, r, size) for r in range(size)]
    for r in range(size):
        boards[r].publish(r, {"state": "blocked", "rank": r,
                              "targets": [(r + 1) % size], "mode": "AND"})

    reader = FileBoard(rdv, 0, size)
    out = reader.read_all()
    assert set(out) == set(range(size))
    assert all(out[r]["rank"] == r and "_age_s" in out[r]
               for r in range(size))
    assert reader.fallback_reads == size  # cold cache: full read once
    import os as _os
    import time as _time

    assert _os.path.exists(_os.path.join(rdv, FileBoard.SUMMARY))

    # steady state: nothing changed AND entries older than the mtime
    # trust horizon → stats only, zero entry parses
    _time.sleep(FileBoard._MTIME_TRUST_S + 0.1)
    reader.read_all()  # recency re-reads of the now-aged entries
    base_reads = reader.fallback_reads
    out2 = reader.read_all()
    assert reader.fallback_reads == base_reads
    assert {r: out2[r]["rank"] for r in out2} == \
        {r: out[r]["rank"] for r in out}

    # one rank republishes → re-read (it is both changed and recent);
    # the other aged, unchanged ranks stay served from the summary
    boards[3].publish(3, {"state": "blocked", "rank": 3,
                          "targets": [7], "mode": "AND"})
    out3 = reader.read_all()
    assert out3[3]["targets"] == [7]
    assert reader.fallback_reads == base_reads + 1

    # a retraction (unlink) disappears without any entry read (rank 3's
    # fresh file stays inside the trust horizon → re-read, nothing else)
    boards[5].publish(5, None)
    before = reader.fallback_reads
    out4 = reader.read_all()
    assert 5 not in out4 and len(out4) == size - 1
    assert reader.fallback_reads <= before + 1  # only recent rank 3

    # a FRESH reader seeds from the summary: only changed/missing files
    # need parsing (rank 3's record in the on-disk summary may predate
    # its republish depending on writer order — at most that one read)
    reader2 = FileBoard(rdv, 1, size)
    out5 = reader2.read_all()
    assert set(out5) == set(out4)
    assert reader2.fallback_reads <= 1

    # per-rank seq stamps are monotonic per publisher
    assert out3[3]["_seq"] > out[3]["_seq"]


def test_fileboard_summary_lock_serializes_compaction(tmp_path):
    """ISSUE 7 satellite (PR-6 FileBoard residual): the summary used to
    be last-writer-wins, so concurrent readers redid each other's
    fallback reads and overwrote each other's compactions.  Compaction
    now runs behind ``pending.summary.lock``:

    * the lock holder is the ONLY summary writer;
    * a reader that loses the race RELOADS the holder's summary instead
      of re-parsing unchanged files, still performs any reads
      correctness requires, and flushes its own dirtiness later;
    * a lock left by a dead reader is taken over past the staleness
      bound.
    """
    import os as _os
    import time as _time

    from mpi_tpu.verify.state import FileBoard

    size = 8
    rdv = str(tmp_path)
    pub = [FileBoard(rdv, r, size) for r in range(size)]
    for r in range(size):
        pub[r].publish(r, {"state": "blocked", "rank": r,
                           "targets": [(r + 1) % size], "mode": "AND"})
    _time.sleep(FileBoard._MTIME_TRUST_S + 0.1)

    # reader A compacts (writes the summary, holding the lock briefly)
    a = FileBoard(rdv, 0, size)
    out_a = a.read_all()
    assert set(out_a) == set(range(size))
    assert a.summary_writes == 1
    lock_path = _os.path.join(rdv, FileBoard.LOCK)
    assert not _os.path.exists(lock_path)  # released after the write

    # reader B arrives while "someone else" holds the lock: it must
    # adopt A's summary (zero redone parses for unchanged files), still
    # return every entry correctly, and NOT write the summary
    fd = _os.open(lock_path, _os.O_CREAT | _os.O_EXCL | _os.O_WRONLY)
    _os.close(fd)
    summary_path = _os.path.join(rdv, FileBoard.SUMMARY)
    mtime_before = _os.stat(summary_path).st_mtime_ns
    b = FileBoard(rdv, 1, size)
    b._cache_loaded = True  # simulate a reader whose cache is cold/stale
    b._cache = {}
    out_b = b.read_all()
    assert {r: out_b[r]["rank"] for r in out_b} == \
        {r: out_a[r]["rank"] for r in out_a}
    assert b.fallback_reads == 0          # adopted A's compaction
    assert b.summary_writes == 0
    assert _os.stat(summary_path).st_mtime_ns == mtime_before

    # a genuinely-changed rank is STILL read under a held lock
    # (correctness never waits on the lock), and the dirtiness is
    # remembered and flushed once the lock frees
    pub[4].publish(4, {"state": "blocked", "rank": 4, "targets": [0],
                       "mode": "AND"})
    _time.sleep(FileBoard._MTIME_TRUST_S + 0.1)
    out_b2 = b.read_all()
    assert out_b2[4]["targets"] == [0]
    assert b.fallback_reads == 1 and b.summary_writes == 0
    _os.unlink(lock_path)
    b.read_all()
    assert b.summary_writes == 1  # deferred compaction flushed

    # stale-lock takeover: a lock whose holder died mid-compaction is
    # reclaimed once its mtime is past the staleness bound
    fd = _os.open(lock_path, _os.O_CREAT | _os.O_EXCL | _os.O_WRONLY)
    _os.close(fd)
    old = _time.time() - FileBoard._LOCK_STALE_S - 1.0
    _os.utime(lock_path, (old, old))
    pub[6].publish(6, {"state": "blocked", "rank": 6, "targets": [2],
                       "mode": "AND"})
    _time.sleep(FileBoard._MTIME_TRUST_S + 0.1)
    out_b3 = b.read_all()
    assert out_b3[6]["targets"] == [2]
    assert b.lock_takeovers == 1 and b.summary_writes == 2
    assert not _os.path.exists(lock_path)


def test_fileboard_two_concurrent_readers(tmp_path):
    """Two readers hammering read_all concurrently while publishers
    churn: every read returns a consistent snapshot (no torn entries,
    correct ranks) and the lock never deadlocks or leaks."""
    import os as _os
    import threading as _th
    import time as _time

    from mpi_tpu.verify.state import FileBoard

    size = 6
    rdv = str(tmp_path)
    pub = [FileBoard(rdv, r, size) for r in range(size)]
    for r in range(size):
        pub[r].publish(r, {"state": "blocked", "rank": r,
                           "targets": [(r + 1) % size], "mode": "AND"})
    stop = _time.monotonic() + 2.0
    errors = []

    def reader_loop(rank):
        board = FileBoard(rdv, rank, size)
        while _time.monotonic() < stop:
            out = board.read_all()
            for r, e in out.items():
                if e["rank"] != r:
                    errors.append(f"torn entry: {r} -> {e}")

    def publisher_loop():
        i = 0
        while _time.monotonic() < stop:
            i += 1
            pub[i % size].publish(i % size, {
                "state": "blocked", "rank": i % size,
                "targets": [(i + 1) % size], "mode": "AND"})
            _time.sleep(0.01)

    threads = [_th.Thread(target=reader_loop, args=(0,)),
               _th.Thread(target=reader_loop, args=(1,)),
               _th.Thread(target=publisher_loop)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert not errors, errors[:5]
    assert not _os.path.exists(_os.path.join(rdv, FileBoard.LOCK))


_E2E_DEADLOCK = """
import os, sys
sys.path.insert(0, {repo!r})
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import DeadlockError

mpit.cvar_write("verify_stall_timeout_s", 1.0)
comm = mpi_tpu.init()   # MPI_TPU_VERIFY=1: pending-op files + analysis
try:
    comm.recv(source=1 - comm.rank, tag=4)
    sys.exit(7)  # impossibly completed
except DeadlockError as e:
    msg = str(e)
    assert "rank 0" in msg and "rank 1" in msg, msg
    assert "tag=4" in msg, msg
    print(f"rank {{comm.rank}} diagnosed", flush=True)
    sys.exit(0)
"""

_E2E_CLEAN_SHM = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit, verify

comm = mpi_tpu.init()   # MPI_TPU_VERIFY=1 over the shm transport
out = comm.allreduce(np.ones(256, np.float32))          # sm arena path
assert float(out[0]) == comm.size
comm.barrier(algorithm="sm")
items = comm.allgather(np.full(4, comm.rank))
assert float(np.asarray(items)[1][0]) == 1.0
assert mpit.pvar_read("coll_sm_hits") >= 1, "arena did not serve"
# sweep the finalize-time lints BEFORE finalize (finalize would drain the
# report into a warning, making a later take_report() vacuously empty)
problems = verify.finalize_report()
assert problems == [], problems
for p in mpit.pvar_list():
    # clock bytes are verify-mode COST (piggybacked stamps), not an event
    if p.startswith("verify_") and p != "verify_clock_bytes":
        assert mpit.pvar_read(p) == 0, (p, mpit.pvar_read(p))
mpi_tpu.finalize()
print("clean shm verify OK", flush=True)
"""


def _spawn_world(tmp_path, script_body, nranks, backend):
    script = tmp_path / "prog.py"
    script.write_text(script_body.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir(exist_ok=True)
    procs = []
    for r in range(nranks):
        env = dict(os.environ)
        env.update({"MPI_TPU_RANK": str(r), "MPI_TPU_SIZE": str(nranks),
                    "MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": backend,
                    "MPI_TPU_VERIFY": "1", "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    return [(p.communicate(timeout=90.0), p.returncode) for p in procs]


def test_e2e_socket_deadlock_diagnosed(tmp_path):
    """Process world + FileBoard: a cross recv-recv deadlock between two
    socket rank PROCESSES is diagnosed on both sides via the rendezvous
    pending-op files — no hang, exit 0 from the handlers."""
    outs = _spawn_world(tmp_path, _E2E_DEADLOCK, 2, "socket")
    for (out, err), code in outs:
        assert code == 0, err[-900:]
        assert "diagnosed" in out


# -- wildcard-race detection (piggybacked vector clocks) ---------------------

def test_wildcard_race_observed_and_named():
    """Ranks 1 and 2 both send tag 7 to rank 0, which waits until BOTH
    are pending before receiving with ANY_SOURCE: the match order is
    pure arrival timing.  The vector clocks prove the two sends
    concurrent, and the detector names both candidate senders, the
    tag, and the receive site."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            while not (comm.iprobe(source=1, tag=7)
                       and comm.iprobe(source=2, tag=7)):
                time.sleep(0.001)
            a = comm.recv(source=-1, tag=7)
            b = comm.recv(source=-1, tag=7)
            return sorted([a, b])
        comm.send(f"m{comm.rank}", 0, tag=7)
        return None

    out = _run(fn, nranks=3)
    assert out[0] == ["m1", "m2"]
    assert ses.read("verify_wildcard_races") >= 1
    race = [ln for ln in verify.take_report() if "wildcard race" in ln]
    assert race, "no race line in the report"
    # the diagnostic names BOTH candidate senders and the receive
    assert "from rank 1" in race[0] and "rank 2" in race[0], race[0]
    assert "tag=7" in race[0]
    assert "test_verify.py" in race[0], race[0]  # site attribution


def test_ordered_senders_no_wildcard_race():
    """The happens-before twin: rank 1 sends its message THEN passes a
    token to rank 2, which only sends after the token — the two sends
    are ordered by the token edge, so even when both messages sit
    pending under the same wildcard receive there is no race, and the
    pvar stays 0 (clock bytes, the verify-mode cost, do not)."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            a = comm.recv(source=-1, tag=9)
            b = comm.recv(source=-1, tag=9)
            return sorted([a, b])
        if comm.rank == 1:
            comm.send("m1", 0, tag=9)
            comm.send("token", 2, tag=1)
        else:
            comm.recv(source=1, tag=1)  # HB edge: m2's send is after m1's
            comm.send("m2", 0, tag=9)
        return None

    out = _run(fn, nranks=3)
    assert out[0] == ["m1", "m2"]
    assert ses.read("verify_wildcard_races") == 0
    assert not [ln for ln in verify.peek_report() if "wildcard race" in ln]
    assert ses.read("verify_clock_bytes") > 0  # stamps did flow


_E2E_RACE = """
import sys, time
sys.path.insert(0, {repo!r})
import mpi_tpu
from mpi_tpu import mpit, verify

comm = mpi_tpu.init()   # MPI_TPU_VERIFY=1: clocks ride the wire frames
if comm.rank == 0:
    while not (comm.iprobe(source=1, tag=7)
               and comm.iprobe(source=2, tag=7)):
        time.sleep(0.001)
    a = comm.recv(source=-1, tag=7)
    b = comm.recv(source=-1, tag=7)
    assert sorted([a, b]) == ["m1", "m2"], (a, b)
    assert mpit.pvar_read("verify_wildcard_races") >= 1
    race = [ln for ln in verify.take_report() if "wildcard race" in ln]
    assert race, "no race line in the report"
    assert "from rank 1" in race[0] and "rank 2" in race[0], race[0]
    print("race observed", flush=True)
else:
    comm.send(f"m{{comm.rank}}", 0, tag=7)
    print("sent", flush=True)
comm.barrier()
mpi_tpu.finalize()
"""


@pytest.mark.parametrize("backend", ["shm", "socket"])
def test_e2e_wildcard_race_process_world(tmp_path, backend):
    """The same race on REAL process transports: the stamps survive the
    wire framing (raw and pickle paths), and rank 0's detector names
    both senders — proving the piggyback works end-to-end, not just on
    the in-process mailbox shortcut."""
    if backend == "shm":
        from mpi_tpu.native import ensure_built

        try:
            ensure_built()
        except Exception as e:  # pragma: no cover - no toolchain
            pytest.skip(f"native shm ring unavailable: {e}")
    outs = _spawn_world(tmp_path, _E2E_RACE, 3, backend)
    for (out, err), code in outs:
        assert code == 0, err[-900:]
    assert "race observed" in outs[0][0][0]


def test_e2e_shm_arena_clean_under_verify(tmp_path):
    """The sm-arena collectives under MPI_TPU_VERIFY=1: arena hits
    happen, results are right, and the verifier stays silent."""
    from mpi_tpu.native import ensure_built

    try:
        ensure_built()
    except Exception as e:  # pragma: no cover - no toolchain
        pytest.skip(f"native shm ring unavailable: {e}")
    outs = _spawn_world(tmp_path, _E2E_CLEAN_SHM, 2, "shm")
    for (out, err), code in outs:
        assert code == 0, err[-900:]
        assert "clean shm verify OK" in out


# -- static linter -----------------------------------------------------------

def test_lint_rank_conditional_collective():
    src = (
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        data = comm.bcast(x, root=0)\n"
        "    else:\n"
        "        data = None\n")
    (f,) = verify.lint_source(src, "prog.py")
    assert f.code == "MPL001" and f.line == 3 and "bcast" in f.msg
    # the matched form is clean
    clean = (
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        data = comm.bcast(big, root=0)\n"
        "    else:\n"
        "        data = comm.bcast(None, root=0)\n")
    assert verify.lint_source(clean, "prog.py") == []
    # a collective OUTSIDE the conditional is clean
    outside = (
        "def main(comm):\n"
        "    data = big if comm.rank == 0 else None\n"
        "    data = comm.bcast(data, root=0)\n")
    assert verify.lint_source(outside, "prog.py") == []


def test_lint_send_send_cycle():
    src = (
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        comm.send(a, 1)\n"
        "        b = comm.recv(source=1)\n"
        "    if comm.rank == 1:\n"
        "        comm.send(c, 0)\n"
        "        d = comm.recv(source=0)\n")
    (f,) = verify.lint_source(src, "prog.py")
    assert f.code == "MPL002" and "sendrecv" in f.msg
    # one side recv-first: no cycle
    ok = src.replace("        comm.send(c, 0)\n        d = comm.recv(source=0)\n",
                     "        d = comm.recv(source=0)\n        comm.send(c, 0)\n")
    assert verify.lint_source(ok, "prog.py") == []


def test_lint_count_truncation():
    src = (
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        MPI_Send(buf, dest=1, datatype=dt, count=8)\n"
        "    if comm.rank == 1:\n"
        "        out = MPI_Recv(source=0, datatype=dt, buf=b, count=4)\n")
    (f,) = verify.lint_source(src, "prog.py")
    assert f.code == "MPL003" and "truncates" in f.msg
    ok = src.replace("count=4", "count=8")
    assert verify.lint_source(ok, "prog.py") == []


def test_lint_revoked_without_errhandler():
    src = (
        "def recover(comm):\n"
        "    comm.revoke()\n"
        "    comm.allreduce(x)\n")
    (f,) = verify.lint_source(src, "prog.py")
    assert f.code == "MPL004" and "RevokedError" in f.msg
    ok_try = (
        "def recover(comm):\n"
        "    comm.revoke()\n"
        "    try:\n"
        "        comm.allreduce(x)\n"
        "    except Exception:\n"
        "        pass\n")
    assert verify.lint_source(ok_try, "prog.py") == []
    ok_handler = (
        "def recover(comm):\n"
        "    comm.set_errhandler(h)\n"
        "    comm.revoke()\n"
        "    comm.allreduce(x)\n")
    assert verify.lint_source(ok_handler, "prog.py") == []


def test_lint_suppression_comment():
    src = (
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        comm.barrier()  # mpilint: ok\n")
    assert verify.lint_source(src, "prog.py") == []


def test_mpilint_cli_and_repo_tree_clean():
    """The CLI exits 0 over the shipped tree (the check.sh gate's lint
    step) and 1 over a broken program."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mpilint.py"),
         os.path.join(REPO, "examples"), os.path.join(REPO, "mpi_tpu")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_mpilint_cli_flags_bad_file(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def main(comm):\n"
        "    if comm.rank == 0:\n"
        "        comm.barrier()\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mpilint.py"),
         str(bad)], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "MPL001" in proc.stdout


def test_check_sh_gate_runs_green():
    """ISSUE 5 satellite: the CI gate (compileall + mpilint [+ guard])
    chains green on the shipped tree."""
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "check.sh")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check.sh: OK" in proc.stdout


# -- compressed collectives: resolved wire dtype in the signature ------------


def test_compressed_wire_dtype_divergence():
    """ISSUE 8 satellite: the signature ring carries the RESOLVED wire
    dtype, so a group mixing bf16/int8 compressed entries raises
    CollectiveMismatchError naming both resolved signatures — instead
    of desynchronizing the segment exchange (one rank decoding frames
    the other never encoded)."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        algo = "compressed:bf16" if comm.rank == 0 else "compressed:int8"
        comm.allreduce(np.ones(64, np.float32), algorithm=algo)  # mpilint: ok

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    msg = str(cause)
    assert "compressed:bf16" in msg and "compressed:int8" in msg
    assert sorted(cause.ranks) == [0, 1]
    assert ses.read("verify_collective_mismatches") >= 1


def test_compressed_vs_uncompressed_divergence():
    """One rank compressed, the other on the classic ring: the
    algorithm field diverges and both ranks get the named error before
    any data moves."""

    def fn(comm):
        algo = "compressed" if comm.rank == 0 else "ring"
        comm.allreduce(np.ones(64, np.float32), algorithm=algo)  # mpilint: ok

    with pytest.raises(RuntimeError) as ei:
        _run(fn)
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    assert "compressed:bf16" in str(cause) and "ring" in str(cause)


def test_compressed_topk_k_rides_signature_counts(monkeypatch):
    """The resolved k rides the signature's COUNTS field (not just the
    algorithm string), so per-rank compress_topk_ratio skew — same
    spelling, same geometry, different k, which would silently misfold
    the sparse accumulation — is diagnosed.  The process-global cvar
    cannot be diverged per thread rank without racing, so the skew is
    injected at the signature boundary itself: a spy on collcheck.check
    first RECORDS that the resolver's k reaches the counts argument,
    then perturbs rank 1's counts and the real ring compare raises."""
    from mpi_tpu import compress
    from mpi_tpu.verify import collcheck

    n = 64
    seen = []
    real_check = collcheck.check

    def spy(comm, coll, **kw):
        seen.append((comm.rank, coll, kw.get("counts")))
        return real_check(comm, coll, **kw)

    monkeypatch.setattr(collcheck, "check", spy)
    _run(lambda c: c.allreduce(np.ones(n, np.float32),
                               algorithm="compressed:topk"))
    k = compress.topk_k(n)
    assert sorted((r, cnt) for r, _, cnt in seen) == [(0, (k,)), (1, (k,))]

    def skewed(comm, coll, **kw):
        if comm.rank == 1 and kw.get("counts") is not None:
            kw["counts"] = (kw["counts"][0] + 1,)  # ratio-skew analogue
        return real_check(comm, coll, **kw)

    monkeypatch.setattr(collcheck, "check", skewed)
    with pytest.raises(RuntimeError) as ei:
        _run(lambda c: c.allreduce(np.ones(n, np.float32),
                                   algorithm="compressed:topk"))
    cause = ei.value.__cause__
    assert isinstance(cause, CollectiveMismatchError)
    assert f"counts=[{k}]" in str(cause) and f"counts=[{k + 1}]" in str(cause)


def test_fileboard_scandir_single_pass(tmp_path):
    """ISSUE 8 satellite (verifier residual (d) tail): read_all's
    presence probe is ONE os.scandir pass over the rendezvous dir —
    never a per-rank os.stat loop (O(P) path lookups, mostly ENOENT
    for the running majority).  At 10 ranks with a sparse board:
    correctness identical (entries, ages, (mtime_ns,size) validation,
    trust horizon), non-pending siblings ignored, and os.stat provably
    out of the loop."""
    import os as _os
    import time as _time

    from mpi_tpu.verify.state import FileBoard

    size = 10
    rdv = str(tmp_path)
    boards = [FileBoard(rdv, r, size) for r in range(size)]
    blocked = [1, 4, 7, 9]  # the common case: only the stalled publish
    for r in blocked:
        boards[r].publish(r, {"state": "blocked", "rank": r,
                              "targets": [(r + 1) % size], "mode": "AND"})
    # sibling files the integer-suffix test must skip
    (tmp_path / "pending.summary.json.tmp.999.0").write_text("junk")
    (tmp_path / "pending.3.tmp").write_text("torn")
    (tmp_path / f"pending.{size + 5}").write_text("{}")  # out of range
    (tmp_path / "port.0").write_text("0")

    reader = FileBoard(rdv, 0, size)
    real_stat = _os.stat

    def no_pending_stat(path, *a, **kw):
        if isinstance(path, str) and "pending." in _os.path.basename(path) \
                and not _os.path.basename(path).startswith(
                    ("pending.summary",)):
            raise AssertionError(f"per-rank os.stat loop is back: {path}")
        return real_stat(path, *a, **kw)

    _os.stat = no_pending_stat
    try:
        out = reader.read_all()
    finally:
        _os.stat = real_stat
    assert set(out) == set(blocked)
    assert all(out[r]["rank"] == r and out[r]["_age_s"] >= 0.0
               for r in blocked)
    assert reader.fallback_reads == len(blocked)  # absent ranks: no read

    # identity validation + trust horizon carry over: age past the
    # horizon, re-read nothing; republish one, re-read exactly it
    _time.sleep(FileBoard._MTIME_TRUST_S + 0.1)
    reader.read_all()  # recency re-reads of the now-aged entries
    base = reader.fallback_reads
    steady = reader.read_all()
    assert set(steady) == set(blocked)
    assert reader.fallback_reads == base  # stats only, zero parses
    boards[4].publish(4, {"state": "blocked", "rank": 4, "targets": [0],
                          "mode": "AND"})
    out2 = reader.read_all()
    assert out2[4]["targets"] == [0]
    assert reader.fallback_reads == base + 1
    # retraction: unlink disappears with no parse
    boards[7].publish(7, None)
    assert 7 not in reader.read_all()
