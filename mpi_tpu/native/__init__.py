"""Native (C++) components and their build/load machinery.

The framework's CPU data plane follows the reference's architecture
(Python transports behind the Communicator plugin boundary) but adds a
native shared-memory ring (shmring.cpp) as the fast same-host path —
the role CUDA/NCCL-style native code plays in GPU frameworks is played
here by XLA/ICI on the TPU side and by this ring on the host side.
"""

from .build import ensure_built, load_shmring

__all__ = ["ensure_built", "load_shmring"]
