"""TCP socket + pickle transport — the reference's L1, reimplemented.

SURVEY.md §2 component #2 [B: "the existing socket/pickle path",
BASELINE.json:5]: per-pair TCP connections, length-prefixed pickle frames,
blocking matched receive.  This backend exists for two reasons (SURVEY.md §4
item 4): it is the CPU fallback, and it is the source-compatibility proof —
the same user program must run here and on backend=tpu.

Wire format per message: a fixed header ``!QQ`` = (flags|payload_len, seq)
followed by ``payload_len`` body bytes — either a pickle of the envelope
``(ctx, tag, obj)``, or (RAW_FLAG set, see transport/codec.py) a raw-array
frame whose numpy payload is sent straight from / received straight into
the array buffer, never pickled.  The context id is an arbitrary hashable
(tree-path tuple), so it rides inside the meta pickle rather than a
fixed-width header field.  The sender's world rank
is established once per connection by a hello frame (``!i``), not repeated
per message.  Rank discovery is file-based rendezvous: each rank binds an
OS-assigned port and publishes it as ``<rdv>/port.<rank>``; peers poll.  The
launcher (mpi_tpu/launcher.py) provides the rendezvous directory.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional

from .. import mpit as _mpit
from ..errors import EpochSkewError
from . import codec
from .base import Transport, TransportError

# Connection handshake: the connector sends (world rank, membership
# epoch), the acceptor answers with ITS epoch.  The epoch stamp is the
# elastic-membership guard (mpi_tpu/membership.py): after a shrink +
# rejoin every survivor requires replaced slots to present the new
# epoch, and a stale-epoch straggler (the falsely-suspected ousted rank)
# is rejected LOUDLY — EpochSkewError on the stale side — instead of
# cross-wiring two world generations through recycled rendezvous files.
_HELLO = struct.Struct("!iq")      # rank, epoch
_HELLO_ACK = struct.Struct("!q")   # acceptor's epoch
_HEADER = struct.Struct("!QQ")  # flags|payload_len, seq
_HOST = "127.0.0.1"
# Grace window before an ahead-of-us peer epoch is declared a SKEW: an
# epoch transition is broadcast, and a healthy member whose reader/
# control thread is scheduler-starved may see a peer's new epoch
# milliseconds before applying its own bump.  A genuinely ousted
# straggler's epoch never catches up, so the diagnosis still fires —
# just one grace later.
_EPOCH_GRACE_S = 2.0


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_into_exact(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` completely from the socket — the receive-side
    zero-copy path (bytes land straight in the final array)."""
    got = 0
    n = len(view)
    while got < n:
        try:
            r = sock.recv_into(view[got:])
        except OSError:
            return False
        if r == 0:
            return False
        got += r
    return True


class SocketTransport(Transport):
    # Loopback/intra-host TCP gets its exchange overlap from the kernel
    # socket buffers; what the engine's segmentation costs it is per-frame
    # host work (header + meta pickle + reader-thread delivery, all under
    # the GIL).  Measured on the host sweep (benchmarks/results/
    # host_sweep_post.json): 4MB segments beat 256KB by >3x at the 16MB
    # allreduce point, so prefer few, large frames here.
    coll_segment_hint = 4 << 20

    # Tuned-dispatch table key (mpi_tpu/tuning): rows measured on this
    # data plane.
    tuning_transport = "socket"

    def __init__(
        self,
        rank: int,
        size: int,
        rdv_dir: str,
        connect_timeout: float = 60.0,
        epoch: int = 0,
    ) -> None:
        super().__init__(rank, size)
        self.epoch = epoch  # a rejoiner is BORN into the current epoch
        self._rdv = rdv_dir
        self._connect_timeout = connect_timeout
        self._closing = False
        self._send_locks: Dict[int, threading.Lock] = {}
        self._conns: Dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._reader_threads = []
        self._seq = 0

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((_HOST, 0))
        self._listener.listen(size + 4)
        port = self._listener.getsockname()[1]
        tmp = os.path.join(rdv_dir, f".port.{rank}.tmp")
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, os.path.join(rdv_dir, f"port.{rank}"))

        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"mpi-tpu-accept-{rank}", daemon=True
        )
        self._accept_thread.start()

    # -- incoming ----------------------------------------------------------

    def _accept_loop(self) -> None:
        # accept ONLY; the hello/ack handshake runs in the per-
        # connection thread — a connector that stalls mid-hello (or a
        # scheduler-starved handshake on a loaded box) must never
        # serialize every OTHER peer's connection setup behind it
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._handshake_and_read, args=(conn,),
                name=f"mpi-tpu-reader-{self.world_rank}", daemon=True)
            # prune finished readers while appending: resident-server
            # worlds accept reconnects at every epoch transition, and
            # an append-only list would grow for the process lifetime
            self._reader_threads = [r for r in self._reader_threads
                                    if r.is_alive()]
            self._reader_threads.append(t)
            t.start()

    def _handshake_and_read(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = _recv_exact(conn, _HELLO.size)
        if hello is None:
            conn.close()
            return
        src, peer_epoch = _HELLO.unpack(hello)
        try:
            # always answer with our epoch FIRST: a rejected stale
            # connector needs it to diagnose (EpochSkewError) rather
            # than see an unexplained dead channel
            conn.sendall(_HELLO_ACK.pack(self.epoch))
        except OSError:
            conn.close()
            return
        if peer_epoch < self.min_peer_epoch.get(src, 0):
            # a dead-and-replaced slot's OLD incarnation dialing in:
            # admitting its reader would cross-wire two generations
            _mpit.count(epoch_skews=1)
            conn.close()
            return
        self._reader_loop(conn, src)

    def _reader_loop(self, conn: socket.socket, src: int) -> None:
        while True:
            head = _recv_exact(conn, _HEADER.size)
            if head is None:
                conn.close()
                return
            word, _seq = _HEADER.unpack(head)
            plen = word & codec.LEN_MASK
            if word & codec.RAW_FLAG:
                # raw frame: tiny meta pickle, then the bytes stream
                # straight into the freshly-allocated result array(s) —
                # one destination per segment for multi-segment frames
                mhead = _recv_exact(conn, codec.META.size)
                if mhead is None:
                    conn.close()
                    return
                (mlen,) = codec.META.unpack(mhead)
                meta = _recv_exact(conn, mlen)
                if meta is None:
                    conn.close()
                    return
                ctx, tag, out = codec.unpack_raw_meta(meta)
                dests = codec.raw_destinations(out)
                total = sum(a.nbytes for a in dests)
                if codec.META.size + mlen + total != plen:
                    # a frame whose meta disagrees with the length word
                    # would desync the byte stream (the remainder of the
                    # body parses as the next header) — kill the channel
                    # and fail loudly instead (threading excepthook),
                    # mirroring the shm receive path's mismatch check
                    conn.close()
                    raise ValueError(
                        f"raw frame length mismatch from rank {src}: "
                        f"header says {plen}, meta implies "
                        f"{codec.META.size + mlen + total}")
                ok = True
                for arr in dests:
                    if arr.nbytes and not _recv_into_exact(
                            conn, memoryview(arr).cast("B")):
                        ok = False
                        break
                if not ok:
                    conn.close()
                    return
                self.mailbox.deliver(src, ctx, tag, out)
                continue
            payload = _recv_exact(conn, plen)
            if payload is None:
                conn.close()
                return
            ctx, tag, obj = pickle.loads(payload)
            self.mailbox.deliver(src, ctx, tag, obj)

    # -- outgoing ----------------------------------------------------------

    def _peer_port_once(self, dest: int) -> Optional[int]:
        """Current content of the peer's rendezvous port file, or None.
        Re-read on every connection retry: a REPLACED slot's rejoiner
        re-publishes this file (atomic rename), and connecting to the
        stale port forever would turn an epoch transition into a hang."""
        try:
            with open(os.path.join(self._rdv, f"port.{dest}")) as f:
                text = f.read().strip()
            return int(text) if text else None
        except (FileNotFoundError, ValueError):
            return None

    def _peer_port(self, dest: int) -> int:
        deadline = time.monotonic() + self._connect_timeout
        while True:
            port = self._peer_port_once(dest)
            if port is not None:
                return port
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rank {self.world_rank}: peer {dest} did not publish a port "
                    f"within {self._connect_timeout}s (rendezvous dir {self._rdv})"
                )
            time.sleep(0.005)

    def _send_lock(self, dest: int) -> threading.Lock:
        # _conn_lock guards only the dict lookups; the (possibly slow)
        # rendezvous poll + connect happens under the per-dest lock so sends
        # to other, already-connected peers are never stalled behind it.
        with self._conn_lock:
            lock = self._send_locks.get(dest)
            if lock is None:
                lock = self._send_locks[dest] = threading.Lock()
            return lock

    def _get_conn_locked(self, dest: int) -> socket.socket:
        """Return the connection to ``dest``; caller holds the per-dest
        lock.  The handshake is hello(rank, epoch) → ack(peer epoch):

        * ack epoch NEWER than ours — WE are the stale straggler (shrunk
          out while we stalled past the detection bound): EpochSkewError,
          the diagnosed spelling of the false-suspicion group split.
        * ack epoch below ``min_peer_epoch[dest]`` — the PEER is a stale
          incarnation still squatting on the old rendezvous endpoint of a
          replaced slot: drop it and retry against a re-read port file
          until the replacement publishes.
        """
        with self._conn_lock:
            conn = self._conns.get(dest)
        if conn is not None:
            return conn
        self._peer_port(dest)  # bounded wait for a first publication
        deadline = time.monotonic() + self._connect_timeout
        skew_since = None
        while True:
            port = self._peer_port_once(dest)
            conn = None
            if port is not None:
                try:
                    conn = socket.create_connection((_HOST, port),
                                                    timeout=5.0)
                except OSError:
                    conn = None
            if conn is not None:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # generous ack window (an abandoned attempt just
                # retries): on an oversubscribed box the acceptor's
                # handshake thread can be scheduler-starved for whole
                # seconds, and hair-trigger ack timeouts turn that into
                # connect churn
                conn.settimeout(10.0)
                try:
                    conn.sendall(_HELLO.pack(self.world_rank, self.epoch))
                    ack = _recv_exact(conn, _HELLO_ACK.size)
                except OSError:
                    ack = None
                if ack is not None:
                    (peer_epoch,) = _HELLO_ACK.unpack(ack)
                    if peer_epoch > self.epoch:
                        conn.close()
                        # grace before the skew verdict: our own epoch
                        # bump may be milliseconds behind a broadcast
                        # transition (self.epoch is re-read each retry)
                        if skew_since is None:
                            skew_since = time.monotonic()
                        if time.monotonic() - skew_since \
                                > _EPOCH_GRACE_S:
                            _mpit.count(epoch_skews=1)
                            raise EpochSkewError(
                                f"rank {self.world_rank}: peer {dest} is "
                                f"at membership epoch {peer_epoch}, this "
                                f"process at {self.epoch} — this process "
                                f"was shrunk out of the world "
                                f"(stale-epoch straggler)",
                                local_epoch=self.epoch,
                                peer_epoch=peer_epoch, peer=dest)
                        time.sleep(0.01)
                        continue
                    skew_since = None
                    if peer_epoch >= self.min_peer_epoch.get(dest, 0):
                        conn.settimeout(None)
                        with self._conn_lock:
                            self._conns[dest] = conn
                        return conn
                conn.close()  # stale incarnation (or torn handshake)
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rank {self.world_rank}: cannot connect to rank "
                    f"{dest} at epoch >= "
                    f"{self.min_peer_epoch.get(dest, 0)} within "
                    f"{self._connect_timeout}s")
            time.sleep(0.01)

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        if not (0 <= dest < self.world_size):
            raise ValueError(f"dest {dest} out of range for world size {self.world_size}")
        if dest == self.world_rank:
            # value-semantics copy (cheap .copy() for arrays)
            self.mailbox.deliver(dest, ctx, tag, codec.value_copy(payload))
            return
        frame = codec.pack_raw_frame(ctx, tag, payload)
        if frame is not None:
            head, bufs = frame
            body = len(head) + sum(b.nbytes for b in bufs)
            with self._send_lock(dest):
                conn = self._get_conn_locked(dest)
                self._seq += 1
                prefix = _HEADER.pack(codec.RAW_FLAG | body, self._seq) + head
                try:
                    conn.sendall(prefix)
                    for b in bufs:
                        if b.nbytes:
                            # sendall reads the array's buffer directly —
                            # the payload is never pickled or re-copied
                            # host-side
                            conn.sendall(memoryview(b).cast("B"))
                except OSError as e:
                    raise TransportError(
                        f"rank {self.world_rank}: send to rank {dest} "
                        f"failed: {e}") from e
            return
        blob = codec.pack_pickle_body(ctx, tag, payload)
        with self._send_lock(dest):
            conn = self._get_conn_locked(dest)
            self._seq += 1
            frame = _HEADER.pack(len(blob), self._seq) + blob
            try:
                conn.sendall(frame)
            except OSError as e:
                raise TransportError(
                    f"rank {self.world_rank}: send to rank {dest} failed: {e}"
                ) from e

    # -- membership (mpi_tpu/membership.py) --------------------------------

    def membership_invalidate(self, dead) -> None:
        """Drop cached connections to replaced slots so the next send
        re-handshakes (port-file re-read + epoch-checked hello).  Takes
        each per-dest send lock: a send streaming a frame on the old
        connection must finish (or fail) before its socket vanishes."""
        for dest in dead:
            with self._send_lock(dest):
                with self._conn_lock:
                    conn = self._conns.pop(dest, None)
                if conn is not None:
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass

    # -- shutdown ----------------------------------------------------------

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            for conn in self._conns.values():
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        self.mailbox.close()
