"""Error handling (mpi_tpu/errors.py): classification, ERRORS_RETURN at
the MPI_* boundary, custom handlers, and the fatal default."""

import numpy as np
import pytest

from mpi_tpu import api, errors
from mpi_tpu.transport.base import RecvTimeout
from mpi_tpu.transport.local import run_local


# -- classification ---------------------------------------------------------


def test_error_class_classification():
    assert errors.error_class(ValueError("user tags must be >= 0")) == errors.MPI_ERR_TAG
    assert errors.error_class(KeyError("rank 9 not in communicator")) == errors.MPI_ERR_RANK
    assert errors.error_class(TypeError("buffer dtype float32 != datatype base float64")) \
        == errors.MPI_ERR_TYPE
    assert errors.error_class(RecvTimeout("no message")) == errors.MPI_ERR_PENDING
    assert errors.error_class(OSError("broken pipe")) == errors.MPI_ERR_IO
    assert errors.error_class(RuntimeError("boom")) == errors.MPI_ERR_OTHER
    assert errors.error_class(ValueError("unknown allreduce algorithm 'x'")) \
        == errors.MPI_ERR_OP


def test_error_string():
    assert errors.error_string(errors.MPI_SUCCESS) == "no error"
    assert "rank" in errors.error_string(errors.MPI_ERR_RANK)
    assert "invalid error class" in errors.error_string(999)


def test_error_code_carries_exception():
    exc = ValueError("bad tag -3")
    code = errors.ErrorCode.from_exception(exc)
    assert code == errors.MPI_ERR_TAG  # compares as int
    assert code.exception is exc
    assert errors.error_class(code) == errors.MPI_ERR_TAG


# -- handler dispatch at the MPI_* boundary ---------------------------------


def test_errors_are_fatal_default_raises():
    def prog(comm):
        assert comm.get_errhandler() is errors.ERRORS_ARE_FATAL
        with pytest.raises(ValueError):
            api.MPI_Send("x", dest=99, comm=comm)

    run_local(prog, 2)


def test_errors_return_yields_code():
    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        code = api.MPI_Send("x", dest=99, comm=comm)
        assert isinstance(code, errors.ErrorCode)
        assert code == errors.MPI_ERR_RANK
        # a successful call is unaffected
        assert api.MPI_Allreduce(1, comm=comm) == comm.size
        # bad algorithm through a collective also returns, not raises
        bad = api.MPI_Allreduce(1, algorithm="nope", comm=comm)
        assert isinstance(bad, errors.ErrorCode)
        comm.set_errhandler(errors.ERRORS_ARE_FATAL)

    run_local(prog, 2)


def test_custom_handler_called_with_comm_and_exc():
    def prog(comm):
        seen = {}

        def handler(c, exc):
            seen["comm"], seen["exc"] = c, exc
            return "fallback"

        comm.set_errhandler(handler)
        out = api.MPI_Recv(source=42, comm=comm)
        assert out == "fallback"
        assert seen["comm"] is comm and isinstance(seen["exc"], Exception)

    run_local(prog, 1)


def test_errhandler_is_per_communicator():
    def prog(comm):
        dup = comm.dup()
        dup.set_errhandler(errors.ERRORS_RETURN)
        # dup returns a code; the original still raises
        assert isinstance(api.MPI_Send("x", dest=99, comm=dup),
                          errors.ErrorCode)
        with pytest.raises(ValueError):
            api.MPI_Send("x", dest=99, comm=comm)

    run_local(prog, 2)


def test_typed_recv_error_path_skips_unpack():
    """Under ERRORS_RETURN a failed typed recv must return the code, not
    try to unpack it into buf."""
    from mpi_tpu import datatypes as dt

    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        t = dt.type_contiguous(2, np.float64).commit()
        buf = np.zeros(2)
        out = api.MPI_Recv(source=57, comm=comm, datatype=t, buf=buf)
        assert isinstance(out, errors.ErrorCode)
        assert np.all(buf == 0)

    run_local(prog, 1)


def test_errhandler_inherited_by_dup_and_split():
    """MPI-3.1 §8.3: new communicators inherit the parent's handler."""
    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        d = comm.dup()
        s = comm.split(0)
        for c in (d, s):
            assert isinstance(api.MPI_Send("x", dest=99, comm=c),
                              errors.ErrorCode)
        comm.set_errhandler(errors.ERRORS_ARE_FATAL)

    run_local(prog, 2)


def test_errhandler_covers_v_variants_and_probe():
    """The whole flat layer honors ERRORS_RETURN, not just the first
    dozen calls (round-3 review finding)."""
    def prog(comm):
        comm.set_errhandler(errors.ERRORS_RETURN)
        assert isinstance(api.MPI_Scatterv(np.zeros(4), [2, 2], root=99,
                                           comm=comm), errors.ErrorCode)
        assert isinstance(api.MPI_Sendrecv_replace("x", dest=99, comm=comm),
                          errors.ErrorCode)
        assert isinstance(api.MPI_Isend("x", dest=99, comm=comm),
                          errors.ErrorCode)
        comm.set_errhandler(errors.ERRORS_ARE_FATAL)

    run_local(prog, 2)


def test_comm_self_is_per_thread_in_local_ranks():
    """Thread-simulated ranks must not share one SELF mailbox (review
    finding: cross-rank self-send theft)."""
    def prog(comm):
        s = api.MPI_COMM_SELF()
        s.send(("mine", comm.rank), dest=0, tag=1)
        comm.barrier()  # both ranks' self-sends are in flight here
        got = s.recv(source=0, tag=1)
        assert got == ("mine", comm.rank)
        return id(s)

    ids = run_local(prog, 2)
    assert ids[0] != ids[1]
