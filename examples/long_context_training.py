"""Long-context TRAINING over the fused ring-attention kernels.

SURVEY.md §2 strategy table says long-context is first-class, and
``examples/ring_attention.py`` proves the forward; this example proves
the TRAINING story end to end: one transformer block (qkv projection →
causal ring attention → output projection → MLP) over a
sequence-sharded ``sp`` mesh, where BOTH attention passes run the
fused Pallas ring kernels — the forward's credit-flow K/V circulation
and the backward's [K, V, dK, dV] full-cycle ring
(``mpi_tpu.tpu.pallas_attention``, round 5).  Every weight gradient is
synchronized with a psum (weights are replicated; activations are
sequence-sharded), so a training step's communication is exactly: the
two attention rings + one gradient allreduce — nothing touches a
global [S, S] score matrix, and per-device activation memory is
O(S/P).

The loss and gradients are checked (tests/test_long_context.py)
against the same block trained on ONE device with dense attention — a
bitwise-independent oracle for the whole step, fused backward
included.

    python examples/long_context_training.py -n 8 --seq-per-rank 64
"""

import argparse
import math
import os
import sys

try:
    import mpi_tpu  # noqa: F401  (path check only)
except ModuleNotFoundError:  # running from a fresh checkout
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def init_params(d: int, hidden: int, seed: int = 0):
    """One transformer block's weights (replicated on every device)."""
    rng = np.random.RandomState(seed)

    def w(*shape):
        return jnp.asarray(rng.randn(*shape) * (1.0 / math.sqrt(shape[0])),
                           jnp.float32)

    return {"wq": w(d, d), "wk": w(d, d), "wv": w(d, d), "wo": w(d, d),
            "w1": w(d, hidden), "w2": w(hidden, d)}


def block_forward(params, x, attention_fn):
    """The block on a [rows, d] slice; ``attention_fn(q, k, v)`` is the
    only non-local op — dense on one device, a ring over ``sp``."""
    q, k, v = x @ params["wq"], x @ params["wk"], x @ params["wv"]
    att = attention_fn(q, k, v)
    h = x + att @ params["wo"]
    return h + jax.nn.relu(h @ params["w1"]) @ params["w2"]


def loss_fn(params, x, y, attention_fn):
    pred = block_forward(params, x, attention_fn)
    return jnp.mean((pred - y) ** 2)


def sharded_train_step(size: int, axis_name: str = "sp",
                       interpret: bool = True,
                       vmem_limit_bytes=None):
    """→ step(params, x_block, y_block) for one sp-sharded device:
    (loss, grads), attention on the fused ring kernels, grads psum'd.
    Wrap in shard_map over a mesh with ``axis_name`` (check_vma=False:
    the kernel leg must not take the interpreter's vma fallback)."""
    from mpi_tpu.tpu.pallas_attention import pallas_ring_attention

    def attention_fn(q, k, v):
        return pallas_ring_attention(q, k, v, axis_name, size,
                                     causal=True, interpret=interpret,
                                     vmem_limit_bytes=vmem_limit_bytes)

    def step(params, xb, yb):
        def local_loss(p):
            # mean-of-block-means == global mean (equal block sizes)
            return jax.lax.pmean(
                loss_fn(p, xb, yb, attention_fn), axis_name)

        loss, grads = jax.value_and_grad(local_loss)(params)
        # weights are replicated but each device's AD yields only the
        # PARTIAL gradient of the terms its shard computed (the
        # classic replicated-params trap).  pmean — not psum — is the
        # right sync: differentiating the pmean'd loss hands every
        # device cotangent 1 (the psum transpose of the 1/P factors),
        # so each partial is d(Σ_r L_r)/dp restricted to this shard's
        # terms and their average is dL_global/dp.  This is the one
        # gradient allreduce of the whole step.
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(g, axis_name), grads)
        return loss, grads

    return step


def dense_train_step():
    """The single-device oracle: same block, dense causal attention."""
    def attention_fn(q, k, v):
        s = (q @ k.T) / math.sqrt(q.shape[-1])
        n = s.shape[0]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -jnp.inf)
        return jax.nn.softmax(s, axis=-1) @ v

    def step(params, x, y):
        return jax.value_and_grad(
            lambda p: loss_fn(p, x, y, attention_fn))(params)

    return step


def _resolve_platform(n: int) -> str:
    """Pick the platform BEFORE any backend initializes — the same
    wedge discipline as ``__graft_entry__._unwedge_guard``: on a
    tunneled accelerator host a wedged device pool blocks the first
    jax device call forever, so an accelerator platform is accepted
    only after a subprocess probe (hard timeout) confirms it answers;
    anything else runs on an ``n``-device virtual CPU mesh."""
    import re
    import subprocess

    want = os.environ.get("JAX_PLATFORMS", "")
    if want not in ("", "cpu"):
        try:
            ok = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, timeout=120.0).returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        if ok:
            return want
        print(f"[long_context_training] {want!r} backend did not answer "
              f"the probe; falling back to a {n}-device CPU mesh")
        for key in list(os.environ):
            if key.startswith(("PALLAS_AXON", "AXON_")):
                del os.environ[key]
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=8)
    ap.add_argument("--seq-per-rank", type=int, default=64)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1e-2)
    args = ap.parse_args()

    platform = _resolve_platform(args.n)
    from jax.sharding import PartitionSpec as P

    from mpi_tpu.tpu import default_mesh

    mesh = default_mesh(args.n, axis_name="sp")
    S = args.n * args.seq_per_rank
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(S, args.d), jnp.float32)
    y = jnp.asarray(rng.randn(S, args.d), jnp.float32)
    params = init_params(args.d, 2 * args.d)

    # interpret follows the platform (bench.py's convention): the CPU
    # tier runs the kernels' serial interpreter data path; a real
    # accelerator runs the COMPILED fused kernels
    interp = platform == "cpu"
    step = sharded_train_step(args.n, interpret=interp)
    jstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P("sp"), P("sp")),
        out_specs=(P(), P()), check_vma=False))
    mode = "serial-interpreter" if interp else "compiled"
    for i in range(args.steps):
        loss, grads = jstep(params, x, y)
        params = jax.tree.map(lambda p, g: p - args.lr * g, params, grads)
        print(f"step {i}: loss={float(loss):.6f} "
              f"(S={S} over {args.n} sp shards, fused fwd+bwd rings, "
              f"{mode})")


if __name__ == "__main__":
    main()
