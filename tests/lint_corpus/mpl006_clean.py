"""Near-miss twin: the write happens after completion."""


def main(comm, buf):
    req = comm.isend(buf, 1, tag=0)
    req.wait()
    buf[0] = 9.9
