"""The segmented data plane, part 2 (ISSUE 2 tentpole): nonblocking
windowed-pairwise alltoall, the segmented-ring reduce_scatter, the
Rabenseifner (reduce_scatter + ring allgather) allreduce composition, the
nonblocking scatter/gather fan-out/fan-in, and unpickled scan prefixes.

Parity: every new path must match a single-process numpy oracle across
group sizes (pow2 and not), ops, scalar/0-dim payloads, list-vs-stacked
block inputs, and segment boundaries forced down to a few elements via
the ``collective_segment_bytes`` cvar.

Zero-copy proof: on BOTH byte-stream transports (socket, shm) the
``bytes_raw_sent`` / ``bytes_pickled_sent`` / ``payload_copies`` mpit
pvars must show the new paths ship array payloads exclusively as raw
frames — 0 pickled array bytes AND 0 host-side payload copies."""

import numpy as np
import pytest

from mpi_tpu import mpit, ops, schedules
from mpi_tpu.transport.local import run_local
from tests.test_shm_backend import run_shm_world
from tests.test_socket_backend import run_socket_world

NRANKS = [1, 2, 3, 4, 5, 8]
WORLDS = {"socket": run_socket_world, "shm": run_shm_world}


@pytest.fixture
def small_segments():
    """Force multi-segment pipelines at test-sized payloads."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)
    yield
    mpit.cvar_write("collective_segment_bytes", old)


def _byte_deltas_during(world, prog, nranks):
    """(pickled, raw, copies) pvar deltas across a threaded rank world
    (thread-backed ranks share the process-global counters, so this sums
    all ranks)."""
    p0 = mpit.counters.bytes_pickled
    r0 = mpit.counters.bytes_raw
    c0 = mpit.counters.copies
    assert all(world(prog, nranks))
    return (mpit.counters.bytes_pickled - p0,
            mpit.counters.bytes_raw - r0,
            mpit.counters.copies - c0)


# -- alltoall: windowed nonblocking pairwise --------------------------------


def test_alltoall_parity_all_sizes():
    """result[src] on rank r == src's block r, for every group size
    (window > P-1, window < P-1, and the degenerate P=1)."""
    for n in NRANKS:
        data = np.random.RandomState(n).randn(n, n, 5)

        def prog(comm):
            return comm.alltoall(list(data[comm.rank]))

        for r, res in enumerate(run_local(prog, n)):
            np.testing.assert_array_equal(np.asarray(res), data[:, r])


def test_alltoall_mixed_payloads_and_aliases():
    """Arbitrary (non-array) payloads still work per slot, and the
    documented aliases run the same pairwise path."""
    def prog(comm):
        objs = [{"s": comm.rank, "d": d} if d == 0
                else np.arange(4.0) + comm.rank * 10 + d
                for d in range(comm.size)]
        return [comm.alltoall(objs, algorithm=a)
                for a in ("auto", "pairwise", "fused")]

    for r, per_algo in enumerate(run_local(prog, 5)):
        for got in per_algo:
            for s in range(5):
                if r == 0:
                    assert got[s] == {"s": s, "d": 0}
                else:
                    np.testing.assert_array_equal(
                        np.asarray(got[s]), np.arange(4.0) + s * 10 + r)


@pytest.mark.parametrize("transport", sorted(WORLDS))
def test_alltoall_zero_pickled_bytes(transport):
    """Every alltoall payload is an array → all wire bytes raw, zero
    host-side copies (the blocks are contiguous views)."""
    n = 4
    nelem = 1 << 14  # 128KB per block

    def prog(comm):
        rng = np.random.RandomState(comm.rank)
        blocks = list(rng.randn(n, nelem))
        # pin the WIRE exchange: this test proves the windowed pairwise
        # engine's byte plane (auto now routes to the coll/sm arena on
        # shm, whose copy accounting is test_coll_sm.py's contract)
        got = comm.alltoall(blocks, algorithm="pairwise")
        for s in range(n):
            np.testing.assert_array_equal(
                np.asarray(got)[s],
                np.random.RandomState(s).randn(n, nelem)[comm.rank])
        return True

    pickled, raw, copies = _byte_deltas_during(WORLDS[transport], prog, n)
    assert pickled == 0, f"alltoall pickled {pickled} bytes"
    assert copies == 0, f"alltoall made {copies} host payload copies"
    assert raw >= n * (n - 1) * nelem * 8  # every off-rank block, raw


# -- reduce_scatter: segmented ring on one working buffer -------------------


@pytest.mark.parametrize("op,oracle", [
    (ops.SUM, lambda d: d.astype(np.float64).sum(0)),
    (ops.MAX, lambda d: d.max(0)),
])
def test_reduce_scatter_parity_ops_sizes(op, oracle, small_segments):
    for n in NRANKS:
        data = np.random.RandomState(n).randn(n, n, 11)

        def prog(comm):
            return comm.reduce_scatter(data[comm.rank], op=op)

        for r, res in enumerate(run_local(prog, n)):
            np.testing.assert_allclose(np.asarray(res), oracle(data[:, r]),
                                       err_msg=f"n={n} r={r}")


def test_reduce_scatter_list_blocks_match_stacked(small_segments):
    """A list of per-destination blocks and the stacked [P, ...] array
    take the same segmented path and produce identical results."""
    n = 4
    data = np.random.RandomState(7).randn(n, n, 9).astype(np.float32)

    def stacked(comm):
        return comm.reduce_scatter(data[comm.rank], op=ops.SUM)

    def listed(comm):
        return comm.reduce_scatter(list(data[comm.rank]), op=ops.SUM)

    for a, b in zip(run_local(stacked, n), run_local(listed, n)):
        assert np.asarray(a).dtype == np.float32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reduce_scatter_scalar_blocks():
    for n in NRANKS:
        def prog(comm):
            return comm.reduce_scatter(
                [float(comm.rank + d) for d in range(comm.size)])

        for r, res in enumerate(run_local(prog, n)):
            assert np.asarray(res).ndim == 0
            assert float(res) == sum(s + r for s in range(n))


def test_reduce_scatter_heterogeneous_block_shapes():
    """Per-destination block shapes may differ (block r has shape
    (2+r,)); the generic per-chunk path handles it — and only copies the
    fold-target chunks (the send-only chunk stays a caller view)."""
    n = 4

    def prog(comm):
        blocks = [np.full(2 + d, float(comm.rank + 1)) for d in range(n)]
        out = comm.reduce_scatter(blocks, op=ops.SUM)
        # the caller's blocks must be untouched (views are read-only use)
        for d in range(n):
            np.testing.assert_array_equal(blocks[d],
                                          np.full(2 + d, comm.rank + 1.0))
        return out

    want_total = float(sum(range(1, n + 1)))
    for r, res in enumerate(run_local(prog, n)):
        np.testing.assert_array_equal(np.asarray(res),
                                      np.full(2 + r, want_total))


def test_reduce_scatter_mixed_dtypes_promote_like_seed():
    """Cross-rank dtype drift (rank 0 float64, rank 1 int64) reduced via
    numpy promotion on the seed path — the in-place fold must not turn
    that into a UFuncOutputCastingError (regression: review of ISSUE 2)."""
    def prog(comm):
        dtype = np.float64 if comm.rank == 0 else np.int64
        blocks = [np.arange(1, 5, dtype=dtype) * (comm.rank + 1)
                  for _ in range(comm.size)]
        return comm.reduce_scatter(blocks, op=ops.SUM)

    for res in run_local(prog, 2):
        np.testing.assert_allclose(np.asarray(res, dtype=np.float64),
                                   np.arange(1, 5) * 3.0)


def test_reduce_scatter_input_not_mutated(small_segments):
    """The segmented path folds into a PRIVATE working buffer — the
    caller's stacked blocks array must come back bit-identical."""
    n = 3
    data = np.random.RandomState(3).randn(n, n, 8)

    def prog(comm):
        mine = data[comm.rank].copy()
        keep = mine.copy()
        comm.reduce_scatter(mine, op=ops.SUM)
        np.testing.assert_array_equal(mine, keep)
        return True

    assert all(run_local(prog, n))


@pytest.mark.parametrize("transport", sorted(WORLDS))
def test_reduce_scatter_zero_pickled_bytes(transport, small_segments):
    """The segmented ring ships only contiguous views of the working
    buffer: zero pickled array bytes, zero host payload copies, and the
    raw volume ≥ the (P-1)/P·N ring lower bound per rank."""
    n = 4
    nelem = n * (1 << 14)  # 512KB total per rank

    def prog(comm):
        rng = np.random.RandomState(comm.rank)
        blocks = rng.randn(n, nelem // n)
        want = np.zeros(nelem // n)
        for s in range(n):
            want += np.random.RandomState(s).randn(n, nelem // n)[comm.rank]
        # explicit ring: this test proves the segmented WIRE engine; on
        # shm worlds "auto" now routes to the collective arena, whose
        # copy contract is asserted in tests/test_coll_sm.py
        out = comm.reduce_scatter(blocks, op=ops.SUM, algorithm="ring")
        np.testing.assert_allclose(out, want)
        return True

    pickled, raw, copies = _byte_deltas_during(WORLDS[transport], prog, n)
    assert pickled == 0, f"reduce_scatter pickled {pickled} bytes"
    assert copies == 0, f"reduce_scatter made {copies} host payload copies"
    assert raw >= n * (n - 1) * (nelem // n) * 8


# -- Rabenseifner allreduce (reduce_scatter + ring allgather) ---------------


@pytest.mark.parametrize("op,oracle", [
    (ops.SUM, lambda xs: sum(x.astype(np.float64) for x in xs)),
    (ops.MAX, lambda xs: np.maximum.reduce(xs)),
])
def test_rabenseifner_parity_ops_sizes(op, oracle, small_segments):
    """Any group size (unlike recursive halving), every shape regime:
    scalars, fewer elements than ranks, multi-chunk, 2-D."""
    for n in NRANKS:
        for shape in [(), (1,), (7,), (250,), (13, 11)]:
            data = [np.random.RandomState(100 * n + i).randint(
                1, 100, size=shape or (1,)).astype(np.float64).reshape(shape)
                for i in range(n)]
            want = np.asarray(oracle(data))

            def prog(comm):
                return comm.allreduce(data[comm.rank], op,
                                      algorithm="rabenseifner")

            for res in run_local(prog, n):
                np.testing.assert_allclose(
                    np.asarray(res, dtype=np.float64).reshape(shape), want,
                    err_msg=f"n={n} shape={shape}")


def test_rabenseifner_matches_ring_dtype_and_auto_cvar(small_segments):
    """Same dtype preservation as ring, and the auto policy hands
    payloads at/above allreduce_rabenseifner_crossover_bytes to the
    composition (steered by the live cvar, restored afterwards)."""
    n = 3  # non-pow2: auto can only be ring or rabenseifner
    data = [np.arange(101, dtype=np.int32) * (i + 1) for i in range(n)]
    want = np.arange(101, dtype=np.int32) * sum(range(1, n + 1))

    def explicit(comm):
        return comm.allreduce(data[comm.rank], algorithm="rabenseifner")

    for res in run_local(explicit, n):
        assert np.asarray(res).dtype == np.int32
        np.testing.assert_array_equal(np.asarray(res), want)

    old = mpit.cvar_read("allreduce_rabenseifner_crossover_bytes")
    mpit.cvar_write("allreduce_rabenseifner_crossover_bytes", 16)
    try:
        for res in run_local(lambda c: c.allreduce(data[c.rank]), n):
            np.testing.assert_array_equal(np.asarray(res), want)
        # pow2 group: the lowered cvar must win over the halving branch
        # (auto checks the rabenseifner crossover FIRST) — payload far
        # below _RING_CROSSOVER_BYTES, yet the composition runs: its
        # 2(P-1) exchange steps send 6 messages per rank at this size,
        # vs recursive halving's log2(P) = 2 — the send count pins
        # which branch executed
        data4 = [np.arange(101, dtype=np.int32) * (i + 1) for i in range(4)]
        want4 = np.arange(101, dtype=np.int32) * 10
        sends0 = mpit.counters.sends
        for res in run_local(lambda c: c.allreduce(data4[c.rank]), 4):
            np.testing.assert_array_equal(np.asarray(res), want4)
        assert mpit.counters.sends - sends0 >= 6 * 4, \
            "auto did not take the rabenseifner branch on the pow2 group"
    finally:
        mpit.cvar_write("allreduce_rabenseifner_crossover_bytes", old)


@pytest.mark.parametrize("transport", sorted(WORLDS))
def test_rabenseifner_zero_pickled_bytes(transport):
    """The composition inherits the engine's zero-pickle plane on both
    byte-stream transports; volume ≥ 2(P-1)/P·N per rank, all raw."""
    n = 4
    data = [np.random.RandomState(i).randn(1 << 16) for i in range(n)]  # 512KB
    want = sum(data)

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM,
                             algorithm="rabenseifner")
        np.testing.assert_allclose(out, want)
        return True

    pickled, raw, copies = _byte_deltas_during(WORLDS[transport], prog, n)
    assert pickled == 0, f"rabenseifner pickled {pickled} bytes"
    assert copies == 0
    assert raw >= 2 * (n - 1) * data[0].nbytes  # n ranks x 2(P-1)/P each


# -- scatter / gather fan-out/fan-in + scan ---------------------------------


def test_scatter_gather_roundtrip_all_sizes():
    for n in NRANKS:
        data = np.random.RandomState(n).randn(n, 6)

        def prog(comm):
            mine = comm.scatter(list(data) if comm.rank == n - 1 else None,
                                root=n - 1)
            np.testing.assert_array_equal(mine, data[comm.rank])
            return comm.gather(mine * 2, root=0)

        res = run_local(prog, n)
        np.testing.assert_array_equal(np.asarray(res[0]), data * 2)
        assert all(r is None for r in res[1:])


@pytest.mark.parametrize("transport", sorted(WORLDS))
def test_scatter_gather_scan_zero_pickled_array_bytes(transport):
    """Array payloads of scatter's fan-out, gather's fan-in and scan's
    partial prefixes all ride raw frames."""
    n = 4
    nelem = 1 << 15  # 256KB

    def prog(comm):
        rng = np.random.RandomState(0)
        parts = rng.randn(n, nelem)
        mine = comm.scatter(list(parts) if comm.rank == 0 else None, root=0)
        np.testing.assert_array_equal(mine, parts[comm.rank])
        # pin the WIRE prefix exchange (auto routes scan to the coll/sm
        # arena on shm; the arena's copy accounting is test_coll_sm.py's)
        sc = comm.scan(mine, algorithm="doubling")
        np.testing.assert_allclose(sc, parts[:comm.rank + 1].sum(0))
        back = comm.gather(mine, root=0)
        if comm.rank == 0:
            np.testing.assert_array_equal(np.asarray(back), parts)
        return True

    pickled, raw, copies = _byte_deltas_during(WORLDS[transport], prog, n)
    assert pickled == 0, f"scatter/gather/scan pickled {pickled} bytes"
    assert copies == 0
    # scatter + gather each move (n-1) blocks; scan moves at least one
    # prefix per doubling round
    assert raw >= (2 * (n - 1) + 1) * nelem * 8


# -- unified algorithm validation -------------------------------------------


def test_algorithm_validation_names_accepted_values():
    """Every host collective rejects unknown algorithms with the same
    message shape — 'unknown <coll> algorithm <a>; accepted: [...]' —
    and accepts its documented aliases."""
    def prog(comm):
        calls = {
            "allreduce": lambda a: comm.allreduce(np.arange(4.0), algorithm=a),
            "allgather": lambda a: comm.allgather(np.arange(4.0), algorithm=a),
            "alltoall": lambda a: comm.alltoall(
                [np.arange(2.0)] * comm.size, algorithm=a),
            "reduce_scatter": lambda a: comm.reduce_scatter(
                np.zeros((comm.size, 2)), algorithm=a),
            "bcast": lambda a: comm.bcast(
                1 if comm.rank == 0 else None, algorithm=a),
            "reduce": lambda a: comm.reduce(np.arange(2.0), algorithm=a),
        }
        msgs = {}
        for coll, call in calls.items():
            call("auto")
            call("fused")  # the TPU tier's name is an explicit alias
            try:
                call("nope")
            except ValueError as e:
                msgs[coll] = str(e)
        return msgs

    for msgs in run_local(prog, 2):
        assert set(msgs) == {"allreduce", "allgather", "alltoall",
                             "reduce_scatter", "bcast", "reduce"}
        for coll, m in msgs.items():
            assert m.startswith(f"unknown {coll} algorithm 'nope'"), m
            assert "accepted: [" in m and "'fused'" in m, m


def test_block_ag_schedule_composes_with_block_rs():
    """The new ring_ag_block_* tables: starting from 'rank r owns chunk
    r' (the block reduce-scatter postcondition), P-1 rotation steps
    deliver every chunk to every rank, each exactly once."""
    for p in [1, 2, 3, 4, 5, 8]:
        held = [{r} for r in range(p)]
        for step in range(p - 1):
            sends = {}
            for r in range(p):
                si = schedules.ring_ag_block_send_chunk(r, step, p)
                assert si in held[r], (p, r, step)
                sends[(r + 1) % p] = si
            for r in range(p):
                ri = schedules.ring_ag_block_recv_chunk(r, step, p)
                assert sends[r] == ri
                assert ri not in held[r], "chunk received twice"
                held[r].add(ri)
        assert all(h == set(range(p)) for h in held)
